"""Append-only, checksummed, block-structured test files.

Equivalent of /root/reference/jepsen/src/jepsen/store/format.clj (format
spec in its docstring :36-226), redesigned per SURVEY.md §7: same block
concepts — typed, CRC-checked blocks; incremental history chunks sealed
as they fill; an index block whose last valid occurrence names the
current test/history/results — but a far simpler encoding (JSON payloads,
length-prefixed binary frames) instead of Fressian.

File layout:

    magic "JTPU1\\n"
    block*        where block = [u32 payload-len][u32 crc32][u8 type]
                               [payload bytes]

Block types:

    1 INDEX    {"test": off, "results": off, "chunks": [off...],
                "n_ops": N}   — offsets of the blocks in force
    2 TEST     serializable test map
    3 CHUNK    list of op dicts (≤ chunk_size ops; CHUNK_SIZE 16384
               mirrors big-vector-chunk-size, format.clj:372-375)
    4 RESULTS  checker results map

Crash recovery: blocks are only referenced by an INDEX written *after*
them; a torn final block fails its CRC or length check and is ignored,
so a crashed run retains history up to its last sealed chunk + index
(format.clj docstring :189-199).  Writers append, fsync, then append a
fresh INDEX — readers use the last valid INDEX.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Any, BinaryIO, Iterator, Optional

from ..history.core import History, Op

MAGIC = b"JTPU1\n"

BLOCK_INDEX = 1
BLOCK_TEST = 2
BLOCK_CHUNK = 3
BLOCK_RESULTS = 4
#: Fault-ledger record (nemesis/ledger.py): one intent/healed entry per
#: block, appended + fsynced before/after each cluster-touching fault.
BLOCK_LEDGER = 5
#: Plan-memo journal entry (plan/cache.py): one settled plan-node
#: verdict per block, keyed by packed digest + plan knobs, so restarted
#: checker processes warm-start past already-decided work.
BLOCK_PLAN = 6
#: Checkerd queue-journal record (checkerd/journal.py): one accepted
#: submission, result, or abandonment per block, appended + fsynced
#: before the daemon acknowledges, so a restarted daemon (or router)
#: replays every in-flight ticket instead of dropping it.
BLOCK_QUEUE = 7
#: Time-series sample batch (telemetry/timeseries.py): one cadence tick
#: of gauge/counter/SLO/profile samples per block.  A monitor process
#: killed mid-write loses at most the torn tail, which BlockWriter
#: truncates on reopen.
BLOCK_SERIES = 8

#: Ops per sealed history chunk (format.clj:372-375).
CHUNK_SIZE = 16384

_HEADER = struct.Struct("<IIB")  # payload-len, crc32, type


def _jsonable(x: Any) -> Any:
    """Best-effort JSON coercion: sets/tuples become lists, unknown
    objects their repr (the reference strips non-serializable test keys
    instead — store.clj:92-101 — which `serializable_test` does; this is
    the safety net for op values)."""
    if x is None or isinstance(x, (bool, int, float, str)):
        return x
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    if isinstance(x, (set, frozenset)):
        return sorted((_jsonable(v) for v in x), key=repr)
    if isinstance(x, dict):
        return {str(k): _jsonable(v) for k, v in x.items()}
    return repr(x)


def _encode(payload: Any) -> bytes:
    """Fast encode: let the C serializer walk the structure directly,
    with _jsonable as the `default` hook for the objects it rejects
    (sets, opaque values) — the recursive pre-walk was the hottest
    function in whole-stack runs (7.4 s of a 35 s 100k-op profile).
    Payloads the fast path cannot route through the hook (e.g. tuple
    dict keys) retry through the full coercing pre-walk.

    The two paths differ only in dict-KEY coercion of non-string
    scalars: the C encoder writes {True: 1} as {"true":1} where the
    pre-walk's str(k) writes {"True":1}.  Readers treat keys as opaque
    strings, so either spelling round-trips; values are identical."""
    try:
        return json.dumps(
            payload, separators=(",", ":"), default=_jsonable
        ).encode()
    except (TypeError, ValueError):
        return json.dumps(
            _jsonable(payload), separators=(",", ":")
        ).encode()


def frame(block_type: int, payload: Any) -> bytes:
    """One self-contained `[u32 len][u32 crc32][u8 type][payload]`
    block as bytes — the store's block layout doubling as the checker
    daemon's wire frame (checkerd/protocol.py), so histories ship over
    the socket in exactly the encoding they rest in on disk."""
    data = _encode(payload)
    return _HEADER.pack(len(data), zlib.crc32(data), block_type) + data


def raw_frame(block_type: int, data: bytes) -> bytes:
    """`frame` for payloads that are already bytes (packed-column
    tensors): CRC-checked like every block, but not JSON."""
    return _HEADER.pack(len(data), zlib.crc32(data), block_type) + data


class BlockWriter:
    """Appends typed, CRC32-checked blocks to a file.  Reopening a file
    with a torn tail (crashed writer) truncates back to the end of the
    last valid block, so new blocks stay reachable by the sequential
    reader scan."""

    def __init__(self, path: str):
        self.path = path
        size = os.path.getsize(path) if os.path.exists(path) else 0
        end = _valid_end(path, size) if size >= len(MAGIC) else 0
        if end > 0:
            if end < size:
                with open(path, "r+b") as tf:
                    tf.truncate(end)
            self.f: BinaryIO = open(path, "ab")
        else:
            self.f = open(path, "wb")
            self.f.write(MAGIC)
            self.f.flush()

    def append(self, block_type: int, payload: Any) -> int:
        """Writes one block; returns its file offset."""
        data = _encode(payload)
        off = self.f.tell()
        self.f.write(_HEADER.pack(len(data), zlib.crc32(data), block_type))
        self.f.write(data)
        self.f.flush()
        return off

    def sync(self) -> None:
        os.fsync(self.f.fileno())

    def close(self) -> None:
        self.f.close()


def _read_block(f: BinaryIO, size: int) -> Optional[tuple[int, int, Any]]:
    """(offset, type, payload) for the block at the current position, or
    None if torn/invalid."""
    off = f.tell()
    header = f.read(_HEADER.size)
    if len(header) < _HEADER.size:
        return None
    length, crc, btype = _HEADER.unpack(header)
    if off + _HEADER.size + length > size:
        return None
    data = f.read(length)
    if len(data) < length or zlib.crc32(data) != crc:
        return None
    try:
        return off, btype, json.loads(data)
    except ValueError:
        return None


def _valid_end(path: str, size: int) -> int:
    """Offset just past the last valid block (or past the magic if none,
    or 0 for a non-JTPU file, which the writer then overwrites)."""
    with open(path, "rb") as f:
        if f.read(len(MAGIC)) != MAGIC:
            return 0
        end = len(MAGIC)
        while True:
            rec = _read_block(f, size)
            if rec is None:
                return end
            end = f.tell()


class TestFile:
    """Read side: scans for the last valid INDEX, exposes test map,
    results, and the history as lazily-loaded chunks."""

    __test__ = False  # not a pytest class, despite the name

    def __init__(self, path: str):
        self.path = path
        self.size = os.path.getsize(path)
        self.f: BinaryIO = open(path, "rb")
        if self.f.read(len(MAGIC)) != MAGIC:
            raise ValueError(f"{path}: not a JTPU1 file")
        self.index: Optional[dict] = None
        self._scan()

    def _scan(self) -> None:
        """Walks every block, remembering the last valid INDEX
        (crash-recovery read path)."""
        while True:
            rec = _read_block(self.f, self.size)
            if rec is None:
                break
            _, btype, payload = rec
            if btype == BLOCK_INDEX:
                self.index = payload

    def _load(self, off: int, want_type: int) -> Any:
        self.f.seek(off)
        rec = _read_block(self.f, self.size)
        if rec is None or rec[1] != want_type:
            raise ValueError(
                f"{self.path}: bad block at {off} (want type {want_type})"
            )
        return rec[2]

    @property
    def test(self) -> Optional[dict]:
        if self.index is None or self.index.get("test") is None:
            return None
        return self._load(self.index["test"], BLOCK_TEST)

    @property
    def results(self) -> Optional[dict]:
        if self.index is None or self.index.get("results") is None:
            return None
        return self._load(self.index["results"], BLOCK_RESULTS)

    def iter_ops(self) -> Iterator[Op]:
        if self.index is None:
            return
        for off in self.index.get("chunks", []):
            for d in self._load(off, BLOCK_CHUNK):
                yield Op.from_dict(d)

    def history(self) -> History:
        return History(list(self.iter_ops()), reindex=False)

    def close(self) -> None:
        self.f.close()

    def __enter__(self) -> "TestFile":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


class HistoryWriter:
    """Streams ops into sealed CHUNK blocks, checkpointing an INDEX after
    every seal so crashes keep everything up to the last seal
    (format.clj:189-199).  Use as the interpreter's `writer` hook."""

    def __init__(
        self,
        writer: BlockWriter,
        *,
        chunk_size: int = CHUNK_SIZE,
        test_offset: Optional[int] = None,
    ):
        self.writer = writer
        self.chunk_size = chunk_size
        self.buffer: list[dict] = []
        self.chunk_offsets: list[int] = []
        self.n_ops = 0
        self.test_offset = test_offset
        self.results_offset: Optional[int] = None

    def append(self, op: Op) -> None:
        self.buffer.append(op.to_dict())
        self.n_ops += 1
        if len(self.buffer) >= self.chunk_size:
            self.seal()
            self.checkpoint()

    def seal(self) -> None:
        if self.buffer:
            off = self.writer.append(BLOCK_CHUNK, self.buffer)
            self.chunk_offsets.append(off)
            self.buffer = []

    def checkpoint(self) -> None:
        self.writer.append(
            BLOCK_INDEX,
            {
                "test": self.test_offset,
                "results": self.results_offset,
                "chunks": self.chunk_offsets,
                "n_ops": self.n_ops,
            },
        )
        self.writer.sync()

    def close(self) -> None:
        self.seal()
        self.checkpoint()


class Handle:
    """One open test file for the whole run lifecycle: the three save
    phases of store.clj:426-466 over one BlockWriter."""

    def __init__(self, path: str):
        self.path = path
        existing_index: Optional[dict] = None
        if os.path.exists(path) and os.path.getsize(path) > len(MAGIC):
            try:
                with TestFile(path) as tf:
                    existing_index = tf.index
            except ValueError:
                existing_index = None
        self.writer = BlockWriter(path)
        self.history_writer: Optional[HistoryWriter] = None
        self._test_offset: Optional[int] = None
        if existing_index:
            # Reopening (e.g. to append fresh analysis results): carry
            # the prior index forward so history chunks stay reachable.
            self._test_offset = existing_index.get("test")
            hw = HistoryWriter(self.writer, test_offset=self._test_offset)
            hw.chunk_offsets = list(existing_index.get("chunks", []))
            hw.n_ops = existing_index.get("n_ops", 0)
            hw.results_offset = existing_index.get("results")
            self.history_writer = hw

    def save_test(self, test_map: dict) -> None:
        """save-0!: the initial test map, before the run."""
        self._test_offset = self.writer.append(BLOCK_TEST, test_map)
        if self.history_writer is not None:
            self.history_writer.test_offset = self._test_offset
        self.writer.sync()

    def open_history_writer(self, chunk_size: int = CHUNK_SIZE) -> HistoryWriter:
        self.history_writer = HistoryWriter(
            self.writer, chunk_size=chunk_size, test_offset=self._test_offset
        )
        return self.history_writer

    def _ensure_history_writer(self) -> HistoryWriter:
        if self.history_writer is None:
            self.history_writer = HistoryWriter(
                self.writer, test_offset=self._test_offset
            )
        return self.history_writer

    def save_run(self, test_map: dict) -> None:
        """save-1!: test + completed history."""
        hw = self._ensure_history_writer()
        hw.seal()
        self._test_offset = self.writer.append(BLOCK_TEST, test_map)
        hw.test_offset = self._test_offset
        hw.checkpoint()

    def save_results(self, results: dict) -> None:
        """save-2!: analysis results."""
        hw = self._ensure_history_writer()
        hw.seal()
        hw.results_offset = self.writer.append(BLOCK_RESULTS, results)
        hw.checkpoint()

    def close(self) -> None:
        self.writer.close()

    def __enter__(self) -> "Handle":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
