"""Persistence: test directories, save phases, logging.

Equivalent of /root/reference/jepsen/src/jepsen/store.clj: test dirs
``store/<name>/<start-time>/`` (:40-62), the non-serializable-keys strip
(:92-101), the three save phases (:426-466), plain-text history dumps
(:369-386), ``current``/``latest`` symlinks (:310-340), per-test log
files (:484-504), and loading/querying past tests (:122-283).

The binary block format lives in `jepsen_tpu.store.format`.
"""

from __future__ import annotations

import datetime as _dt
import logging
import os
import shutil
from typing import Any, Iterator, Optional

from ..history.core import History, Op
from .format import CHUNK_SIZE, Handle, HistoryWriter, TestFile

#: Test-map keys that hold live objects and never serialize
#: (store.clj:92-101).
NONSERIALIZABLE_KEYS = (
    "client",
    "nemesis",
    "generator",
    "checker",
    "model",
    "net",
    "db",
    "os",
    "remote",
    "sessions",
    "barrier",
    "store",
    # Live FaultLedger handle; its durable form is nemesis.ledger in
    # the same store dir.
    "fault-ledger",
    # Live HealthMonitor + a test-supplied probe callable; their durable
    # form is results["resilience"]["nodes"].
    "node-health",
    "health-probe",
    # Live StreamingSession (jepsen_tpu/streaming/); its durable form
    # is results["streaming"].
    "streaming-session",
    # Run outputs saved in their own blocks, not inside the test map:
    "history",
    "results",
)

TEST_FILE = "test.jtpu"
LOG_FILE = "jepsen.log"

log = logging.getLogger(__name__)


def serializable_test(test: dict) -> dict:
    return {k: v for k, v in test.items() if k not in NONSERIALIZABLE_KEYS}


def base_dir(test_or_root: Any = None) -> str:
    """The store root: test["store-dir"] or ./store (store.clj:33-38)."""
    if isinstance(test_or_root, str):
        return test_or_root
    if isinstance(test_or_root, dict):
        return test_or_root.get("store-dir", "store")
    return "store"


def time_str(t: Optional[_dt.datetime] = None) -> str:
    t = t or _dt.datetime.now()
    return t.strftime("%Y%m%dT%H%M%S.%f")[:-3]


def test_dir(test: dict) -> str:
    """store/<name>/<start-time>/ (store.clj:40-62)."""
    name = test.get("name", "noname")
    start = test.get("start-time")
    if start is None:
        raise ValueError("test has no start-time; call make_test_dir first")
    return os.path.join(base_dir(test), str(name), str(start))


def path(test: dict, *more: str) -> str:
    return os.path.join(test_dir(test), *more)


def make_test_dir(test: dict) -> dict:
    """Assigns a start-time (if absent), creates the directory, and
    points the `current` and `latest` symlinks at it."""
    test = dict(test)
    test.setdefault("start-time", time_str())
    d = test_dir(test)
    os.makedirs(d, exist_ok=True)
    _update_symlinks(test)
    return test

def _update_symlinks(test: dict) -> None:
    d = test_dir(test)
    name_dir = os.path.dirname(d)
    root = base_dir(test)
    for link_dir, link_name in ((name_dir, "latest"), (root, "current")):
        link = os.path.join(link_dir, link_name)
        try:
            if os.path.islink(link):
                os.unlink(link)
            os.symlink(os.path.relpath(d, link_dir), link)
        except OSError as e:  # pragma: no cover - symlink-less filesystems
            log.debug("couldn't update symlink %s: %s", link, e)


class Store:
    """with-handle for one test run: the open block file plus txt dumps
    (store.clj:412-424)."""

    def __init__(self, test: dict):
        self.test = test
        self.dir = test_dir(test)
        self.handle = Handle(os.path.join(self.dir, TEST_FILE))

    # -- save phases (store.clj:426-466) -------------------------------

    def save_0(self, test: dict) -> None:
        self.handle.save_test(serializable_test(test))

    def history_writer(self, chunk_size: int = CHUNK_SIZE) -> HistoryWriter:
        return self.handle.open_history_writer(chunk_size)

    def save_1(self, test: dict, history: History) -> None:
        self.handle.save_run(serializable_test(test))
        write_history_txt(os.path.join(self.dir, "history.txt"), history)

    def save_2(self, results: dict) -> None:
        self.handle.save_results(results)

    def close(self) -> None:
        self.handle.close()

    def __enter__(self) -> "Store":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


def write_history_txt(p: str, history: History) -> None:
    """Plain-text one-op-per-line dump (store.clj:369-386)."""
    with open(p, "w") as f:
        for op in history:
            f.write(str(op))
            f.write("\n")


def start_logging(test: dict, *, console: bool = False) -> logging.Handler:
    """Attaches a jepsen.log file handler for this test's directory
    (store.clj:484-504).  Returns the handler; pass it to stop_logging."""
    handler = logging.FileHandler(path(test, LOG_FILE))
    handler.setFormatter(
        logging.Formatter(
            "%(asctime)s %(levelname)s [%(threadName)s] %(name)s: %(message)s"
        )
    )
    root = logging.getLogger()
    root.addHandler(handler)
    if root.level > logging.INFO or root.level == logging.NOTSET:
        root.setLevel(logging.INFO)
    return handler


def stop_logging(handler: logging.Handler) -> None:
    logging.getLogger().removeHandler(handler)
    handler.close()


# -- reading past tests (store.clj:122-283) -----------------------------


def load(d: str) -> TestFile:
    """Opens a stored test dir (or .jtpu file) for reading."""
    if os.path.isdir(d):
        d = os.path.join(d, TEST_FILE)
    return TestFile(d)


def tests(root: str = "store") -> dict[str, dict[str, str]]:
    """{test-name: {start-time: dir}} of all stored runs."""
    out: dict[str, dict[str, str]] = {}
    if not os.path.isdir(root):
        return out
    for name in sorted(os.listdir(root)):
        name_dir = os.path.join(root, name)
        if not os.path.isdir(name_dir) or name in ("current", "latest"):
            continue
        runs = {}
        for t in sorted(os.listdir(name_dir)):
            d = os.path.join(name_dir, t)
            if os.path.isdir(d) and not os.path.islink(d):
                runs[t] = d
        if runs:
            out[name] = runs
    return out


def latest(root: str = "store") -> Optional[str]:
    """The most recent run dir: the `current` symlink when it resolves,
    else the newest run found by scanning (symlink-less filesystems,
    deleted runs)."""
    link = os.path.join(root, "current")
    if os.path.islink(link):
        target = os.path.realpath(link)
        if os.path.isdir(target):
            return target
    newest: Optional[str] = None
    newest_time = ""
    for runs in tests(root).values():
        for t, d in runs.items():
            if t > newest_time:
                newest_time, newest = t, d
    return newest


def delete(root: str = "store", name: Optional[str] = None) -> None:
    """Deletes stored tests (store.clj:523-531)."""
    target = os.path.join(root, name) if name else root
    if os.path.isdir(target):
        shutil.rmtree(target)
