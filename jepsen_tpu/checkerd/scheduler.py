"""The checkerd cohort scheduler: cross-run merge onto one device pool.

One worker thread owns the devices.  It pops the queue, waits one
batch window so concurrent runs' submissions land, then takes every
queued request *compatible* with the head (same model spec, algorithm,
and budgets — budgets gate compatibility so a tight-budget request
never rides a cohort that outlives it) and checks them as ONE merged
cohort through the existing settling ladder
(parallel/independent.py._check_linearizable): every key of every
request becomes a (ticket, key-index) entry in one subs map, so the
stream witness, refutation screens, batched BFS, settle memo, and mesh
sharding amortize across runs exactly as they do across keys.

Budgets: a request's `budget-s` (the run's checker_budget) bounds its
cohort's wall clock via utils.timeout — on expiry the worker abandons
the check thread (check_safe semantics) and every member request
reports per-key "unknown".  A non-positive budget is already expired
and short-circuits deterministically.  The WGL degradation ladder
(ops/degrade.py) runs inside the cohort check as usual; captured steps
ride back in each request's result metadata.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from typing import Any, Optional

from .. import telemetry
from ..checker.core import merge_valid
from ..telemetry import flight, profile
from ..utils import timeout as _timeout
from . import overload

_BLOWN = object()

#: Done requests linger for late polls; a sweep drops them after this.
_RESULT_TTL_S = 600.0
#: Hard cap on remembered tickets (done ones evict oldest-first).
_MAX_TICKETS = 4096


def _count_ops(subs: dict, packs: dict) -> int:
    """Total op count of a submission (latency-estimator feature);
    best-effort — a shape the codecs don't expose counts as zero."""
    n = 0
    for h in subs.values():
        try:
            n += len(h)
        except TypeError:
            pass
    for p in packs.values():
        try:
            n += int(p.n)
        except (AttributeError, TypeError, ValueError):
            pass
    return n


class Request:
    """One run's submitted history: per-key subhistories (ops mode)
    and/or packed tensors (packed mode), plus check parameters."""

    def __init__(
        self,
        *,
        run: str,
        model_spec: dict,
        algorithm: str = "wgl-tpu",
        n_keys: int = 0,
        budget_s: Optional[float] = None,
        time_limit_s: Optional[float] = None,
        subs: Optional[dict[int, Any]] = None,
        packs: Optional[dict[int, Any]] = None,
        trace: Optional[dict] = None,
        tenant: Optional[str] = None,
        deadline_s: Optional[float] = None,
    ):
        from .protocol import canonical_spec

        self.run = run
        self.model_spec = model_spec
        self.algorithm = algorithm
        self.n_keys = n_keys
        self.budget_s = budget_s
        self.time_limit_s = time_limit_s
        self.subs = subs or {}
        self.packs = packs or {}
        #: Fair-queueing identity: explicit SUBMIT "tenant" when given,
        #: else the run name (matching the router's quota identity).
        self.tenant = str(tenant or run or "anonymous")
        #: Client deadline in seconds from submission; the admission
        #: plane sheds early (overload.py) when the predicted verdict
        #: latency plus queue wait cannot meet it.  None = never shed.
        self.deadline_s = (float(deadline_s)
                           if isinstance(deadline_s, (int, float))
                           and deadline_s > 0 else None)
        self.n_ops = _count_ops(self.subs, self.packs)
        #: The submitting run's trace context ({"trace-id",
        #: "parent-span"}) — deliberately NOT part of `compat`: a
        #: cohort merges requests from different traces, and each
        #: request's copy of the cohort spans is stamped with its own.
        self.trace = trace if isinstance(trace, dict) else None
        #: Cohort-compatibility key: requests merge iff this matches.
        self.compat = canonical_spec({
            "model": canonical_spec(model_spec),
            "algorithm": algorithm,
            "budget-s": budget_s,
            "time-limit-s": time_limit_s,
        })
        self.ticket: str = ""
        self.state = "new"  # queued | running | done
        self.result: Optional[dict] = None
        self.submitted_t = 0.0
        self.started_t = 0.0
        self.done_t = 0.0
        #: Disconnect-abandonment bookkeeping (non-streaming tickets
        #: only): the submitting connection's id, whether any OTHER
        #: connection has polled this ticket (then the submitter dying
        #: must not cancel it), and whether it is condemned — condemned
        #: tickets drop out of the queue at the next cohort boundary.
        self.owner_conn: Optional[int] = None
        self.adopted = False
        self.abandoned = False


class Scheduler:
    def __init__(
        self,
        *,
        batch_window_s: float = 0.05,
        max_budget_s: Optional[float] = None,
        bound: Optional[int] = None,
        profile_dir: Optional[str] = None,
        plan_cache_dir: Optional[str] = None,
        queue_path: Optional[str] = None,
        tenant_weights: Optional[dict[str, float]] = None,
        fair_quantum: float = overload.DEFAULT_QUANTUM,
    ):
        self.batch_window_s = batch_window_s
        self.max_budget_s = max_budget_s
        self.bound = bound
        if profile_dir:
            # The daemon's own fleet-wide profile store + postmortem
            # dir: every cohort's pass records aggregate here.
            profile.set_store(profile_dir)
            flight.set_dir(profile_dir)
        if plan_cache_dir:
            # Daemon warm start: the plan memo journal + XLA compile
            # cache under one dir, so a restarted checkerd re-checking
            # byte-identical histories skips settled work AND the
            # recompiles (jepsen_tpu/plan/cache.py).
            from ..plan import cache as plan_cache

            plan_cache.configure(plan_cache_dir)
        self._cond = threading.Condition()
        #: Deficit-round-robin per-tenant queues (overload.py) — the
        #: FIFO list's replacement.  Guarded by self._cond like it was.
        self._fq = overload.FairQueue(
            quantum=fair_quantum, weights=tenant_weights,
        )
        #: Verdict-latency estimator feeding deadline-aware shedding.
        self.estimator = overload.LatencyEstimator()
        #: Per-tenant service record (queue-wait p95, served/shed).
        self.tenant_stats = overload.TenantStats()
        self.n_shed = 0
        self._tickets: dict[str, Request] = {}
        #: canonical model spec -> live Model instance.  THE warm path:
        #: one instance per spec for the daemon's lifetime means one
        #: XLA compile, one interner, and digest-stable settle-memo
        #: keys across every run that ever submits that model.
        self._models: dict[str, Any] = {}
        self._stop = False
        self._t0 = time.monotonic()
        self._busy_s = 0.0
        self.n_requests = 0
        self.n_keys_total = 0
        self.n_cohorts = 0
        self.n_cohorts_merged = 0
        self.n_requests_merged = 0
        self._lat_count = 0
        self._lat_total = 0.0
        self._lat_max = 0.0
        self._lat_last = 0.0
        self._runs: dict[str, dict[str, Any]] = {}
        self.n_abandoned = 0
        self.n_replayed = 0
        #: Durable queue: every accepted submission journals before its
        #: TICKET leaves, every verdict journals before the request is
        #: marked done, and a restarted daemon re-queues what's left
        #: (checkerd/journal.py).  None = the old in-memory-only queue.
        self.journal = None
        if queue_path:
            from .journal import QueueJournal

            self.journal = QueueJournal(queue_path)
            self._replay_journal()
        self._thread = threading.Thread(
            target=self._loop, name="checkerd-worker", daemon=True
        )
        self._thread.start()

    def _replay_journal(self) -> None:
        """Restores journal state before the worker starts: finished
        tickets re-answer late polls with their journaled bytes
        (replay idempotence); unfinished ones re-queue under their
        ORIGINAL ticket ids and re-form cohorts through the normal
        worker path — the plan compiler and the plan/XLA caches make
        the re-check a warm start."""
        import logging

        from .journal import request_from_record

        log = logging.getLogger(__name__)
        now = time.monotonic()
        for ticket, res in self.journal.finished().items():
            req = Request(run="replayed", model_spec={})
            req.ticket = ticket
            req.state = "done"
            req.result = res
            req.n_keys = len(res.get("key-results") or [])
            req.submitted_t = req.done_t = now
            with self._cond:
                self._tickets[ticket] = req
        for ticket, rec in self.journal.unfinished().items():
            try:
                req = request_from_record(rec)
            except Exception as e:  # noqa: BLE001 — one corrupt record
                # must not stop the rest of the replay.
                telemetry.count("checkerd.queue.replay-failed")
                log.warning("queue replay: ticket %s unrecoverable: %r",
                            ticket, e)
                continue
            req.ticket = ticket
            req.submitted_t = now
            req.state = "queued"
            with self._cond:
                self._tickets[ticket] = req
                self._fq.push(req)
                self.n_requests += 1
                self.n_keys_total += req.n_keys
                self._run_entry_locked(req.run)["submitted"] += 1
                self.n_replayed += 1
            telemetry.count("checkerd.queue.replayed")
        if self.n_replayed or self._tickets:
            log.info("queue replay: %d unfinished re-queued, %d finished "
                     "results restored", self.n_replayed,
                     len(self._tickets) - self.n_replayed)

    # -- admission ----------------------------------------------------------

    def submit(self, req: Request, *, owner_conn: Optional[int] = None) -> str:
        """Admits one request, or raises overload.OverloadShed — BEFORE
        any ticket is minted or journaled, so a shed is never an acked
        submission (the no-silent-loss invariant is trivial for sheds:
        there is nothing to lose)."""
        now = time.monotonic()
        with self._cond:
            self._maybe_shed_locked(req)
            req.ticket = uuid.uuid4().hex[:12]
            req.submitted_t = now
            req.state = "queued"
            req.owner_conn = owner_conn
            self._sweep_locked(now)
            self._tickets[req.ticket] = req
            self._fq.push(req)
            self.n_requests += 1
            self.n_keys_total += req.n_keys
            r = self._run_entry_locked(req.run)
            r["submitted"] += 1
            self._cond.notify_all()
        if self.journal is not None:
            # Durability before acknowledgement: the TICKET reply only
            # leaves after this returns, so every pollable ticket is a
            # replayable ticket.  (Journaled outside _cond — an fsync
            # must not stall pollers.)
            from .journal import request_to_record

            self.journal.record_submit(req.ticket, request_to_record(req))
        if telemetry.enabled():
            telemetry.count("checkerd.requests")
            telemetry.count("checkerd.keys", req.n_keys)
        return req.ticket

    def _maybe_shed_locked(self, req: Request) -> None:
        """Deadline-aware admission (overload.py): raises OverloadShed
        when the predicted verdict latency plus the current queue wait
        cannot meet the request's client deadline.  Requests without a
        deadline are never shed — they queue like they always did."""
        if req.deadline_s is None:
            return
        queued_keys = sum(r.n_keys for r in self._fq.requests())
        wait_s = self.estimator.queue_wait_s(queued_keys)
        check_s = self.estimator.predict_s(req.n_keys, req.n_ops)
        estimate = (wait_s + check_s) * overload.brownout().shed_factor()
        if estimate <= req.deadline_s:
            return
        self.n_shed += 1
        self.tenant_stats.record_shed(req.tenant)
        telemetry.count("checkerd.overload.shed")
        telemetry.count("checkerd.overload.shed-deadline")
        raise overload.OverloadShed(
            f"predicted verdict latency {estimate:.2f}s exceeds the "
            f"{req.deadline_s:.2f}s client deadline "
            f"(queue wait ~{wait_s:.2f}s over {queued_keys} keys)",
            retry_after_s=max(0.5, wait_s),
            tenant=req.tenant,
            estimate_s=estimate,
            deadline_s=req.deadline_s,
        )

    def poll(self, ticket: str, conn_id: Optional[int] = None) -> dict:
        """A POLL reply payload: PENDING-shaped while queued/running,
        the RESULT payload once done, or an error marker."""
        with self._cond:
            req = self._tickets.get(ticket)
            if req is None:
                return {"_error": f"unknown ticket {ticket!r}"}
            if (conn_id is not None and req.owner_conn is not None
                    and conn_id != req.owner_conn):
                # Someone other than the submitting connection wants
                # this verdict: the submitter dying no longer abandons
                # the ticket.
                req.adopted = True
            if req.state == "done" and req.result is not None:
                return dict(req.result)
            return {
                "_pending": True,
                "state": req.state,
                "queue-depth": len(self._fq),
            }

    def abandon(self, ticket: str, conn_id: Optional[int] = None) -> bool:
        """Cancels a still-queued ticket whose submitting connection
        died mid-PENDING, so its keys drop out at the next cohort
        boundary instead of riding the merged cohort forever.  Running
        or done tickets are left alone (their work is already spent or
        delivered), and so are adopted tickets — some other connection
        is waiting on them."""
        with self._cond:
            req = self._tickets.get(ticket)
            if req is None or req.state != "queued" or req.abandoned:
                return False
            if conn_id is not None and (req.owner_conn != conn_id
                                        or req.adopted):
                return False
            req.abandoned = True
            self.n_abandoned += 1
        telemetry.count("checkerd.ticket-abandoned")
        if self.journal is not None:
            self.journal.record_abandon(ticket)
        return True

    def model_for(self, spec: dict) -> Any:
        """The daemon-wide model instance for a spec (building it on
        first sight — which also validates the spec for the submitter's
        ERROR frame)."""
        from .protocol import canonical_spec, model_from_spec

        key = canonical_spec(spec)
        with self._cond:
            m = self._models.get(key)
            if m is None:
                m = model_from_spec(spec)
                self._models[key] = m
            return m

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._fq)

    def stop(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=5.0)
        if self.journal is not None:
            self.journal.close()

    # -- bookkeeping --------------------------------------------------------

    def _run_entry_locked(self, run: str) -> dict[str, Any]:
        r = self._runs.get(run)
        if r is None:
            r = self._runs[run] = {
                "submitted": 0, "done": 0, "merged": 0,
                "last-latency-s": None,
            }
        return r

    def _sweep_locked(self, now: float) -> None:
        dead = [
            t for t, r in self._tickets.items()
            if r.state == "done" and now - r.done_t > _RESULT_TTL_S
        ]
        for t in dead:
            del self._tickets[t]
        while len(self._tickets) >= _MAX_TICKETS:
            victim = next(
                (t for t, r in self._tickets.items() if r.state == "done"),
                None,
            )
            if victim is None:
                break  # all live; admission still proceeds
            del self._tickets[victim]

    def stats(self) -> dict:
        """JSON-able fleet stats for STATS frames and the /fleet page."""
        with self._cond:
            now = time.monotonic()
            uptime = max(now - self._t0, 1e-9)
            queued: dict[str, int] = {}
            running: dict[str, int] = {}
            for r in self._fq.requests():
                queued[r.run] = queued.get(r.run, 0) + 1
            for r in self._tickets.values():
                if r.state == "running":
                    running[r.run] = running.get(r.run, 0) + 1
            runs = {}
            for run, d in self._runs.items():
                runs[run] = {
                    **d,
                    "queued": queued.get(run, 0),
                    "running": running.get(run, 0),
                }
            fair = self._fq.snapshot()
            tenants = self.tenant_stats.snapshot()
            for t, fq in fair.items():
                tenants.setdefault(t, {}).update(fq)
            out = {
                "uptime-s": round(uptime, 3),
                "queue-depth": len(self._fq),
                "overload": {
                    "brownout-level": overload.brownout().level,
                    "shed": self.n_shed,
                    "quantum": self._fq.quantum,
                    "weights": dict(self._fq.weights),
                    "tenants": tenants,
                },
                "requests": self.n_requests,
                "keys": self.n_keys_total,
                "cohorts": self.n_cohorts,
                "cohorts-merged": self.n_cohorts_merged,
                "requests-merged": self.n_requests_merged,
                "merge-ratio": round(
                    self.n_requests_merged / self.n_requests, 4
                ) if self.n_requests else 0.0,
                "busy-s": round(self._busy_s, 3),
                "utilization": round(self._busy_s / uptime, 4),
                "verdict-latency": {
                    "count": self._lat_count,
                    "mean-s": round(
                        self._lat_total / self._lat_count, 4
                    ) if self._lat_count else None,
                    "max-s": round(self._lat_max, 4),
                    "last-s": round(self._lat_last, 4),
                },
                "models-cached": len(self._models),
                "abandoned": self.n_abandoned,
                "replayed": self.n_replayed,
                "runs": runs,
            }
        out["queue-journal"] = (
            self.journal.stats() if self.journal is not None else None
        )
        out["devices"] = _device_info()
        # Observability surface: the degrade ladder's last chip probe
        # verdict and the fleet-wide profile-store aggregate (the
        # daemon's store accumulates a record per pass across every
        # run that ever submitted — the ROADMAP-3 training set).
        from ..ops import degrade

        out["chip-health"] = degrade.chip_state()
        out["profile-records"] = profile.count_records()
        out["profile-by-pass"] = profile.by_pass()
        # Roofline summary over the store's recent tail: per-pass
        # achieved-vs-peak medians (telemetry/roofline.py), capped so a
        # long-lived daemon's STATS stays O(tail) not O(history).
        try:
            from ..telemetry import roofline

            p = profile.store_path()
            recs = profile.read(p)[-2000:] if p else []
            out["roofline"] = roofline.summarize(recs) if recs else None
        except Exception:  # noqa: BLE001 — STATS must never fail on
            # an advisory summary
            out["roofline"] = None
        # Plan-layer health: routing flag, persistent cache hit rates,
        # and which passes the cost model covers — the /fleet plan
        # panel renders this block.
        from .. import plan as _plan
        from ..plan import cache as plan_cache
        from ..plan import costmodel

        out["plan"] = {
            "enabled": _plan.enabled(),
            "cache": plan_cache.stats(),
            "costmodel": costmodel.model_info(),
        }
        return out

    # -- the worker ---------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not len(self._fq) and not self._stop:
                    self._cond.wait(0.5)
                    # Idle samples let the brownout ladder de-escalate
                    # (sample() takes no scheduler lock; _cond is ours).
                    overload.brownout().sample(
                        len(self._fq), overload.process_rss_mb(),
                    )
                if self._stop:
                    return
            if self.batch_window_s > 0:
                # The merge window: concurrent runs submitting "at the
                # same time" land in one cohort instead of racing the
                # worker's pop.
                time.sleep(self.batch_window_s)
            with self._cond:
                # The cohort boundary is where abandoned tickets leave:
                # their keys never join the merged subs map, so a dead
                # client can't burn cohort budget.
                condemned = self._fq.drop_abandoned()
                if condemned:
                    now = time.monotonic()
                    for r in condemned:
                        r.state = "done"
                        r.done_t = now
                        r.result = {
                            "valid": "unknown",
                            "error": "checkerd: ticket abandoned "
                                     "(submitting connection died "
                                     "before the cohort formed)",
                            "key-results": [{
                                "valid": "unknown",
                                "error": "checkerd: ticket abandoned",
                            }] * r.n_keys,
                            "checkerd": {"ticket": r.ticket,
                                         "abandoned": True},
                        }
                if not len(self._fq):
                    continue
                # Deficit round-robin picks the head (the fairness
                # decision); compatible requests from EVERY tenant then
                # ride the cohort — the merge amortizes device work, so
                # joining costs the fleet nothing, and take_compat
                # charges each tenant's deficit for its own keys.
                head = self._fq.next_head()
                if head is None:
                    continue
                group = [head] + self._fq.take_compat(head.compat)
                now = time.monotonic()
                for r in group:
                    r.state = "running"
                    r.started_t = now
            # One pressure sample per cohort: queue depth + RSS drive
            # the brownout ladder the plan compiler consults.
            overload.brownout().sample(
                self.queue_depth(), overload.process_rss_mb(),
            )
            t_run = time.monotonic()
            try:
                self._check_group(group)
            except Exception as e:  # noqa: BLE001 — a cohort crash must
                # not kill the daemon; every member degrades to unknown.
                err = {
                    "valid": "unknown",
                    "error": f"checkerd cohort failed: {e!r}",
                }
                for r in group:
                    if r.result is None:
                        r.result = {
                            "valid": "unknown",
                            "key-results": [dict(err)] * r.n_keys,
                            "checkerd": {"error": err["error"]},
                        }
            dt = time.monotonic() - t_run
            # Feed the deadline-shed estimator with what this cohort
            # actually cost per key (observed fallback when the plan
            # cost model is untrained).
            self.estimator.observe(sum(r.n_keys for r in group), dt)
            if self.journal is not None:
                # The replay-idempotence rule: a verdict is durable
                # BEFORE any poll can observe state "done", so a crash
                # between here and the mark-done below re-serves the
                # same bytes instead of re-checking.
                for r in group:
                    if r.result is not None:
                        self.journal.record_result(r.ticket, r.result)
            with self._cond:
                self._busy_s += dt
                self.n_cohorts += 1
                if len({r.run for r in group}) > 1:
                    self.n_cohorts_merged += 1
                    self.n_requests_merged += len(group)
                now = time.monotonic()
                for r in group:
                    r.state = "done"
                    r.done_t = now
                    lat = now - r.submitted_t
                    self._lat_count += 1
                    self._lat_total += lat
                    self._lat_max = max(self._lat_max, lat)
                    self._lat_last = lat
                    e = self._run_entry_locked(r.run)
                    e["done"] += 1
                    e["last-latency-s"] = round(lat, 4)
                    if len(group) > 1:
                        e["merged"] += 1
                    self.tenant_stats.observe_wait(
                        r.tenant, r.started_t - r.submitted_t,
                    )
                self._cond.notify_all()
            if telemetry.enabled():
                telemetry.count("checkerd.cohorts")
                if len(group) > 1:
                    telemetry.count("checkerd.cohorts-merged")

    def _check_group(self, group: list[Request]) -> None:
        from ..checker.linearizable import Linearizable
        from ..ops import degrade
        from ..parallel.independent import IndependentChecker

        head = group[0]
        model = self.model_for(head.model_spec)
        budget = head.budget_s
        if budget is not None and self.max_budget_s is not None:
            budget = min(budget, self.max_budget_s)
        elif budget is None:
            budget = self.max_budget_s

        merged_subs = {
            (r.ticket, i): h for r in group for i, h in r.subs.items()
        }
        merged_packs = {
            (r.ticket, i): p for r in group for i, p in r.packs.items()
        }

        lin = Linearizable(
            model, head.algorithm, time_limit_s=head.time_limit_s,
        )
        chk = IndependentChecker(lin, bound=self.bound)
        test = {"model": model}

        def run_cohort() -> tuple[dict, list]:
            out: dict[Any, dict] = {}
            with degrade.capture() as steps:
                if merged_subs:
                    out.update(
                        chk._check_linearizable(test, merged_subs, {})
                    )
                if merged_packs:
                    out.update(_settle_packs(
                        merged_packs, model, lin,
                        deadline=None if budget is None
                        else time.monotonic() + budget,
                    ))
            return out, list(steps)

        blown = False
        merged: dict[Any, dict] = {}
        steps: list = []
        merged_runs_pre = len({r.run for r in group})
        # Span capture window: everything the cohort records between
        # mark and the capture below ships back to each member request
        # (stamped with ITS trace context) so the submitting run's
        # trace shows the daemon-side work.  The single worker thread
        # serializes cohorts, so the global window is cohort-exact.
        mark = telemetry.event_mark()
        t_check = time.monotonic()
        with telemetry.span(
            "checkerd.cohort",
            runs=merged_runs_pre, requests=len(group),
            keys=sum(r.n_keys for r in group),
        ):
            if budget is not None and budget <= 0:
                blown = True
            elif budget is not None:
                got = _timeout(budget * 1000.0, run_cohort,
                               default=_BLOWN)
                if got is _BLOWN:
                    blown = True
                else:
                    merged, steps = got
            else:
                merged, steps = run_cohort()
        check_s = time.monotonic() - t_check
        cohort_spans = telemetry.events_between(mark)
        # A long-lived daemon must not saturate the trace-event cap:
        # each cohort's events are shipped then dropped.
        telemetry.trim_events(mark)
        if blown:
            flight.note("checkerd-budget-exceeded",
                        budget_s=budget,
                        runs=[r.run for r in group])
            flight.dump("checkerd-budget-exceeded")
            if telemetry.enabled():
                telemetry.count("checkerd.budget-exceeded")

        unknown = {
            "valid": "unknown",
            "error": f"checkerd: {budget} s request budget exhausted; "
                     f"cohort abandoned (checker_budget semantics)",
        }
        merged_runs = len({r.run for r in group})
        cohort_keys = sum(r.n_keys for r in group)
        for r in group:
            krs = []
            for i in range(r.n_keys):
                kr = merged.get((r.ticket, i))
                krs.append(dict(unknown) if kr is None and blown
                           else kr if kr is not None
                           else {"valid": "unknown",
                                 "error": "checkerd: key missing from "
                                          "cohort result"})
            meta = {
                "ticket": r.ticket,
                "merged-runs": merged_runs,
                "cohort-requests": len(group),
                "cohort-keys": cohort_keys,
                "queue-wait-s": round(r.started_t - r.submitted_t, 4),
                "check-s": round(check_s, 4),
            }
            if cohort_spans:
                # Each request gets its own stamped copy: the spans
                # carry the SUBMITTER's trace id / parent span, so the
                # client adopts them straight into its trace and
                # trace_merge.py nests them under its analyze span.
                spans = []
                for ev in cohort_spans:
                    e = dict(ev)
                    attrs = dict(e.get("attrs") or {})
                    if r.trace:
                        if r.trace.get("trace-id"):
                            attrs["trace_id"] = r.trace["trace-id"]
                        if r.trace.get("parent-span"):
                            attrs["parent_span"] = r.trace["parent-span"]
                    if attrs:
                        e["attrs"] = attrs
                    spans.append(e)
                meta["spans"] = spans
                meta["pid"] = os.getpid()
            if blown:
                meta["budget-exceeded"] = True
            if steps:
                meta["degradations"] = steps
            # Death-state summary for remote forensics: which keys went
            # bad and how, without the client digging through every
            # key-result.  The full certificates / deepest configs ride
            # in krs themselves, so client-side dossiers are built from
            # the same bytes an in-process check would have produced.
            bad = {
                str(i): {
                    "valid": kr.get("valid"),
                    "algorithm": kr.get("algorithm"),
                    "reason": kr.get("unknown-reason") or kr.get("error"),
                }
                for i, kr in enumerate(krs)
                if isinstance(kr, dict)
                and kr.get("valid") in (False, "unknown")
            }
            if bad:
                meta["forensics"] = {
                    "bad-keys": bad, "count": len(bad),
                }
            r.result = {
                "valid": merge_valid(k.get("valid") for k in krs)
                if krs else True,
                "key-results": krs,
                "checkerd": meta,
            }


def _settle_packs(
    packs: dict[Any, Any], model: Any, lin: Any,
    deadline: Optional[float],
) -> dict[Any, dict]:
    """The settling ladder for wire-packed submissions, which skip
    re-encoding: cohort-wide stream witness, then per-pack settle memo,
    refutation screen, and exact CPU engine.  (No batched-BFS tier:
    packed submissions are the bulk-transport path and the stream +
    screen + memo trio decides the common families; survivors go
    straight to the exact engine, still sound.)"""
    from ..checker.refute import check_refute
    from ..ops.wgl_stream import check_wgl_witness_stream
    from ..parallel import independent as pind

    # Compiled-plan route: the same stream / memo / decide-mode screen
    # / exact pipeline as a pass DAG (jepsen_tpu/plan/), with the
    # daemon's persistent plan memo in front when --plan-cache is set.
    from ..plan import enabled as _plan_enabled

    if _plan_enabled():
        try:
            from ..plan.compiler import run_packs

            return run_packs(packs, model, lin, deadline)
        except Exception:  # noqa: BLE001 — legacy ladder is the net
            telemetry.count("wgl.plan.fallback")
            import logging

            logging.getLogger(__name__).warning(
                "plan executor failed; using the legacy packs ladder",
                exc_info=True,
            )

    pm = model.packed()

    def left() -> Optional[float]:
        if deadline is None:
            return None
        return max(1.0, deadline - time.monotonic())

    out: dict[Any, dict] = {}
    live = []
    for k, p in packs.items():
        if p.n == 0:
            out[k] = {"valid": True, "algorithm": "empty"}
        else:
            live.append(k)
    if not live:
        return out
    if "stream" in overload.dropped_passes():
        # Brownout level 1+: the witness beam is the first optional
        # tier to go — it only ever proves keys early, so skipping it
        # routes work to the sound exact tiers below.
        telemetry.count("checkerd.overload.brownout-skip-stream")
        stream_v = [None] * len(live)
    else:
        try:
            stream_v = check_wgl_witness_stream(
                [packs[k] for k in live], pm, time_limit_s=left(),
            )
        except Exception:  # noqa: BLE001 — sound fallback below
            stream_v = [None] * len(live)
    rest = []
    for k, v in zip(live, stream_v):
        if v is True:
            out[k] = {
                "valid": True,
                "algorithm": "wgl-tpu-stream",
                "configs-explored": int(packs[k].n_ok),
            }
        else:
            rest.append(k)
    for k in rest:
        p = packs[k]
        digest = pind._settle_digest(p, pm)
        hit = pind._memo_get(digest)
        if hit is not None:
            hit["memo-hit"] = True
            out[k] = hit
            continue
        ref = None
        try:
            b = left()
            ref = check_refute(
                p, pm, time_limit_s=30.0 if b is None else min(b, 30.0),
            )
        except Exception:  # noqa: BLE001 — screens may not veto
            ref = None
        if ref is not None:
            res, engine = ref, "refute-screen"
        else:
            res, engine = lin._cpu_exact(p, pm, "auto", time_limit_s=left())
        r: dict[str, Any] = {
            "valid": res.valid,
            "algorithm": engine,
            "configs-explored": int(res.configs_explored),
        }
        if res.valid == "unknown" and res.reason:
            r["reason"] = res.reason
        pind._memo_put(digest, r)
        out[k] = r
    return out


def _device_info() -> dict:
    """Platform + count of the devices this daemon owns; never raises
    (stats must work even mid-backend-initialization)."""
    try:
        import jax

        devs = jax.devices()
        return {"count": len(devs), "platform": devs[0].platform}
    except Exception as e:  # noqa: BLE001
        return {"count": 0, "platform": None, "error": repr(e)}
