"""The checkerd client: CheckerdClient (wire) and RemoteChecker (Checker).

RemoteChecker is the drop-in: it wraps an IndependentChecker-over-
Linearizable (or a bare Linearizable), performs the per-key split
client-side — KV payloads don't survive JSON, and keys never need to
cross the wire anyway (protocol.py) — ships op dicts to the daemon, and
reassembles a result shaped exactly like the in-process checker's.  Any
transport failure, unknown-model refusal, or client-side poll timeout
falls back to in-process checking (counted as `checkerd.fallback`), so
pointing a run at a dead daemon costs one connect timeout, never the
verdict.

Budget semantics: the run's `checker_budget` rides the SUBMIT frame and
is enforced server-side per request; RemoteChecker declares
`supervises_children` so check_safe doesn't start a racing client-side
watchdog that would expire first (network overhead) and discard the
server's richer answer.  On fallback the budget applies in-process as
usual.
"""

from __future__ import annotations

import logging
import socket
import time
from typing import Any, Optional

from .. import telemetry
from ..checker.core import Checker, check_safe, merge_valid
from . import overload
from .protocol import (
    F_CHUNK,
    F_COMMIT,
    F_ERROR,
    F_PACKED,
    F_PENDING,
    F_POLL,
    F_RESULT,
    F_SHED,
    F_STATS,
    F_STATS_REPLY,
    F_SUBMIT,
    F_TICKET,
    ProtocolError,
    connect,
    model_to_spec,
    pack_key_frame,
    read_frame,
    write_frame,
)

log = logging.getLogger(__name__)

#: Ops per CHUNK frame (the store's chunk size; one frame stays small
#: enough to stream while a 16k-op key still ships in one piece).
CHUNK_OPS = 16384

#: Poll cadence while waiting on a verdict.
POLL_INTERVAL_S = 0.05

#: Client-side wait ceiling when neither a checker budget nor a time
#: limit bounds the request.
DEFAULT_DEADLINE_S = 3600.0

#: Ceiling on how long a client sleeps honoring a SHED's RETRY-AFTER
#: before moving on (next sibling / in-process fallback); a saturated
#: daemon can ask for patience, not captivity.
MAX_SHED_WAIT_S = 5.0


class RemoteUnavailable(Exception):
    """The daemon can't serve this request: unreachable, refused the
    model, protocol failure, or client-side deadline.  Triggers the
    in-process fallback."""


class ShedByServer(RemoteUnavailable):
    """The admission plane refused the COMMIT with a structured
    RETRY-AFTER (F_SHED) — an honest overload signal, not a failure.
    Subclasses RemoteUnavailable so unaware callers still fall back
    in-process; aware callers honor `retry_after_s` first."""

    def __init__(self, payload: dict):
        self.shed = overload.OverloadShed.from_payload(payload or {})
        super().__init__(
            f"shed by daemon ({self.shed.reason}); retry after "
            f"{self.shed.retry_after_s:.2f}s"
        )

    @property
    def retry_after_s(self) -> float:
        return self.shed.retry_after_s


class CheckerdClient:
    """One connection to a checkerd daemon."""

    def __init__(self, addr: str, *, connect_timeout: float = 3.0,
                 io_timeout: float = 60.0):
        self.addr = addr
        try:
            self.sock = connect(addr, timeout=connect_timeout)
        except OSError as e:
            raise RemoteUnavailable(
                f"checkerd at {addr}: {e}"
            ) from e
        self.sock.settimeout(io_timeout)
        self.rf = self.sock.makefile("rb")
        self.wf = self.sock.makefile("wb")

    def close(self) -> None:
        for f in (self.rf, self.wf, self.sock):
            try:
                f.close()
            except OSError:
                pass

    def __enter__(self) -> "CheckerdClient":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # -- wire helpers -------------------------------------------------------

    def _send(self, ftype: int, payload: Any) -> None:
        try:
            write_frame(self.wf, ftype, payload)
        except OSError as e:
            raise RemoteUnavailable(f"send failed: {e}") from e

    def _recv(self) -> tuple[int, Any]:
        try:
            self.wf.flush()
            fr = read_frame(self.rf)
        except (OSError, ProtocolError, socket.timeout) as e:
            raise RemoteUnavailable(f"recv failed: {e}") from e
        if fr is None:
            raise RemoteUnavailable("daemon closed the connection")
        if fr[0] == F_ERROR:
            raise RemoteUnavailable(
                f"daemon error: {fr[1].get('error')}"
            )
        if fr[0] == F_SHED:
            telemetry.count("checkerd.shed-received")
            raise ShedByServer(fr[1])
        return fr

    # -- API ----------------------------------------------------------------

    def submit_ops(
        self,
        run: str,
        model_spec: dict,
        subs_ops: list[list[dict]],
        *,
        algorithm: str = "wgl-tpu",
        budget_s: Optional[float] = None,
        time_limit_s: Optional[float] = None,
        trace: Optional[dict] = None,
        tenant: Optional[str] = None,
        deadline_s: Optional[float] = None,
    ) -> str:
        """Submits per-key op-dict lists (submit order = reply order);
        returns the poll ticket.  `trace` is the submitting run's
        telemetry.trace_context(); daemon-side spans for this request
        are stamped with it so they nest under the run's analyze span.
        `deadline_s` is the client's total patience: a daemon that
        predicts it can't answer in time sheds at COMMIT (ShedByServer)
        instead of wasting both sides' budget."""
        self._send(F_SUBMIT, {
            "run": run,
            "model": model_spec,
            "algorithm": algorithm,
            "n-keys": len(subs_ops),
            "packed": False,
            "budget-s": budget_s,
            "time-limit-s": time_limit_s,
            "tenant": tenant,
            "deadline-s": deadline_s,
            "trace": trace,
        })
        for i, ops in enumerate(subs_ops):
            for lo in range(0, len(ops), CHUNK_OPS) or (0,):
                self._send(F_CHUNK, {
                    "key": i, "ops": ops[lo:lo + CHUNK_OPS],
                })
        self._send(F_COMMIT, {})
        ftype, payload = self._recv()
        if ftype != F_TICKET:
            raise RemoteUnavailable(f"expected TICKET, got {ftype}")
        return payload["ticket"]

    def submit_packed(
        self,
        run: str,
        model_spec: dict,
        packs: list,
        *,
        algorithm: str = "wgl-tpu",
        budget_s: Optional[float] = None,
        time_limit_s: Optional[float] = None,
        trace: Optional[dict] = None,
        tenant: Optional[str] = None,
        deadline_s: Optional[float] = None,
    ) -> str:
        """Submits already-packed columnar histories (history/packed.py
        PackedOps) as binary frames — the bulk-transport path."""
        from ..history.packed import packed_to_bytes

        self._send(F_SUBMIT, {
            "run": run,
            "model": model_spec,
            "algorithm": algorithm,
            "n-keys": len(packs),
            "packed": True,
            "budget-s": budget_s,
            "time-limit-s": time_limit_s,
            "tenant": tenant,
            "deadline-s": deadline_s,
            "trace": trace,
        })
        for i, p in enumerate(packs):
            self._send(F_PACKED, pack_key_frame(i, packed_to_bytes(p)))
        self._send(F_COMMIT, {})
        ftype, payload = self._recv()
        if ftype != F_TICKET:
            raise RemoteUnavailable(f"expected TICKET, got {ftype}")
        return payload["ticket"]

    def poll(self, ticket: str) -> tuple[int, dict]:
        self._send(F_POLL, {"ticket": ticket})
        return self._recv()

    def wait(
        self,
        ticket: str,
        *,
        deadline_s: Optional[float] = None,
        interval_s: float = POLL_INTERVAL_S,
    ) -> dict:
        """Polls until RESULT; RemoteUnavailable past the deadline."""
        t0 = time.monotonic()
        while True:
            ftype, payload = self.poll(ticket)
            if ftype == F_RESULT:
                return payload
            if ftype != F_PENDING:
                raise RemoteUnavailable(
                    f"expected PENDING/RESULT, got {ftype}"
                )
            if (deadline_s is not None
                    and time.monotonic() - t0 > deadline_s):
                raise RemoteUnavailable(
                    f"no verdict for ticket {ticket} within "
                    f"{deadline_s} s"
                )
            time.sleep(interval_s)

    def stats(self) -> dict:
        self._send(F_STATS, {})
        ftype, payload = self._recv()
        if ftype != F_STATS_REPLY:
            raise RemoteUnavailable(f"expected STATS_REPLY, got {ftype}")
        return payload


def fetch_stats(addr: str, *, timeout: float = 2.0) -> dict:
    """One-shot fleet stats (the /fleet page's data source)."""
    with CheckerdClient(addr, connect_timeout=timeout,
                        io_timeout=timeout) as c:
        return c.stats()


class RemoteChecker(Checker):
    """Routes a linearizable check through a checkerd daemon.

    `base` is the checker a plain run would use: an IndependentChecker
    whose base is Linearizable (per-key mode) or a bare Linearizable
    (whole-history mode).  Anything the daemon can't serve — and any
    transport failure — checks in-process via `base` instead.
    """

    #: The daemon applies the checker budget per request; check_safe
    #: must not race a local watchdog against it (Compose-style
    #: exemption, checker/core.py).
    supervises_children = True

    def __init__(
        self,
        base: Checker,
        addr: str,
        *,
        run_id: Optional[str] = None,
        fallback: bool = True,
        connect_timeout: float = 3.0,
        tenant: Optional[str] = None,
    ):
        self.base = base
        #: Admission identity for the daemon's weighted fair queue;
        #: None lets the daemon fall back to the run name.
        self.tenant = tenant
        #: Comma-separated addresses are a failover chain: a dead
        #: daemon's ticket is retried against the next sibling (full
        #: re-submission from the client's own copy of the ops) before
        #: the in-process fallback.  A federation router counts as one
        #: address — it fails over internally with its journaled bytes.
        self.addrs = [a.strip() for a in addr.split(",") if a.strip()]
        self.addr = self.addrs[0] if self.addrs else addr
        self.run_id = run_id
        self.fallback = fallback
        self.connect_timeout = connect_timeout

    # -- checker plumbing ---------------------------------------------------

    def _lin(self):
        from ..checker.linearizable import Linearizable
        from ..parallel.independent import IndependentChecker

        if isinstance(self.base, IndependentChecker) and \
                isinstance(self.base.base, Linearizable):
            return self.base.base, True
        if isinstance(self.base, Linearizable):
            return self.base, False
        return None, False

    def check(self, test: dict, history, opts: dict) -> dict:
        try:
            return self._remote(test, history, opts)
        except RemoteUnavailable as e:
            telemetry.count("checkerd.fallback")
            log.warning(
                "checkerd unavailable (%s); checking in-process", e,
            )
            if not self.fallback:
                return {"valid": "unknown",
                        "error": f"checkerd unavailable: {e}"}
            # In-process fallback keeps full checker_budget semantics:
            # base doesn't supervise children, so check_safe arms the
            # local watchdog from test["checker_budget"].
            res = check_safe(self.base, test, history, opts)
            if isinstance(res, dict):
                res.setdefault("checkerd", {})["fallback"] = str(e)
            return res

    def _remote(self, test: dict, history, opts: dict) -> dict:
        from ..parallel.independent import subhistories

        lin, independent = self._lin()
        if lin is None:
            raise RemoteUnavailable(
                f"base checker {type(self.base).__name__} has no "
                f"remote form"
            )
        model = lin.model or test.get("model")
        if model is None:
            raise RemoteUnavailable("no model to describe to the daemon")
        spec = model_to_spec(model)
        if spec is None:
            raise RemoteUnavailable(
                f"model {type(model).__name__} has no wire spec"
            )

        if independent:
            subs = subhistories(history)
            keys = list(subs)
            if not keys:
                return {"valid": True, "results": {}, "key-count": 0}
            subs_ops = [[o.to_dict() for o in subs[k]] for k in keys]
        else:
            keys = [None]
            subs_ops = [[o.to_dict() for o in history]]

        budget = (test or {}).get("checker_budget")
        run = self.run_id or str((test or {}).get("name") or "run")
        deadline = DEFAULT_DEADLINE_S
        if budget is not None or lin.time_limit_s is not None:
            deadline = (budget or 0.0) + (lin.time_limit_s or 0.0) + 300.0

        # The failover chain: each address gets a full attempt (its own
        # streamed ticket if one exists, else a fresh submission).  A
        # daemon dying mid-wait surfaces as RemoteUnavailable and the
        # next sibling re-checks the same ops — per-key verdicts are
        # deterministic, so the retried result matches what the dead
        # daemon would have returned.  Each address sits behind a
        # process-wide circuit breaker (overload.breaker_for): an
        # address that keeps failing is skipped for a jittered backoff
        # window instead of eating a connect timeout per run, and a
        # half-open probe re-admits it.  An honest SHED is not a
        # failure — the breaker stays closed, the client sleeps out the
        # (bounded) RETRY-AFTER once, retries, then moves on.
        last: Optional[RemoteUnavailable] = None
        payload = None
        served_by = self.addr
        for n, addr in enumerate(self.addrs):
            if n:
                telemetry.count("checkerd.failover")
                log.warning(
                    "checkerd %s failed (%s); retrying ticket against "
                    "sibling %s", self.addrs[n - 1], last, addr,
                )
            br = overload.breaker_for(addr)
            if not br.allow():
                telemetry.count("checkerd.breaker-skip")
                last = RemoteUnavailable(
                    f"circuit open for {addr} (recent failures)"
                )
                continue
            for shed_try in (0, 1):
                try:
                    payload = self._attempt(
                        addr, test, keys, subs_ops, spec, lin,
                        independent, run, budget, deadline,
                    )
                    br.record_success()
                    served_by = addr
                    break
                except ShedByServer as e:
                    # The daemon answered — it's healthy, just full.
                    br.record_success()
                    telemetry.count("checkerd.client-shed")
                    last = e
                    if shed_try == 0:
                        wait = min(e.retry_after_s, MAX_SHED_WAIT_S)
                        log.info(
                            "checkerd %s shed the request; honoring "
                            "retry-after %.2fs", addr, wait,
                        )
                        time.sleep(wait)
                except RemoteUnavailable as e:
                    br.record_failure()
                    last = e
                    break
            if payload is not None:
                break
        if payload is None:
            raise last or RemoteUnavailable("no checkerd address")

        krs = payload.get("key-results") or []
        if len(krs) != len(keys):
            raise RemoteUnavailable(
                f"daemon returned {len(krs)} key results for "
                f"{len(keys)} keys"
            )
        meta = payload.get("checkerd") or {}
        meta["addr"] = served_by
        # Adopt the daemon's spans for this request into our trace, so
        # the run's trace.json (and tools/trace_merge.py) shows the
        # cohort/settle work under the daemon's own pid.
        telemetry.adopt_remote_events(meta.get("spans"),
                                      pid=meta.get("pid"))
        if not independent:
            res = dict(krs[0])
            res["checkerd"] = meta
            return res
        results = dict(zip(keys, krs))
        failures = [k for k, r in results.items()
                    if r.get("valid") is False]
        return {
            "valid": merge_valid(r.get("valid") for r in krs),
            "key-count": len(keys),
            "failures": failures[:32],
            "failure-count": len(failures),
            "results": results,
            "checkerd": meta,
        }

    def _attempt(
        self,
        addr: str,
        test: dict,
        keys: list,
        subs_ops: list,
        spec: dict,
        lin: Any,
        independent: bool,
        run: str,
        budget: Optional[float],
        deadline: float,
    ) -> dict:
        """One full submit-and-wait against one address."""
        # A streaming session may have shipped this exact submission
        # CHUNK-by-CHUNK while the run was still going (streaming/
        # remote.py); consume its ticket instead of re-uploading.
        ticket = None
        sess = (test or {}).get("streaming-session")
        if independent and sess is not None:
            ticket = sess.remote_ticket(
                addr, keys, spec, lin.algorithm, budget,
                lin.time_limit_s,
            )
            if ticket is not None:
                telemetry.count("checkerd.stream-ticket")
                log.info("consuming streamed checkerd ticket %s", ticket)

        with CheckerdClient(
            addr, connect_timeout=self.connect_timeout,
        ) as c:
            if ticket is None:
                ticket = c.submit_ops(
                    run, spec, subs_ops,
                    algorithm=lin.algorithm,
                    budget_s=budget,
                    time_limit_s=lin.time_limit_s,
                    trace=telemetry.trace_context()
                    if telemetry.enabled() else None,
                    tenant=self.tenant,
                    # The client's own wait ceiling rides the SUBMIT so
                    # the daemon can shed at COMMIT instead of checking
                    # into a void nobody is still polling.
                    deadline_s=deadline,
                )
            return c.wait(ticket, deadline_s=deadline)


def wrap_remote(checker: Checker, addr: str, *,
                run_id: Optional[str] = None) -> Checker:
    """Routes every remotable piece of a checker tree through the
    daemon: Linearizable and IndependentChecker-over-Linearizable become
    RemoteChecker; Compose children are wrapped recursively; anything
    else is returned unchanged (stats/set checkers are cheap host work
    not worth a round trip)."""
    from ..checker.core import Compose
    from ..checker.linearizable import Linearizable
    from ..parallel.independent import IndependentChecker

    if isinstance(checker, RemoteChecker):
        return checker
    if isinstance(checker, Compose):
        return Compose({
            name: wrap_remote(c, addr, run_id=run_id)
            for name, c in checker.checkers.items()
        })
    if isinstance(checker, Linearizable):
        return RemoteChecker(checker, addr, run_id=run_id)
    if isinstance(checker, IndependentChecker) and \
            isinstance(checker.base, Linearizable):
        return RemoteChecker(checker, addr, run_id=run_id)
    return checker
