"""`python -m jepsen_tpu.checkerd` — run the checker daemon."""

import sys

from .server import main

if __name__ == "__main__":
    sys.exit(main())
