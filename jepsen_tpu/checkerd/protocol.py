"""The checkerd wire protocol: store-format frames over a TCP stream.

A frame is exactly a store block (store/format.py):

    [u32 payload-len][u32 crc32][u8 type][payload]

JSON payloads go through the store's `_encode` (same coercions, same
bytes as at rest); packed-history payloads are raw binary
(history/packed.py `packed_to_bytes`), CRC-checked like everything
else.  Frame types live above the store's block-type range so a frame
can never be mistaken for an on-disk block.

A submit conversation, client -> server:

    SUBMIT {"run", "model", "algorithm", "n-keys", "packed",
            "budget-s", "time-limit-s",
            "tenant": name | null, "deadline-s": s | null,
            "trace": {"trace-id", "parent-span"} | null}
    CHUNK  {"key": i, "ops": [op dicts...]}        (repeatable, ops mode)
    PACKED <u32 key-index><packed bytes>           (one per key, packed mode)
    COMMIT {}
                                  <- TICKET {"ticket", "queue-depth"}
                                   | SHED {"shed": true, "reason",
                                           "retry-after-s", "tenant",
                                           "estimate-s"}
    POLL {"ticket"}               <- PENDING {"state", "queue-depth"}
                                   | RESULT {"valid", "key-results",
                                             "checkerd": {...meta}}
                                   | ERROR {"error"}
    STATS {}                      <- STATS_REPLY {...fleet stats...}
    RESUME {"session"}            <- RESUME_OK {"received": {i: count},
                                                "n-keys"}
                                   | ERROR {"error"}

A streamed SUBMIT may carry a client-minted "session" token; the
server then parks the half-uploaded submission when the connection
dies, and a RESUME on a fresh connection re-attaches to it, replying
with the per-key op counts it already holds (the stable bound) so the
client re-sends only the tail.

The optional SUBMIT "trace" field is the submitting run's telemetry
trace context (telemetry.trace_context()).  The daemon stamps the
cohort's span events with it and ships them back in RESULT meta
("spans" + "pid"), so the run's trace — and tools/trace_merge.py —
can nest daemon-side work under the run's analyze span.  Absent or
null means the submitter doesn't want span transport (older clients
remain wire-compatible: unknown SUBMIT fields are ignored).

Key identity never crosses the wire: the client submits subhistories in
key order and the server replies with `key-results` in the same order,
so arbitrary (unhashable-after-JSON, tuple, KV-subclass) keys stay a
client-side concern.
"""

from __future__ import annotations

import json
import socket
import struct
import zlib
from typing import Any, BinaryIO, Optional

from ..store.format import _HEADER, frame, raw_frame

# Frame types (store blocks use 1..5; leave headroom).
F_SUBMIT = 16
F_CHUNK = 17
F_PACKED = 18  # binary payload: u32 key-index + packed_to_bytes()
F_COMMIT = 19
F_TICKET = 20
F_POLL = 21
F_PENDING = 22
F_RESULT = 23
F_STATS = 24
F_STATS_REPLY = 25
F_ERROR = 26
#: Streaming reconnect (streaming/remote.py): a client whose upload
#: connection died re-attaches to its parked server-side submission and
#: learns the daemon's stable bound — per-key received-op counts — so
#: it re-sends only the tail past the last FULL stable block instead of
#: re-uploading or falling back to a whole-history recheck.
F_RESUME = 27       # {"session": token}
F_RESUME_OK = 28    # {"received": {key-index: op-count}, "n-keys": n}
#: Overload control (checkerd/overload.py): a COMMIT the admission
#: plane refuses answers with a structured RETRY-AFTER instead of a
#: TICKET — deadline-aware shedding and weighted admission are honest,
#: machine-readable refusals, never ERROR-shaped silence.
F_SHED = 29         # {"shed": true, "reason", "retry-after-s", ...}

#: Frame types whose payload is raw bytes, not JSON.
BINARY_TYPES = frozenset({F_PACKED})

#: Upper bound on a single frame's payload: big enough for a 16k-op
#: CHUNK or a multi-million-row packed tensor, small enough that a
#: corrupt length field can't balloon one read into the whole heap.
MAX_FRAME = 1 << 28

_KEY_PREFIX = struct.Struct("<I")


class ProtocolError(Exception):
    """A malformed, truncated, or CRC-failing frame."""


def write_frame(wf: BinaryIO, ftype: int, payload: Any) -> None:
    """Writes one frame; `payload` is bytes for BINARY_TYPES, else any
    JSON-able value."""
    if ftype in BINARY_TYPES:
        wf.write(raw_frame(ftype, payload))
    else:
        wf.write(frame(ftype, payload))


def read_frame(rf: BinaryIO) -> Optional[tuple[int, Any]]:
    """Reads one frame -> (type, payload), or None on clean EOF.  A
    partial header/payload or CRC mismatch raises ProtocolError: on a
    stream (unlike a crash-torn file tail) a bad frame means the
    conversation is unrecoverable."""
    header = _read_exactly(rf, _HEADER.size, eof_ok=True)
    if header is None:
        return None
    length, crc, ftype = _HEADER.unpack(header)
    if length > MAX_FRAME:
        raise ProtocolError(f"frame length {length} exceeds {MAX_FRAME}")
    data = _read_exactly(rf, length, eof_ok=False)
    if zlib.crc32(data) != crc:
        raise ProtocolError(f"frame type {ftype}: CRC mismatch")
    if ftype in BINARY_TYPES:
        return ftype, data
    try:
        return ftype, json.loads(data)
    except ValueError as e:
        raise ProtocolError(f"frame type {ftype}: bad JSON: {e}") from e


def _read_exactly(rf: BinaryIO, n: int, *, eof_ok: bool) -> Optional[bytes]:
    chunks: list[bytes] = []
    got = 0
    while got < n:
        b = rf.read(n - got)
        if not b:
            if eof_ok and got == 0:
                return None
            raise ProtocolError(f"truncated frame: {got}/{n} bytes")
        chunks.append(b)
        got += len(b)
    return b"".join(chunks) if len(chunks) != 1 else chunks[0]


def pack_key_frame(key_index: int, packed_bytes: bytes) -> bytes:
    """Payload for an F_PACKED frame: the key's submit-order index
    prefixed to the packed-column tensor bytes."""
    return _KEY_PREFIX.pack(key_index) + packed_bytes


def unpack_key_frame(data: bytes) -> tuple[int, bytes]:
    if len(data) < _KEY_PREFIX.size:
        raise ProtocolError("packed frame shorter than its key prefix")
    (i,) = _KEY_PREFIX.unpack_from(data)
    return i, data[_KEY_PREFIX.size:]


def parse_addr(addr: str) -> tuple[str, int]:
    """"host:port" -> (host, port); bare "port" means localhost."""
    if ":" in addr:
        host, _, port = addr.rpartition(":")
        return host or "127.0.0.1", int(port)
    return "127.0.0.1", int(addr)


# ---------------------------------------------------------------------------
# Model specs: the models a verdict can be computed for server-side.
# ---------------------------------------------------------------------------

def model_to_spec(model: Any) -> Optional[dict]:
    """A JSON description of a model instance, or None when the model
    (or its initial value) has no wire form — the client then checks
    in-process.  Only covers the stock models; a custom Model subclass
    carries arbitrary Python the daemon can't be asked to import."""
    from ..models.collections import FIFOQueue, SetModel, UnorderedQueue
    from ..models.mutex import Mutex
    from ..models.registers import CASRegister, MultiRegister, Register

    spec: Optional[dict] = None
    # CASRegister subclasses Register: exact type checks, most specific
    # first, so a further subclass (unknown step semantics) is refused.
    t = type(model)
    if t is CASRegister:
        spec = {"type": "cas-register", "value": model.value}
    elif t is Register:
        spec = {"type": "register", "value": model.value}
    elif t is MultiRegister:
        spec = {
            "type": "multi-register",
            "values": sorted(model.values.items(), key=repr),
        }
    elif t is Mutex:
        spec = {"type": "mutex", "locked": bool(model.locked)}
    elif t is FIFOQueue:
        spec = {"type": "fifo-queue", "items": list(model.items)}
    elif t is UnorderedQueue:
        spec = {"type": "unordered-queue", "pending": list(model.pending)}
    elif t is SetModel:
        spec = {"type": "set", "items": sorted(model.items, key=repr)}
    if spec is None:
        return None
    try:
        # Strict round-trip probe: _encode's repr() safety net would
        # silently change values like object() — refuse instead.
        json.dumps(spec)
    except (TypeError, ValueError):
        return None
    return spec


def model_from_spec(spec: dict) -> Any:
    """Rebuilds a model instance from its wire spec.  Raises ValueError
    for unknown types, which the server surfaces as an ERROR frame (the
    client falls back in-process)."""
    from ..models.collections import FIFOQueue, SetModel, UnorderedQueue
    from ..models.mutex import Mutex
    from ..models.registers import CASRegister, MultiRegister, Register

    t = spec.get("type")
    if t == "cas-register":
        return CASRegister(spec.get("value"))
    if t == "register":
        return Register(spec.get("value"))
    if t == "multi-register":
        return MultiRegister({k: v for k, v in spec.get("values") or []})
    if t == "mutex":
        return Mutex(bool(spec.get("locked")))
    if t == "fifo-queue":
        return FIFOQueue(tuple(spec.get("items") or ()))
    if t == "unordered-queue":
        return UnorderedQueue(tuple(spec.get("pending") or ()))
    if t == "set":
        return SetModel(frozenset(spec.get("items") or ()))
    raise ValueError(f"unknown model spec type {t!r}")


def canonical_spec(spec: dict) -> str:
    """Deterministic string form of a model spec — the model-cache and
    cohort-compatibility key."""
    return json.dumps(spec, sort_keys=True, separators=(",", ":"))


def connect(addr: str, timeout: float = 3.0) -> socket.socket:
    host, port = parse_addr(addr)
    s = socket.create_connection((host, port), timeout=timeout)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s
