"""The checkerd federation router: one address, N daemons, zero lost
verdicts.

`jepsen checkerd-router` is a front-end speaking the same framed wire
protocol as a daemon, so clients point at it unchanged (``--remote
router-host:port``).  It adds what a single daemon can't:

* **Placement.**  Each submission is buffered whole, then placed on the
  daemon with the lowest queue depth (the PR 9 /metrics gauge, sampled
  via STATS with a short cache) minus a model-cache-affinity bonus: the
  daemon that last checked this canonical model spec has the model
  instance, settle memo, and XLA executables warm, so equal depths
  break toward it.
* **Failover.**  The router keeps every ticket's raw frames (and, with
  ``--queue``, journals them in checkerd.queue framing).  When a poll
  finds the owning daemon dead — connection refused, reset, or an
  "unknown ticket" from a daemon that restarted without its own journal
  — the buffered frames replay byte-identically against a sibling,
  counted as `router.failover`.  Per-key verdicts are deterministic, so
  the retried result is what the dead daemon would have said.
* **Health.**  Daemons run the same suspect→quarantined→readmitted
  state machine as test nodes (control/health.py): data-path failures
  are passive signals, a stats round-trip is the active probe, and
  quarantined daemons drop out of placement until probes readmit them.
* **Admission.**  ``--tenant-quota`` bounds each tenant's in-flight
  tickets and ``--max-inflight`` bounds the fleet total; a submission
  over either limit gets one deterministic SHED frame with a
  structured RETRY-AFTER at SUBMIT time instead of unbounded router
  memory.  A daemon's own SHED (deadline-aware load shedding,
  checkerd/overload.py) is tried against a sibling first and forwarded
  to the client only when every healthy daemon sheds.  The client
  honors the retry-after (checkerd/client.py ShedByServer) or falls
  back in-process when allowed.

The router submits to daemons on short-lived connections and polls on
fresh ones, so its forwarded SUBMITs carry ``"detached": true`` —
opting out of the daemon's abandon-on-disconnect (server.py), whose
purpose is reclaiming cohort keys from *clients* that vanish.
"""

from __future__ import annotations

import logging
import socketserver
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from .. import telemetry
from ..control.health import monitor_for_targets
from . import ROUTER_PORT, overload
from .client import CheckerdClient, RemoteUnavailable, ShedByServer, fetch_stats
from .journal import QueueJournal, frames_from_record, frames_to_record
from .protocol import (
    F_CHUNK,
    F_COMMIT,
    F_ERROR,
    F_PACKED,
    F_PENDING,
    F_POLL,
    F_RESULT,
    F_RESUME,
    F_RESUME_OK,
    F_SHED,
    F_STATS,
    F_STATS_REPLY,
    F_SUBMIT,
    F_TICKET,
    ProtocolError,
    canonical_spec,
    read_frame,
    write_frame,
)
from .server import MAX_PARKED_SESSIONS

log = logging.getLogger(__name__)

#: How long a daemon's STATS snapshot stays fresh for placement; past
#: this a placement decision re-polls.  Short enough that queue-depth
#: routing tracks bursts, long enough that a poll storm doesn't turn
#: into a stats storm.
STATS_CACHE_S = 1.0

#: Queue-depth equivalent of having the model already cached: the
#: affinity daemon wins placement unless a sibling is this much idler.
AFFINITY_BONUS = 1.0

#: Placement score for a daemon whose stats can't be fetched — still a
#: candidate (the submit attempt is the real probe) but last resort.
UNREACHABLE_DEPTH = 1e6

#: Finished router tickets answer late polls this long (mirrors the
#: scheduler's result TTL), then fall to the lazy sweep.
DONE_TTL_S = 600.0

#: Hard cap on remembered tickets; beyond it the oldest finished ones
#: are dropped (pending tickets are bounded by admission control).
MAX_TICKETS = 4096


class _RSub:
    """One buffered SUBMIT conversation: the raw frames (for replay to
    any daemon) plus the per-key op counts that answer a RESUME."""

    def __init__(self, meta: Any):
        if not isinstance(meta, dict):
            raise ProtocolError("SUBMIT payload must be a dict")
        self.meta = meta
        self.streaming = bool(meta.get("streaming"))
        self.session = meta.get("session") if self.streaming else None
        self.run = str(meta.get("run") or "anonymous")
        self.tenant = str(meta.get("tenant") or self.run)
        self.spec_key = canonical_spec(meta.get("model") or {})
        self.n_keys = int(meta.get("n-keys") or 0)
        self.counts: dict[int, int] = {}
        self.frames: list = [(F_SUBMIT, meta)]

    def add(self, ftype: int, payload: Any) -> None:
        self.frames.append((ftype, payload))
        if ftype == F_CHUNK and isinstance(payload, dict):
            try:
                i = int(payload.get("key"))
            except (TypeError, ValueError) as e:
                raise ProtocolError("CHUNK without a key index") from e
            ops = payload.get("ops")
            self.counts[i] = self.counts.get(i, 0) + (
                len(ops) if isinstance(ops, list) else 0
            )
            if self.streaming and i >= self.n_keys:
                self.n_keys = i + 1

    def received(self) -> dict[str, int]:
        return {str(i): c for i, c in self.counts.items()}


class _TicketRec:
    """One router ticket: where it lives now and the frames to move it."""

    __slots__ = ("ticket", "run", "tenant", "spec_key", "frames", "addr",
                 "daemon_ticket", "result", "done_t", "busy")

    def __init__(self, ticket: str, run: str, spec_key: str, frames: list,
                 tenant: Optional[str] = None):
        self.ticket = ticket
        self.run = run
        self.tenant = tenant or run
        self.spec_key = spec_key
        self.frames = frames
        self.addr: Optional[str] = None
        self.daemon_ticket: Optional[str] = None
        self.result: Optional[dict] = None
        self.done_t: Optional[float] = None
        #: A failover in progress; concurrent pollers wait it out.
        self.busy = False


class Router:
    """Federation state: daemon registry, health, tickets, admission."""

    def __init__(
        self,
        daemons: list[str],
        *,
        tenant_quota: Optional[int] = None,
        max_inflight: Optional[int] = None,
        probe_interval_s: float = 2.0,
        stats_timeout_s: float = 2.0,
        io_timeout_s: float = 60.0,
        queue_path: Optional[str] = None,
    ):
        self.daemons = list(dict.fromkeys(daemons))
        if not self.daemons:
            raise ValueError("router needs at least one daemon address")
        self.tenant_quota = tenant_quota
        self.max_inflight = max_inflight
        self.stats_timeout_s = stats_timeout_s
        self.io_timeout_s = io_timeout_s
        self._lock = threading.Lock()
        self._tickets: dict[str, _TicketRec] = {}  # guarded-by: self._lock
        #: canonical model spec -> the daemon that last checked it (its
        #: model/settle/XLA caches are warm for that spec).
        self._affinity: dict[str, str] = {}  # guarded-by: self._lock
        self._stats_cache: dict[str, tuple[float, dict]] = {}  # guarded-by: self._lock
        self.sessions: dict = {}  # guarded-by: self.sessions_lock
        self.sessions_lock = threading.Lock()
        self.n_submits = 0
        self.n_results = 0
        self.n_failovers = 0
        self.n_rejected = 0
        self.n_replayed = 0
        self.shed_by_tenant: dict[str, int] = {}  # guarded-by: self._lock
        self._t0 = time.monotonic()
        self.health = monitor_for_targets(
            self.daemons, self._probe, interval_s=probe_interval_s,
        )
        self.journal = QueueJournal(queue_path) if queue_path else None
        if self.journal is not None:
            self._restore()

    def stop(self) -> None:
        self.health.stop()
        if self.journal is not None:
            self.journal.close()

    # -- daemon health + stats ----------------------------------------------

    def _probe(self, test: dict, addr: Any) -> bool:
        """The active health probe: a STATS round-trip (doubles as a
        placement-gauge refresh when it succeeds)."""
        try:
            st = fetch_stats(str(addr), timeout=self.stats_timeout_s)
        except (RemoteUnavailable, OSError):
            return False
        with self._lock:
            self._stats_cache[str(addr)] = (time.monotonic(), st)
        return True

    def _stats_for(self, addr: str) -> Optional[dict]:
        """The daemon's stats, at most STATS_CACHE_S old; a failed
        fetch is a passive health signal and returns None."""
        now = time.monotonic()
        with self._lock:
            ent = self._stats_cache.get(addr)
        if ent is not None and now - ent[0] <= STATS_CACHE_S:
            return ent[1]
        try:
            st = fetch_stats(addr, timeout=self.stats_timeout_s)
        except RemoteUnavailable:
            self.health.signal(addr, "stats-failed")
            return None
        with self._lock:
            self._stats_cache[addr] = (time.monotonic(), st)
        return st

    # -- placement -----------------------------------------------------------

    def _place(self, spec_key: str, exclude: set) -> str:
        """The daemon to submit to: lowest queue depth wins, the spec's
        affinity daemon gets a bonus, quarantined daemons sit out."""
        cands = [d for d in self.daemons
                 if d not in exclude and not self.health.is_quarantined(d)]
        if not cands:
            raise RemoteUnavailable(
                "no healthy checkerd daemon (all quarantined or already "
                "tried)"
            )
        with self._lock:
            aff = self._affinity.get(spec_key)

        def score(d: str) -> tuple[float, int]:
            st = self._stats_for(d)
            depth = (float(st.get("queue-depth") or 0)
                     if st is not None else UNREACHABLE_DEPTH)
            if d == aff:
                depth -= AFFINITY_BONUS
            return depth, self.daemons.index(d)

        return min(cands, key=score)

    def _replay_to(self, addr: str, frames: list) -> tuple[str, int]:
        """Plays a buffered submission against one daemon; returns its
        (ticket, queue-depth).  A daemon SHED surfaces as ShedByServer
        (raised by CheckerdClient._recv), any other failure as
        RemoteUnavailable."""
        with CheckerdClient(
            addr, connect_timeout=self.stats_timeout_s,
            io_timeout=self.io_timeout_s,
        ) as c:
            for ftype, payload in frames:
                c._send(ftype, payload)
            ftype, payload = c._recv()
            if ftype != F_TICKET:
                raise RemoteUnavailable(f"expected TICKET, got {ftype}")
            return str(payload["ticket"]), int(payload.get("queue-depth") or 0)

    def _send_to_daemon(self, rec: _TicketRec, exclude: set) -> int:
        """Places and submits `rec`, walking siblings on failure;
        returns the accepting daemon's queue depth.  A shedding daemon
        is healthy-but-full: it is skipped without a health signal, and
        when EVERY candidate sheds the last ShedByServer propagates so
        the handler forwards the structured refusal to the client."""
        tried = set(exclude)
        last: Optional[RemoteUnavailable] = None
        while True:
            try:
                addr = self._place(rec.spec_key, tried)
            except RemoteUnavailable as e:
                raise last or e
            try:
                daemon_ticket, depth = self._replay_to(addr, rec.frames)
            except ShedByServer as e:
                last = e
                tried.add(addr)
                telemetry.count("router.daemon-shed")
                log.info("daemon %s shed ticket %s (%s); trying a "
                         "sibling", addr, rec.ticket, e)
                continue
            except RemoteUnavailable as e:
                last = e
                tried.add(addr)
                self.health.signal(addr, "submit-failed")
                telemetry.count("router.daemon-unreachable")
                log.warning("daemon %s refused ticket %s (%s); trying a "
                            "sibling", addr, rec.ticket, e)
                continue
            with self._lock:
                rec.addr = addr
                rec.daemon_ticket = daemon_ticket
                self._affinity[rec.spec_key] = addr
            return depth

    # -- admission -----------------------------------------------------------

    def admission_reason(self, tenant: str) -> Optional[str]:
        """Why this tenant's submission must be shed, or None.
        Deterministic: both bounds are router-local counts, no daemon
        round-trip involved."""
        with self._lock:
            pending = sum(1 for r in self._tickets.values()
                          if r.result is None)
            if (self.max_inflight is not None
                    and pending >= self.max_inflight):
                return (f"fleet at its --max-inflight bound "
                        f"({pending}/{self.max_inflight} tickets in flight)")
            if self.tenant_quota is not None:
                mine = sum(1 for r in self._tickets.values()
                           if r.result is None and r.tenant == tenant)
                if mine >= self.tenant_quota:
                    return (f"tenant {tenant!r} at its --tenant-quota "
                            f"({mine}/{self.tenant_quota} tickets in flight)")
        return None

    def record_shed(self, tenant: str) -> None:
        with self._lock:
            self.n_rejected += 1
            self.shed_by_tenant[tenant] = \
                self.shed_by_tenant.get(tenant, 0) + 1

    # -- the ticket lifecycle ------------------------------------------------

    def submit(self, rsub: _RSub, commit_payload: dict) -> tuple[str, int]:
        """Places one buffered submission; returns (router ticket,
        accepting daemon's queue depth).  Raises RemoteUnavailable when
        no daemon accepts it (the client falls back)."""
        meta = dict(rsub.meta)
        meta["detached"] = True
        frames = [(F_SUBMIT, meta)] + rsub.frames[1:]
        frames.append((F_COMMIT, commit_payload))
        ticket = "r" + uuid.uuid4().hex[:11]
        rec = _TicketRec(ticket, rsub.run, rsub.spec_key, frames,
                         tenant=rsub.tenant)
        self._sweep()
        # Daemon first, then journal, then the TICKET reply: a crash
        # between submit and journal means the client never saw a
        # ticket (safe); a journaled ticket is always pollable after a
        # router restart.
        depth = self._send_to_daemon(rec, exclude=set())
        if self.journal is not None:
            self.journal.record_submit(ticket, {
                "run": rec.run,
                "tenant": rec.tenant,
                "spec-key": rec.spec_key,
                "frames": frames_to_record(frames),
            })
        with self._lock:
            self._tickets[ticket] = rec
            self.n_submits += 1
        telemetry.count("router.submit")
        return ticket, depth

    def poll(self, ticket: str) -> tuple[int, dict]:
        """One poll -> (frame type, payload) for the client."""
        with self._lock:
            rec = self._tickets.get(ticket)
        if rec is None:
            return F_ERROR, {"error": f"unknown ticket {ticket!r}"}
        if rec.result is not None:
            return F_RESULT, rec.result
        if rec.addr is None:
            # Restored from the journal: the first poll re-places it.
            return self._failover(rec, "restored from journal")
        try:
            with CheckerdClient(
                rec.addr, connect_timeout=self.stats_timeout_s,
                io_timeout=self.io_timeout_s,
            ) as c:
                ftype, payload = c.poll(str(rec.daemon_ticket))
        except RemoteUnavailable as e:
            # Dead daemon OR one that restarted without its journal and
            # forgot the ticket — either way the buffered frames move.
            return self._failover(rec, str(e))
        if ftype == F_RESULT:
            self._finish(rec, payload)
            return F_RESULT, payload
        if ftype == F_PENDING:
            return F_PENDING, payload
        return F_ERROR, {"error": f"daemon sent frame type {ftype}"}

    def _finish(self, rec: _TicketRec, result: dict) -> None:
        # Journal before the reply leaves (replay-idempotence rule, as
        # in the scheduler): any verdict a client observed survives a
        # router restart.
        if self.journal is not None:
            self.journal.record_result(rec.ticket, result)
        with self._lock:
            if rec.result is None:
                rec.result = result
                rec.done_t = time.monotonic()
                self.n_results += 1
        telemetry.count("router.result")

    def _failover(self, rec: _TicketRec, why: str) -> tuple[int, dict]:
        with self._lock:
            if rec.busy:
                # Another poller is already moving this ticket.
                return F_PENDING, {"state": "failover", "queue-depth": 0}
            rec.busy = True
            dead = rec.addr
        if dead is not None:
            with self._lock:
                self.n_failovers += 1
            telemetry.count("router.failover")
            self.health.signal(dead, "poll-failed")
            log.warning("daemon %s lost ticket %s (%s); failing over",
                        dead, rec.ticket, why)
        # The client already holds a TICKET for this submission, so the
        # replay must not be deadline-shed by the sibling — an acked
        # ticket yields a verdict, full stop.  Strip the deadline from
        # the replayed SUBMIT (mirrors the scheduler's own journal
        # replay, which never re-sheds).
        if rec.frames and rec.frames[0][0] == F_SUBMIT \
                and isinstance(rec.frames[0][1], dict) \
                and rec.frames[0][1].get("deadline-s") is not None:
            meta = dict(rec.frames[0][1])
            meta.pop("deadline-s", None)
            rec.frames = [(F_SUBMIT, meta)] + rec.frames[1:]
        try:
            depth = self._send_to_daemon(
                rec, exclude={dead} if dead is not None else set(),
            )
        except RemoteUnavailable as e:
            with self._lock:
                rec.busy = False
            return F_ERROR, {
                "error": f"checkerd federation: ticket {rec.ticket} lost "
                         f"({why}) and no healthy sibling accepted it: {e}",
            }
        with self._lock:
            rec.busy = False
        return F_PENDING, {"state": "failover", "queue-depth": depth}

    def _sweep(self) -> None:
        """Lazy eviction at submit time: expired finished tickets go,
        then the oldest finished ones if the map is still over cap."""
        now = time.monotonic()
        with self._lock:
            for t in [t for t, r in self._tickets.items()
                      if r.done_t is not None
                      and now - r.done_t > DONE_TTL_S]:
                del self._tickets[t]
            if len(self._tickets) > MAX_TICKETS:
                done = sorted(
                    (t for t, r in self._tickets.items()
                     if r.result is not None),
                    key=lambda t: self._tickets[t].done_t or 0.0,
                )
                for t in done[:len(self._tickets) - MAX_TICKETS]:
                    del self._tickets[t]

    def _restore(self) -> None:
        """Re-arms journaled tickets after a router restart: finished
        ones answer late polls with the exact journaled bytes,
        unfinished ones re-place on first poll."""
        for ticket, res in self.journal.finished().items():
            rec = _TicketRec(ticket, "replayed", "", [])
            rec.result = res
            rec.done_t = time.monotonic()
            self._tickets[ticket] = rec
        for ticket, sr in self.journal.unfinished().items():
            try:
                frames = frames_from_record(sr.get("frames") or [])
            except (TypeError, ValueError, KeyError) as e:
                telemetry.count("router.replay-failed")
                log.warning("journaled ticket %s unreplayable: %r",
                            ticket, e)
                continue
            rec = _TicketRec(
                ticket, str(sr.get("run") or "anonymous"),
                str(sr.get("spec-key") or ""), frames,
                tenant=str(sr.get("tenant") or "") or None,
            )
            self._tickets[ticket] = rec
            self.n_replayed += 1
            telemetry.count("router.replayed")
        if self._tickets:
            log.info("router journal restored %d finished + %d pending "
                     "tickets", self.n_results, self.n_replayed)

    # -- sessions (streaming resume through the router) ----------------------

    def park(self, rsub: _RSub) -> None:
        with self.sessions_lock:
            self.sessions[rsub.session] = rsub
            while len(self.sessions) > MAX_PARKED_SESSIONS:
                del self.sessions[next(iter(self.sessions))]

    def parked(self, token: Any) -> Optional[_RSub]:
        with self.sessions_lock:
            return self.sessions.get(token)

    def unpark(self, rsub: _RSub) -> None:
        if rsub.session is not None:
            with self.sessions_lock:
                self.sessions.pop(rsub.session, None)

    # -- reporting -----------------------------------------------------------

    def stats(self) -> dict:
        daemons: dict[str, Any] = {}
        for d in self.daemons:
            st = self._stats_for(d)
            daemons[d] = st if st is not None else {"unreachable": True}
        health = self.health.summary()
        depth = sum(
            int(st.get("queue-depth") or 0) for st in daemons.values()
            if isinstance(st, dict) and not st.get("unreachable")
        )
        with self._lock:
            pending = sum(1 for r in self._tickets.values()
                          if r.result is None)
            return {
                "router": True,
                "uptime-s": round(time.monotonic() - self._t0, 3),
                "daemons": daemons,
                "health": health,
                "queue-depth": depth,
                "inflight": pending,
                "submits": self.n_submits,
                "results": self.n_results,
                "failovers": self.n_failovers,
                "admission-rejected": self.n_rejected,
                "shed-by-tenant": dict(self.shed_by_tenant),
                "replayed": self.n_replayed,
                "affinity": dict(self._affinity),
                "quota": {"tenant-quota": self.tenant_quota,
                          "max-inflight": self.max_inflight},
                "queue-journal": (self.journal.stats()
                                  if self.journal is not None else None),
            }


class _RouterHandler(socketserver.StreamRequestHandler):
    """Same conversation shape as the daemon's handler; SUBMIT..COMMIT
    is buffered in the router, placed at COMMIT."""

    def handle(self) -> None:
        router: Router = self.server.router  # type: ignore[attr-defined]
        rsub: Optional[_RSub] = None
        #: A rejected submission's CHUNK/PACKED/COMMIT frames are
        #: swallowed so the single admission ERROR is the only reply.
        rejecting = False
        while True:
            try:
                fr = read_frame(self.rfile)
            except ProtocolError as e:
                self._reply(F_ERROR, {"error": str(e)})
                return
            if fr is None:
                return
            ftype, payload = fr
            try:
                if ftype == F_SUBMIT:
                    rejecting = False
                    meta = payload if isinstance(payload, dict) else {}
                    tenant = str(meta.get("tenant")
                                 or meta.get("run") or "anonymous")
                    reason = router.admission_reason(tenant)
                    if reason is not None:
                        rejecting = True
                        rsub = None
                        router.record_shed(tenant)
                        telemetry.count("router.admission-rejected")
                        log.warning("admission shed for %s: %s",
                                    tenant, reason)
                        # Structured soft refusal, not an ERROR: the
                        # quota is a congestion signal the client can
                        # wait out, not a protocol failure.
                        self._reply(F_SHED, overload.OverloadShed(
                            reason=f"router admission: {reason}",
                            retry_after_s=1.0,
                            tenant=tenant,
                        ).payload())
                    else:
                        rsub = _RSub(payload)
                        if rsub.session:
                            router.park(rsub)
                elif ftype in (F_CHUNK, F_PACKED):
                    if rejecting:
                        continue
                    if rsub is None:
                        raise ProtocolError("CHUNK/PACKED before SUBMIT")
                    rsub.add(ftype, payload)
                elif ftype == F_RESUME:
                    token = (payload.get("session")
                             if isinstance(payload, dict) else None)
                    parked = router.parked(token)
                    if parked is None:
                        self._reply(F_ERROR, {
                            "error": f"unknown session {token!r} (router "
                                     "restarted or session evicted)",
                        })
                    else:
                        rejecting = False
                        rsub = parked
                        self._reply(F_RESUME_OK, {
                            "received": rsub.received(),
                            "n-keys": rsub.n_keys,
                        })
                elif ftype == F_COMMIT:
                    if rejecting:
                        rejecting = False
                        continue
                    if rsub is None:
                        raise ProtocolError("COMMIT before SUBMIT")
                    s, rsub = rsub, None
                    router.unpark(s)
                    try:
                        ticket, depth = router.submit(
                            s, payload if isinstance(payload, dict)
                            else {},
                        )
                    except ShedByServer as e:
                        # Every healthy daemon shed it: forward the
                        # structured refusal so the client can honor
                        # the retry-after.
                        router.record_shed(s.tenant)
                        telemetry.count("router.shed-forwarded")
                        self._reply(F_SHED, e.shed.payload())
                        continue
                    self._reply(F_TICKET, {
                        "ticket": ticket, "queue-depth": depth,
                    })
                elif ftype == F_POLL:
                    rtype, rp = router.poll(str(payload.get("ticket")))
                    self._reply(rtype, rp)
                elif ftype == F_STATS:
                    self._reply(F_STATS_REPLY, router.stats())
                else:
                    self._reply(F_ERROR, {
                        "error": f"unexpected frame type {ftype}",
                    })
            except (ProtocolError, ValueError, RemoteUnavailable) as e:
                rsub = None
                self._reply(F_ERROR, {"error": str(e)})
            except BrokenPipeError:
                return
            except Exception as e:  # noqa: BLE001 — per-connection wall
                log.exception("router handler error")
                rsub = None
                self._reply(F_ERROR, {"error": repr(e)})

    def _reply(self, ftype: int, payload: Any) -> None:
        try:
            write_frame(self.wfile, ftype, payload)
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            pass


class RouterServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True
    router: Router


def make_router_server(
    host: str = "127.0.0.1",
    port: int = ROUTER_PORT,
    **router_kw: Any,
) -> RouterServer:
    daemons = router_kw.pop("daemons")
    srv = RouterServer((host, port), _RouterHandler)
    srv.router = Router(daemons, **router_kw)
    return srv


class _RouterMetricsHandler(BaseHTTPRequestHandler):
    """Prometheus scrape surface for the federation: fleet-wide queue
    depth, in-flight tickets, failover/admission counters, and how many
    daemons placement can currently use."""

    router: Router  # bound by make_router_metrics_server

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        if self.path.split("?", 1)[0] not in ("/metrics", "/metrics/"):
            self.send_error(404)
            return
        try:
            st = self.router.stats()
            healthy = sum(
                1 for h in (st.get("health") or {}).values()
                if h.get("state") != "quarantined"
            )
            extra = {
                "router.daemons": len(self.router.daemons),
                "router.daemons-healthy": healthy,
                "router.queue-depth": st.get("queue-depth", 0),
                "router.inflight": st.get("inflight", 0),
                "router.submits": st.get("submits", 0),
                "router.results": st.get("results", 0),
                "router.failovers": st.get("failovers", 0),
                "router.admission-rejected": st.get(
                    "admission-rejected", 0),
                "router.replayed": st.get("replayed", 0),
            }
            extra_labeled = {
                "router.shed": (
                    "tenant", st.get("shed-by-tenant") or {}, "counter"),
            }
            body = telemetry.prometheus_text(
                extra_gauges=extra, extra_labeled=extra_labeled,
            ).encode()
        except Exception as e:  # noqa: BLE001 — a scrape must not 500
            body = f"# metrics error: {e!r}\n".encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt: str, *args: Any) -> None:
        log.debug("router metrics: " + fmt, *args)


def make_router_metrics_server(
    router: Router, host: str = "127.0.0.1", port: int = 0,
) -> ThreadingHTTPServer:
    handler = type("BoundRouterMetrics", (_RouterMetricsHandler,),
                   {"router": router})
    return ThreadingHTTPServer((host, port), handler)


def serve(
    host: str = "0.0.0.0",
    port: int = ROUTER_PORT,
    *,
    daemons: list[str],
    tenant_quota: Optional[int] = None,
    max_inflight: Optional[int] = None,
    probe_interval_s: float = 2.0,
    metrics_port: Optional[int] = None,
    queue_path: Optional[str] = None,
) -> None:
    """Blocking entrypoint for `jepsen checkerd-router`."""
    srv = make_router_server(
        host, port,
        daemons=daemons,
        tenant_quota=tenant_quota,
        max_inflight=max_inflight,
        probe_interval_s=probe_interval_s,
        queue_path=queue_path,
    )
    bound_port = srv.server_address[1]
    msrv = None
    if metrics_port is not None:
        msrv = make_router_metrics_server(srv.router, host, metrics_port)
        threading.Thread(
            target=msrv.serve_forever, name="router-metrics", daemon=True,
        ).start()
        log.info("checkerd-router /metrics on %s:%d",
                 host, msrv.server_address[1])
    log.info("checkerd-router serving on %s:%d -> %s",
             host, bound_port, ", ".join(daemons))
    print(f"checkerd-router serving on {host}:{bound_port} "
          f"-> {', '.join(daemons)}")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.shutdown()
        srv.server_close()
        srv.router.stop()
        if msrv is not None:
            msrv.shutdown()
            msrv.server_close()


def main(argv: Optional[list[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="jepsen-tpu-checkerd-router",
        description="federation front-end for a fleet of checkerd "
                    "daemons: queue-depth placement, failover, "
                    "per-tenant admission",
    )
    p.add_argument("--host", "-b", default="0.0.0.0")
    p.add_argument("--port", "-p", type=int, default=ROUTER_PORT)
    p.add_argument(
        "--daemon", "-d", action="append", default=[], metavar="ADDR",
        help="a daemon address (host:port); repeatable",
    )
    p.add_argument(
        "--tenant-quota", type=int, default=None, metavar="N",
        help="max in-flight tickets per run name; over it SUBMIT gets "
        "a deterministic checkerd.admission-rejected error",
    )
    p.add_argument(
        "--max-inflight", type=int, default=None, metavar="N",
        help="max in-flight tickets fleet-wide (bounded queue depth)",
    )
    p.add_argument(
        "--probe-interval", type=float, default=2.0, metavar="S",
        help="health-probe cadence for suspect/quarantined daemons",
    )
    p.add_argument(
        "--metrics-port", type=int, default=ROUTER_PORT + 1, metavar="P",
        help="HTTP port for the Prometheus /metrics scrape surface "
        f"(default {ROUTER_PORT + 1}; -1 disables)",
    )
    p.add_argument(
        "--queue", default=None, metavar="PATH",
        help="crash-safe ticket journal (checkerd.queue framing): a "
        "restarted router keeps answering polls for every journaled "
        "ticket",
    )
    opts = p.parse_args(argv)
    if not opts.daemon:
        p.error("at least one --daemon ADDR is required")
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s [%(threadName)s] "
               "%(name)s: %(message)s",
    )
    serve(
        opts.host, opts.port,
        daemons=opts.daemon,
        tenant_quota=opts.tenant_quota,
        max_inflight=opts.max_inflight,
        probe_interval_s=opts.probe_interval,
        metrics_port=None if opts.metrics_port < 0 else opts.metrics_port,
        queue_path=opts.queue,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
