"""The checkerd overload control plane: degrade gracefully, never lie.

Four mechanisms, composed through the fleet stack (scheduler, server,
router, client, streaming feed):

* **Weighted fair queueing** (`FairQueue`).  Deficit round-robin over
  per-tenant queues replaces the scheduler's FIFO list: each tenant
  accumulates `quantum * weight` key-credits per scheduling round and
  a request is served once its tenant's credit covers its key count.
  A whale tenant can saturate its own queue without starving a light
  tenant — the light tenant's head is always at most one round away.
  Quota becomes a *weight*, not a cliff: an over-subscribed tenant
  waits proportionally longer instead of being hard-rejected.

* **Deadline-aware load shedding** (`LatencyEstimator` +
  `OverloadShed`).  A SUBMIT may carry a client ``deadline-s``; at
  admission the scheduler estimates queue wait plus predicted verdict
  latency — the plan cost model's per-pass regressors
  (plan/costmodel.py) when trained, the observed per-key verdict rate
  otherwise — and sheds *early* with a structured RETRY-AFTER reply
  (F_SHED) instead of burning device time on a verdict nobody will
  read.  A shed is an honest, machine-readable refusal: the client
  retries after the hint or falls back in-process, never hangs.

* **Brownout ladder** (`BrownoutController`).  Under sustained
  pressure (queue-depth / RSS samples breaching their thresholds for
  `up_count` consecutive samples) the fleet drops optional plan passes
  first — level 1 skips the stream-witness beam, level 2 also drops
  the batched-BFS accelerator and doubles the shed estimate — before
  anything degrades to honest-unknown.  Transitions are recorded
  through the PR 2 degradation machinery (ops/degrade.record), so
  brownouts appear in flight recorder dumps and result metadata like
  every other degradation.  All tiers that remain are sound: the
  witness beam and BFS accelerator only ever *prove* keys early;
  dropping them routes work to the exact CPU tiers.

* **Client-side circuit breakers** (`CircuitBreaker`).  RemoteChecker
  and RemoteFeed consult a per-address breaker before dialing: after
  `failure_threshold` consecutive transport failures the breaker opens
  and holds requests off the address for a jittered exponential
  backoff, then half-opens to let one probe through.  A browning-out
  fleet is not hammered by retry storms.

Counters/gauges live in the ``checkerd.overload.*`` namespace
(declared in analysis/rules/protocol.py; doc/counters.md).
"""

from __future__ import annotations

import math
import os
import random
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from .. import telemetry

# ---------------------------------------------------------------------------
# Shed replies
# ---------------------------------------------------------------------------


class OverloadShed(Exception):
    """An admission refused by the overload control plane.  Carries the
    structured F_SHED payload; the server/router turns it into a frame,
    the client into a bounded retry or an in-process fallback — never a
    silent loss."""

    def __init__(self, reason: str, retry_after_s: float, *,
                 tenant: Optional[str] = None,
                 estimate_s: Optional[float] = None,
                 deadline_s: Optional[float] = None):
        super().__init__(reason)
        self.reason = reason
        self.retry_after_s = max(0.1, float(retry_after_s))
        self.tenant = tenant
        self.estimate_s = estimate_s
        self.deadline_s = deadline_s

    def payload(self) -> dict:
        out: dict[str, Any] = {
            "shed": True,
            "reason": self.reason,
            "retry-after-s": round(self.retry_after_s, 3),
        }
        if self.tenant is not None:
            out["tenant"] = self.tenant
        if self.estimate_s is not None:
            out["estimate-s"] = round(self.estimate_s, 3)
        if self.deadline_s is not None:
            out["deadline-s"] = self.deadline_s
        return out

    @classmethod
    def from_payload(cls, payload: dict) -> "OverloadShed":
        # Wire-facing: a malformed shed from a buggy peer degrades to
        # the default backoff, never a client-side parse crash.
        try:
            retry = float(payload.get("retry-after-s") or 1.0)
        except (TypeError, ValueError):
            retry = 1.0
        return cls(
            str(payload.get("reason") or "shed"),
            retry,
            tenant=payload.get("tenant"),
            estimate_s=payload.get("estimate-s"),
            deadline_s=payload.get("deadline-s"),
        )


# ---------------------------------------------------------------------------
# Weighted fair queueing (deficit round-robin)
# ---------------------------------------------------------------------------

#: Key-credits granted per tenant per scheduling round.  One round
#: serves roughly one quantum-sized request per active tenant, so the
#: worst-case wait for a light tenant's head is one cohort per heavier
#: tenant — the starvation bound tests/test_overload.py pins down.
DEFAULT_QUANTUM = 8.0


def request_cost(req: Any) -> float:
    """The DRR cost of serving one request, in key-units."""
    return max(1.0, float(getattr(req, "n_keys", 0) or 0))


class FairQueue:
    """Deficit round-robin over per-tenant FIFO queues.

    NOT thread-safe: the scheduler calls it under its own condition
    lock, like the list it replaces.  Requests need ``tenant``,
    ``compat``, ``n_keys`` and ``abandoned`` attributes.

    Deficits only accumulate while a tenant has queued work and reset
    to zero when its queue drains (standard DRR: no banking credit
    while idle).  Requests that join another tenant's cohort via the
    compat merge (`take_compat`) are charged too — merged service is
    cheap for the fleet but still counts as service for fairness.
    """

    def __init__(self, *, quantum: float = DEFAULT_QUANTUM,
                 weights: Optional[dict[str, float]] = None):
        self.quantum = float(quantum)
        self.weights: dict[str, float] = dict(weights or {})
        self._queues: dict[str, deque] = {}
        self._deficit: dict[str, float] = {}
        self._ring: list[str] = []
        self._cursor = 0

    def weight(self, tenant: str) -> float:
        w = self.weights.get(tenant, 1.0)
        return w if w > 0 else 1.0

    def set_weight(self, tenant: str, weight: float) -> None:
        self.weights[tenant] = float(weight)

    def __len__(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def push(self, req: Any) -> None:
        t = str(getattr(req, "tenant", None) or "anonymous")
        q = self._queues.get(t)
        if q is None:
            q = self._queues[t] = deque()
            self._deficit.setdefault(t, 0.0)
            self._ring.append(t)
        q.append(req)

    def requests(self) -> list:
        """Snapshot of every queued request (stats/iteration)."""
        return [r for q in self._queues.values() for r in q]

    def _retire(self, tenant: str) -> None:
        """Drops a drained tenant from the ring, resetting its credit."""
        if not self._queues.get(tenant):
            self._queues.pop(tenant, None)
            self._deficit[tenant] = 0.0
            try:
                i = self._ring.index(tenant)
            except ValueError:
                return
            del self._ring[i]
            if i < self._cursor:
                self._cursor -= 1
            if self._ring:
                self._cursor %= len(self._ring)
            else:
                self._cursor = 0

    def drop_abandoned(self) -> list:
        """Removes and returns every abandoned request (the scheduler
        settles them as honest unknowns at the cohort boundary)."""
        condemned = []
        for t in list(self._queues):
            q = self._queues[t]
            keep = deque(r for r in q if not r.abandoned)
            condemned.extend(r for r in q if r.abandoned)
            self._queues[t] = keep
            self._retire(t)
        return condemned

    def next_head(self) -> Optional[Any]:
        """Pops the next request DRR order serves, advancing every
        active tenant's deficit by however many whole rounds the pick
        needs (equivalent to running the classic visit loop, but O(n)
        per pop instead of O(rounds * n))."""
        if not self._ring:
            return None
        n = len(self._ring)
        best: Optional[tuple[tuple[int, int], str]] = None
        for dist in range(n):
            t = self._ring[(self._cursor + dist) % n]
            head = self._queues[t][0]
            need = request_cost(head) - self._deficit[t]
            per_round = self.quantum * self.weight(t)
            rounds = 0 if need <= 0 else int(math.ceil(need / per_round))
            key = (rounds, dist)
            if best is None or key < best[0]:
                best = (key, t)
        (rounds, _dist), tenant = best
        if rounds:
            for t in self._ring:
                self._deficit[t] += rounds * self.quantum * self.weight(t)
        req = self._queues[tenant].popleft()
        self._deficit[tenant] -= request_cost(req)
        n = len(self._ring)
        self._cursor = (self._ring.index(tenant) + 1) % n
        self._retire(tenant)
        return req

    def take_compat(self, compat: Any) -> list:
        """Pops every queued request whose compat key matches —
        they ride the forming cohort for free fleet-wise, but each
        tenant is charged for its own keys."""
        taken = []
        for t in list(self._queues):
            q = self._queues[t]
            matched = [r for r in q if r.compat == compat]
            if not matched:
                continue
            self._queues[t] = deque(r for r in q if r.compat != compat)
            for r in matched:
                self._deficit[t] -= request_cost(r)
            taken.extend(matched)
            self._retire(t)
        return taken

    def snapshot(self) -> dict[str, dict]:
        """Per-tenant queue state for stats()/the /fleet panel."""
        return {
            t: {
                "queued": len(q),
                "queued-keys": int(sum(r.n_keys for r in q)),
                "deficit": round(self._deficit.get(t, 0.0), 3),
                "weight": self.weight(t),
            }
            for t, q in self._queues.items()
        }


# ---------------------------------------------------------------------------
# Per-tenant service accounting (queue-wait p95, served/shed counts)
# ---------------------------------------------------------------------------

_WAIT_WINDOW = 256


class TenantStats:
    """Rolling per-tenant service record.  Thread-safe (one lock; every
    call is O(1) except the p95 snapshot)."""

    def __init__(self, window: int = _WAIT_WINDOW):
        self._lock = threading.Lock()
        self._window = window
        self._waits: dict[str, deque] = {}
        self._served: dict[str, int] = {}
        self._shed: dict[str, int] = {}

    def observe_wait(self, tenant: str, wait_s: float) -> None:
        with self._lock:
            d = self._waits.get(tenant)
            if d is None:
                d = self._waits[tenant] = deque(maxlen=self._window)
            d.append(float(wait_s))
            self._served[tenant] = self._served.get(tenant, 0) + 1

    def record_shed(self, tenant: str) -> None:
        with self._lock:
            self._shed[tenant] = self._shed.get(tenant, 0) + 1

    def shed_counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._shed)

    def wait_p95(self, tenant: str) -> Optional[float]:
        with self._lock:
            d = self._waits.get(tenant)
            if not d:
                return None
            xs = sorted(d)
        return xs[min(len(xs) - 1, int(math.ceil(0.95 * len(xs))) - 1)]

    def snapshot(self) -> dict[str, dict]:
        with self._lock:
            tenants = set(self._waits) | set(self._served) | set(self._shed)
            out = {}
            for t in tenants:
                d = self._waits.get(t)
                xs = sorted(d) if d else []
                p95 = (xs[min(len(xs) - 1,
                              int(math.ceil(0.95 * len(xs))) - 1)]
                       if xs else None)
                out[t] = {
                    "served": self._served.get(t, 0),
                    "shed": self._shed.get(t, 0),
                    "queue-wait-p95-s": round(p95, 4)
                    if p95 is not None else None,
                }
            return out


# ---------------------------------------------------------------------------
# Deadline shedding: predicted verdict latency + queue wait
# ---------------------------------------------------------------------------

#: Heuristic per-key verdict cost before any observation or trained
#: model exists (conservative: a fresh daemon under-sheds rather than
#: over-sheds).
DEFAULT_PER_KEY_S = 0.05
DEFAULT_BASE_S = 0.2

#: Cost-model passes summed into a predicted verdict latency; the
#: subset that dominates a cohort's wall clock.
_PREDICT_PASSES = ("stream-witness", "refute-screen", "packs-exact",
                   "settle-exact")


class LatencyEstimator:
    """Predicted verdict latency for an incoming submission.

    Prefers the trained plan cost model (per-pass ridge regressors on
    log1p shape features — the learned-performance-model approach);
    falls back to the observed per-key verdict rate over a rolling
    window, then to a fixed heuristic.  Thread-safe.
    """

    def __init__(self, window: int = 128):
        self._lock = threading.Lock()
        self._obs: deque = deque(maxlen=window)  # (keys, check_s)

    def observe(self, keys: int, check_s: float) -> None:
        if keys <= 0 or check_s < 0:
            return
        with self._lock:
            self._obs.append((int(keys), float(check_s)))

    def _observed_per_key_s(self) -> Optional[float]:
        with self._lock:
            if not self._obs:
                return None
            total_k = sum(k for k, _ in self._obs)
            total_s = sum(s for _, s in self._obs)
        if total_k <= 0:
            return None
        return total_s / total_k

    def predict_s(self, n_keys: int, n_ops: int = 0) -> float:
        """Predicted check seconds for one submission."""
        n_keys = max(1, int(n_keys))
        try:
            from ..plan import costmodel

            m = costmodel.active_model()
        except Exception:  # noqa: BLE001 — estimation must never fail
            m = None
        if m is not None:
            feats = {"keys": n_keys, "ops": max(n_ops, n_keys)}
            total = 0.0
            covered = 0
            for p in _PREDICT_PASSES:
                y = m.predict_s(p, feats, {})
                if y is not None:
                    total += y
                    covered += 1
            if covered:
                telemetry.count("checkerd.overload.predict-model")
                return total
        per_key = self._observed_per_key_s()
        if per_key is not None:
            telemetry.count("checkerd.overload.predict-observed")
            return DEFAULT_BASE_S + per_key * n_keys
        telemetry.count("checkerd.overload.predict-heuristic")
        return DEFAULT_BASE_S + DEFAULT_PER_KEY_S * n_keys

    def queue_wait_s(self, queued_keys: int) -> float:
        """Estimated wait until a submission admitted *now* starts:
        the backlog's keys at the observed (or heuristic) rate."""
        if queued_keys <= 0:
            return 0.0
        per_key = self._observed_per_key_s()
        if per_key is None:
            per_key = DEFAULT_PER_KEY_S
        return per_key * queued_keys


# ---------------------------------------------------------------------------
# Brownout ladder
# ---------------------------------------------------------------------------

#: Env override for chaos/testing: force a brownout level (0..2)
#: regardless of samples.  Read on every sample so a restarted daemon
#: under test picks it up without code changes.  The value is either a
#: literal level or ``file:PATH`` — the level lives in PATH's contents
#: (missing/empty file = no force), so the self-chaos harness
#: (nemesis/selfchaos.py) can drive memory-pressure faults in a child
#: daemon it cannot re-env.
FORCE_ENV = "JEPSEN_BROWNOUT_FORCE"


def _env_indirect(value: Optional[str]) -> Optional[str]:
    """Resolves a fault-env value, following one ``file:PATH`` hop."""
    if not value:
        return None
    if value.startswith("file:"):
        try:
            with open(value[5:], "r", encoding="utf-8") as f:
                return f.read().strip() or None
        except OSError:
            return None
    return value

#: Optional pass families the ladder drops, by level.  Both only ever
#: prove keys early (witness/accelerator tiers); the exact tiers they
#: defer to are sound, so browning out trades latency, never truth.
LEVEL_DROPS = {1: ("stream",), 2: ("stream", "batched")}


class BrownoutController:
    """Hysteresis ladder over sustained pressure samples.

    ``sample(queue_depth, rss_mb)`` is called once per scheduler loop
    iteration.  Pressure at tier N means queue depth >= queue_high *
    2**(N-1) or RSS >= rss_high_mb * (1 + 0.25*(N-1)).  `up_count`
    consecutive samples at or above the next tier escalate one level;
    `down_count` consecutive samples below the current tier
    de-escalate.  Transitions are recorded via degrade.record (the PR 2
    machinery) and the current level is exported as the
    ``checkerd.overload.brownout-level`` gauge.
    """

    def __init__(self, *, queue_high: float = 32.0,
                 rss_high_mb: Optional[float] = 8192.0,
                 up_count: int = 3, down_count: int = 6,
                 max_level: int = 2):
        self.queue_high = float(queue_high)
        self.rss_high_mb = rss_high_mb
        self.up_count = max(1, int(up_count))
        self.down_count = max(1, int(down_count))
        self.max_level = int(max_level)
        self._lock = threading.Lock()
        self._level = 0
        self._above = 0
        self._below = 0
        self.transitions = 0

    @property
    def level(self) -> int:
        forced = _env_indirect(os.environ.get(FORCE_ENV))
        if forced:
            try:
                return max(0, min(self.max_level, int(forced)))
            except ValueError:
                pass
        with self._lock:
            return self._level

    def dropped_passes(self) -> tuple:
        """Plan pass ids the current level drops (plan/compiler.py
        consults this when building cohort/packs plans)."""
        return LEVEL_DROPS.get(self.level, ())

    def _pressure_tier(self, queue_depth: float,
                       rss_mb: Optional[float]) -> int:
        tier = 0
        for n in range(1, self.max_level + 1):
            hot = queue_depth >= self.queue_high * (2 ** (n - 1))
            if (not hot and rss_mb is not None
                    and self.rss_high_mb is not None):
                hot = rss_mb >= self.rss_high_mb * (1 + 0.25 * (n - 1))
            if hot:
                tier = n
        return tier

    def sample(self, queue_depth: float,
               rss_mb: Optional[float] = None) -> int:
        """Feeds one pressure sample; returns the (possibly new) level."""
        from ..ops import degrade

        tier = self._pressure_tier(queue_depth, rss_mb)
        with self._lock:
            level = self._level
            if tier > level:
                self._above += 1
                self._below = 0
                if self._above >= self.up_count:
                    self._level = min(level + 1, self.max_level)
                    self._above = 0
            elif tier < level:
                self._below += 1
                self._above = 0
                if self._below >= self.down_count:
                    self._level = max(level - 1, 0)
                    self._below = 0
            else:
                self._above = self._below = 0
            new = self._level
            changed = new != level
            if changed:
                self.transitions += 1
        if changed:
            action = (f"enter-level-{new}" if new > level
                      else f"exit-to-level-{new}")
            degrade.record("brownout", action)
            telemetry.count(f"checkerd.overload.brownout-{action}")
        telemetry.gauge("checkerd.overload.brownout-level", self.level)
        return self.level

    def shed_factor(self) -> float:
        """Multiplier on the shed estimate: a browning-out fleet sheds
        deadline'd work earlier (level 2 doubles the estimate)."""
        lvl = self.level
        return 1.0 if lvl < 2 else 2.0


#: Process-wide brownout controller — the scheduler samples it, the
#: plan compiler consults it (lazy import, no cycle), tests swap it.
_brownout = BrownoutController()
_brownout_lock = threading.Lock()


def brownout() -> BrownoutController:
    return _brownout


def set_brownout(ctrl: Optional[BrownoutController]) -> BrownoutController:
    """Installs a controller (None = a fresh default); returns it."""
    global _brownout
    with _brownout_lock:
        _brownout = ctrl if ctrl is not None else BrownoutController()
        return _brownout


def brownout_level() -> int:
    return _brownout.level


def dropped_passes() -> tuple:
    return _brownout.dropped_passes()


def process_rss_mb() -> Optional[float]:
    """Current RSS in MiB from /proc (Linux; None elsewhere) — the
    brownout ladder's memory gauge."""
    try:
        with open("/proc/self/statm", "rb") as f:
            fields = f.read().split()
        pages = int(fields[1])
        return pages * (os.sysconf("SC_PAGE_SIZE") / (1024.0 * 1024.0))
    except (OSError, IndexError, ValueError):
        return None


# ---------------------------------------------------------------------------
# Client-side circuit breakers
# ---------------------------------------------------------------------------

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half-open"


class CircuitBreaker:
    """Per-address circuit breaker with jittered exponential backoff.

    closed -> open after `failure_threshold` consecutive failures;
    open -> half-open once the backoff expires (one probe allowed);
    half-open -> closed on success, -> open (longer backoff) on
    failure.  `clock` and `rng` are injectable for deterministic tests.
    """

    def __init__(self, *, failure_threshold: int = 3,
                 base_backoff_s: float = 0.5,
                 max_backoff_s: float = 30.0,
                 jitter: float = 0.25,
                 clock: Callable[[], float] = time.monotonic,
                 rng: Callable[[], float] = random.random):
        self.failure_threshold = max(1, int(failure_threshold))
        self.base_backoff_s = float(base_backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.jitter = float(jitter)
        self._clock = clock
        self._rng = rng
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opens = 0
        self._open_until = 0.0
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            if (self._state == OPEN
                    and self._clock() >= self._open_until):
                return HALF_OPEN
            return self._state

    def _backoff_s(self) -> float:
        b = min(self.max_backoff_s,
                self.base_backoff_s * (2 ** max(0, self._opens - 1)))
        return b * (1.0 + self.jitter * (2.0 * self._rng() - 1.0))

    def allow(self) -> bool:
        """Whether a call may be attempted now.  In half-open exactly
        one caller gets True (the probe) until it reports back."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._clock() < self._open_until:
                return False
            if self._probing:
                return False
            self._state = HALF_OPEN
            self._probing = True
            return True

    def record_success(self) -> None:
        with self._lock:
            was = self._state
            self._state = CLOSED
            self._failures = 0
            self._opens = 0
            self._probing = False
        if was != CLOSED:
            telemetry.count("checkerd.overload.breaker-closed")

    def record_failure(self) -> None:
        with self._lock:
            self._probing = False
            if self._state == HALF_OPEN:
                self._opens += 1
                self._state = OPEN
                self._open_until = self._clock() + self._backoff_s()
                opened = True
            else:
                self._failures += 1
                if (self._state == CLOSED
                        and self._failures >= self.failure_threshold):
                    self._opens += 1
                    self._state = OPEN
                    self._open_until = self._clock() + self._backoff_s()
                    opened = True
                else:
                    opened = False
        if opened:
            telemetry.count("checkerd.overload.breaker-opened")

    def stats(self) -> dict:
        with self._lock:
            return {"state": self._state, "failures": self._failures,
                    "opens": self._opens}


_breakers: dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()


def breaker_for(addr: str) -> CircuitBreaker:
    """The process-wide breaker for one daemon/router address."""
    with _breakers_lock:
        b = _breakers.get(addr)
        if b is None:
            b = _breakers[addr] = CircuitBreaker()
        return b


def reset_breakers() -> None:
    with _breakers_lock:
        _breakers.clear()
