"""Crash-safe checkerd queue journal: zero in-flight verdicts lost.

`checkerd.queue` is an append-only journal in store/format.py framing
(`BLOCK_QUEUE` blocks, append + fsync per record, torn-tail truncation
free from BlockWriter — the same durability contract as the nemesis
fault ledger and the plan memo).  Three record kinds:

* ``submit``  — one accepted submission, written the moment the
  scheduler admits it (before the TICKET reply leaves the daemon).
  Carries everything needed to rebuild the Request after a crash:
  op dicts per key and packed tensors as base64, so a restarted daemon
  re-forms cohorts through the normal plan compiler and warm-starts
  from the plan/XLA caches.
* ``result``  — the finished verdict, journaled BEFORE the request is
  marked done (the replay-idempotence rule: a poll can only ever
  observe a RESULT that is already durable, so replaying the journal
  after a crash reproduces exactly the verdicts clients saw).
* ``abandon`` — a ticket cancelled because its submitting connection
  died mid-PENDING; replay must not resurrect it.

A ticket with a ``submit`` record but no ``result``/``abandon`` is
*unfinished*: the restarted daemon re-queues it under its original
ticket id so a reconnecting client's POLL keeps working.  Fresh
``result`` records survive restart too (late polls get the same bytes);
stale ones are dropped by compaction on open.

The federation router shares this journal class for its own in-flight
ticket store: `frames_to_record`/`frames_from_record` serialize raw
wire frames (PACKED payloads as base64) so a failed daemon's ticket can
be re-submitted to a sibling byte-identically.
"""

from __future__ import annotations

import base64
import logging
import os
import threading
import time
from typing import Any, Optional

from .. import telemetry
from ..store import format as fmt

log = logging.getLogger(__name__)

QUEUE_FILE = "checkerd.queue"

#: Fault-injection hook for the self-chaos harness (nemesis/
#: selfchaos.py): set to "enospc" and every journal append fails like
#: a full --queue disk.  ``file:PATH`` indirects through a file's
#: contents (overload._env_indirect) so the harness toggles the fault
#: in a live child daemon.  Read per append, same pattern as
#: ops/degrade.maybe_fault, so a daemon under test flips behavior
#: without restart.  The append path already treats OSError as a
#: degraded-durability signal (checkerd.queue.append-failed), never a
#: crash.
FAULT_ENV = "JEPSEN_QUEUE_FAULT"


def _maybe_disk_fault() -> None:
    import errno

    from .overload import _env_indirect

    if _env_indirect(os.environ.get(FAULT_ENV)) == "enospc":
        raise OSError(errno.ENOSPC,
                      f"injected disk-full ({FAULT_ENV}=enospc)")

#: Finished-ticket results are kept across restarts this long (matches
#: the scheduler's in-memory _RESULT_TTL_S) so late polls after a crash
#: still see their verdict; older ones fall to compaction.
KEEP_RESULTS_S = 600.0


class QueueJournal:
    """The durable ticket queue.  Thread-safe; one instance per file."""

    def __init__(self, path: str, *, keep_results_s: float = KEEP_RESULTS_S):
        self.path = path
        self.keep_results_s = keep_results_s
        self._lock = threading.Lock()
        self._submits: dict[str, dict] = {}
        self._submit_ts: dict[str, float] = {}
        self._results: dict[str, dict] = {}
        self._result_ts: dict[str, float] = {}
        self._abandoned: set[str] = set()
        self.loaded = 0
        self.appended = 0
        self.torn = False
        self.compacted = 0
        self._writer: Optional[fmt.BlockWriter] = None
        self._load()

    # -- recovery ------------------------------------------------------------

    def _load(self) -> None:
        """Replays the journal, detects a torn tail, compacts dead
        records, and opens the writer (whose constructor truncates any
        torn tail before we append)."""
        size = 0
        if os.path.exists(self.path):
            size = os.path.getsize(self.path)
            try:
                with open(self.path, "rb") as f:
                    if f.read(len(fmt.MAGIC)) == fmt.MAGIC:
                        end = len(fmt.MAGIC)
                        while True:
                            rec = fmt._read_block(f, size)
                            if rec is None:
                                break
                            end = f.tell()
                            _, btype, payload = rec
                            if btype != fmt.BLOCK_QUEUE:
                                continue
                            self._absorb(payload)
                            self.loaded += 1
                        if end < size:
                            self.torn = True
                            telemetry.count("checkerd.queue.torn-tail")
                            log.warning(
                                "queue journal %s: torn tail truncated "
                                "(%d of %d bytes valid)",
                                self.path, end, size,
                            )
            except OSError as e:
                log.warning("queue journal %s unreadable: %r", self.path, e)
        dead = self._drop_stale()
        if dead or self.torn:
            self._compact(size)
        self._writer = fmt.BlockWriter(self.path)

    def _absorb(self, payload: Any) -> None:
        if not isinstance(payload, dict):
            return
        kind = payload.get("rec")
        ticket = payload.get("ticket")
        if not isinstance(ticket, str):
            return
        if kind == "submit" and isinstance(payload.get("req"), dict):
            self._submits[ticket] = payload["req"]
            self._submit_ts[ticket] = float(payload.get("ts") or 0.0)
        elif kind == "result" and isinstance(payload.get("result"), dict):
            self._results[ticket] = payload["result"]
            self._result_ts[ticket] = float(payload.get("ts") or 0.0)
        elif kind == "abandon":
            self._abandoned.add(ticket)

    def _drop_stale(self) -> int:
        """Removes abandoned tickets and expired results from the
        in-memory view; returns how many records compaction can shed
        (finished tickets' submit records are dead weight too — the
        result alone answers late polls)."""
        now = time.time()
        dead = 0
        for t in self._abandoned:
            if self._submits.pop(t, None) is not None:
                self._submit_ts.pop(t, None)
                dead += 1
        dead += len(self._abandoned)
        self._abandoned.clear()
        for t in [t for t, ts in self._result_ts.items()
                  if now - ts > self.keep_results_s]:
            del self._results[t]
            del self._result_ts[t]
            dead += 1
        for t in [t for t in self._results if t in self._submits]:
            del self._submits[t]
            self._submit_ts.pop(t, None)
            dead += 1
        return dead

    def _compact(self, old_size: int) -> None:
        """Rewrites the journal with only live records (unfinished
        submits + fresh results), atomically via tmp + rename."""
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(fmt.MAGIC)
                for t, req in self._submits.items():
                    # Original submit time, not now(): compaction must
                    # never grow a record (torn-tail truncation promises
                    # size monotonically shrinks) and the ts is the
                    # submission's, not the rewrite's.
                    f.write(fmt.frame(fmt.BLOCK_QUEUE, {
                        "rec": "submit", "ticket": t, "req": req,
                        "ts": self._submit_ts.get(t, 0.0),
                    }))
                for t, res in self._results.items():
                    f.write(fmt.frame(fmt.BLOCK_QUEUE, {
                        "rec": "result", "ticket": t, "result": res,
                        "ts": self._result_ts.get(t, 0.0),
                    }))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            self.compacted += 1
            telemetry.count("checkerd.queue.compacted")
            log.info("queue journal %s compacted (%d -> %d bytes)",
                     self.path, old_size, os.path.getsize(self.path))
        except OSError as e:
            log.warning("queue journal compaction failed: %r", e)
            try:
                os.unlink(tmp)
            except OSError as e2:
                log.debug("queue journal tmp cleanup failed: %r", e2)

    # -- the append path -----------------------------------------------------

    def _append(self, payload: dict) -> bool:
        with self._lock:
            if self._writer is None:
                return False
            try:
                _maybe_disk_fault()
                self._writer.append(fmt.BLOCK_QUEUE, payload)
                self._writer.sync()
                self.appended += 1
            except (OSError, TypeError, ValueError) as e:
                telemetry.count("checkerd.queue.append-failed")
                log.warning("queue journal append failed: %r", e)
                return False
        telemetry.count("checkerd.queue.append")
        return True

    def record_submit(self, ticket: str, req: dict) -> bool:
        """Journals one accepted submission.  Must complete before the
        TICKET reply: a ticket the client can poll is a ticket the
        journal can replay."""
        now = round(time.time(), 3)
        with self._lock:
            self._submits[ticket] = req
            self._submit_ts[ticket] = now
        return self._append({
            "rec": "submit", "ticket": ticket, "req": req, "ts": now,
        })

    def record_result(self, ticket: str, result: dict) -> bool:
        """Journals the verdict.  Must complete before the request is
        marked done (the replay-idempotence rule)."""
        now = round(time.time(), 3)
        with self._lock:
            self._results[ticket] = result
            self._result_ts[ticket] = now
            self._submits.pop(ticket, None)
            self._submit_ts.pop(ticket, None)
        return self._append({
            "rec": "result", "ticket": ticket, "result": result, "ts": now,
        })

    def record_abandon(self, ticket: str) -> bool:
        with self._lock:
            self._submits.pop(ticket, None)
            self._submit_ts.pop(ticket, None)
        return self._append({
            "rec": "abandon", "ticket": ticket, "ts": round(time.time(), 3),
        })

    # -- the replay view -----------------------------------------------------

    def unfinished(self) -> dict[str, dict]:
        """ticket -> submit record for every accepted submission with
        no durable verdict — what a restarted daemon must re-queue."""
        with self._lock:
            return dict(self._submits)

    def finished(self) -> dict[str, dict]:
        """ticket -> result for verdicts that must answer late polls."""
        with self._lock:
            return dict(self._results)

    def stats(self) -> dict:
        with self._lock:
            return {
                "path": self.path,
                "loaded": self.loaded,
                "appended": self.appended,
                "unfinished": len(self._submits),
                "finished": len(self._results),
                "torn-tail": self.torn,
                "compactions": self.compacted,
            }

    def close(self) -> None:
        with self._lock:
            if self._writer is not None:
                try:
                    self._writer.close()
                except OSError as e:
                    log.debug("queue journal close failed: %r", e)
                self._writer = None


# ---------------------------------------------------------------------------
# Request <-> record codecs (scheduler side)
# ---------------------------------------------------------------------------


def request_to_record(req: Any) -> dict:
    """Serializes a scheduler Request to a JSON-able journal record.
    Ops keep their original indices (reindex=False on replay) so
    replayed certificates cite the same history positions; packed
    tensors ride as base64 of the columnar wire bytes."""
    from ..history.packed import packed_to_bytes

    return {
        "run": req.run,
        "model": req.model_spec,
        "algorithm": req.algorithm,
        "n-keys": req.n_keys,
        "budget-s": req.budget_s,
        "time-limit-s": req.time_limit_s,
        "tenant": req.tenant,
        # The deadline is relative to the ORIGINAL submission; a
        # replayed request is already admitted, so replay never
        # re-sheds it — the field rides along for forensics only.
        "deadline-s": req.deadline_s,
        "trace": req.trace,
        "subs": {
            str(i): h.to_dicts() for i, h in req.subs.items()
        },
        "packs": {
            str(i): base64.b64encode(packed_to_bytes(p)).decode("ascii")
            for i, p in req.packs.items()
        },
    }


def request_from_record(rec: dict) -> Any:
    """Rebuilds a Request from a journal record (raises on a corrupt
    record; the caller skips and counts it)."""
    from ..history.core import History
    from ..history.packed import packed_from_bytes
    from .scheduler import Request

    subs = {
        int(i): History(ops, reindex=False)
        for i, ops in (rec.get("subs") or {}).items()
    }
    packs = {
        int(i): packed_from_bytes(base64.b64decode(b64))
        for i, b64 in (rec.get("packs") or {}).items()
    }
    return Request(
        run=str(rec.get("run") or "anonymous"),
        model_spec=rec.get("model") or {},
        algorithm=str(rec.get("algorithm") or "wgl-tpu"),
        n_keys=int(rec.get("n-keys") or 0),
        budget_s=rec.get("budget-s"),
        time_limit_s=rec.get("time-limit-s"),
        subs=subs,
        packs=packs,
        trace=rec.get("trace"),
        tenant=rec.get("tenant"),
    )


# ---------------------------------------------------------------------------
# Wire-frame <-> record codecs (router side)
# ---------------------------------------------------------------------------


def frames_to_record(frames: list) -> list:
    """Serializes captured wire frames ((ftype, payload) pairs; PACKED
    payloads are raw bytes) for the router's journal, so a dead
    daemon's ticket replays byte-identically against a sibling."""
    out = []
    for ftype, payload in frames:
        if isinstance(payload, (bytes, bytearray)):
            out.append({
                "t": int(ftype),
                "b64": base64.b64encode(bytes(payload)).decode("ascii"),
            })
        else:
            out.append({"t": int(ftype), "p": payload})
    return out


def frames_from_record(entries: list) -> list:
    frames = []
    for e in entries:
        if "b64" in e:
            frames.append((int(e["t"]), base64.b64decode(e["b64"])))
        else:
            frames.append((int(e["t"]), e.get("p")))
    return frames
