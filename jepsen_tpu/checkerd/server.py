"""The checkerd TCP server: frames in, verdicts out.

One handler thread per connection parses frames (protocol.py) and talks
to the shared Scheduler; the scheduler's single worker thread owns the
devices.  Submissions are connection-scoped state machines
(SUBMIT -> CHUNK*/PACKED* -> COMMIT -> TICKET), polls and stats are
stateless, and any per-connection failure answers with an ERROR frame
instead of touching the daemon.
"""

from __future__ import annotations

import logging
import socketserver
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

from . import DEFAULT_PORT
from .protocol import (
    F_CHUNK,
    F_COMMIT,
    F_ERROR,
    F_PACKED,
    F_PENDING,
    F_POLL,
    F_RESULT,
    F_RESUME,
    F_RESUME_OK,
    F_SHED,
    F_STATS,
    F_STATS_REPLY,
    F_SUBMIT,
    F_TICKET,
    ProtocolError,
    read_frame,
    unpack_key_frame,
    write_frame,
)
from . import overload
from .scheduler import Request, Scheduler
from .. import telemetry

log = logging.getLogger(__name__)

#: Parked streaming sessions the daemon keeps for reconnecting
#: clients; LRU eviction past this (a leaked session must not pin its
#: half-uploaded history forever, and a session that just resumed must
#: not be the one evicted).
MAX_PARKED_SESSIONS = 64

#: Evicted session tokens remembered so a late RESUME gets an honest
#: "evicted" refusal (the client falls back to post-hoc) instead of an
#: indistinguishable "unknown session".
MAX_EVICTED_REMEMBERED = 256


class _Submission:
    """Connection-local accumulation of one SUBMIT conversation."""

    def __init__(self, meta: dict):
        self.meta = meta
        self.n_keys = int(meta.get("n-keys") or 0)
        if not 0 <= self.n_keys <= 1_000_000:
            raise ProtocolError(f"implausible n-keys {self.n_keys}")
        #: A streamed submission (streaming/remote.py) opens with a
        #: DEFERRED key count: chunks grow it as keys first appear and
        #: COMMIT's payload finalizes it.
        self.streaming = bool(meta.get("streaming"))
        #: Client-minted resume token: the submission is parked when
        #: its connection dies and a RESUME re-attaches to it.
        self.session = meta.get("session") if self.streaming else None
        self.ops: dict[int, list] = {}
        self.packs: dict[int, Any] = {}

    def received(self) -> dict[str, int]:
        """Per-key op counts already held — the stable bound a resuming
        client continues from."""
        return {str(i): len(ops) for i, ops in self.ops.items()}

    def _check_key(self, i: Any) -> int:
        i = int(i)
        if self.streaming and self.n_keys <= i < 1_000_000:
            self.n_keys = i + 1
        if not 0 <= i < self.n_keys:
            raise ProtocolError(
                f"key index {i} outside 0..{self.n_keys - 1}"
            )
        return i

    def finalize_keys(self, payload: dict) -> None:
        """Applies COMMIT's `n-keys` override (streamed submissions
        declare the count only once the run ends)."""
        n = payload.get("n-keys") if isinstance(payload, dict) else None
        if n is None:
            return
        n = int(n)
        if not self.n_keys <= n <= 1_000_000:
            raise ProtocolError(
                f"COMMIT n-keys {n} below the {self.n_keys} keys seen"
            )
        self.n_keys = n

    def add_chunk(self, payload: dict) -> None:
        i = self._check_key(payload.get("key"))
        ops = payload.get("ops")
        if not isinstance(ops, list):
            raise ProtocolError("CHUNK without an ops list")
        self.ops.setdefault(i, []).extend(ops)
        telemetry.count("ingest.decode.ops", len(ops))

    def add_packed(self, data: bytes) -> None:
        from ..history.packed import packed_from_bytes

        i, body = unpack_key_frame(data)
        i = self._check_key(i)
        try:
            self.packs[i] = packed_from_bytes(body)
        except ValueError as e:
            raise ProtocolError(f"key {i}: {e}") from e
        telemetry.count("ingest.decode.packs")
        telemetry.count("ingest.decode.pack-bytes", len(body))

    def build(self, scheduler: Scheduler) -> Request:
        with telemetry.span("ingest.decode.build",
                            keys=len(self.ops) + len(self.packs)):
            return self._build(scheduler)

    def _build(self, scheduler: Scheduler) -> Request:
        from ..history.core import History

        meta = self.meta
        spec = meta.get("model")
        if not isinstance(spec, dict):
            raise ProtocolError("SUBMIT without a model spec")
        # Validates the spec (unknown type -> ValueError -> ERROR frame)
        # and warms the daemon-wide instance before the queue sees it.
        scheduler.model_for(spec)
        subs = {
            # Ops arrive as to_dict() dicts with their original indices;
            # reindex=False keeps them, so per-key certificates cite
            # positions in the submitting run's full history.
            i: History(ops, reindex=False)
            for i, ops in self.ops.items()
        }
        return Request(
            run=str(meta.get("run") or "anonymous"),
            model_spec=spec,
            algorithm=str(meta.get("algorithm") or "wgl-tpu"),
            n_keys=self.n_keys,
            budget_s=meta.get("budget-s"),
            time_limit_s=meta.get("time-limit-s"),
            subs=subs,
            packs=self.packs,
            trace=meta.get("trace"),
            tenant=meta.get("tenant"),
            deadline_s=meta.get("deadline-s"),
        )


class _Handler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        sched: Scheduler = self.server.scheduler  # type: ignore[attr-defined]
        sub: Optional[_Submission] = None
        conn_id = id(self)
        owned: list[str] = []
        try:
            self._converse(sched, sub, conn_id, owned)
        finally:
            # Disconnect mid-PENDING: a ticket whose submitting
            # connection died with nobody else polling it would keep
            # its keys in the merged cohort forever — cancel it instead
            # (dropped at the next cohort boundary, counted as
            # checkerd.ticket-abandoned).  Streamed tickets are exempt:
            # their poller arrives later on a fresh connection.
            for t in owned:
                sched.abandon(t, conn_id)

    def _converse(
        self,
        sched: Scheduler,
        sub: Optional[_Submission],
        conn_id: int,
        owned: list[str],
    ) -> None:
        while True:
            try:
                fr = read_frame(self.rfile)
            except ProtocolError as e:
                self._reply(F_ERROR, {"error": str(e)})
                return  # stream desynced: close
            if fr is None:
                return
            ftype, payload = fr
            try:
                if ftype == F_SUBMIT:
                    sub = _Submission(payload)
                    if sub.session:
                        # Streamed with a resume token: survive this
                        # connection's death so a RESUME re-attaches.
                        self._park(sub)
                elif ftype == F_CHUNK:
                    self._need(sub, "CHUNK").add_chunk(payload)
                elif ftype == F_PACKED:
                    self._need(sub, "PACKED").add_packed(payload)
                elif ftype == F_RESUME:
                    token = (payload.get("session")
                             if isinstance(payload, dict) else None)
                    parked = self._parked(token)
                    if parked is None:
                        # Honest RESUME refusal: an evicted session is
                        # named as such so the client knows its stream
                        # is unrecoverable and falls back to post-hoc
                        # (never wedges waiting for a bound that will
                        # not come).
                        if self._was_evicted(token):
                            telemetry.count("checkerd.resume-refused")
                            self._reply(F_ERROR, {
                                "error": f"session {token!r} evicted "
                                "(parked-session LRU bound; resume "
                                "refused — submit post-hoc)",
                            })
                        else:
                            self._reply(F_ERROR, {
                                "error": f"unknown session {token!r} "
                                "(daemon restarted or session evicted)",
                            })
                    else:
                        sub = parked
                        self._reply(F_RESUME_OK, {
                            "received": sub.received(),
                            "n-keys": sub.n_keys,
                        })
                elif ftype == F_COMMIT:
                    s = self._need(sub, "COMMIT")
                    s.finalize_keys(payload)
                    req = s.build(sched)
                    sub = None
                    if s.session:
                        self._unpark(s)
                    # Detached submissions (the federation router, which
                    # submits on a short-lived connection and polls on
                    # fresh ones) opt out of abandon-on-disconnect, as
                    # do streamed ones (their poller arrives later).
                    detached = s.streaming or bool(s.meta.get("detached"))
                    try:
                        ticket = sched.submit(
                            req,
                            owner_conn=None if detached else conn_id,
                        )
                    except overload.OverloadShed as shed:
                        # Structured refusal: no ticket was minted or
                        # journaled, so nothing can be silently lost.
                        self._reply(F_SHED, shed.payload())
                        continue
                    if not detached:
                        owned.append(ticket)
                    self._reply(F_TICKET, {
                        "ticket": ticket,
                        "queue-depth": sched.queue_depth(),
                    })
                elif ftype == F_POLL:
                    r = sched.poll(str(payload.get("ticket")), conn_id)
                    if "_error" in r:
                        self._reply(F_ERROR, {"error": r["_error"]})
                    elif r.pop("_pending", None):
                        self._reply(F_PENDING, r)
                    else:
                        self._reply(F_RESULT, r)
                elif ftype == F_STATS:
                    self._reply(F_STATS_REPLY, sched.stats())
                else:
                    self._reply(F_ERROR, {
                        "error": f"unexpected frame type {ftype}",
                    })
            except (ProtocolError, ValueError) as e:
                sub = None
                self._reply(F_ERROR, {"error": str(e)})
            except BrokenPipeError:
                return
            except Exception as e:  # noqa: BLE001 — per-connection wall
                log.exception("checkerd handler error")
                sub = None
                self._reply(F_ERROR, {"error": repr(e)})

    def _need(self, sub: Optional[_Submission], what: str) -> _Submission:
        if sub is None:
            raise ProtocolError(f"{what} before SUBMIT")
        return sub

    def _park(self, sub: _Submission) -> None:
        """Parks (or LRU-touches) a streamed submission.  Eviction is
        least-recently-used — dict insertion order, refreshed on every
        park and resume — bounded by MAX_PARKED_SESSIONS; each victim
        is counted (checkerd.parked-evicted) and remembered so its
        RESUME gets an honest refusal."""
        srv = self.server
        with srv.sessions_lock:  # type: ignore[attr-defined]
            srv.sessions.pop(sub.session, None)  # type: ignore[attr-defined]
            srv.sessions[sub.session] = sub  # type: ignore[attr-defined]
            while len(srv.sessions) > MAX_PARKED_SESSIONS:  # type: ignore[attr-defined]
                victim = next(iter(srv.sessions))  # type: ignore[attr-defined]
                del srv.sessions[victim]  # type: ignore[attr-defined]
                srv.evicted_sessions.append(victim)  # type: ignore[attr-defined]
                telemetry.count("checkerd.parked-evicted")

    def _parked(self, token: Any) -> Optional[_Submission]:
        srv = self.server
        with srv.sessions_lock:  # type: ignore[attr-defined]
            sub = srv.sessions.get(token)  # type: ignore[attr-defined]
            if sub is not None:
                # LRU touch: a resuming session moves to the young end.
                srv.sessions.pop(token, None)  # type: ignore[attr-defined]
                srv.sessions[token] = sub  # type: ignore[attr-defined]
            return sub

    def _was_evicted(self, token: Any) -> bool:
        srv = self.server
        with srv.sessions_lock:  # type: ignore[attr-defined]
            return token in srv.evicted_sessions  # type: ignore[attr-defined]

    def _unpark(self, sub: _Submission) -> None:
        srv = self.server
        with srv.sessions_lock:  # type: ignore[attr-defined]
            srv.sessions.pop(sub.session, None)  # type: ignore[attr-defined]

    def _reply(self, ftype: int, payload: Any) -> None:
        try:
            write_frame(self.wfile, ftype, payload)
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            pass


class CheckerdServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True
    scheduler: Scheduler
    #: Parked streaming submissions by resume token (F_RESUME),
    #: LRU-ordered: oldest-touched first.
    sessions: dict
    sessions_lock: threading.Lock
    #: Recently LRU-evicted session tokens (honest RESUME refusals).
    evicted_sessions: Any


def make_server(
    host: str = "127.0.0.1",
    port: int = DEFAULT_PORT,
    *,
    batch_window_s: float = 0.05,
    max_budget_s: Optional[float] = None,
    bound: Optional[int] = None,
    profile_dir: Optional[str] = None,
    plan_cache_dir: Optional[str] = None,
    queue_path: Optional[str] = None,
    tenant_weights: Optional[dict] = None,
) -> CheckerdServer:
    from collections import deque

    srv = CheckerdServer((host, port), _Handler)
    srv.sessions = {}
    srv.sessions_lock = threading.Lock()
    srv.evicted_sessions = deque(maxlen=MAX_EVICTED_REMEMBERED)
    srv.scheduler = Scheduler(
        batch_window_s=batch_window_s,
        max_budget_s=max_budget_s,
        bound=bound,
        profile_dir=profile_dir,
        plan_cache_dir=plan_cache_dir,
        queue_path=queue_path,
        tenant_weights=tenant_weights,
    )
    return srv


class _MetricsHandler(BaseHTTPRequestHandler):
    """Prometheus-text scrape endpoint for the daemon: process
    telemetry plus scheduler gauges (queue depth, utilization,
    profile-record count) and the one-hot chip_health family."""

    scheduler: Scheduler  # class attribute bound by make_metrics_server

    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler API
        if self.path.split("?", 1)[0] not in ("/metrics", "/metrics/"):
            self.send_error(404)
            return
        from .. import telemetry
        from ..ops import degrade

        try:
            st = self.scheduler.stats()
            ov = st.get("overload") or {}
            extra = {
                "checkerd.queue-depth": st.get("queue-depth", 0),
                "checkerd.utilization": st.get("utilization", 0.0),
                "checkerd.uptime-s": st.get("uptime-s", 0.0),
                "checkerd.requests": st.get("requests", 0),
                "checkerd.cohorts": st.get("cohorts", 0),
                "checkerd.merge-ratio": st.get("merge-ratio", 0.0),
                "checkerd.profile-records": st.get("profile-records", 0),
                "checkerd.overload.brownout-level":
                    ov.get("brownout-level", 0),
                "checkerd.overload.shed-total": ov.get("shed", 0),
            }
            # Per-tenant admission/fairness families (satellite 3):
            # jepsen_checkerd_shed_total{tenant=...} and the queue-wait
            # p95 gauge per tenant.
            tenants = ov.get("tenants") or {}
            shed_by_tenant = {
                t: d.get("shed", 0) for t, d in tenants.items()
                if d.get("shed")
            }
            wait_p95 = {
                t: d["queue-wait-p95-s"] for t, d in tenants.items()
                if d.get("queue-wait-p95-s") is not None
            }
            extra_labeled = {
                "checkerd.shed": ("tenant", shed_by_tenant, "counter"),
                "checkerd.queue-wait-p95-seconds":
                    ("tenant", wait_p95, "gauge"),
            }
            # SLO sweep on every scrape: the daemon-surface gauges
            # (queue depth, merge ratio) only exist here, so this is
            # where their rules get their samples.
            from ..telemetry import slo

            slo.evaluate(extra, degrade.chip_state())
            body = telemetry.prometheus_text(
                extra_gauges=extra, chip_state=degrade.chip_state(),
                slo_firing=slo.firing_gauges(),
                extra_labeled=extra_labeled,
            ).encode()
        except Exception as e:  # noqa: BLE001 — a scrape must not 500
            # the daemon into a restart loop; answer degraded instead.
            body = f"# metrics error: {e!r}\n".encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt: str, *args: Any) -> None:
        log.debug("metrics: " + fmt, *args)


def make_metrics_server(
    scheduler: Scheduler, host: str = "127.0.0.1", port: int = 0,
) -> ThreadingHTTPServer:
    """A /metrics HTTP listener bound to `scheduler` (port 0 = ephemeral
    for tests); the caller runs serve_forever in a daemon thread."""
    handler = type("BoundMetrics", (_MetricsHandler,),
                   {"scheduler": scheduler})
    return ThreadingHTTPServer((host, port), handler)


def serve(
    host: str = "0.0.0.0",
    port: int = DEFAULT_PORT,
    *,
    batch_window_s: float = 0.05,
    max_budget_s: Optional[float] = None,
    metrics_port: Optional[int] = None,
    profile_dir: Optional[str] = None,
    plan_cache_dir: Optional[str] = None,
    queue_path: Optional[str] = None,
    tenant_weights: Optional[dict] = None,
) -> None:
    """Blocking entrypoint for `jepsen checkerd`."""
    srv = make_server(
        host, port,
        batch_window_s=batch_window_s, max_budget_s=max_budget_s,
        profile_dir=profile_dir,
        plan_cache_dir=plan_cache_dir,
        queue_path=queue_path,
        tenant_weights=tenant_weights,
    )
    bound_port = srv.server_address[1]
    msrv = None
    if metrics_port is not None:
        msrv = make_metrics_server(srv.scheduler, host, metrics_port)
        threading.Thread(
            target=msrv.serve_forever, name="checkerd-metrics",
            daemon=True,
        ).start()
        log.info("checkerd /metrics on %s:%d",
                 host, msrv.server_address[1])
    log.info("checkerd serving on %s:%d", host, bound_port)
    print(f"checkerd serving on {host}:{bound_port} "
          f"(batch window {batch_window_s}s)")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.shutdown()
        srv.server_close()
        srv.scheduler.stop()
        if msrv is not None:
            msrv.shutdown()
            msrv.server_close()


def main(argv: Optional[list[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="jepsen-tpu-checkerd",
        description="long-lived linearizability-checker daemon",
    )
    p.add_argument("--host", "-b", default="0.0.0.0")
    p.add_argument("--port", "-p", type=int, default=DEFAULT_PORT)
    p.add_argument(
        "--batch-window", type=float, default=0.05, metavar="S",
        help="seconds to linger after the first queued request so "
        "concurrent runs merge into one cohort (default 0.05)",
    )
    p.add_argument(
        "--max-budget", type=float, default=None, metavar="S",
        help="clamp every request's checker budget to this many "
        "seconds, protecting the pool from pathological histories",
    )
    p.add_argument(
        "--platform", default=None, choices=["cpu", "tpu"],
        help="pin the JAX backend before the first device touch",
    )
    p.add_argument(
        "--metrics-port", type=int, default=DEFAULT_PORT + 1,
        metavar="P",
        help="HTTP port for the Prometheus /metrics scrape surface "
        f"(default {DEFAULT_PORT + 1}; -1 disables)",
    )
    p.add_argument(
        "--profile-dir", default=None, metavar="DIR",
        help="directory for the fleet-wide per-pass cost-profile "
        "store (profiles.jsonl) and postmortem dumps",
    )
    p.add_argument(
        "--plan-cache", default=None, metavar="DIR",
        help="directory for the persistent plan memo and XLA compile "
        "cache: a restarted daemon re-checking byte-identical "
        "histories warm-starts from it (jepsen_tpu/plan/cache.py)",
    )
    p.add_argument(
        "--queue", default=None, metavar="PATH",
        help="crash-safe queue journal file (checkerd.queue): every "
        "accepted submission and verdict is journaled + fsynced, and "
        "a restarted daemon replays unfinished tickets under their "
        "original ids — zero in-flight verdicts lost",
    )
    p.add_argument(
        "--tenant-weight", action="append", default=[],
        metavar="NAME=W",
        help="fair-queue weight for a tenant (repeatable; default 1.0 "
        "each): service share under saturation is weight-proportional, "
        "never a hard cliff",
    )
    opts = p.parse_args(argv)
    weights: dict[str, float] = {}
    for spec in opts.tenant_weight:
        name, _, w = spec.partition("=")
        try:
            weights[name] = float(w)
        except ValueError:
            p.error(f"--tenant-weight {spec!r}: expected NAME=FLOAT")
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s [%(threadName)s] "
               "%(name)s: %(message)s",
    )
    if opts.platform:
        import jax

        jax.config.update("jax_platforms", opts.platform)
    serve(
        opts.host, opts.port,
        batch_window_s=opts.batch_window, max_budget_s=opts.max_budget,
        metrics_port=None if opts.metrics_port < 0 else opts.metrics_port,
        profile_dir=opts.profile_dir,
        plan_cache_dir=opts.plan_cache,
        queue_path=opts.queue,
        tenant_weights=weights or None,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
