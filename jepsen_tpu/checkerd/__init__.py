"""Checker-as-a-service: a long-lived linearizability-checker daemon.

The production story for many concurrent test runs (CI fleets,
continuous verification of a live DB fleet) is one accelerator pool
shared by everyone — not one process per run, each paying its own JAX
startup and XLA compile.  This package is that pool:

  * ``server``    — a TCP daemon owning the JAX devices.  One worker
                    thread drains a scheduler queue, merging compatible
                    per-key cohorts from *multiple concurrent runs* into
                    a single pass through the settling ladder
                    (parallel/independent.py), sharded over the device
                    mesh — so XLA compilation, the settle memo, and warm
                    devices are amortized fleet-wide.
  * ``protocol``  — the framed wire protocol.  Frames reuse the store's
                    block layout (store/format.py: [len][crc32][type]
                    [payload]); history payloads are op-dict chunks
                    shaped like BLOCK_CHUNK, or raw packed-column
                    tensors (history/packed.py packed_to_bytes).
  * ``scheduler`` — the cohort queue: admission, cross-run merge,
                    per-request budgets, fleet stats.
  * ``client``    — CheckerdClient (submit/poll/stats) and
                    RemoteChecker, the drop-in Checker that ships the
                    work to a daemon and falls back to in-process
                    checking when the daemon is unreachable.

Start one with ``jepsen checkerd`` (any suite CLI) or
``python -m jepsen_tpu.checkerd``; point runs at it with
``--remote host:port`` or the JEPSEN_CHECKERD env var.  The web
dashboard's ``/fleet`` page renders its stats.
"""

from __future__ import annotations

#: Default TCP port for the daemon (client, CLI, and /fleet page agree).
DEFAULT_PORT = 7462

#: Default TCP port for the federation router (`jepsen checkerd-router`,
#: router.py): a front-end that places submissions across N daemons by
#: queue depth and model-cache affinity, fails over mid-run, and
#: enforces per-tenant admission.
ROUTER_PORT = 7472

#: Environment variable naming a default daemon address ("host:port").
#: When set, core.analyze routes every linearizable check through it.
ADDR_ENV = "JEPSEN_CHECKERD"
