"""DB protocol: installing, starting, and breaking the system under test.

Equivalent of /root/reference/jepsen/src/jepsen/db.clj: the `DB`
protocol (:12-14), optional `Kill` (:16-19), `Pause` (:30-33),
`Primary` (:35-42), and `LogFiles` (:44-48) capabilities, and `cycle`
— teardown-then-setup across all nodes with ≤3 retries (:158-199).
"""

from __future__ import annotations

import logging
from typing import Any, Optional, Sequence

from .control import Session, health, on_nodes

log = logging.getLogger(__name__)

#: Setup/teardown attempts before giving up (db.clj:158-160).
CYCLE_TRIES = 3


class DB:
    """Installs and runs the database on one node (db.clj:12-14)."""

    def setup(self, test: dict, sess: Session, node: str) -> None:
        pass

    def teardown(self, test: dict, sess: Session, node: str) -> None:
        pass

    # -- optional capabilities ------------------------------------------

    def kill(self, test: dict, sess: Session, node: str) -> None:
        """Kill -9 the DB processes (Kill, db.clj:16-19)."""
        raise NotImplementedError

    def start(self, test: dict, sess: Session, node: str) -> None:
        raise NotImplementedError

    def pause(self, test: dict, sess: Session, node: str) -> None:
        """SIGSTOP (Pause, db.clj:30-33)."""
        raise NotImplementedError

    def resume(self, test: dict, sess: Session, node: str) -> None:
        """SIGCONT."""
        raise NotImplementedError

    def primaries(self, test: dict) -> Sequence[str]:
        """Nodes currently believed primary (Primary, db.clj:35-42)."""
        raise NotImplementedError

    def setup_primary(self, test: dict, sess: Session, node: str) -> None:
        """One-time setup run on the first node (db.clj:35-42)."""
        pass

    def log_files(self, test: dict, sess: Session, node: str) -> Sequence[str]:
        """Paths to snarf after the run (LogFiles, db.clj:44-48)."""
        return []

    # -- capability sniffing --------------------------------------------

    def supports(self, capability: str) -> bool:
        """True if this DB overrides `capability` (kill/pause/primaries),
        the duck-typed analog of (satisfies? Kill db)."""
        mine = getattr(type(self), capability, None)
        return mine is not None and mine is not getattr(DB, capability, None)


class NoopDB(DB):
    """No database: for in-memory and generator-only tests
    (tests.clj noop-test)."""


noop = NoopDB()


class Tcpdump(DB):
    """A DB that captures packets from setup to teardown and yields the
    pcap as a log file (db.clj:88-156).  Compose it next to your real
    DB.  Options:

      ports         ports to capture (filter `port a or port b ...`)
      clients_only  only traffic involving the control node's IP
      filter        extra pcap filter string, AND-ed in
    """

    DIR = "/tmp/jepsen-tpu/tcpdump"

    def __init__(self, *, ports: Sequence[int] = (),
                 clients_only: bool = False,
                 filter: Optional[str] = None):
        self.ports = list(ports)
        self.clients_only = clients_only
        self.filter = filter
        self.log_file = f"{self.DIR}/log"
        self.cap_file = f"{self.DIR}/tcpdump.pcap"
        self.pid_file = f"{self.DIR}/pid"

    def _filter_str(self, test: dict) -> str:
        # Each clause parenthesized: pcap's `and` binds tighter than
        # `or`, so a bare `port a or port b and host x` would capture
        # ALL of port a's traffic (the reference db.clj:111-117 has
        # this flaw; fixed here).
        parts = []
        if self.ports:
            parts.append(
                "(" + " or ".join(f"port {p}" for p in self.ports) + ")"
            )
        if self.clients_only:
            from .control.util import control_ip

            parts.append(f"host {control_ip(test)}")
        if self.filter:
            parts.append(f"({self.filter})")
        return " and ".join(p for p in parts if p)

    def setup(self, test: dict, sess: Session, node: str) -> None:
        from .control.util import start_daemon

        with sess.su():
            sess.exec("mkdir", "-p", self.DIR)
            # -U: unbuffered — SIGINT is supposed to flush the capture
            # but loses the tail in practice (db.clj:128-134).
            args: list = ["-w", self.cap_file, "-s", "65535",
                          "-B", "16384", "-U"]
            f = self._filter_str(test)
            if f:
                args.append(f)
            start_daemon(
                sess, "tcpdump", *args,
                pidfile=self.pid_file, logfile=self.log_file,
                chdir=self.DIR,
            )

    def teardown(self, test: dict, sess: Session, node: str) -> None:
        from .control.util import stop_daemon

        with sess.su():
            # Clean INT first so tcpdump flushes, then the hard stop.
            sess.exec_star(
                "bash", "-c",
                f"test -e {self.pid_file} && "
                f"kill -INT $(cat {self.pid_file}) && sleep 0.2; true",
            )
            stop_daemon(sess, self.pid_file)
            sess.exec_star("rm", "-rf", self.DIR)

    def log_files(self, test: dict, sess: Session, node: str):
        return [self.log_file, self.cap_file]


class ComposedDB(DB):
    """Runs several DBs as one: setup in order, teardown in reverse,
    log files merged; Kill/Pause/Primary route to the first DB that
    implements them (the reference composes DBs ad hoc; this is the
    common shape, e.g. Tcpdump + real DB)."""

    def __init__(self, dbs: Sequence[DB]):
        self.dbs = list(dbs)

    def setup(self, test, sess, node):
        for db in self.dbs:
            db.setup(test, sess, node)

    def teardown(self, test, sess, node):
        for db in reversed(self.dbs):
            db.teardown(test, sess, node)

    def _first_with(self, name: str):
        for db in self.dbs:
            if db.supports(name):
                return db
        return None

    def supports(self, capability: str) -> bool:
        # A wrapper "supports" a capability only if something inside
        # does — the inherited check would see our routing methods and
        # claim everything.
        return self._first_with(capability) is not None

    def kill(self, test, sess, node):
        db = self._first_with("kill")
        if db is None:
            raise NotImplementedError
        return db.kill(test, sess, node)

    def start(self, test, sess, node):
        db = self._first_with("start")
        if db is None:
            raise NotImplementedError
        return db.start(test, sess, node)

    def pause(self, test, sess, node):
        db = self._first_with("pause")
        if db is None:
            raise NotImplementedError
        return db.pause(test, sess, node)

    def resume(self, test, sess, node):
        db = self._first_with("resume")
        if db is None:
            raise NotImplementedError
        return db.resume(test, sess, node)

    def primaries(self, test):
        db = self._first_with("primaries")
        if db is None:
            raise NotImplementedError
        return db.primaries(test)

    def log_files(self, test, sess, node):
        out: list = []
        for db in self.dbs:
            out.extend(db.log_files(test, sess, node) or [])
        return out


def setup(test: dict, db: Optional[DB] = None) -> None:
    """Sets up the DB on all surviving nodes in parallel (per-node
    failures go through the node-loss policy), then primary setup on
    the first node still in rotation (core.clj:164-173)."""
    db = db or test.get("db") or noop
    health.run_phase(test, "db setup", lambda s, n: db.setup(test, s, n))
    sessions = test.get("sessions") or {}
    primary = next(
        (
            n for n in test.get("nodes") or []
            if n in sessions and not health.is_quarantined(test, n)
        ),
        None,
    )
    if primary is not None:
        health.run_phase(
            test,
            "db primary setup",
            lambda s, n: db.setup_primary(test, s, n),
            [primary],
        )


def teardown(test: dict, db: Optional[DB] = None) -> None:
    db = db or test.get("db") or noop
    on_nodes(test, lambda s, n: db.teardown(test, s, n))


def cycle(test: dict, db: Optional[DB] = None) -> None:
    """Teardown then setup, retried ≤3 times (db.clj:158-199)."""
    db = db or test.get("db") or noop
    last: Optional[Exception] = None
    for attempt in range(CYCLE_TRIES):
        try:
            teardown(test, db)
            setup(test, db)
            return
        except Exception as e:  # noqa: BLE001
            last = e
            log.warning(
                "db cycle failed (%d/%d): %r", attempt + 1, CYCLE_TRIES, e
            )
    raise last  # type: ignore[misc]


def snarf_logs(test: dict, dest_dir: str, db: Optional[DB] = None) -> None:
    """Downloads every node's log files into dest_dir/<node>/
    (core.clj:101-128)."""
    import os

    db = db or test.get("db") or noop

    def snarf(sess: Session, node: str) -> None:
        files = list(db.log_files(test, sess, node))
        if not files:
            return
        node_dir = os.path.join(dest_dir, str(node))
        os.makedirs(node_dir, exist_ok=True)
        try:
            sess.download(files, node_dir)
        except Exception as e:  # noqa: BLE001
            log.warning("couldn't snarf logs from %s: %r", node, e)

    on_nodes(test, snarf)
