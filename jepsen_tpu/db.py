"""DB protocol: installing, starting, and breaking the system under test.

Equivalent of /root/reference/jepsen/src/jepsen/db.clj: the `DB`
protocol (:12-14), optional `Kill` (:16-19), `Pause` (:30-33),
`Primary` (:35-42), and `LogFiles` (:44-48) capabilities, and `cycle`
— teardown-then-setup across all nodes with ≤3 retries (:158-199).
"""

from __future__ import annotations

import logging
from typing import Any, Optional, Sequence

from .control import Session, on_nodes

log = logging.getLogger(__name__)

#: Setup/teardown attempts before giving up (db.clj:158-160).
CYCLE_TRIES = 3


class DB:
    """Installs and runs the database on one node (db.clj:12-14)."""

    def setup(self, test: dict, sess: Session, node: str) -> None:
        pass

    def teardown(self, test: dict, sess: Session, node: str) -> None:
        pass

    # -- optional capabilities ------------------------------------------

    def kill(self, test: dict, sess: Session, node: str) -> None:
        """Kill -9 the DB processes (Kill, db.clj:16-19)."""
        raise NotImplementedError

    def start(self, test: dict, sess: Session, node: str) -> None:
        raise NotImplementedError

    def pause(self, test: dict, sess: Session, node: str) -> None:
        """SIGSTOP (Pause, db.clj:30-33)."""
        raise NotImplementedError

    def resume(self, test: dict, sess: Session, node: str) -> None:
        """SIGCONT."""
        raise NotImplementedError

    def primaries(self, test: dict) -> Sequence[str]:
        """Nodes currently believed primary (Primary, db.clj:35-42)."""
        raise NotImplementedError

    def setup_primary(self, test: dict, sess: Session, node: str) -> None:
        """One-time setup run on the first node (db.clj:35-42)."""
        pass

    def log_files(self, test: dict, sess: Session, node: str) -> Sequence[str]:
        """Paths to snarf after the run (LogFiles, db.clj:44-48)."""
        return []

    # -- capability sniffing --------------------------------------------

    def supports(self, capability: str) -> bool:
        """True if this DB overrides `capability` (kill/pause/primaries),
        the duck-typed analog of (satisfies? Kill db)."""
        mine = getattr(type(self), capability, None)
        return mine is not None and mine is not getattr(DB, capability, None)


class NoopDB(DB):
    """No database: for in-memory and generator-only tests
    (tests.clj noop-test)."""


noop = NoopDB()


def setup(test: dict, db: Optional[DB] = None) -> None:
    """Sets up the DB on all nodes in parallel, then primary setup on
    the first node (core.clj:164-173)."""
    db = db or test.get("db") or noop
    on_nodes(test, lambda s, n: db.setup(test, s, n))
    nodes = test.get("nodes") or []
    if nodes:
        on_nodes(
            test,
            lambda s, n: db.setup_primary(test, s, n),
            [nodes[0]],
        )


def teardown(test: dict, db: Optional[DB] = None) -> None:
    db = db or test.get("db") or noop
    on_nodes(test, lambda s, n: db.teardown(test, s, n))


def cycle(test: dict, db: Optional[DB] = None) -> None:
    """Teardown then setup, retried ≤3 times (db.clj:158-199)."""
    db = db or test.get("db") or noop
    last: Optional[Exception] = None
    for attempt in range(CYCLE_TRIES):
        try:
            teardown(test, db)
            setup(test, db)
            return
        except Exception as e:  # noqa: BLE001
            last = e
            log.warning(
                "db cycle failed (%d/%d): %r", attempt + 1, CYCLE_TRIES, e
            )
    raise last  # type: ignore[misc]


def snarf_logs(test: dict, dest_dir: str, db: Optional[DB] = None) -> None:
    """Downloads every node's log files into dest_dir/<node>/
    (core.clj:101-128)."""
    import os

    db = db or test.get("db") or noop

    def snarf(sess: Session, node: str) -> None:
        files = list(db.log_files(test, sess, node))
        if not files:
            return
        node_dir = os.path.join(dest_dir, str(node))
        os.makedirs(node_dir, exist_ok=True)
        try:
            sess.download(files, node_dir)
        except Exception as e:  # noqa: BLE001
            log.warning("couldn't snarf logs from %s: %r", node, e)

    on_nodes(test, snarf)
