"""Durable, tiered time-series history + in-process quantile rings.

The observatory's scrape surface (``prometheus_text``) is a point in
time; a standing `jepsen monitor` run needs *history* — what was the
verdict lag an hour ago, when did the queue start growing — that
survives process restarts and costs bounded disk over a week.  Two
pieces live here:

``SeriesStore``
    A crash-safe, tiered store of sampled series built on the same
    block framing as test files (store/format.py): each cadence tick
    appends one ``BLOCK_SERIES`` frame ``{"t": unix_s, "s": {name:
    value}}``.  A torn tail (SIGKILL mid-append) fails its CRC and is
    truncated by ``BlockWriter`` on reopen, so restarts resume cleanly.

    Disk stays bounded by two mechanisms: *downsampling tiers* and
    *rotation*.  Tier 0 holds raw samples at the monitor cadence;
    tier 1 aggregates each series over ``TIER1_S`` buckets
    (min/max/mean/last/n); tier 2 over ``TIER2_S``.  Each tier is one
    file plus at most one rotated predecessor (``.1``), rotated when it
    crosses ``max_tier_bytes`` — so a week-long run holds at most
    ``3 * 2 * max_tier_bytes`` of series history while tier 2 still
    spans days.  In-memory rings (bounded deques per series) are
    rebuilt from disk on open, which is what lets the ``/monitor``
    dashboard serve sparklines across a monitor-process restart.

``observe()`` / ``quantile_gauges()``
    A small in-process ring of raw observations per named series
    (e.g. every streaming verdict-lag sample), from which p50/p95/p99
    are computed on demand.  ``prometheus_text`` exports these as a
    Prometheus summary family and the SLO engine thresholds on the
    ``<name>.p95`` gauge instead of a single last-sample gauge.

``Sampler``
    The cadence collector: one ``sample()`` call flattens the
    telemetry registry (counters, gauges), SLO firing states, chip
    health, and per-pass profile medians (with the cost-model
    predicted-vs-measured drift ratio when a trained model is active)
    into one flat ``{name: float}`` dict and appends it to the store.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
from typing import Any, Iterator, Optional

from ..store.format import BLOCK_SERIES, MAGIC, BlockWriter, _read_block

log = logging.getLogger(__name__)

#: Default downsampling bucket widths (seconds).
TIER1_S = 30.0
TIER2_S = 300.0

#: Default per-tier file-size rotation threshold.  3 tiers x 2
#: generations x 4 MiB = 24 MiB worst-case disk for a week of history.
MAX_TIER_BYTES = 4 * 1024 * 1024

#: In-memory ring length per series per tier (what the dashboard can
#: sparkline without touching disk).
MEM_POINTS = 720

#: File-name stem for tier files inside the store directory.
SERIES_STEM = "series-t{tier}.jtpu"

_QUANTS = (("p50", 0.50), ("p95", 0.95), ("p99", 0.99))

#: Raw observations kept per quantile ring.
QUANT_RING = 1024

_rings_lock = threading.Lock()
_rings: dict[str, collections.deque] = {}


# ---------------------------------------------------------------------------
# Quantile rings (in-process, feeding prometheus summaries + SLO gauges)
# ---------------------------------------------------------------------------


def observe(name: str, value: Any) -> None:
    """Records one raw observation into `name`'s quantile ring."""
    try:
        v = float(value)
    except (TypeError, ValueError):
        return
    with _rings_lock:
        ring = _rings.get(name)
        if ring is None:
            ring = _rings[name] = collections.deque(maxlen=QUANT_RING)
        ring.append(v)


def quantiles(name: str) -> dict[str, float]:
    """{"p50": v, "p95": v, "p99": v} over `name`'s ring (empty when
    nothing observed)."""
    with _rings_lock:
        ring = _rings.get(name)
        vals = sorted(ring) if ring else []
    if not vals:
        return {}
    n = len(vals)
    out = {}
    for label, q in _QUANTS:
        # Nearest-rank on the sorted ring: robust, no interpolation.
        i = min(n - 1, max(0, int(round(q * (n - 1)))))
        out[label] = vals[i]
    return out


def quantile_gauges() -> dict[str, float]:
    """Flat {"<series>.p50": v, ...} over every observed ring — the
    extra-gauge dict SLO rules threshold on (a p95 over the ring is a
    far steadier alert input than the last single sample)."""
    with _rings_lock:
        names = list(_rings.keys())
    out: dict[str, float] = {}
    for name in names:
        for label, v in quantiles(name).items():
            out[f"{name}.{label}"] = v
    return out


def ring_names() -> list[str]:
    with _rings_lock:
        return sorted(_rings.keys())


def reset_rings() -> None:
    with _rings_lock:
        _rings.clear()


# ---------------------------------------------------------------------------
# Durable tiered store
# ---------------------------------------------------------------------------


def _iter_series_file(path: str) -> Iterator[dict]:
    """Every intact BLOCK_SERIES payload in `path`, in file order; torn
    or foreign blocks end the scan (the BlockWriter reopen truncates
    them before new writes, so readers just stop at the tear)."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return
    try:
        with open(path, "rb") as f:
            if f.read(len(MAGIC)) != MAGIC:
                return
            while True:
                rec = _read_block(f, size)
                if rec is None:
                    return
                _, btype, payload = rec
                if btype == BLOCK_SERIES and isinstance(payload, dict):
                    yield payload
    except OSError:
        return


def series_path(directory: str, tier: int = 0) -> str:
    """Tier file path inside a monitor store dir (no store needed)."""
    return os.path.join(directory, SERIES_STEM.format(tier=tier))


def _agg_value(v: Any) -> Optional[float]:
    """Numeric value of one stored sample: raw float for tier 0, the
    mean (falling back to last) for tier 1/2 aggregate rows."""
    if isinstance(v, dict):
        v = v.get("mean", v.get("last"))
    try:
        return float(v)
    except (TypeError, ValueError):
        return None


def read_disk_names(directory: str, tier: int = 0) -> list[str]:
    """Series names present in a tier's files on disk — the dashboard's
    cross-process listing (a detached `jepsen serve` has no SeriesStore
    in memory for the monitor's dir)."""
    names: set[str] = set()
    path = series_path(directory, tier)
    for p in (path + ".1", path):
        for payload in _iter_series_file(p):
            s = payload.get("s")
            if isinstance(s, dict):
                names.update(s.keys())
    return sorted(names)


def read_disk_series(
    directory: str,
    name: str,
    *,
    tier: int = 0,
    since: Optional[float] = None,
    limit: int = 0,
) -> list[tuple[float, float]]:
    """[(t, value)] for one series straight from a tier's files on
    disk, oldest first (rotated generation before current)."""
    pts: list[tuple[float, float]] = []
    path = series_path(directory, tier)
    for p in (path + ".1", path):
        for payload in _iter_series_file(p):
            s = payload.get("s")
            if not isinstance(s, dict) or name not in s:
                continue
            try:
                t = float(payload.get("t"))
            except (TypeError, ValueError):
                continue
            if since is not None and t < since:
                continue
            v = _agg_value(s[name])
            if v is not None:
                pts.append((t, v))
    if limit and len(pts) > limit:
        pts = pts[-limit:]
    return pts


class SeriesTail:
    """Incremental reader of one tier file for the SSE stream: each
    `poll()` returns the sample payloads appended since the last call.

    A half-written block (the writer is live, not crashed) fails its
    CRC and simply isn't consumed — the position stays put and the next
    poll picks it up once complete.  Rotation (the file replaced by
    `.1`) is detected by inode change or shrink: the old handle is
    drained to its tear, then the new file is followed from its top.
    """

    def __init__(self, path: str, *, from_end: bool = True):
        self.path = path
        self.f: Optional[Any] = None
        self.pos = 0
        self.ino: Optional[int] = None
        if from_end:
            # Swallow existing history: the SSE client bootstraps from
            # /api/series and only wants what comes after.
            self.poll()

    def _open(self) -> bool:
        try:
            f = open(self.path, "rb")
        except OSError:
            return False
        if f.read(len(MAGIC)) != MAGIC:
            f.close()
            return False
        self.f = f
        self.pos = len(MAGIC)
        try:
            self.ino = os.fstat(f.fileno()).st_ino
        except OSError:
            self.ino = None
        return True

    def _drain(self) -> list[dict]:
        out: list[dict] = []
        f = self.f
        if f is None:
            return out
        try:
            size = os.fstat(f.fileno()).st_size
            f.seek(self.pos)
            while True:
                rec = _read_block(f, size)
                if rec is None:
                    return out
                self.pos = f.tell()
                _, btype, payload = rec
                if btype == BLOCK_SERIES and isinstance(payload, dict):
                    out.append(payload)
        except OSError:
            return out

    def poll(self) -> list[dict]:
        out: list[dict] = []
        try:
            st: Optional[os.stat_result] = os.stat(self.path)
        except OSError:
            st = None
        if self.f is not None and st is not None and (
            st.st_ino != self.ino or st.st_size < self.pos
        ):
            out.extend(self._drain())  # finish the rotated generation
            self.close()
        if self.f is None:
            if st is None or not self._open():
                return out
        out.extend(self._drain())
        return out

    def close(self) -> None:
        if self.f is not None:
            try:
                self.f.close()
            except OSError as e:
                log.debug("series tail close failed: %r", e)
            self.f = None


class _Agg:
    """One open downsampling bucket: per-series [min, max, sum, n, last]."""

    __slots__ = ("bucket", "stats")

    def __init__(self, bucket: int):
        self.bucket = bucket
        self.stats: dict[str, list] = {}

    def add(self, samples: dict[str, float]) -> None:
        for name, v in samples.items():
            st = self.stats.get(name)
            if st is None:
                self.stats[name] = [v, v, v, 1, v]
            else:
                if v < st[0]:
                    st[0] = v
                if v > st[1]:
                    st[1] = v
                st[2] += v
                st[3] += 1
                st[4] = v

    def payload(self) -> dict[str, dict]:
        return {
            name: {
                "min": st[0],
                "max": st[1],
                "mean": st[2] / st[3],
                "last": st[4],
                "n": st[3],
            }
            for name, st in self.stats.items()
        }


class SeriesStore:
    """The durable tiered series store for one monitor directory.

    Thread-safe: `append` may race `query` (the web handler samples
    from a different thread than the monitor loop)."""

    def __init__(
        self,
        directory: str,
        *,
        max_tier_bytes: int = MAX_TIER_BYTES,
        mem_points: int = MEM_POINTS,
        tier1_s: float = TIER1_S,
        tier2_s: float = TIER2_S,
    ):
        self.directory = directory
        self.max_tier_bytes = max_tier_bytes
        self.mem_points = mem_points
        self.tier_widths = (0.0, float(tier1_s), float(tier2_s))
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)
        #: tier -> {series name -> deque[(t, value)]}
        self._mem: list[dict[str, collections.deque]] = [{}, {}, {}]  # guarded-by: self._lock
        #: open aggregation buckets for tiers 1 and 2 (index by tier).
        self._aggs: list[Optional[_Agg]] = [None, None, None]  # guarded-by: self._lock
        self._writers: list[Optional[BlockWriter]] = [None, None, None]  # guarded-by: self._lock
        self._rebuild()

    # -- paths / files ------------------------------------------------------

    def tier_path(self, tier: int) -> str:
        return os.path.join(self.directory, SERIES_STEM.format(tier=tier))

    def _writer(self, tier: int) -> BlockWriter:
        w = self._writers[tier]
        if w is None:
            w = self._writers[tier] = BlockWriter(self.tier_path(tier))
        return w

    def _rebuild(self) -> None:
        """Reloads the in-memory rings from disk (rotated generation
        first, then current) so a restarted monitor serves continuous
        sparklines."""
        for tier in range(3):
            rings: dict[str, collections.deque] = {}
            path = self.tier_path(tier)
            for p in (path + ".1", path):
                for payload in _iter_series_file(p):
                    t = payload.get("t")
                    samples = payload.get("s")
                    if not isinstance(samples, dict):
                        continue
                    self._mem_add(rings, t, samples, tier)
            self._mem[tier] = rings

    def _mem_add(
        self, rings: dict, t: Any, samples: dict, tier: int
    ) -> None:
        try:
            t = float(t)
        except (TypeError, ValueError):
            return
        for name, v in samples.items():
            if isinstance(v, dict):  # tier 1/2 aggregate rows
                v = v.get("mean", v.get("last"))
            try:
                v = float(v)
            except (TypeError, ValueError):
                continue
            ring = rings.get(name)
            if ring is None:
                ring = rings[name] = collections.deque(
                    maxlen=self.mem_points
                )
            ring.append((t, v))

    def _rotate_if_needed(self, tier: int) -> None:
        w = self._writers[tier]
        if w is None:
            return
        try:
            if w.f.tell() < self.max_tier_bytes:
                return
            # fsync before the close+rename: the rotated-out `.1`
            # generation is the archive readers trust — renaming bytes
            # the kernel hasn't durably written would let a power cut
            # eat the end of a file we just promoted to "sealed".
            w.sync()
            w.close()
        except (OSError, ValueError):
            pass
        self._writers[tier] = None
        path = self.tier_path(tier)
        try:
            os.replace(path, path + ".1")
        except OSError as e:
            log.warning("series tier %d rotate failed: %r", tier, e)

    # -- write path ---------------------------------------------------------

    def append(
        self, samples: dict[str, Any], t: Optional[float] = None
    ) -> None:
        """Appends one cadence tick of raw samples.  Non-numeric values
        are dropped; tiers 1/2 flush their previous bucket when `t`
        crosses a bucket boundary."""
        if t is None:
            t = time.time()
        clean: dict[str, float] = {}
        for name, v in samples.items():
            try:
                clean[name] = float(v)
            except (TypeError, ValueError):
                continue
        if not clean:
            return
        with self._lock:
            self._append_tier(0, t, clean)
            self._mem_add(self._mem[0], t, clean, 0)
            for tier in (1, 2):
                self._roll_agg(tier, t, clean)

    def _append_tier(self, tier: int, t: float, payload: dict) -> None:
        try:
            w = self._writer(tier)
            w.append(BLOCK_SERIES, {"t": round(t, 3), "s": payload})
            self._rotate_if_needed(tier)
        except OSError as e:
            log.warning("series tier %d append failed: %r", tier, e)

    def _roll_agg(self, tier: int, t: float, samples: dict) -> None:
        width = self.tier_widths[tier]
        bucket = int(t // width)
        agg = self._aggs[tier]
        if agg is not None and agg.bucket != bucket:
            self._flush_agg(tier, agg)
            agg = None
        if agg is None:
            agg = self._aggs[tier] = _Agg(bucket)
        agg.add(samples)

    def _flush_agg(self, tier: int, agg: _Agg) -> None:
        width = self.tier_widths[tier]
        t_end = (agg.bucket + 1) * width
        payload = agg.payload()
        self._append_tier(tier, t_end, payload)
        self._mem_add(self._mem[tier], t_end, payload, tier)

    def flush(self) -> None:
        """Flushes open aggregation buckets and fsyncs every tier —
        call on orderly shutdown (crash loses only open buckets and the
        torn tail)."""
        with self._lock:
            for tier in (1, 2):
                agg = self._aggs[tier]
                if agg is not None and agg.stats:
                    self._flush_agg(tier, agg)
                    self._aggs[tier] = None
            for w in self._writers:
                if w is not None:
                    try:
                        w.sync()
                    except OSError:
                        pass

    def close(self) -> None:
        self.flush()
        with self._lock:
            for i, w in enumerate(self._writers):
                if w is not None:
                    try:
                        w.close()
                    except OSError as e:
                        log.debug("series tier %d close failed: %r",
                                  i, e)
                    self._writers[i] = None

    # -- read path ----------------------------------------------------------

    def names(self) -> list[str]:
        with self._lock:
            seen: set[str] = set()
            for rings in self._mem:
                seen.update(rings.keys())
            return sorted(seen)

    def query(
        self,
        name: str,
        *,
        tier: int = 0,
        since: Optional[float] = None,
        limit: int = 0,
    ) -> list[tuple[float, float]]:
        """[(t, value)] for one series from the in-memory ring of a
        tier, oldest first.  `since` filters by timestamp; `limit`
        keeps the newest N."""
        with self._lock:
            ring = self._mem[tier].get(name)
            pts = list(ring) if ring else []
        if since is not None:
            pts = [p for p in pts if p[0] >= since]
        if limit and len(pts) > limit:
            pts = pts[-limit:]
        return pts

    def disk_bytes(self) -> int:
        total = 0
        for tier in range(3):
            path = self.tier_path(tier)
            for p in (path, path + ".1"):
                try:
                    total += os.path.getsize(p)
                except OSError:
                    pass
        return total

    def resident_points(self) -> int:
        """Total in-memory ring points across every tier and series —
        the bounded number the memory-ceiling test asserts on."""
        with self._lock:
            return sum(
                len(r) for rings in self._mem for r in rings.values()
            )


# ---------------------------------------------------------------------------
# Cadence sampler
# ---------------------------------------------------------------------------

#: Gauge prefixes sampled raw into the store every tick (counters are
#: stored as their cumulative values; the dashboard diffs for rates).
_SKIP_PREFIXES = ("lint.",)


def _profile_medians(path: str, *, tail: int = 400) -> dict[str, float]:
    """{"profile.<pass>.median-s": v} over the newest `tail` records of
    a profile store, plus the cost-model drift ratio
    (measured / predicted, median over the same window) when a trained
    model covers the pass."""
    from ..plan import costmodel

    try:
        from . import profile as _profile

        records = _profile.read(path)[-tail:]
    except Exception:  # noqa: BLE001 — sampling never raises
        return {}
    if not records:
        return {}
    by_pass: dict[str, list[float]] = {}
    ratios: list[float] = []
    model = None
    try:
        model = costmodel.active_model()
    except Exception:  # noqa: BLE001
        model = None
    for rec in records:
        measured = costmodel.record_cost_s(rec)
        if measured <= 0:
            continue
        by_pass.setdefault(rec["pass"], []).append(measured)
        if model is not None:
            try:
                pred = model.predict_s(
                    rec["pass"], rec["features"], rec["plan"]
                )
            except Exception:  # noqa: BLE001
                pred = None
            if pred is not None and pred > 0:
                ratios.append(measured / pred)
    out: dict[str, float] = {}
    for name, vals in by_pass.items():
        vals.sort()
        out[f"profile.{name}.median-s"] = vals[len(vals) // 2]
    if ratios:
        ratios.sort()
        out["monitor.cost-drift-ratio"] = ratios[len(ratios) // 2]
    return out


class Sampler:
    """Collects one flat sample dict per cadence tick and appends it to
    a SeriesStore.  Profile medians (a file read) refresh every
    `profile_every` ticks, not every tick."""

    def __init__(
        self,
        store: SeriesStore,
        *,
        profile_path: Optional[str] = None,
        profile_every: int = 6,
    ):
        self.store = store
        self.profile_path = profile_path
        self.profile_every = max(1, profile_every)
        self._ticks = 0
        self._profile_cache: dict[str, float] = {}

    def collect(self, extra: Optional[dict] = None) -> dict[str, float]:
        from . import summary as _summary
        from . import slo as _slo

        samples: dict[str, float] = {}
        try:
            summ = _summary()
            for name, v in summ.get("counters", {}).items():
                if name.startswith(_SKIP_PREFIXES):
                    continue
                try:
                    samples[name] = float(v)
                except (TypeError, ValueError):
                    continue
            for name, g in summ.get("gauges", {}).items():
                try:
                    samples[name] = float(g["last"])
                except (TypeError, ValueError, KeyError):
                    continue
        except Exception:  # noqa: BLE001 — sampling never raises
            pass
        try:
            for name, v in _slo.firing_gauges().items():
                samples[f"slo.{name}"] = float(v)
        except Exception:  # noqa: BLE001
            pass
        for name, v in quantile_gauges().items():
            samples[name] = v
        self._ticks += 1
        if self.profile_path and (
            self._ticks % self.profile_every == 1 or not self._profile_cache
        ):
            self._profile_cache = _profile_medians(self.profile_path)
        samples.update(self._profile_cache)
        if extra:
            for name, v in extra.items():
                try:
                    samples[name] = float(v)
                except (TypeError, ValueError):
                    continue
        return samples

    def sample(
        self, extra: Optional[dict] = None, t: Optional[float] = None
    ) -> dict[str, float]:
        samples = self.collect(extra)
        if samples:
            self.store.append(samples, t)
        return samples
