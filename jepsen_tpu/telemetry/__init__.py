"""Run-scoped telemetry: spans, counters, gauges, Chrome-trace export.

The checker's north star is serving heavy traffic as fast as the
hardware allows (ROADMAP.md); the prerequisite is knowing where time
goes.  This module is the zero-dependency substrate: a process-wide,
thread-safe registry of

  * **spans** — `with span("wgl.block"):` timed sections, aggregated
    per name (count / total / max) and appended to a bounded trace-event
    buffer;
  * **counters** — monotonically accumulated values
    (`count("wgl.h2d_bytes", n)`);
  * **gauges** — last/min/max samples (`gauge("wgl.beam", B)`).

Everything is **off by default**: set ``JEPSEN_TELEMETRY=1`` (or call
`enable()`) to record.  When disabled, `span()` returns a shared no-op
context manager and `count`/`gauge` return immediately after one module
bool check, so hot paths pay ~nothing — bench.py's throughput contract
(< 2% regression with telemetry unset) is guarded by
tests/test_telemetry.py.

Two exporters, both written by `export(dir)`:

  * ``telemetry.json`` — the `summary()` dict: per-span statistics,
    counters, gauges.  `tools/trace_view.py` pretty-prints it.
  * ``trace.json`` — Chrome trace-event format ("X" complete events,
    microsecond timestamps), loadable in Perfetto (https://ui.perfetto.dev)
    or chrome://tracing for a per-thread flame view of a run.

Span names are dotted ``subsystem.phase`` (taxonomy in doc/design.md):
``lifecycle.*`` (core.py run phases), ``interpreter.*`` (per-op worker
dispatch), ``checker.<Name>`` (check_safe), ``wgl.*`` (device search:
compile vs execute, witness tiers, stream), ``bench.*`` (bench.py
phases).  The registry is process-wide on purpose — a run's worker
threads, checker pools, and device callbacks all land in one trace.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Optional

log = logging.getLogger(__name__)

ENV_VAR = "JEPSEN_TELEMETRY"

#: Trace-event buffer cap: a 1M-op run with per-op spans would otherwise
#: grow without bound.  Aggregated span stats keep counting past the
#: cap; only the per-event trace detail is dropped (and reported in
#: `summary()["trace_events_dropped"]`).
MAX_TRACE_EVENTS = 200_000

_enabled = os.environ.get(ENV_VAR, "") not in ("", "0", "false", "no")
_lock = threading.Lock()

#: Wall-clock epoch (ns) matching the perf_counter origin below, so
#: trace timestamps can be related to log lines.
_T0_NS = time.perf_counter_ns()
_T0_WALL = time.time()

# name -> [count, total_ns, max_ns]
_span_stats: dict[str, list] = {}
_counters: dict[str, Any] = {}
# name -> [last, min, max, n_samples]
_gauges: dict[str, list] = {}
# (name, t0_ns_rel, dur_ns, tid, thread_name, attrs-or-None)
_events: list[tuple] = []
_events_dropped = 0


def enabled() -> bool:
    return _enabled


def enable(on: bool = True) -> None:
    """Programmatic override of JEPSEN_TELEMETRY (tests, embedding)."""
    global _enabled
    _enabled = bool(on)


def reset() -> None:
    """Clears every registry — the start of a run scope."""
    global _events_dropped
    with _lock:
        _span_stats.clear()
        _counters.clear()
        _gauges.clear()
        _events.clear()
        _events_dropped = 0


class _NoopSpan:
    """Shared do-nothing span for the disabled path."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False

    def set(self, **attrs: Any) -> None:
        pass


_NOOP = _NoopSpan()


class Span:
    __slots__ = ("name", "attrs", "_t0")

    def __init__(self, name: str, attrs: Optional[dict]):
        self.name = name
        self.attrs = attrs

    def set(self, **attrs: Any) -> None:
        """Attaches attributes mid-span (e.g. a result computed inside)."""
        if self.attrs is None:
            self.attrs = attrs
        else:
            self.attrs.update(attrs)

    def __enter__(self) -> "Span":
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc: Any) -> bool:
        global _events_dropped
        t0 = self._t0
        dur = time.perf_counter_ns() - t0
        t = threading.current_thread()
        with _lock:
            st = _span_stats.get(self.name)
            if st is None:
                _span_stats[self.name] = [1, dur, dur]
            else:
                st[0] += 1
                st[1] += dur
                if dur > st[2]:
                    st[2] = dur
            if len(_events) < MAX_TRACE_EVENTS:
                _events.append(
                    (self.name, t0 - _T0_NS, dur, t.ident, t.name,
                     self.attrs)
                )
            else:
                _events_dropped += 1
        return False


def span(name: str, **attrs: Any) -> Any:
    """Context manager timing a named section.  Disabled -> shared no-op.

    Hot loops that would pay for building `attrs` should gate on
    `enabled()` instead of relying on this check alone."""
    if not _enabled:
        return _NOOP
    return Span(name, attrs or None)


def count(name: str, n: Any = 1) -> None:
    """Adds `n` to a named counter (monotone accumulator)."""
    if not _enabled:
        return
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def gauge(name: str, value: Any) -> None:
    """Samples a named gauge, tracking last/min/max."""
    if not _enabled:
        return
    with _lock:
        g = _gauges.get(name)
        if g is None:
            _gauges[name] = [value, value, value, 1]
        else:
            g[0] = value
            if value < g[1]:
                g[1] = value
            if value > g[2]:
                g[2] = value
            g[3] += 1


def summary() -> dict:
    """The aggregate view exported as telemetry.json."""
    with _lock:
        spans = {
            name: {
                "count": c,
                "total_s": round(t / 1e9, 6),
                "max_s": round(m / 1e9, 6),
                "mean_s": round(t / c / 1e9, 6),
            }
            for name, (c, t, m) in _span_stats.items()
        }
        return {
            "enabled": _enabled,
            "recorded_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "spans": spans,
            "counters": dict(_counters),
            "gauges": {
                name: {"last": g[0], "min": g[1], "max": g[2],
                       "samples": g[3]}
                for name, g in _gauges.items()
            },
            "trace_events": len(_events),
            "trace_events_dropped": _events_dropped,
        }


def top_spans(n: int = 5) -> list[tuple[str, dict]]:
    """The n spans with the largest total time, descending — the
    run-summary 'where did the time go' line."""
    s = summary()["spans"]
    return sorted(
        s.items(), key=lambda kv: kv[1]["total_s"], reverse=True
    )[:n]


def phases(prefix: str) -> dict[str, float]:
    """{short-name: total_s} of every span under `prefix.` — bench.py
    embeds phases("bench") in its JSON line."""
    pre = prefix + "."
    return {
        name[len(pre):]: st["total_s"]
        for name, st in summary()["spans"].items()
        if name.startswith(pre)
    }


#: Counter families recording robustness events: watchdog op timeouts,
#: drain stragglers, blown checker budgets, device degradation-ladder
#: steps, and daemon start retries.  One list so bench.py, the web
#: /telemetry/ page, and core.py surface the same set.
RESILIENCE_COUNTER_PREFIXES = (
    "interpreter.op-timeouts",
    "interpreter.drain-timeouts",
    "checker.budget-exceeded",
    "wgl.degrade.",
    "daemon.start-retries",
    # Fault-ledger events: nemesis.residue.* (stranded iptables/tc/
    # clock state found by the post-teardown sweep), nemesis.teardown.
    # failed, nemesis.ledger.{intents,healed}.
    "nemesis.",
    # Node health: node.{suspect,quarantined,readmitted}, node.probe.*,
    # node.signal.*, node.setup.failed.
    "node.",
    # Transport flapping: net.reconnects, net.retry.exhausted.
    "net.",
    # Per-worker client open failures against a dead/dying node.
    "client.open.",
    # Remote checking degraded to in-process (checkerd unreachable or
    # refusing the request) and server-side blown request budgets.
    "checkerd.fallback",
    "checkerd.budget-exceeded",
)


def resilience_counters() -> dict[str, Any]:
    """The subset of counters that record degradation/retry/timeout
    events — the resilience trajectory a perf regression in robustness
    shows up in (empty when telemetry is disabled or nothing fired)."""
    with _lock:
        items = dict(_counters)
    return {
        k: v
        for k, v in sorted(items.items())
        if any(k.startswith(p) for p in RESILIENCE_COUNTER_PREFIXES)
    }


#: Tier-population counters of the independent checker's settling
#: ladder (parallel/independent.py): how many keys each tier decided
#: (wgl.settle.{stream-proven, batched-proven, batched-refuted,
#: cpu-settled, memo-hit}).  The shape of a run's work: an all-valid
#: workload is all stream-proven; an invalid-heavy one shows its bad
#: keys split across device refutations, CPU settles, and memo hits.
SETTLE_COUNTER_PREFIX = "wgl.settle."


def settle_counters() -> dict[str, Any]:
    """The wgl.settle.* counters — per-tier key populations of the
    cohort-settling ladder (empty when telemetry is disabled or no
    independent check ran)."""
    with _lock:
        items = dict(_counters)
    return {
        k: v
        for k, v in sorted(items.items())
        if k.startswith(SETTLE_COUNTER_PREFIX)
    }


def chrome_trace() -> dict:
    """The recorded spans as a Chrome trace-event dict ("X" complete
    events, µs timestamps) — Perfetto / chrome://tracing loadable."""
    with _lock:
        events = list(_events)
    pid = os.getpid()
    out = []
    tnames: dict[int, str] = {}
    for name, t0_rel, dur, tid, tname, attrs in events:
        ev: dict[str, Any] = {
            "name": name,
            "cat": name.split(".", 1)[0],
            "ph": "X",
            "ts": t0_rel / 1000.0,
            "dur": dur / 1000.0,
            "pid": pid,
            "tid": tid,
        }
        if attrs:
            ev["args"] = attrs
        out.append(ev)
        tnames[tid] = tname
    for tid, tname in tnames.items():
        out.append({
            "name": "thread_name",
            "ph": "M",
            "pid": pid,
            "tid": tid,
            "args": {"name": tname},
        })
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "jepsen_tpu.telemetry",
            "t0_unix_s": _T0_WALL,
        },
    }


def export(directory: str) -> Optional[tuple[str, str]]:
    """Writes telemetry.json + trace.json into `directory`; returns the
    two paths, or None when disabled or on a write failure (a side
    output must never change a run's outcome)."""
    if not _enabled:
        return None
    try:
        os.makedirs(directory, exist_ok=True)
        sum_path = os.path.join(directory, "telemetry.json")
        trace_path = os.path.join(directory, "trace.json")
        with open(sum_path, "w") as f:
            json.dump(summary(), f, indent=2, sort_keys=True,
                      default=repr)
            f.write("\n")
        with open(trace_path, "w") as f:
            json.dump(chrome_trace(), f, default=repr)
            f.write("\n")
        return sum_path, trace_path
    except OSError as e:
        log.warning("telemetry export to %s failed: %r", directory, e)
        return None


def log_top_spans(logger: logging.Logger, n: int = 5) -> None:
    """INFO-logs the top-n spans by total time (the run summary line)."""
    if not _enabled:
        return
    tops = top_spans(n)
    if not tops:
        return
    parts = [
        f"{name} {st['total_s']:.3f}s x{st['count']}"
        for name, st in tops
    ]
    logger.info("telemetry top spans: %s", "; ".join(parts))
