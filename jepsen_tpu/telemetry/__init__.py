"""Run-scoped telemetry: spans, counters, gauges, Chrome-trace export.

The checker's north star is serving heavy traffic as fast as the
hardware allows (ROADMAP.md); the prerequisite is knowing where time
goes.  This module is the zero-dependency substrate: a process-wide,
thread-safe registry of

  * **spans** — `with span("wgl.block"):` timed sections, aggregated
    per name (count / total / max) and appended to a bounded trace-event
    buffer;
  * **counters** — monotonically accumulated values
    (`count("wgl.h2d_bytes", n)`);
  * **gauges** — last/min/max samples (`gauge("wgl.beam", B)`).

Everything is **off by default**: set ``JEPSEN_TELEMETRY=1`` (or call
`enable()`) to record.  When disabled, `span()` returns a shared no-op
context manager and `count`/`gauge` return immediately after one module
bool check, so hot paths pay ~nothing — bench.py's throughput contract
(< 2% regression with telemetry unset) is guarded by
tests/test_telemetry.py.

Two exporters, both written by `export(dir)`:

  * ``telemetry.json`` — the `summary()` dict: per-span statistics,
    counters, gauges.  `tools/trace_view.py` pretty-prints it.
  * ``trace.json`` — Chrome trace-event format ("X" complete events,
    microsecond timestamps), loadable in Perfetto (https://ui.perfetto.dev)
    or chrome://tracing for a per-thread flame view of a run.

Span names are dotted ``subsystem.phase`` (taxonomy in doc/design.md):
``lifecycle.*`` (core.py run phases), ``interpreter.*`` (per-op worker
dispatch), ``checker.<Name>`` (check_safe), ``wgl.*`` (device search:
compile vs execute, witness tiers, stream), ``bench.*`` (bench.py
phases).  The registry is process-wide on purpose — a run's worker
threads, checker pools, and device callbacks all land in one trace.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
import uuid
from typing import Any, Callable, Optional

log = logging.getLogger(__name__)

ENV_VAR = "JEPSEN_TELEMETRY"

#: Trace-event buffer cap: a 1M-op run with per-op spans would otherwise
#: grow without bound.  Aggregated span stats keep counting past the
#: cap; only the per-event trace detail is dropped (and reported in
#: `summary()["trace_events_dropped"]`).
MAX_TRACE_EVENTS = 200_000

_enabled = os.environ.get(ENV_VAR, "") not in ("", "0", "false", "no")
_lock = threading.Lock()

#: Wall-clock epoch (ns) matching the perf_counter origin below, so
#: trace timestamps can be related to log lines.
_T0_NS = time.perf_counter_ns()
_T0_WALL = time.time()

# name -> [count, total_ns, max_ns]
_span_stats: dict[str, list] = {}
_counters: dict[str, Any] = {}
# name -> [last, min, max, n_samples]
_gauges: dict[str, list] = {}
# (name, t0_ns_rel, dur_ns, tid, thread_name, attrs-or-None)
_events: list[tuple] = []
_events_dropped = 0

#: Events adopted from *other* processes (checkerd RESULT meta["spans"])
#: so a run's trace.json shows daemon-side work under its own pid.
#: Wall-clock timestamped dicts, bounded to keep adoption cheap.
MAX_FOREIGN_EVENTS = 4096
_foreign: list[dict] = []

# Trace context: every run scope mints a trace id; spans created by
# work done *for* that run — in this process or a daemon — carry it so
# tools/trace_merge.py can fuse the processes into one timeline.
_trace_id: Optional[str] = None
_parent_span: Optional[str] = None

#: Per-thread span-exit hook: profile.capture() installs a callback
#: `(span_name, dur_ns) -> None` to fold compile/execute span durations
#: into the active pass record without touching the hot-path registry.
_pass_hook = threading.local()


def enabled() -> bool:
    return _enabled


def enable(on: bool = True) -> None:
    """Programmatic override of JEPSEN_TELEMETRY (tests, embedding)."""
    global _enabled
    _enabled = bool(on)


def reset() -> None:
    """Clears every registry — the start of a run scope."""
    global _events_dropped, _trace_id, _parent_span
    with _lock:
        _span_stats.clear()
        _counters.clear()
        _gauges.clear()
        _events.clear()
        _foreign.clear()
        _events_dropped = 0
        _trace_id = None
        _parent_span = None


#: Counter prefixes whose values outlive a single run: the search loop
#: and the online/streaming path accumulate across many core.run scopes
#: (each of which resets telemetry), and checkerd fleet counters belong
#: to the daemon, not any one request.  `scoped_reset` keeps these.
FLEET_COUNTER_PREFIXES = (
    "nemesis.search.",
    "wgl.online.",
    "wgl.plan.",
    "checkerd.",
    "router.",
    "ingest.",
    "chaos.",
)


def scoped_reset(
    prefix_keep: tuple = FLEET_COUNTER_PREFIXES,
) -> None:
    """`reset()` that preserves counters under `prefix_keep` — the
    start-of-run scope for processes embedded in a longer-lived loop
    (nemesis search, streaming feeds, checkerd clients), where a plain
    reset would silently zero fleet-scoped counters."""
    global _events_dropped, _trace_id, _parent_span
    with _lock:
        kept = {
            k: v for k, v in _counters.items()
            if any(k.startswith(p) for p in prefix_keep)
        }
        _span_stats.clear()
        _counters.clear()
        _counters.update(kept)
        _gauges.clear()
        _events.clear()
        _foreign.clear()
        _events_dropped = 0
        _trace_id = None
        _parent_span = None


# ---------------------------------------------------------------------------
# Trace context
# ---------------------------------------------------------------------------


def new_span_id() -> str:
    """A fresh 16-hex span id (also used for trace ids)."""
    return uuid.uuid4().hex[:16]


def trace_id() -> str:
    """The current trace id, minted lazily per run scope."""
    global _trace_id
    with _lock:
        if _trace_id is None:
            _trace_id = new_span_id()
        return _trace_id


def trace_context() -> dict:
    """The propagatable context: ``{"trace-id", "parent-span"}``.
    Sent over the checkerd wire (SUBMIT "trace" field), stored in
    `test["trace-parent"]` for search child runs, and stamped onto
    daemon-side spans so they nest under the originating run."""
    return {"trace-id": trace_id(), "parent-span": _parent_span}


def seed_trace(ctx: Optional[dict]) -> None:
    """Adopts a propagated trace context (or mints a fresh one when
    `ctx` is falsy/malformed) — called at the start of a run scope."""
    global _trace_id, _parent_span
    tid = psp = None
    if isinstance(ctx, dict):
        tid = ctx.get("trace-id") or ctx.get("trace_id")
        psp = ctx.get("parent-span") or ctx.get("parent_span")
    with _lock:
        _trace_id = str(tid) if tid else new_span_id()
        _parent_span = str(psp) if psp else None


def set_parent_span(span_id: Optional[str]) -> None:
    """Sets the span id subsequent propagated work should nest under
    (core.analyze sets its analyze span's id here)."""
    global _parent_span
    _parent_span = span_id


def set_pass_hook(cb: Optional[Callable[[str, int], None]]) -> None:
    """Installs (or clears, with None) this thread's span-exit hook."""
    _pass_hook.cb = cb


class _NoopSpan:
    """Shared do-nothing span for the disabled path."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc: Any) -> bool:
        return False

    def set(self, **attrs: Any) -> None:
        pass


_NOOP = _NoopSpan()


class Span:
    __slots__ = ("name", "attrs", "_t0")

    def __init__(self, name: str, attrs: Optional[dict]):
        self.name = name
        self.attrs = attrs

    def set(self, **attrs: Any) -> None:
        """Attaches attributes mid-span (e.g. a result computed inside)."""
        if self.attrs is None:
            self.attrs = attrs
        else:
            self.attrs.update(attrs)

    def __enter__(self) -> "Span":
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc: Any) -> bool:
        global _events_dropped
        t0 = self._t0
        dur = time.perf_counter_ns() - t0
        t = threading.current_thread()
        with _lock:
            st = _span_stats.get(self.name)
            if st is None:
                _span_stats[self.name] = [1, dur, dur]
            else:
                st[0] += 1
                st[1] += dur
                if dur > st[2]:
                    st[2] = dur
            if len(_events) < MAX_TRACE_EVENTS:
                _events.append(
                    (self.name, t0 - _T0_NS, dur, t.ident, t.name,
                     self.attrs)
                )
            else:
                _events_dropped += 1
        cb = getattr(_pass_hook, "cb", None)
        if cb is not None:
            try:
                cb(self.name, dur)
            except Exception:  # noqa: BLE001 — profiling must not
                # change a pass's outcome, but a silently dead hook
                # means silently missing cost records.
                log.debug("span-exit hook failed for %s",
                          self.name, exc_info=True)
        return False


def span(name: str, **attrs: Any) -> Any:
    """Context manager timing a named section.  Disabled -> shared no-op.

    Hot loops that would pay for building `attrs` should gate on
    `enabled()` instead of relying on this check alone."""
    if not _enabled:
        return _NOOP
    return Span(name, attrs or None)


def count(name: str, n: Any = 1) -> None:
    """Adds `n` to a named counter (monotone accumulator)."""
    if not _enabled:
        return
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def counter_value(name: str) -> float:
    """The current value of one named counter (0 when absent) — the
    cheap single-counter read rate derivations (monitor cadence) need
    without building the whole summary()."""
    with _lock:
        v = _counters.get(name, 0)
    return float(v) if isinstance(v, (int, float)) else 0.0


def gauge(name: str, value: Any) -> None:
    """Samples a named gauge, tracking last/min/max."""
    if not _enabled:
        return
    with _lock:
        g = _gauges.get(name)
        if g is None:
            _gauges[name] = [value, value, value, 1]
        else:
            g[0] = value
            if value < g[1]:
                g[1] = value
            if value > g[2]:
                g[2] = value
            g[3] += 1


def summary() -> dict:
    """The aggregate view exported as telemetry.json."""
    with _lock:
        spans = {
            name: {
                "count": c,
                "total_s": round(t / 1e9, 6),
                "max_s": round(m / 1e9, 6),
                "mean_s": round(t / c / 1e9, 6),
            }
            for name, (c, t, m) in _span_stats.items()
        }
        return {
            "enabled": _enabled,
            "recorded_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "trace_id": _trace_id,
            "spans": spans,
            "counters": dict(_counters),
            "gauges": {
                name: {"last": g[0], "min": g[1], "max": g[2],
                       "samples": g[3]}
                for name, g in _gauges.items()
            },
            "trace_events": len(_events),
            "trace_events_dropped": _events_dropped,
        }


def top_spans(n: int = 5) -> list[tuple[str, dict]]:
    """The n spans with the largest total time, descending — the
    run-summary 'where did the time go' line."""
    s = summary()["spans"]
    return sorted(
        s.items(), key=lambda kv: kv[1]["total_s"], reverse=True
    )[:n]


def phases(prefix: str) -> dict[str, float]:
    """{short-name: total_s} of every span under `prefix.` — bench.py
    embeds phases("bench") in its JSON line."""
    pre = prefix + "."
    return {
        name[len(pre):]: st["total_s"]
        for name, st in summary()["spans"].items()
        if name.startswith(pre)
    }


#: Counter families recording robustness events: watchdog op timeouts,
#: drain stragglers, blown checker budgets, device degradation-ladder
#: steps, and daemon start retries.  One list so bench.py, the web
#: /telemetry/ page, and core.py surface the same set.
RESILIENCE_COUNTER_PREFIXES = (
    "interpreter.op-timeouts",
    "interpreter.drain-timeouts",
    "checker.budget-exceeded",
    "wgl.degrade.",
    "daemon.start-retries",
    # Fault-ledger events: nemesis.residue.* (stranded iptables/tc/
    # clock state found by the post-teardown sweep), nemesis.teardown.
    # failed, nemesis.ledger.{intents,healed}.
    "nemesis.",
    # Node health: node.{suspect,quarantined,readmitted}, node.probe.*,
    # node.signal.*, node.setup.failed.
    "node.",
    # Transport flapping: net.reconnects, net.retry.exhausted.
    "net.",
    # Per-worker client open failures against a dead/dying node.
    "client.open.",
    # Remote checking degraded to in-process (checkerd unreachable or
    # refusing the request) and server-side blown request budgets.
    "checkerd.fallback",
    "checkerd.budget-exceeded",
)


def resilience_counters() -> dict[str, Any]:
    """The subset of counters that record degradation/retry/timeout
    events — the resilience trajectory a perf regression in robustness
    shows up in (empty when telemetry is disabled or nothing fired)."""
    with _lock:
        items = dict(_counters)
    return {
        k: v
        for k, v in sorted(items.items())
        if any(k.startswith(p) for p in RESILIENCE_COUNTER_PREFIXES)
    }


#: Tier-population counters of the independent checker's settling
#: ladder (parallel/independent.py): how many keys each tier decided
#: (wgl.settle.{stream-proven, batched-proven, batched-refuted,
#: cpu-settled, memo-hit}).  The shape of a run's work: an all-valid
#: workload is all stream-proven; an invalid-heavy one shows its bad
#: keys split across device refutations, CPU settles, and memo hits.
SETTLE_COUNTER_PREFIX = "wgl.settle."


def settle_counters() -> dict[str, Any]:
    """The wgl.settle.* counters — per-tier key populations of the
    cohort-settling ladder (empty when telemetry is disabled or no
    independent check ran)."""
    with _lock:
        items = dict(_counters)
    return {
        k: v
        for k, v in sorted(items.items())
        if k.startswith(SETTLE_COUNTER_PREFIX)
    }


# ---------------------------------------------------------------------------
# Cross-process span transport
# ---------------------------------------------------------------------------


def event_mark() -> int:
    """An opaque cursor into the trace-event buffer; pass it to
    `events_between` to capture the events recorded since."""
    with _lock:
        return len(_events)


def events_between(mark: int, limit: int = 256) -> list[dict]:
    """The events appended since `mark`, as JSON-able dicts with
    wall-clock timestamps — the payload checkerd attaches to RESULT
    meta["spans"] so clients can adopt daemon-side work into their own
    traces.  Bounded to `limit`; newest events win (the interesting
    spans — cohort, settle tiers — close last)."""
    with _lock:
        evs = _events[mark:]
    out = []
    for name, t0_rel, dur, tid, tname, attrs in evs[-limit:]:
        ev: dict[str, Any] = {
            "name": name,
            "t0_unix_s": _T0_WALL + t0_rel / 1e9,
            "dur_s": dur / 1e9,
            "tid": tid,
            "thread": tname,
        }
        if attrs:
            ev["attrs"] = dict(attrs)
        out.append(ev)
    return out


def trim_events(mark: int) -> None:
    """Truncates the trace-event buffer back to `mark` — a long-lived
    daemon captures each cohort's events then trims, so the 200k cap
    never saturates across weeks of uptime."""
    global _events_dropped
    with _lock:
        if 0 <= mark <= len(_events):
            del _events[mark:]
            _events_dropped = 0


def adopt_remote_events(events: Any, pid: Any = None) -> None:
    """Adopts span events captured in another process (see
    `events_between`) into this run's trace.  They render under their
    own pid in `chrome_trace()`, timestamp-rebased via wall clock."""
    if not _enabled or not isinstance(events, list):
        return
    with _lock:
        room = MAX_FOREIGN_EVENTS - len(_foreign)
        for ev in events[:max(0, room)]:
            if not isinstance(ev, dict) or "name" not in ev:
                continue
            e = dict(ev)
            if pid is not None:
                e.setdefault("pid", pid)
            _foreign.append(e)


def foreign_events() -> list[dict]:
    """The adopted cross-process events (copies)."""
    with _lock:
        return [dict(e) for e in _foreign]


def chrome_trace() -> dict:
    """The recorded spans as a Chrome trace-event dict ("X" complete
    events, µs timestamps) — Perfetto / chrome://tracing loadable.
    Adopted remote events (checkerd daemon spans) appear under their
    own pid, rebased onto this process's clock via wall time."""
    with _lock:
        events = list(_events)
        foreign = [dict(e) for e in _foreign]
        tid_ = _trace_id
    pid = os.getpid()
    out = []
    tnames: dict[int, str] = {}
    for name, t0_rel, dur, tid, tname, attrs in events:
        ev: dict[str, Any] = {
            "name": name,
            "cat": name.split(".", 1)[0],
            "ph": "X",
            "ts": t0_rel / 1000.0,
            "dur": dur / 1000.0,
            "pid": pid,
            "tid": tid,
        }
        if attrs:
            ev["args"] = attrs
        out.append(ev)
        tnames[tid] = tname
    for tid, tname in tnames.items():
        out.append({
            "name": "thread_name",
            "ph": "M",
            "pid": pid,
            "tid": tid,
            "args": {"name": tname},
        })
    fpids: dict[Any, bool] = {}
    for ev in foreign:
        try:
            ts_us = (float(ev["t0_unix_s"]) - _T0_WALL) * 1e6
            dur_us = float(ev.get("dur_s", 0.0)) * 1e6
        except (KeyError, TypeError, ValueError):
            continue
        fpid = ev.get("pid", 0)
        e: dict[str, Any] = {
            "name": ev["name"],
            "cat": str(ev["name"]).split(".", 1)[0],
            "ph": "X",
            "ts": ts_us,
            "dur": dur_us,
            "pid": fpid,
            "tid": ev.get("tid", 0),
        }
        if ev.get("attrs"):
            e["args"] = ev["attrs"]
        out.append(e)
        fpids[fpid] = True
    for fpid in fpids:
        out.append({
            "name": "process_name",
            "ph": "M",
            "pid": fpid,
            "tid": 0,
            "args": {"name": f"checkerd[{fpid}]"},
        })
    return {
        "traceEvents": out,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "jepsen_tpu.telemetry",
            "t0_unix_s": _T0_WALL,
            "trace_id": tid_,
        },
    }


def export(directory: str) -> Optional[tuple[str, str]]:
    """Writes telemetry.json + trace.json into `directory`; returns the
    two paths, or None when disabled or on a write failure (a side
    output must never change a run's outcome)."""
    if not _enabled:
        return None
    # The flush is the SLO engine's heartbeat: every export re-evaluates
    # the declarative rules over the registry (telemetry/slo.py), so a
    # blown SLO journals its transition and dumps a postmortem even in
    # processes that never serve /metrics.
    try:
        from . import slo as _slo

        _slo.evaluate()
    except Exception:  # noqa: BLE001 — alerting never breaks the flush
        log.warning("slo evaluation on export failed", exc_info=True)
    try:
        os.makedirs(directory, exist_ok=True)
        sum_path = os.path.join(directory, "telemetry.json")
        trace_path = os.path.join(directory, "trace.json")
        with open(sum_path, "w") as f:
            json.dump(summary(), f, indent=2, sort_keys=True,
                      default=repr)
            f.write("\n")
        with open(trace_path, "w") as f:
            json.dump(chrome_trace(), f, default=repr)
            f.write("\n")
        return sum_path, trace_path
    except OSError as e:
        log.warning("telemetry export to %s failed: %r", directory, e)
        return None


def log_top_spans(logger: logging.Logger, n: int = 5) -> None:
    """INFO-logs the top-n spans by total time (the run summary line)."""
    if not _enabled:
        return
    tops = top_spans(n)
    if not tops:
        return
    parts = [
        f"{name} {st['total_s']:.3f}s x{st['count']}"
        for name, st in tops
    ]
    logger.info("telemetry top spans: %s", "; ".join(parts))


# ---------------------------------------------------------------------------
# Prometheus scrape surface
# ---------------------------------------------------------------------------

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_]")

#: The chip-health states the degrade ladder can report; rendered
#: one-hot so a scrape always sees the full state space.
CHIP_HEALTH_STATES = (
    "unprobed", "ok", "wedged", "ok-after-reset", "absent",
)


def _prom_name(name: str) -> str:
    return "jepsen_" + _PROM_BAD.sub("_", name)


def prometheus_text(
    extra_gauges: Optional[dict] = None,
    chip_state: Optional[str] = None,
    lint_findings: Optional[dict] = None,
    slo_firing: Optional[dict] = None,
    extra_labeled: Optional[dict] = None,
) -> str:
    """The registry rendered in Prometheus text exposition format:
    counters as `counter`, gauge last-values and span totals/counts as
    `gauge`.  `extra_gauges` ({name: number}) lets a server mix in
    surface-local values (queue depth, utilization); `chip_state`
    renders the one-hot `jepsen_chip_health{state=...}` family;
    `lint_findings` (from a jepsenlint store summary: either the flat
    {severity: count} or the nested {family: {severity: count}} shape)
    renders `jepsen_lint_findings{...}` gauges — nested input adds the
    `family` label;
    `slo_firing` ({rule: 0|1}) renders the
    `jepsen_slo_firing{rule=...}` family — when omitted, the default
    SLO engine's current state (telemetry/slo.py) is exported, so every
    scrape surface alerts for free;
    `extra_labeled` ({family: (label_name, {label_value: number},
    "counter"|"gauge")}) renders single-label families like
    `jepsen_checkerd_shed_total{tenant=...}` — counters get the
    `_total` suffix appended here, so pass the bare family name."""
    with _lock:
        counters = dict(_counters)
        gauges = {k: g[0] for k, g in _gauges.items()}
        spans = {k: (c, t) for k, (c, t, _m) in _span_stats.items()}
    lines: list[str] = []
    for name in sorted(counters):
        v = counters[name]
        if not isinstance(v, (int, float)):
            continue
        pn = _prom_name(name) + "_total"
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {v}")
    for name in sorted(gauges):
        v = gauges[name]
        if not isinstance(v, (int, float)):
            continue
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {v}")
    if spans:
        lines.append("# TYPE jepsen_span_seconds_total counter")
        lines.append("# TYPE jepsen_span_count_total counter")
        for name in sorted(spans):
            c, t = spans[name]
            lines.append(
                f'jepsen_span_seconds_total{{span="{name}"}} {t / 1e9:.6f}'
            )
            lines.append(f'jepsen_span_count_total{{span="{name}"}} {c}')
    for name in sorted(extra_gauges or {}):
        v = (extra_gauges or {})[name]
        if not isinstance(v, (int, float)):
            continue
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {v}")
    # Histogram-style quantile export (Prometheus summary families)
    # from the in-process time-series rings: one `<name>_dist` family
    # per observed series (the `_dist` suffix keeps the family distinct
    # from the same series' last-sample gauge), e.g.
    # jepsen_wgl_online_verdict_lag_s_dist{quantile="0.95"} — so
    # dashboards and SLO rules see the recent distribution instead of
    # a single sample.  Empty until something observes.
    try:
        from . import timeseries as _ts

        _qmap = {"p50": "0.5", "p95": "0.95", "p99": "0.99"}
        for sname in _ts.ring_names():
            qs = _ts.quantiles(sname)
            if not qs:
                continue
            pn = _prom_name(sname) + "_dist"
            lines.append(f"# TYPE {pn} summary")
            for label in ("p50", "p95", "p99"):
                if label in qs:
                    lines.append(
                        f'{pn}{{quantile="{_qmap[label]}"}} {qs[label]}'
                    )
    except Exception:  # noqa: BLE001 — scrape must render regardless
        pass
    if lint_findings:
        lines.append("# TYPE jepsen_lint_findings gauge")
        for key in sorted(lint_findings):
            v = lint_findings[key]
            if isinstance(v, dict):
                # {family: {severity: count}} from summary["families"].
                for sev in sorted(v):
                    n = v[sev]
                    if not isinstance(n, (int, float)):
                        continue
                    lines.append(
                        f'jepsen_lint_findings{{family="{key}",'
                        f'severity="{sev}"}} {n}')
                continue
            if not isinstance(v, (int, float)):
                continue
            lines.append(
                f'jepsen_lint_findings{{severity="{key}"}} {v}')
    if chip_state is not None:
        lines.append("# TYPE jepsen_chip_health gauge")
        known = chip_state in CHIP_HEALTH_STATES
        for st in CHIP_HEALTH_STATES:
            hot = 1 if st == chip_state or (
                st == "unprobed" and not known) else 0
            lines.append(f'jepsen_chip_health{{state="{st}"}} {hot}')
    if slo_firing is None:
        try:
            from . import slo as _slo

            slo_firing = _slo.firing_gauges()
        except Exception:  # noqa: BLE001 — scrape must render regardless
            slo_firing = None
    if slo_firing:
        lines.append("# TYPE jepsen_slo_firing gauge")
        for rule in sorted(slo_firing):
            v = slo_firing[rule]
            if not isinstance(v, (int, float)):
                continue
            lines.append(
                f'jepsen_slo_firing{{rule="{rule}"}} {int(bool(v))}')
    for family in sorted(extra_labeled or {}):
        try:
            label, values, ptype = (extra_labeled or {})[family]
        except (TypeError, ValueError):
            continue
        if ptype not in ("counter", "gauge") or not isinstance(
                values, dict):
            continue
        pn = _prom_name(family) + ("_total" if ptype == "counter" else "")
        lines.append(f"# TYPE {pn} {ptype}")
        for lv in sorted(values, key=str):
            v = values[lv]
            if not isinstance(v, (int, float)):
                continue
            lines.append(f'{pn}{{{label}="{lv}"}} {v}')
    return "\n".join(lines) + "\n"
