"""Roofline accounting: per-pass FLOP/byte cost vs device peaks.

The observability half of ROADMAP item 1 ("peak-FLOPs WGL kernels"):
before any kernel can be *driven* toward peak, the tree must be able to
say how far from peak each pass runs.  This module

  * pulls XLA's HLO cost analysis off a jitted callable
    (`cost_analysis`), normalizing the two shapes jax hands back —
    `Lowered.cost_analysis()` returns a flat dict, and
    `Compiled.cost_analysis()` a per-computation list of dicts — and
    failing open to None on any backend that can't report it;
  * wraps jit creation sites (`instrument`) so every device call notes
    {flops, bytes_accessed, transcendentals} into the enclosing
    `profile.capture` via the per-thread cost hook, cached per
    argument-aval signature so the lowering is paid once per shape;
  * holds a small device-peak registry (known TPU generations by
    device_kind substring, plus a CPU fallback calibrated once by a
    tiny matmul/memcpy probe and cached on disk), and
  * turns measured execute_s + cost into the roofline block
    (`annotate`): achieved FLOP/s, bytes/s, arithmetic intensity,
    fraction-of-peak ratios, the memory/compute knee, and which side of
    it the pass landed on.

Everything here is advisory: a cost-analysis failure, an unknown
device, or a cache write error degrades to explicit nulls — never a
dropped record, never a changed verdict.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Callable, Optional

from . import enabled as _enabled
from . import gauge as _gauge

log = logging.getLogger(__name__)

#: Cost keys every record carries (explicit None when unknown).
COST_KEYS = ("flops", "bytes_accessed", "transcendentals")

#: XLA cost-analysis key -> record key.  XLA spells the byte counter
#: with a space ("bytes accessed").
_XLA_KEYS = {
    "flops": "flops",
    "bytes accessed": "bytes_accessed",
    "transcendentals": "transcendentals",
}

#: Roofline keys `annotate` emits (explicit None when underivable).
ROOFLINE_KEYS = (
    "achieved_flops_per_s", "achieved_bytes_per_s",
    "arithmetic_intensity", "flops_ratio", "bandwidth_ratio",
    "knee_intensity", "bound",
)

#: Peak FLOP/s and HBM bytes/s by TPU generation, matched as a
#: substring of `device.device_kind` (bf16 matmul peaks per chip, HBM
#: bandwidth per chip — the published per-generation datasheet
#: numbers; good to the ~10% a roofline plot needs, not a benchmark).
TPU_PEAKS = (
    # (kind substring, peak_flops_per_s, peak_bytes_per_s)
    ("v6", 918e12, 1640e9),
    ("v5p", 459e12, 2765e9),
    ("v5e", 197e12, 819e9),
    ("v5 lite", 197e12, 819e9),
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
    ("v2", 45e12, 700e9),
)

#: On-disk cache for the CPU calibration probe (one file per machine;
#: override with JEPSEN_ROOFLINE_CACHE, empty string disables disk).
CACHE_ENV = "JEPSEN_ROOFLINE_CACHE"
_DEFAULT_CACHE = os.path.join(
    os.path.expanduser("~"), ".cache", "jepsen_tpu", "roofline_cpu.json"
)

#: Per-instrumented-fn cap on cached aval signatures (a runaway shape
#: space must not grow memory unboundedly).
_COST_CACHE_CAP = 64

_lock = threading.Lock()
_cpu_peaks: Optional[dict] = None  # process-level calibration memo

# ---------------------------------------------------------------- cost


def _normalize_cost(raw: Any) -> Optional[dict]:
    """XLA cost analysis (dict, or Compiled's list of per-computation
    dicts) -> {flops, bytes_accessed, transcendentals} with numeric
    values, or None when nothing usable is present."""
    if isinstance(raw, (list, tuple)):
        merged: dict[str, float] = {}
        for entry in raw:
            got = _normalize_cost(entry)
            if got:
                for k, v in got.items():
                    if v is not None:
                        merged[k] = merged.get(k, 0.0) + v
        return merged and {
            k: merged.get(k) for k in COST_KEYS
        } or None
    if not isinstance(raw, dict):
        return None
    out: dict[str, Optional[float]] = {}
    for xla_key, key in _XLA_KEYS.items():
        v = raw.get(xla_key)
        if isinstance(v, (int, float)) and v >= 0:
            out[key] = float(v)
    if not out:
        return None
    return {k: out.get(k) for k in COST_KEYS}


def cost_analysis(fn: Any, *args: Any, **kwargs: Any) -> Optional[dict]:
    """Best-effort {flops, bytes_accessed, transcendentals} for calling
    `fn(*args, **kwargs)`.  Tries, in order: `fn.cost_analysis()` (fn
    is already a Lowered/Compiled), `fn.lower(...).cost_analysis()`
    (fn is a jitted callable; lowering runs HloCostAnalysis without an
    XLA compile).  Fails open to None."""
    for attempt in (
        lambda: fn.cost_analysis(),
        lambda: fn.lower(*args, **kwargs).cost_analysis(),
    ):
        try:
            got = _normalize_cost(attempt())
        except Exception:  # noqa: BLE001 — backend support is optional
            got = None
        if got is not None:
            return got
    return None


def _aval_key(args: tuple, kwargs: dict) -> Optional[tuple]:
    """Hashable (shape, dtype) signature of a call's arguments — the
    cache key under which one lowering's cost stands for every call
    with the same avals.  None when an argument defies summarizing."""
    parts = []
    try:
        for a in list(args) + sorted(kwargs.items()):
            if isinstance(a, tuple):
                a = a[1]
            shape = getattr(a, "shape", None)
            dtype = getattr(a, "dtype", None)
            if shape is not None:
                parts.append((tuple(shape), str(dtype)))
            elif isinstance(a, (int, float, bool)) or a is None:
                parts.append(("py", repr(a)))
            else:
                return None
        return tuple(parts)
    except Exception:  # noqa: BLE001
        return None


def _specs(args: tuple, kwargs: dict) -> tuple:
    """Replaces array-likes with jax.ShapeDtypeStruct so a deferred
    lowering needs no live device buffers (scalars pass through)."""
    import jax

    def spec(a: Any) -> Any:
        if hasattr(a, "shape") and hasattr(a, "dtype"):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)
        return a

    return (tuple(spec(a) for a in args),
            {k: spec(v) for k, v in kwargs.items()})


class _Instrumented:
    """A jitted callable that notes its XLA cost into the enclosing
    profile.capture on every call.  Transparent otherwise: `.fn` is
    the wrapped jit, and lower/trace attributes pass through.

    The expensive part — `fn.lower(...).cost_analysis()`, ~100 ms per
    novel aval signature — NEVER runs on the call path: an unresolved
    signature is handed to the capture as a pending entry (aval specs
    only, no buffers) and resolved at record() time, after the pass's
    wall clock has been read.  A ~ms lowering inside a measured span
    would otherwise dominate exactly the small kernels the profile
    store exists to compare (it visibly skewed the stream-sweep knob
    medians the cost model trains on)."""

    __slots__ = ("fn", "_costs")

    def __init__(self, fn: Callable):
        self.fn = fn
        self._costs: dict[tuple, Optional[dict]] = {}

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        out = self.fn(*args, **kwargs)
        if _enabled():
            try:
                self._note(args, kwargs)
            except Exception:  # noqa: BLE001 — never change the pass
                log.debug("roofline note failed", exc_info=True)
        return out

    def _note(self, args: tuple, kwargs: dict) -> None:
        from . import profile

        key = _aval_key(args, kwargs)
        if key is None:
            return
        if key in self._costs:
            cost = self._costs[key]
            if cost is not None:
                profile.note_cost(cost)
            return
        profile.note_cost_pending(self, key, _specs(args, kwargs))

    def resolve(self, key: tuple, specs: tuple) -> Optional[dict]:
        """Computes (and caches) the cost for one aval signature from
        its buffer-free specs — called by Capture.record() outside the
        measured window."""
        if key not in self._costs:
            if len(self._costs) >= _COST_CACHE_CAP:
                self._costs.clear()
            args, kwargs = specs
            self._costs[key] = cost_analysis(self.fn, *args, **kwargs)
        return self._costs[key]

    def lower(self, *args: Any, **kwargs: Any) -> Any:
        return self.fn.lower(*args, **kwargs)


def instrument(fn: Callable) -> Callable:
    """Wraps a jitted callable so each call reports its XLA FLOP/byte
    cost to the active capture (idempotent; cheap when disabled)."""
    if isinstance(fn, _Instrumented):
        return fn
    return _Instrumented(fn)


# --------------------------------------------------------------- peaks


def _cache_path() -> Optional[str]:
    p = os.environ.get(CACHE_ENV)
    if p == "":
        return None
    return p or _DEFAULT_CACHE


def _calibrate_cpu_probe() -> dict:
    """One tiny matmul + memcpy probe: measured CPU peak FLOP/s and
    bytes/s for the roofline denominator.  ~100ms once per machine."""
    import numpy as np

    n = 256
    a = np.random.default_rng(0).random((n, n), dtype=np.float32)
    b = a.copy()
    best_flops = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        a @ b
        dt = time.perf_counter() - t0
        if dt > 0:
            best_flops = max(best_flops, 2.0 * n * n * n / dt)
    buf = np.zeros(4 << 20, dtype=np.uint8)
    best_bw = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        buf.copy()
        dt = time.perf_counter() - t0
        if dt > 0:
            best_bw = max(best_bw, 2.0 * buf.nbytes / dt)
    return {
        "peak_flops_per_s": best_flops or None,
        "peak_bytes_per_s": best_bw or None,
        "source": "cpu-calibrated",
        "calibrated_at": time.time(),
    }


def calibrate_cpu(force: bool = False) -> dict:
    """The calibrated CPU peaks: process memo -> disk cache -> run the
    probe (then persist both).  `force` re-measures."""
    global _cpu_peaks
    with _lock:
        if _cpu_peaks is not None and not force:
            return dict(_cpu_peaks)
    path = _cache_path()
    if path and not force:
        try:
            with open(path) as f:
                got = json.load(f)
            if isinstance(got, dict) and got.get("peak_flops_per_s"):
                with _lock:
                    _cpu_peaks = got
                return dict(got)
        except (OSError, ValueError):
            pass
    peaks = _calibrate_cpu_probe()
    with _lock:
        _cpu_peaks = peaks
    if path:
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(peaks, f)
            os.replace(tmp, path)
        except OSError:
            pass
    return dict(peaks)


def peaks_for_device(device: Optional[dict]) -> dict:
    """{peak_flops_per_s, peak_bytes_per_s, source} for a record's
    `device` block.  TPU -> generation registry by device_kind
    substring; CPU -> calibrated probe; anything else -> nulls."""
    null = {"peak_flops_per_s": None, "peak_bytes_per_s": None,
            "source": None}
    if not isinstance(device, dict):
        return null
    platform = (device.get("platform") or "").lower()
    if platform == "tpu":
        kind = (device.get("device_kind") or "").lower()
        for sub, flops, bw in TPU_PEAKS:
            if sub in kind:
                return {"peak_flops_per_s": flops,
                        "peak_bytes_per_s": bw,
                        "source": f"tpu-registry:{sub}"}
        return null
    if platform == "cpu":
        try:
            got = calibrate_cpu()
        except Exception:  # noqa: BLE001 — numpy probe must fail open
            return null
        return {"peak_flops_per_s": got.get("peak_flops_per_s"),
                "peak_bytes_per_s": got.get("peak_bytes_per_s"),
                "source": got.get("source", "cpu-calibrated")}
    return null


# ------------------------------------------------------------ annotate


def _num(v: Any) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) else None


def _sig(v: float) -> float:
    """6 significant figures: decimal-place rounding flattens
    achieved/peak ratios (25 B/s over a 1.2 TB/s peak is 2e-11 — zero
    at round(_, 9)) while keeping the JSON short."""
    return float(f"{v:.6g}")


def annotate(timing: Optional[dict], cost: Optional[dict],
             device: Optional[dict] = None) -> dict:
    """The record's `roofline` block: achieved rates, intensity, and
    position against the device peaks.  Every underivable field is an
    explicit None so consumers index without KeyError."""
    out: dict[str, Any] = {k: None for k in ROOFLINE_KEYS}
    peaks = peaks_for_device(device)
    pf = _num(peaks.get("peak_flops_per_s"))
    pb = _num(peaks.get("peak_bytes_per_s"))
    out["peak_flops_per_s"] = pf
    out["peak_bytes_per_s"] = pb
    out["peak_source"] = peaks.get("source")
    if pf and pb:
        out["knee_intensity"] = round(pf / pb, 4)
    ex = _num((timing or {}).get("execute_s"))
    flops = _num((cost or {}).get("flops"))
    byt = _num((cost or {}).get("bytes_accessed"))
    if ex and ex > 0:
        if flops is not None:
            out["achieved_flops_per_s"] = round(flops / ex, 3)
        if byt is not None:
            out["achieved_bytes_per_s"] = round(byt / ex, 3)
    if flops is not None and byt:
        out["arithmetic_intensity"] = round(flops / byt, 6)
    if out["achieved_flops_per_s"] is not None and pf:
        out["flops_ratio"] = _sig(out["achieved_flops_per_s"] / pf)
    if out["achieved_bytes_per_s"] is not None and pb:
        out["bandwidth_ratio"] = _sig(out["achieved_bytes_per_s"] / pb)
    ai, knee = out["arithmetic_intensity"], out["knee_intensity"]
    if ai is not None and knee is not None:
        out["bound"] = "compute" if ai >= knee else "memory"
    return out


def export_gauges(record: dict) -> None:
    """Publishes one record's roofline numbers as wgl.roofline.* gauges
    (pass-scoped), so /metrics scrapes carry the latest achieved-vs-
    peak position per pass with zero extra plumbing."""
    if not _enabled():
        return
    name = record.get("pass") or "unknown"
    roof = record.get("roofline")
    cost = record.get("cost")
    if not isinstance(roof, dict):
        return
    for key in ("achieved_flops_per_s", "achieved_bytes_per_s",
                "arithmetic_intensity", "flops_ratio",
                "bandwidth_ratio"):
        v = roof.get(key)
        if isinstance(v, (int, float)):
            _gauge(f"wgl.roofline.{name}.{key}", v)
    if isinstance(cost, dict):
        for key in ("flops", "bytes_accessed"):
            v = cost.get(key)
            if isinstance(v, (int, float)):
                _gauge(f"wgl.roofline.{name}.{key}", v)


# ------------------------------------------------------------ summarize


def _median(vals: list[float]) -> Optional[float]:
    if not vals:
        return None
    vals = sorted(vals)
    n = len(vals)
    mid = n // 2
    if n % 2:
        return vals[mid]
    return (vals[mid - 1] + vals[mid]) / 2.0


def summarize(records: list[dict]) -> dict:
    """Per-pass roofline aggregate over normalized records: medians of
    the achieved/ratio fields, the consensus bound, and coverage (how
    many records actually carried cost numbers) — the shape the
    checkerd STATS block, /fleet panel, and bench JSON all share."""
    by_pass: dict[str, dict[str, list]] = {}
    for rec in records:
        name = rec.get("pass") or "unknown"
        slot = by_pass.setdefault(name, {
            "n": [], "execute_s": [], "flops": [], "bytes_accessed": [],
            "achieved_flops_per_s": [], "achieved_bytes_per_s": [],
            "arithmetic_intensity": [], "flops_ratio": [],
            "bandwidth_ratio": [], "bound": [], "knee": [],
        })
        slot["n"].append(1)
        cost = rec.get("cost") if isinstance(rec.get("cost"), dict) \
            else {}
        roof = rec.get("roofline") \
            if isinstance(rec.get("roofline"), dict) else {}
        ex = _num((rec.get("timing") or {}).get("execute_s"))
        if ex is not None:
            slot["execute_s"].append(ex)
        for key in ("flops", "bytes_accessed"):
            v = _num(cost.get(key))
            if v is not None:
                slot[key].append(v)
        for key in ("achieved_flops_per_s", "achieved_bytes_per_s",
                    "arithmetic_intensity", "flops_ratio",
                    "bandwidth_ratio"):
            v = _num(roof.get(key))
            if v is not None:
                slot[key].append(v)
        if roof.get("bound") in ("compute", "memory"):
            slot["bound"].append(roof["bound"])
        v = _num(roof.get("knee_intensity"))
        if v is not None:
            slot["knee"].append(v)
    out: dict[str, dict] = {}
    for name, slot in sorted(by_pass.items()):
        bound = None
        if slot["bound"]:
            bound = max(set(slot["bound"]), key=slot["bound"].count)
        out[name] = {
            "n": len(slot["n"]),
            "with_cost": len(slot["flops"]),
            "median_execute_s": _median(slot["execute_s"]),
            "median_flops": _median(slot["flops"]),
            "median_bytes_accessed": _median(slot["bytes_accessed"]),
            "median_achieved_flops_per_s":
                _median(slot["achieved_flops_per_s"]),
            "median_achieved_bytes_per_s":
                _median(slot["achieved_bytes_per_s"]),
            "median_arithmetic_intensity":
                _median(slot["arithmetic_intensity"]),
            "median_flops_ratio": _median(slot["flops_ratio"]),
            "median_bandwidth_ratio": _median(slot["bandwidth_ratio"]),
            "knee_intensity": _median(slot["knee"]),
            "bound": bound,
        }
    return out
