"""Per-pass cost profiles: the training set for the ROADMAP-3 model.

Every WGL checking pass (witness / stream / frontier / batched / BFS /
settle / exact-CPU) runs under `capture()`, which assembles one
structured record — history-shape features, plan knobs, the measured
compile-vs-execute split, device-memory high-water mark, and the
degradation/outcome — and appends it to a crash-safe JSONL store under
the run's store dir (checkerd keeps its own store and aggregates
fleet-wide counts into stats()).

Crash-safety contract: `append` opens/appends/closes one line per
record, so a SIGKILL mid-run loses at most the line being written;
`read` skips torn or garbage lines instead of failing the file.  A
learned cost model can therefore always train on whatever survived.

Record schema (`SCHEMA_VERSION`, field-by-field meaning in
doc/design.md "Fleet observatory"):

    {"v", "ts", "trace_id", "pass", "features": {...},
     "plan": {...}, "timing": {"compile_s", "execute_s", "total_s"},
     "device": {"platform", "peak_bytes"}, "outcome", "degraded"}

The compile/execute split rides the span taxonomy: span names ending
``.compile`` accumulate into compile_s; execute spans (``.chunk`` /
``.block``) into execute_s — both folded in via the per-thread
span-exit hook, so nested passes (a settle cohort running batched
kernels) see their children's device time without double bookkeeping.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from typing import Any, Iterator, Optional

from . import (  # noqa: F401 — the package is the registry
    enabled,
    set_pass_hook,
    _pass_hook,
    trace_id,
)
from . import count as _count

log = logging.getLogger(__name__)

SCHEMA_VERSION = 1

#: File name of the profile store inside a store/run directory.
PROFILE_FILE = "profiles.jsonl"

#: Span-name suffixes classified as compilation / device execution.
COMPILE_SUFFIXES = (".compile",)
EXECUTE_SUFFIXES = (".chunk", ".block")

_lock = threading.Lock()
_store_path: Optional[str] = None


def set_store(directory: Optional[str]) -> Optional[str]:
    """Points the process's profile store at
    `<directory>/profiles.jsonl` (None clears it).  Returns the path."""
    global _store_path
    with _lock:
        if directory is None:
            _store_path = None
        else:
            _store_path = os.path.join(directory, PROFILE_FILE)
        return _store_path


def store_path() -> Optional[str]:
    with _lock:
        return _store_path


def append(record: dict) -> Optional[str]:
    """Appends one record line to the store (crash-safe: a single
    open-append-close).  No-op when telemetry is disabled or no store
    is set; returns the path written, else None.  A profile write
    failure must never change a pass's outcome."""
    if not enabled():
        return None
    path = store_path()
    if path is None:
        return None
    try:
        line = json.dumps(record, sort_keys=True, default=repr)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "a") as f:
            f.write(line + "\n")
        _count("profile.records")
        return path
    except (OSError, TypeError, ValueError) as e:
        log.warning("profile append to %s failed: %r", path, e)
        return None


def normalize(rec: dict) -> dict:
    """A raw store record coerced to the canonical shape every consumer
    (profile_diff, costmodel_train, the observatory) can index without
    KeyError.  Stores are written by whichever process version happens
    to be running — client and daemon records routinely disagree on
    schema — so missing/mistyped keys degrade to neutral values
    (pass -> "unknown", dicts -> {}) instead of raising."""
    name = rec.get("pass")
    out = dict(rec)
    out["pass"] = name if isinstance(name, str) and name else "unknown"
    for k in ("features", "plan", "timing"):
        v = rec.get(k)
        out[k] = v if isinstance(v, dict) else {}
    timing = {}
    for k, v in out["timing"].items():
        try:
            timing[k] = float(v)
        except (TypeError, ValueError):
            continue
    out["timing"] = timing
    return out


def read(path: str) -> list[dict]:
    """Every intact record in a profile store, normalized
    (`normalize`); torn/garbage lines (crash mid-append) are skipped,
    not fatal."""
    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(normalize(rec))
    except OSError:
        pass
    return out


def count_records(path: Optional[str] = None) -> int:
    """Intact-record count of a store (defaults to the active one)."""
    p = path or store_path()
    if not p:
        return 0
    return len(read(p))


def by_pass(path: Optional[str] = None) -> dict[str, int]:
    """{pass-name: record count} for a store — the per-tier coverage
    view the CI smoke asserts on."""
    p = path or store_path()
    agg: dict[str, int] = {}
    if not p:
        return agg
    for rec in read(p):
        name = rec["pass"]
        agg[name] = agg.get(name, 0) + 1
    return agg


def _device_info() -> dict:
    """Best-effort device platform + peak-memory HWM.  CPU backends
    report no memory_stats; any failure degrades to nulls."""
    info: dict[str, Any] = {"platform": None, "peak_bytes": None}
    try:
        import jax

        dev = jax.local_devices()[0]
        info["platform"] = getattr(dev, "platform", None)
        stats = dev.memory_stats() if hasattr(dev, "memory_stats") else None
        if stats:
            info["peak_bytes"] = stats.get(
                "peak_bytes_in_use", stats.get("bytes_in_use")
            )
    except Exception:  # noqa: BLE001 — profiling never raises
        pass
    return info


class Capture:
    """The mutable record under assembly; `capture()` yields it."""

    __slots__ = ("pass_name", "features", "plan", "outcome", "degraded",
                 "_compile_ns", "_execute_ns", "_t0")

    def __init__(self, pass_name: str):
        self.pass_name = pass_name
        self.features: dict[str, Any] = {}
        self.plan: dict[str, Any] = {}
        self.outcome: Any = None
        self.degraded: Any = None
        self._compile_ns = 0
        self._execute_ns = 0
        self._t0 = time.perf_counter_ns()

    def feature(self, **kw: Any) -> None:
        self.features.update(kw)

    def knob(self, **kw: Any) -> None:
        self.plan.update(kw)

    def _on_span(self, name: str, dur_ns: int) -> None:
        if name.endswith(COMPILE_SUFFIXES):
            self._compile_ns += dur_ns
        elif name.endswith(EXECUTE_SUFFIXES):
            self._execute_ns += dur_ns

    def record(self) -> dict:
        total = time.perf_counter_ns() - self._t0
        dev = _device_info()
        return {
            "v": SCHEMA_VERSION,
            "ts": time.time(),
            "trace_id": trace_id(),
            "pass": self.pass_name,
            "features": dict(self.features),
            "plan": dict(self.plan),
            "timing": {
                "compile_s": round(self._compile_ns / 1e9, 6),
                "execute_s": round(self._execute_ns / 1e9, 6),
                "total_s": round(total / 1e9, 6),
            },
            "device": dev,
            "outcome": self.outcome,
            "degraded": self.degraded,
        }


@contextlib.contextmanager
def capture(pass_name: str, **features: Any) -> Iterator[Capture]:
    """Profiles one checking pass: installs the span-exit hook (chained
    with any enclosing capture, so a settle cohort also sees its
    batched children's compile/execute time), times the body, and
    appends the assembled record on exit.  Cheap no-op when telemetry
    is disabled."""
    cap = Capture(pass_name)
    cap.features.update(features)
    if not enabled():
        yield cap
        return
    prev = getattr(_pass_hook, "cb", None)

    def hook(name: str, dur_ns: int) -> None:
        cap._on_span(name, dur_ns)
        if prev is not None:
            prev(name, dur_ns)

    set_pass_hook(hook)
    try:
        yield cap
    except Exception as e:
        if cap.outcome is None:
            cap.outcome = f"error:{type(e).__name__}"
        raise
    finally:
        set_pass_hook(prev)
        append(cap.record())
