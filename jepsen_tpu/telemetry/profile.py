"""Per-pass cost profiles: the training set for the ROADMAP-3 model.

Every WGL checking pass (witness / stream / frontier / batched / BFS /
settle / exact-CPU) runs under `capture()`, which assembles one
structured record — history-shape features, plan knobs, the measured
compile-vs-execute split, XLA FLOP/byte cost, the roofline position
against device peaks, device-memory high-water mark, and the
degradation/outcome — and appends it to a crash-safe JSONL store under
the run's store dir (checkerd keeps its own store and aggregates
fleet-wide counts into stats()).

Crash-safety contract: `append` opens/appends/closes one line per
record, so a SIGKILL mid-run loses at most the line being written;
`read` skips torn or garbage lines instead of failing the file.  A
learned cost model can therefore always train on whatever survived.

Record schema (`SCHEMA_VERSION`, field-by-field meaning in
doc/design.md "Roofline observatory"):

    {"v", "ts", "trace_id", "pass", "features": {...},
     "plan": {...}, "timing": {"compile_s", "execute_s", "total_s"},
     "cost": {"flops", "bytes_accessed", "transcendentals",
              "device_calls"},
     "roofline": {"achieved_flops_per_s", "achieved_bytes_per_s",
                  "arithmetic_intensity", "flops_ratio",
                  "bandwidth_ratio", "knee_intensity", "bound",
                  "peak_flops_per_s", "peak_bytes_per_s",
                  "peak_source"},
     "device": {"platform", "device_kind", "peak_bytes"},
     "outcome", "degraded"}

v1 records (PR 9 .. 15) predate the cost/roofline blocks; `normalize`
fills them with explicit nulls so mixed stores keep loading.  Every
cost/roofline field is None — never missing, never a dropped record —
on backends that can't report cost analysis.

The compile/execute split rides the span taxonomy: span names ending
``.compile`` accumulate into compile_s; execute spans (``.chunk`` /
``.block``) into execute_s — both folded in via the per-thread
span-exit hook, so nested passes (a settle cohort running batched
kernels) see their children's device time without double bookkeeping.
FLOP/byte cost rides a second per-thread hook the same way:
roofline-instrumented jit wrappers call `note_cost`, and nested
captures chain so a settle cohort accumulates its children's FLOPs.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time
from typing import Any, Iterator, Optional

from . import (  # noqa: F401 — the package is the registry
    enabled,
    set_pass_hook,
    _pass_hook,
    trace_id,
)
from . import count as _count

log = logging.getLogger(__name__)

SCHEMA_VERSION = 2

#: File name of the profile store inside a store/run directory.
PROFILE_FILE = "profiles.jsonl"

#: Span-name suffixes classified as compilation / device execution.
COMPILE_SUFFIXES = (".compile",)
EXECUTE_SUFFIXES = (".chunk", ".block")

#: Explicit-null templates for the v2 blocks: a record always carries
#: every key, with None marking "backend could not report this".
COST_NULL = {"flops": None, "bytes_accessed": None,
             "transcendentals": None, "device_calls": 0}
ROOFLINE_NULL = {
    "achieved_flops_per_s": None, "achieved_bytes_per_s": None,
    "arithmetic_intensity": None, "flops_ratio": None,
    "bandwidth_ratio": None, "knee_intensity": None, "bound": None,
    "peak_flops_per_s": None, "peak_bytes_per_s": None,
    "peak_source": None,
}
DEVICE_NULL = {"platform": None, "device_kind": None,
               "peak_bytes": None}

_lock = threading.Lock()
_store_path: Optional[str] = None

#: Per-thread cost hook: `capture()` installs a callback
#: `(cost: dict) -> None`; roofline-instrumented jits call `note_cost`
#: after each device call to fold {flops, bytes_accessed,
#: transcendentals} into the active pass record.
_cost_hook = threading.local()


def set_cost_hook(cb: Optional[Any]) -> None:
    """Installs this thread's cost callback (None clears)."""
    _cost_hook.cb = cb


def note_cost(cost: dict) -> None:
    """Reports one device call's XLA cost to the active capture (no-op
    outside a capture).  A hook failure never changes the pass."""
    cb = getattr(_cost_hook, "cb", None)
    if cb is None:
        return
    try:
        cb(cost)
    except Exception:  # noqa: BLE001 — profiling must not raise
        log.debug("cost hook failed", exc_info=True)


def note_cost_pending(resolver: Any, key: tuple, specs: tuple) -> None:
    """Reports one device call whose cost is not yet known: the active
    capture stores (resolver, key, specs) and calls
    `resolver.resolve(key, specs)` at record() time — AFTER the pass's
    clocks are read — so the ~100 ms-per-novel-shape lowering never
    lands inside a measured span."""
    cb = getattr(_cost_hook, "pending", None)
    if cb is None:
        return
    try:
        cb(resolver, key, specs)
    except Exception:  # noqa: BLE001 — profiling must not raise
        log.debug("pending-cost hook failed", exc_info=True)


def set_store(directory: Optional[str]) -> Optional[str]:
    """Points the process's profile store at
    `<directory>/profiles.jsonl` (None clears it).  Returns the path."""
    global _store_path
    with _lock:
        if directory is None:
            _store_path = None
        else:
            _store_path = os.path.join(directory, PROFILE_FILE)
        return _store_path


def store_path() -> Optional[str]:
    with _lock:
        return _store_path


def append(record: dict) -> Optional[str]:
    """Appends one record line to the store (crash-safe: a single
    open-append-close).  No-op when telemetry is disabled or no store
    is set; returns the path written, else None.  A profile write
    failure must never change a pass's outcome."""
    if not enabled():
        return None
    path = store_path()
    if path is None:
        return None
    try:
        line = json.dumps(record, sort_keys=True, default=repr)
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "a") as f:
            f.write(line + "\n")
        _count("profile.records")
        return path
    except (OSError, TypeError, ValueError) as e:
        log.warning("profile append to %s failed: %r", path, e)
        return None


def normalize(rec: dict) -> dict:
    """A raw store record coerced to the canonical v2 shape every
    consumer (profile_diff, costmodel_train, perf_gate, the
    observatory) can index without KeyError.  Stores are written by
    whichever process version happens to be running — client and
    daemon records routinely disagree on schema, and v1 records
    predate the cost/roofline blocks — so missing/mistyped keys
    degrade to neutral values (pass -> "unknown", dicts -> {},
    cost/roofline -> explicit nulls) instead of raising."""
    name = rec.get("pass")
    out = dict(rec)
    out["pass"] = name if isinstance(name, str) and name else "unknown"
    for k in ("features", "plan", "timing"):
        v = rec.get(k)
        out[k] = v if isinstance(v, dict) else {}
    timing = {}
    for k, v in out["timing"].items():
        try:
            timing[k] = float(v)
        except (TypeError, ValueError):
            continue
    out["timing"] = timing
    for k, template in (("cost", COST_NULL),
                        ("roofline", ROOFLINE_NULL),
                        ("device", DEVICE_NULL)):
        v = rec.get(k)
        block = dict(template)
        if isinstance(v, dict):
            block.update(v)
        out[k] = block
    v = rec.get("v")
    out["v"] = v if isinstance(v, int) else 1
    return out


def read(path: str) -> list[dict]:
    """Every intact record in a profile store, normalized
    (`normalize`); torn/garbage lines (crash mid-append) are skipped,
    not fatal."""
    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict):
                    out.append(normalize(rec))
    except OSError:
        pass
    return out


def count_records(path: Optional[str] = None) -> int:
    """Intact-record count of a store (defaults to the active one)."""
    p = path or store_path()
    if not p:
        return 0
    return len(read(p))


def by_pass(path: Optional[str] = None) -> dict[str, int]:
    """{pass-name: record count} for a store — the per-tier coverage
    view the CI smoke asserts on."""
    p = path or store_path()
    agg: dict[str, int] = {}
    if not p:
        return agg
    for rec in read(p):
        name = rec["pass"]
        agg[name] = agg.get(name, 0) + 1
    return agg


def _device_info() -> dict:
    """Best-effort device platform, kind, and peak-memory HWM.  Each
    field fails open independently: a backend exposing memory_stats
    but raising in device_kind (or vice versa) loses only that field,
    never the whole block."""
    info: dict[str, Any] = dict(DEVICE_NULL)
    try:
        import jax

        dev = jax.local_devices()[0]
    except Exception:  # noqa: BLE001 — profiling never raises
        return info
    try:
        info["platform"] = getattr(dev, "platform", None)
    except Exception:  # noqa: BLE001
        pass
    try:
        info["device_kind"] = getattr(dev, "device_kind", None)
    except Exception:  # noqa: BLE001
        pass
    try:
        stats = dev.memory_stats() if hasattr(dev, "memory_stats") \
            else None
        if stats:
            info["peak_bytes"] = stats.get(
                "peak_bytes_in_use", stats.get("bytes_in_use")
            )
    except Exception:  # noqa: BLE001
        pass
    return info


class Capture:
    """The mutable record under assembly; `capture()` yields it."""

    __slots__ = ("pass_name", "features", "plan", "outcome", "degraded",
                 "_compile_ns", "_execute_ns", "_t0",
                 "_cost", "_device_calls", "_pending")

    def __init__(self, pass_name: str):
        self.pass_name = pass_name
        self.features: dict[str, Any] = {}
        self.plan: dict[str, Any] = {}
        self.outcome: Any = None
        self.degraded: Any = None
        self._compile_ns = 0
        self._execute_ns = 0
        self._cost: dict[str, float] = {}
        self._device_calls = 0
        self._pending: dict[tuple, list] = {}
        self._t0 = time.perf_counter_ns()

    def feature(self, **kw: Any) -> None:
        self.features.update(kw)

    def knob(self, **kw: Any) -> None:
        self.plan.update(kw)

    def _on_span(self, name: str, dur_ns: int) -> None:
        if name.endswith(COMPILE_SUFFIXES):
            self._compile_ns += dur_ns
        elif name.endswith(EXECUTE_SUFFIXES):
            self._execute_ns += dur_ns

    def add_cost(self, cost: dict, n: int = 1) -> None:
        """Accumulates `n` device calls' {flops, bytes_accessed,
        transcendentals} into the pass total (unknown fields skipped)."""
        self._device_calls += n
        for key in ("flops", "bytes_accessed", "transcendentals"):
            v = cost.get(key)
            if isinstance(v, (int, float)):
                self._cost[key] = self._cost.get(key, 0.0) + float(v) * n

    def add_pending(self, resolver: Any, key: tuple,
                    specs: tuple) -> None:
        """Remembers one call whose cost resolves at record() time
        (repeat calls with the same signature just bump the count)."""
        k = (id(resolver), key)
        ent = self._pending.get(k)
        if ent is None:
            self._pending[k] = [resolver, key, specs, 1]
        else:
            ent[3] += 1

    def _resolve_pending(self) -> None:
        for resolver, key, specs, n in self._pending.values():
            try:
                cost = resolver.resolve(key, specs)
            except Exception:  # noqa: BLE001 — cost is advisory
                cost = None
            if cost:
                self.add_cost(cost, n)
        self._pending.clear()

    def record(self) -> dict:
        # Read the clock BEFORE resolving pending cost analyses: the
        # deferred lowerings are exactly the work we keep out of the
        # measured numbers.
        total = time.perf_counter_ns() - self._t0
        self._resolve_pending()
        dev = _device_info()
        timing = {
            "compile_s": round(self._compile_ns / 1e9, 6),
            "execute_s": round(self._execute_ns / 1e9, 6),
            "total_s": round(total / 1e9, 6),
        }
        cost = dict(COST_NULL)
        cost["device_calls"] = self._device_calls
        for k, v in self._cost.items():
            cost[k] = round(v, 3)
        roofline = dict(ROOFLINE_NULL)
        try:
            from . import roofline as _roofline

            roofline.update(_roofline.annotate(
                timing, cost if self._cost else None, dev))
        except Exception:  # noqa: BLE001 — the roofline block is
            # advisory; its failure must not drop the record
            log.debug("roofline annotate failed", exc_info=True)
        return {
            "v": SCHEMA_VERSION,
            "ts": time.time(),
            "trace_id": trace_id(),
            "pass": self.pass_name,
            "features": dict(self.features),
            "plan": dict(self.plan),
            "timing": timing,
            "cost": cost,
            "roofline": roofline,
            "device": dev,
            "outcome": self.outcome,
            "degraded": self.degraded,
        }


#: Innermost-first stack of live captures — lets a callee that
#: RESOLVES a knob (e.g. the witness block chooser) record the chosen
#: value on the pass record its caller opened, so the cost model
#: trains on what actually ran.
_active: list[Capture] = []


def annotate(**knobs: Any) -> None:
    """Merges `knobs` into the innermost active capture's plan block;
    silent no-op outside any capture (plain engine calls)."""
    if _active:
        _active[-1].knob(**knobs)


@contextlib.contextmanager
def capture(pass_name: str, **features: Any) -> Iterator[Capture]:
    """Profiles one checking pass: installs the span-exit and cost
    hooks (chained with any enclosing capture, so a settle cohort also
    sees its batched children's compile/execute time and FLOPs), times
    the body, and appends the assembled record on exit.  Cheap no-op
    when telemetry is disabled."""
    cap = Capture(pass_name)
    cap.features.update(features)
    if not enabled():
        yield cap
        return
    prev = getattr(_pass_hook, "cb", None)
    prev_cost = getattr(_cost_hook, "cb", None)

    def hook(name: str, dur_ns: int) -> None:
        cap._on_span(name, dur_ns)
        if prev is not None:
            prev(name, dur_ns)

    def cost_cb(cost: dict) -> None:
        cap.add_cost(cost)
        if prev_cost is not None:
            prev_cost(cost)

    prev_pending = getattr(_cost_hook, "pending", None)

    def pending_cb(resolver: Any, key: tuple, specs: tuple) -> None:
        cap.add_pending(resolver, key, specs)
        if prev_pending is not None:
            prev_pending(resolver, key, specs)

    set_pass_hook(hook)
    set_cost_hook(cost_cb)
    _cost_hook.pending = pending_cb
    _active.append(cap)
    try:
        yield cap
    except Exception as e:
        if cap.outcome is None:
            cap.outcome = f"error:{type(e).__name__}"
        raise
    finally:
        _active.pop()
        set_pass_hook(prev)
        set_cost_hook(prev_cost)
        _cost_hook.pending = prev_pending
        rec = cap.record()
        append(rec)
        try:
            from . import roofline as _roofline

            _roofline.export_gauges(rec)
        except Exception:  # noqa: BLE001
            log.debug("roofline gauge export failed", exc_info=True)
