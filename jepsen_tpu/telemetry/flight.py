"""Flight recorder: a bounded ring of recent notable events, dumped to
``postmortem.json`` when something goes wrong.

The ring is **always on** (unlike spans, which need JEPSEN_TELEMETRY):
the events it records — watchdog op timeouts, blown checker budgets,
degradation-ladder steps, chip probes/resets, run crashes — are rare
by construction, so `note()` costs one deque append regardless of
telemetry state.  When a trigger fires, `dump(reason)` snapshots the
ring plus the telemetry counters and top spans into the run's store
dir; a postmortem is then readable even when the process that wrote it
is gone.

Triggers (the full list lives in doc/design.md "Fleet observatory"):
  * interpreter watchdog op-timeout fires
  * check_safe's checker budget blows
  * core.run exits via an exception
  * checkerd marks a request budget-exceeded
  * the WGL degradation ladder records a step
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from typing import Any, Optional

from . import summary, top_spans

log = logging.getLogger(__name__)

#: Ring capacity: triggers are rare events, not per-op traffic, so a
#: few hundred entries cover the interesting tail of any run.
MAX_EVENTS = 512

POSTMORTEM_FILE = "postmortem.json"

_lock = threading.Lock()
_ring: collections.deque = collections.deque(maxlen=MAX_EVENTS)
_dir: Optional[str] = None
_dumps = 0


def set_dir(directory: Optional[str]) -> None:
    """Points postmortem dumps at `directory` (the run's store dir)."""
    global _dir
    with _lock:
        _dir = directory


def reset() -> None:
    """Clears the ring (start of a run scope)."""
    global _dumps
    with _lock:
        _ring.clear()
        _dumps = 0


def note(kind: str, **fields: Any) -> None:
    """Records one event in the ring.  Always on; never raises."""
    try:
        ev = {"t": time.time(), "kind": kind}
        if fields:
            ev.update(fields)
        with _lock:
            _ring.append(ev)
    except Exception:  # noqa: BLE001
        pass


def events() -> list[dict]:
    with _lock:
        return [dict(e) for e in _ring]


def dump_count() -> int:
    with _lock:
        return _dumps


def status() -> dict:
    """{events, dumps, dir} — bench.py embeds this in its JSON line."""
    with _lock:
        return {"events": len(_ring), "dumps": _dumps, "dir": _dir}


def dump(reason: str, directory: Optional[str] = None) -> Optional[str]:
    """Writes postmortem.json (ring + counters + top spans) into
    `directory` (default: the configured dir).  Returns the path, or
    None when no dir is set or the write fails — a postmortem must
    never change the outcome it documents."""
    global _dumps
    with _lock:
        d = directory or _dir
        ring = [dict(e) for e in _ring]
    if not d:
        return None
    try:
        snap = {
            "reason": reason,
            "dumped_at": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
            ),
            "events": ring,
            "counters": summary().get("counters", {}),
            "top_spans": [
                {"name": n, **st} for n, st in top_spans(8)
            ],
        }
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, POSTMORTEM_FILE)
        with open(path, "w") as f:
            json.dump(snap, f, indent=2, sort_keys=True, default=repr)
            f.write("\n")
        with _lock:
            _dumps += 1
        log.info("flight recorder: postmortem (%s) -> %s", reason, path)
        return path
    except OSError as e:
        log.warning("flight recorder dump failed: %r", e)
        return None
