"""Declarative SLO rules over the telemetry registry.

ROADMAP item 5 names "verdict-lag SLOs alerting through /metrics"; this
module is the general mechanism: a small set of declarative
threshold/burn-rate rules evaluated against the live registry (gauge
last-values, counter deltas, surface-local extras like checkerd queue
depth, and the chip-health state) on every telemetry flush and on every
/metrics scrape.

Rule kinds:

  * ``gauge-above`` / ``gauge-below`` — the gauge's last sample crossed
    a threshold (verdict lag, queue depth, merge ratio);
  * ``counter-above`` — a monotone counter's absolute value crossed a
    threshold (quarantined nodes);
  * ``counter-rate-above`` — burn rate: the counter's increase per
    second since the previous evaluation exceeds the threshold
    (op-timeout rate);
  * ``chip-unhealthy`` — the degrade ladder reports a bad chip state
    (wedged / absent).

A rule *fires* after ``for_count`` consecutive breaching evaluations
(hysteresis against one-sample blips) and *clears* on the first clean
one.  Both transitions append a record to a crash-safe ``slo.jsonl``
(one open-append-fsync-close per line, torn tails skipped on read —
the profile-store contract), firing additionally notes into the flight
recorder and dumps a postmortem, so every blown SLO ships the ring
that led up to it.  Current state exports as
``jepsen_slo_firing{rule=...}`` 0/1 gauges via ``prometheus_text()``.

Missing inputs are never breaches: a rule whose gauge has no sample
has no opinion, so an idle process scrapes all-zeros rather than
firing vacuously.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Optional

from . import _gauges, _counters, _lock as _reg_lock
from . import count as _count
from . import flight

log = logging.getLogger(__name__)

#: File name of the SLO transition journal inside a store/run dir.
SLO_FILE = "slo.jsonl"

_KINDS = (
    "gauge-above", "gauge-below", "counter-above", "counter-rate-above",
    "chip-unhealthy",
)


@dataclass(frozen=True)
class Rule:
    """One declarative alert rule.

    - name:      stable identifier; the `rule` label on the exported
                 gauge and the key in slo.jsonl records.
    - kind:      one of `_KINDS`.
    - target:    gauge/counter name the rule reads (resolved against
                 surface extras first, then the registry); unused for
                 chip-unhealthy.
    - threshold: the boundary value (rate rules: per second).
    - for_count: consecutive breaching evaluations before firing.
    """

    name: str
    kind: str
    target: str = ""
    threshold: float = 0.0
    for_count: int = 1

    def __post_init__(self) -> None:
        if self.kind not in _KINDS:
            raise ValueError(f"unknown SLO rule kind {self.kind!r}")


#: The stock rule set over the gauges/counters the subsystems already
#: emit.  Replace or extend with `reset(rules=...)`.
DEFAULT_RULES: tuple[Rule, ...] = (
    # Online checking: the verdict must land promptly after the last op
    # (streaming/pipeline.py gauges the measured lag at finish()).
    Rule("verdict-lag", "gauge-above", "wgl.online.verdict-lag-s", 30.0),
    # Checkerd pool health: a deep queue means runs are waiting on the
    # daemon; a near-zero merge ratio under load means cohort merging —
    # the whole point of the shared pool — stopped happening.
    Rule("checkerd-queue-depth", "gauge-above", "checkerd.queue-depth",
         32.0, for_count=2),
    Rule("checkerd-merge-ratio", "gauge-below", "checkerd.merge-ratio",
         0.01, for_count=3),
    # Cluster health: any quarantined node is an alert; op timeouts
    # above a trickle mean the workload is burning its watchdogs.
    Rule("quarantined-nodes", "counter-above", "node.quarantined", 0.0),
    Rule("op-timeout-rate", "counter-rate-above",
         "interpreter.op-timeouts", 0.5, for_count=2),
    # Accelerator health straight from the degrade ladder.
    Rule("chip-health", "chip-unhealthy"),
)

#: Rules the standing monitor (jepsen_tpu/monitor/) layers on top of
#: the defaults.  The p95 rule thresholds on the quantile gauges the
#: time-series rings export (telemetry/timeseries.quantile_gauges(),
#: passed as evaluation extras) instead of a single last-sample gauge
#: — one slow verdict no longer pages; a shifted distribution does.
#: The drift rule watches the PR 12 cost model's predictions against
#: measured pass costs (monitor.cost-drift-ratio, a rolling median of
#: measured/predicted) and fires when retraining is due.
MONITOR_RULES: tuple[Rule, ...] = (
    Rule("monitor-verdict-lag", "gauge-above", "monitor.verdict-lag-s",
         60.0, for_count=2),
    Rule("verdict-lag-p95", "gauge-above",
         "wgl.online.verdict-lag-s.p95", 30.0),
    Rule("cost-drift", "gauge-above", "monitor.cost-drift-ratio",
         3.0, for_count=3),
    # Overload control plane: a sustained shed rate means the fleet is
    # saturated past the point graceful degradation can absorb —
    # capacity or weights need attention, not just patience (the
    # brownout ladder and deadline shedding are already doing their
    # jobs when this fires).
    Rule("checkerd-shed-rate", "counter-rate-above",
         "checkerd.overload.shed", 1.0, for_count=3),
)

#: Rules the live (suite-backed) monitor adds when a real cluster is
#: under watch.  Daemon restarts outside fault windows at a sustained
#: rate mean the target is crash-looping on its own; client
#: reconnect-storms mean the op stream is mostly backoff; a fault
#: window left outstanding for consecutive cadences means a heal
#: failed and residue is accumulating on a live machine.
LIVE_MONITOR_RULES: tuple[Rule, ...] = (
    Rule("live-daemon-restart-rate", "counter-rate-above",
         "monitor.live.daemon-restarts", 0.2, for_count=2),
    Rule("live-reconnect-rate", "counter-rate-above",
         "monitor.live.client-reconnects", 5.0, for_count=3),
    Rule("live-unhealed-window", "gauge-above",
         "monitor.live.outstanding", 0.5, for_count=3),
)

#: Per-tenant rules a fleet member (`jepsen monitor --tenant`) adds:
#: each tenant's monitor evaluates these against its *own* counters
#: into its *own* slo.jsonl, so one tenant's shed storm or epoch
#: churn alerts that tenant's sinks without paging the fleet.  A
#: sustained shed-backoff rate means the tenant's DRR share can't
#: cover its offered load (weight or deadline needs attention); a
#: deadline-unmet means verification work was actually dropped;
#: epoch restarts at a sustained rate mean the rolling checker keeps
#: losing its prefix-discard invariant.
TENANT_RULES: tuple[Rule, ...] = (
    Rule("tenant-shed-backoff-rate", "counter-rate-above",
         "monitor.shed.backoffs", 2.0, for_count=3),
    Rule("tenant-shed-deadline-unmet", "counter-above",
         "monitor.shed.deadline-unmet", 0.0),
    Rule("tenant-epoch-restart-rate", "counter-rate-above",
         "monitor.epoch-restarts", 0.1, for_count=3),
)


class SLOEngine:
    """Evaluates a rule set against registry snapshots and journals
    firing/cleared transitions.  One module-level default instance
    serves the process (like the flight recorder); tests build their
    own."""

    def __init__(self, rules: Optional[tuple] = None,
                 directory: Optional[str] = None):
        self._lock = threading.Lock()
        self.rules: tuple[Rule, ...] = tuple(rules if rules is not None
                                             else DEFAULT_RULES)
        self._dir = directory
        # name -> {"firing", "breaches", "since", "value",
        #          "prev_counter", "prev_t"}
        self._state: dict[str, dict] = {r.name: self._fresh()
                                        for r in self.rules}

    @staticmethod
    def _fresh() -> dict:
        return {"firing": False, "breaches": 0, "since": None,
                "value": None, "prev_counter": None, "prev_t": None}

    def set_dir(self, directory: Optional[str]) -> None:
        with self._lock:
            self._dir = directory

    def set_rules(self, rules: tuple) -> None:
        with self._lock:
            self.rules = tuple(rules)
            self._state = {r.name: self._fresh() for r in self.rules}

    # -- evaluation -----------------------------------------------------

    def _value(self, rule: Rule, gauges: dict, counters: dict,
               extras: dict, chip_state: Optional[str],
               st: dict, now: float):
        """(observed value, breached | None).  None = no opinion (the
        input is absent), never a breach."""
        if rule.kind == "chip-unhealthy":
            if chip_state is None:
                return None, None
            return chip_state, chip_state in ("wedged", "absent")
        if rule.kind in ("gauge-above", "gauge-below"):
            v = extras.get(rule.target, gauges.get(rule.target))
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                return None, None
            if rule.kind == "gauge-above":
                return v, v > rule.threshold
            return v, v < rule.threshold
        v = extras.get(rule.target, counters.get(rule.target))
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            return None, None
        if rule.kind == "counter-above":
            return v, v > rule.threshold
        # counter-rate-above: needs two samples to have an opinion.
        prev_v, prev_t = st["prev_counter"], st["prev_t"]
        st["prev_counter"], st["prev_t"] = v, now
        if prev_v is None or prev_t is None or now <= prev_t:
            return None, None
        rate = max(0.0, (v - prev_v)) / (now - prev_t)
        return round(rate, 6), rate > rule.threshold

    def evaluate(self, extra_gauges: Optional[dict] = None,
                 chip_state: Optional[str] = None,
                 now: Optional[float] = None) -> list[dict]:
        """One evaluation sweep; returns the transition records
        appended (empty when nothing changed state).  Never raises:
        alerting must not change the outcome of the thing it watches."""
        now = time.time() if now is None else now
        extras = dict(extra_gauges or {})
        with _reg_lock:
            gauges = {k: g[0] for k, g in _gauges.items()}
            counters = dict(_counters)
        transitions: list[dict] = []
        with self._lock:
            for rule in self.rules:
                st = self._state[rule.name]
                try:
                    value, breached = self._value(
                        rule, gauges, counters, extras, chip_state,
                        st, now)
                except Exception:  # noqa: BLE001 — one bad rule only
                    log.warning("SLO rule %s evaluation failed",
                                rule.name, exc_info=True)
                    continue
                st["value"] = value
                if breached:
                    st["breaches"] += 1
                    if (not st["firing"]
                            and st["breaches"] >= rule.for_count):
                        st["firing"] = True
                        st["since"] = now
                        transitions.append(self._transition(
                            "firing", rule, value, now))
                else:
                    st["breaches"] = 0
                    if st["firing"]:
                        st["firing"] = False
                        st["since"] = None
                        transitions.append(self._transition(
                            "cleared", rule, value, now))
            path = self._path()
        for rec in transitions:
            self._append(path, rec)
            if rec["rec"] == "firing":
                _count("slo.fired")
                flight.note("slo-firing", rule=rec["rule"],
                            value=rec["value"],
                            threshold=rec["threshold"])
                # The postmortem: the flight ring as of the moment the
                # SLO blew, dumped next to the journal.
                flight.dump(f"slo-{rec['rule']}")
            else:
                _count("slo.cleared")
                flight.note("slo-cleared", rule=rec["rule"],
                            value=rec["value"])
        if transitions:
            _count("slo.transitions", len(transitions))
        return transitions

    @staticmethod
    def _transition(rec: str, rule: Rule, value: Any,
                    now: float) -> dict:
        return {
            "rec": rec,
            "rule": rule.name,
            "kind": rule.kind,
            "target": rule.target,
            "threshold": rule.threshold,
            "value": value,
            "t": now,
        }

    def _path(self) -> Optional[str]:
        return (os.path.join(self._dir, SLO_FILE)
                if self._dir else None)

    @staticmethod
    def _append(path: Optional[str], rec: dict) -> None:
        """Crash-safe single-line append: a SIGKILL mid-write loses at
        most this line, and `read` skips the torn tail."""
        if path is None:
            return
        try:
            line = json.dumps(rec, sort_keys=True, default=repr)
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "a") as f:
                f.write(line + "\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError as e:
            log.warning("slo journal append to %s failed: %r", path, e)

    # -- views ----------------------------------------------------------

    def firing_gauges(self) -> dict[str, int]:
        """{rule: 0|1} over EVERY configured rule, so the exported
        family is always complete and a cleared rule scrapes as 0."""
        with self._lock:
            return {r.name: int(self._state[r.name]["firing"])
                    for r in self.rules}

    def status(self) -> list[dict]:
        """Per-rule detail for the web panel."""
        with self._lock:
            out = []
            for r in self.rules:
                st = self._state[r.name]
                out.append({
                    "rule": r.name,
                    "kind": r.kind,
                    "target": r.target,
                    "threshold": r.threshold,
                    "firing": st["firing"],
                    "since": st["since"],
                    "value": st["value"],
                })
            return out


def read(path: str) -> list[dict]:
    """Every intact transition record in an slo.jsonl; torn or garbage
    lines (crash mid-append) are skipped, not fatal."""
    out: list[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and rec.get("rec"):
                    out.append(rec)
    except OSError:
        pass
    return out


# ---------------------------------------------------------------------------
# Module-level default engine (the flight-recorder pattern)
# ---------------------------------------------------------------------------

_engine = SLOEngine()


def set_dir(directory: Optional[str]) -> None:
    """Points the default engine's journal at <directory>/slo.jsonl
    (None detaches it)."""
    _engine.set_dir(directory)


def reset(rules: Optional[tuple] = None) -> None:
    """Clears all rule state; optionally installs a new rule set."""
    _engine.set_rules(tuple(rules if rules is not None
                            else DEFAULT_RULES))


def evaluate(extra_gauges: Optional[dict] = None,
             chip_state: Optional[str] = None,
             now: Optional[float] = None) -> list[dict]:
    return _engine.evaluate(extra_gauges, chip_state, now)


def firing_gauges() -> dict[str, int]:
    return _engine.firing_gauges()


def status() -> list[dict]:
    return _engine.status()


def slo_path(directory: str) -> str:
    return os.path.join(directory, SLO_FILE)
