"""The double-buffered online checking session.

Dataflow::

    interpreter threads                checker thread (daemon)
    ───────────────────                ───────────────────────
    journal ──► feed(op) ──► buffer A        buffer B ──► ingest
                  (append under lock)          │  per-key PackedBuilder
                                               │  quiet keys ─► stream
                         swap every            │  witness batch (device)
                         ~50 ms / 2048 ops ◄───┘  big streams ─► frontier
                                                  advance (device)

One buffer fills on the host while the other's ops are routed, packed
and checked against the device — the generate/interpret side never
blocks on checking, and the checking side always has a full batch to
amortize H2D transfer over.

Verdicts are recorded against the packed digest of the key's history
at proof time (`parallel.independent._settle_digest`).  At analyze,
the post-hoc checkers re-pack each key and consume a verdict only when
digests match — a key that received ops after its proof is re-proven
or falls back, never served stale.  A consumed verdict also
invalidates nothing; a DROPPED one (key changed after proof) evicts
its settle-memo entry via `invalidate_settle_memo` so the cross-run
cohort can't replay it either.

The session is fail-open everywhere: any internal error marks it
broken, feed() becomes a no-op, and analyze simply finds no verdicts
to consume — online checking can cost latency, never the verdict.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Optional

from .. import telemetry
from ..telemetry import profile
from ..history.core import Op
from ..history.packed import PackedBuilder
from ..models.base import PackedModel
from .frontier import FrontierCarry

log = logging.getLogger(__name__)

#: Swap the buffers at least this often even when the run is slow.
SWAP_INTERVAL_S = 0.05
#: ...and as soon as this many ops are waiting.
SWAP_OPS = 2048
#: Single-stream mode: replan+advance the frontier only after this many
#: new stable rows (each advance replans the whole prefix on host, so
#: this bounds total planning work to O(n^2 / ADVANCE_ROWS)).
ADVANCE_ROWS = 32768
#: Keyed mode: a key whose builder exceeds this many rows graduates
#: from batched whole-key rechecks to its own FrontierCarry.
FRONTIER_ROWS = 65536
#: Keyed mode: don't re-prove a still-growing key until it has at least
#: this many rows more than at its last proof.
RECHECK_MIN_ROWS = 256


class DoubleBuffer:
    """The host half of the pipeline: `put` appends to the filling
    list, `take` swaps it out whole.  Contention is one lock around a
    list append — the interpreter side never waits on checking."""

    __slots__ = ("_lock", "_filling")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._filling: list = []

    def put(self, item: Any) -> int:
        with self._lock:
            self._filling.append(item)
            return len(self._filling)

    def take(self) -> list:
        with self._lock:
            batch, self._filling = self._filling, []
            return batch


class StreamingSession:
    """Online checker for one run.  `feed(op)` from the interpreter's
    journal; `finish()` once the run ends (drains, finalizes, measures
    verdict lag); `consume(key, digest)` from the post-hoc checkers.

    Mode is auto-detected from the first client invoke: a `KV` payload
    means a keyed (independent) workload with per-key builders and
    batched stream-witness proofs; anything else means one stream
    checked by a single incremental `FrontierCarry`.
    """

    MODE_KEYED = "keyed"
    MODE_SINGLE = "single"

    def __init__(
        self,
        pm: PackedModel,
        *,
        swap_interval_s: float = SWAP_INTERVAL_S,
        swap_ops: int = SWAP_OPS,
        advance_rows: int = ADVANCE_ROWS,
        frontier_rows: int = FRONTIER_ROWS,
        recheck_min_rows: int = RECHECK_MIN_ROWS,
        remote: Optional[Any] = None,
        run_id: str = "run",
    ):
        self.pm = pm
        self.swap_interval_s = swap_interval_s
        self.swap_ops = swap_ops
        self.advance_rows = advance_rows
        self.frontier_rows = frontier_rows
        self.recheck_min_rows = recheck_min_rows
        self.run_id = run_id

        self.mode: Optional[str] = None
        self.finished = False
        self.broken = False
        self.broken_reason: Optional[str] = None
        self.verdict_lag_s: Optional[float] = None

        self._buf = DoubleBuffer()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        #: ops seen before the first client invoke (mode unknown);
        #: replayed ahead of the batch that decides the mode.
        self._carry: list = []
        # keyed mode
        self._pending: dict = {}            # process -> key (in-flight route)
        self._builders: dict = {}           # key -> PackedBuilder
        self._changed: dict = {}            # key -> True (ops since last check)
        self._checked_rows: dict = {}       # key -> n_rows at last attempt
        self._frontiers: dict = {}          # key -> FrontierCarry (big keys)
        self._fr_rows: dict = {}            # key -> n_rows at last advance
        # single mode
        self._builder: Optional[PackedBuilder] = None
        self._frontier: Optional[FrontierCarry] = None
        self._adv_rows = 0

        #: key (or None for single-stream) -> {"digest": str, "res": dict}
        self._verdicts: dict = {}
        #: key -> digest of the pack at its last witness attempt.  The
        #: witness is deterministic, so an identical pack can only
        #: repeat the same answer — finalize skips those (the big win:
        #: invalid keys restart the stream engine every attempt, and
        #: re-attempting them at finish() would put that cost straight
        #: into the verdict lag).
        self._attempted: dict = {}
        #: largest total row count a single mid-run stream batch has
        #: carried — the witness engine compiled buckets for that
        #: shape, so finalize chunks to it (wgl_witness buckets both
        #: the window and the block count; one oversized finalize pass
        #: would pay a fresh XLA compile seconds before the verdict).
        self._stream_rows_hwm = 0

        self._ops_ingested = 0
        self._swaps = 0
        self._checks = 0
        self._rechecks = 0

        #: streaming/remote.py RemoteFeed, already configured to mirror
        #: the submission RemoteChecker would make, or None.
        self._remote = remote

        self._thread = threading.Thread(
            target=self._loop, name="streaming-checker", daemon=True
        )
        self._thread.start()

    # -- producer side (interpreter threads) --------------------------------

    def feed(self, op: Op) -> None:
        """Appends one journal op.  Cheap and non-blocking; called from
        the interpreter's worker threads."""
        if self.broken or self.finished:
            return
        if self._buf.put(op) >= self.swap_ops:
            self._wake.set()

    # -- checker thread ------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.swap_interval_s)
            self._wake.clear()
            batch = self._buf.take()
            if not batch:
                continue
            try:
                self._ingest(batch)
            except Exception as e:  # noqa: BLE001
                self._break(f"{type(e).__name__}: {e}")

    def _break(self, reason: str) -> None:
        self.broken = True
        self.broken_reason = reason
        telemetry.count("wgl.online.broken")
        log.warning("streaming session broken, falling back post-hoc: %s",
                    reason)

    def _ingest(self, batch: list) -> None:
        self._swaps += 1
        self._ops_ingested += len(batch)
        telemetry.count("wgl.online.ops-ingested", len(batch))
        with telemetry.span("wgl.online.swap", ops=len(batch)):
            if self.mode is None:
                if self._carry:
                    batch = self._carry + batch
                    self._carry = []
                self._detect_mode(batch)
            if self.mode == self.MODE_KEYED:
                self._ingest_keyed(batch)
            elif self.mode == self.MODE_SINGLE:
                self._ingest_single(batch)
            else:
                # No client invoke yet (nemesis/info noise): hold the
                # ops, in order, until the mode-deciding invoke lands.
                self._carry = batch

    def _detect_mode(self, batch: list) -> None:
        from ..parallel.independent import KV

        for op in batch:
            if op.is_invoke and op.is_client_op:
                self.mode = (self.MODE_KEYED if isinstance(op.value, KV)
                             else self.MODE_SINGLE)
                log.info("streaming session: %s mode", self.mode)
                if self.mode == self.MODE_SINGLE:
                    self._builder = PackedBuilder(self.pm.encode)
                    self._frontier = FrontierCarry(self.pm)
                return

    # -- keyed (independent) mode -------------------------------------------

    def _route(self, o: Op):
        """Mirrors `parallel.independent.subhistories` exactly — same
        pending map, same KV unwrap, same drops — so the per-key op
        sequences (and hence packed digests) match what the post-hoc
        checker derives from the full history."""
        from ..parallel.independent import KV

        val = o.value
        if isinstance(val, KV):
            if o.is_invoke:
                self._pending[o.process] = val.key
            else:
                self._pending.pop(o.process, None)
            return val.key, o.replace(value=val.value)
        if (not o.is_invoke) and o.process in self._pending:
            return self._pending.pop(o.process), o.replace(value=val)
        return None, None

    def _ingest_keyed(self, batch: list) -> None:
        # Route scalar (the pending map is inherently sequential), then
        # ingest columnar: one append_many per touched key.
        routed_by_key: dict = {}
        for op in batch:
            k, routed = self._route(op)
            if routed is None:
                continue
            lst = routed_by_key.get(k)
            if lst is None:
                lst = routed_by_key[k] = []
            lst.append(routed)
        touched = {}
        for k, rops in routed_by_key.items():
            b = self._builders.get(k)
            if b is None:
                b = self._builders[k] = PackedBuilder(self.pm.encode)
            b.append_many(rops)
            touched[k] = True
            if self._remote is not None:
                self._remote.put_many(k, rops)
        for k in touched:
            self._changed[k] = True
            v = self._verdicts.pop(k, None)
            if v is not None:
                # The key grew past its proof: the recorded verdict —
                # and any memoized copy — describes a history that no
                # longer exists.
                self._invalidate(v["digest"])
                self._rechecks += 1
                telemetry.count("wgl.online.rechecks")
        self._advance_big_keys(touched)
        self._check_quiet_keys()

    def _invalidate(self, digest: str) -> None:
        from ..parallel.independent import invalidate_settle_memo

        invalidate_settle_memo(digest)

    def _advance_big_keys(self, touched: dict) -> None:
        """Keys too large for whole-key rechecks carry their own
        frontier, advanced as their stable prefix grows."""
        for k in touched:
            b = self._builders[k]
            if b.n_rows < self.frontier_rows:
                continue
            fr = self._frontiers.get(k)
            if fr is None:
                fr = self._frontiers[k] = FrontierCarry(self.pm)
                self._fr_rows[k] = 0
                telemetry.count("wgl.online.key-frontiers")
            if fr.dead:
                continue
            if b.n_rows - self._fr_rows[k] >= self.advance_rows:
                packed, s = b.snapshot()
                fr.advance(packed, s)
                self._fr_rows[k] = b.n_rows

    def _check_quiet_keys(self) -> None:
        """Batches every changed, currently-quiet key through one
        stream-witness pass and records proofs by digest."""
        quiet = []
        for k in list(self._changed):
            b = self._builders[k]
            if k in self._frontiers:
                continue  # frontier keys conclude at finish()
            if b.in_flight > 0:
                continue
            if k in self._checked_rows and \
                    b.n_rows - self._checked_rows[k] < self.recheck_min_rows:
                continue
            quiet.append(k)
        if not quiet:
            return
        packs = []
        for k in quiet:
            packs.append(self._builders[k].snapshot()[0])
            self._checked_rows[k] = self._builders[k].n_rows
            del self._changed[k]
        self._stream_batch(quiet, packs)

    def _stream_batch(self, keys: list, packs: list) -> None:
        """One stream-witness pass over per-key packs; proofs recorded
        against each pack's digest."""
        from ..ops.wgl_stream import check_wgl_witness_stream
        from ..parallel.independent import _memo_put, _settle_digest

        self._checks += 1
        telemetry.count("wgl.online.keys-checked", len(keys))
        digests = [_settle_digest(p, self.pm) for p in packs]
        self._stream_rows_hwm = max(self._stream_rows_hwm,
                                    sum(int(p.n) for p in packs))
        kw: dict = {}
        from ..plan import enabled as _plan_enabled
        if _plan_enabled():
            from ..plan import costmodel
            knobs, _src = costmodel.choose_stream_knobs(
                len(packs), sum(int(p.n) for p in packs))
            kw["segment_keys"] = knobs["segment"]
            kw["max_restarts"] = knobs["max_restarts"]
        verdicts = check_wgl_witness_stream(packs, self.pm, **kw)
        for k, d, v in zip(keys, digests, verdicts):
            self._attempted[k] = d
            if v is True:
                res = {"valid": True, "algorithm": "wgl-online"}
                self._verdicts[k] = {"digest": d, "res": res}
                _memo_put(d, res)

    # -- single-stream mode ---------------------------------------------------

    def _ingest_single(self, batch: list) -> None:
        from ..parallel.independent import KV

        b = self._builder
        for op in batch:
            if isinstance(op.value, KV):
                self._break("KV op in single-stream mode")
                return
        b.append_many(batch)
        fr = self._frontier
        if fr is not None and not fr.dead and \
                b.n_rows - self._adv_rows >= self.advance_rows:
            packed, s = b.snapshot()
            fr.advance(packed, s)
            self._adv_rows = b.n_rows

    # -- completion ------------------------------------------------------------

    def finish(self) -> dict:
        """Stops the checker thread, drains the last buffer, runs the
        final proofs, and measures the verdict lag (time from the last
        op to the last online verdict).  Idempotent."""
        if self.finished:
            return self.stats()
        t0 = time.monotonic()
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=60.0)
        self.finished = True
        if not self.broken:
            try:
                # The frontier/session cost record: final-drain device
                # work (wgl.online.chunk spans) folds in via the span
                # hook; mid-run device time rides as a feature from the
                # frontier carries (it ran on the checker thread).
                with profile.capture(
                    "frontier",
                    keys=len(self._builders) or 1,
                    ops=int(self._ops_ingested),
                ) as _pf:
                    _pf.knob(mode=self.mode,
                             advance_rows=self.advance_rows)
                    batch = self._buf.take()
                    if batch:
                        self._ingest(batch)
                    self._finalize()
                    frontiers = list(self._frontiers.values())
                    if self._frontier is not None:
                        frontiers.append(self._frontier)
                    _pf.feature(
                        checks=self._checks,
                        device_s=round(sum(
                            fr.device_s for fr in frontiers), 6),
                    )
                    _pf.outcome = {"proven": len(self._verdicts)}
            except Exception as e:  # noqa: BLE001
                self._break(f"{type(e).__name__}: {e}")
        if self._remote is not None:
            self._remote.commit(list(self._builders))
        self.verdict_lag_s = time.monotonic() - t0
        telemetry.gauge("wgl.online.verdict-lag-s", self.verdict_lag_s)
        # Also into the quantile ring: the SLO p95 rule and the
        # /metrics summary family threshold on the distribution over
        # recent sessions, not this one sample.
        try:
            from ..telemetry import timeseries

            timeseries.observe("wgl.online.verdict-lag-s",
                               self.verdict_lag_s)
        except Exception:  # noqa: BLE001 — observability is side output
            pass
        # The verdict-lag SLO samples the gauge the instant it lands:
        # a blown lag budget dumps its postmortem here, at finish time,
        # not on the next telemetry flush.
        try:
            from ..telemetry import slo

            slo.evaluate()
        except Exception:  # noqa: BLE001 — alerting is side output
            log.warning("SLO evaluation at finish failed", exc_info=True)
        return self.stats()

    def _finalize(self) -> None:
        from ..parallel.independent import _memo_put, _settle_digest

        if self.mode == self.MODE_SINGLE:
            final = self._builder.finish()
            d = _settle_digest(final, self.pm)
            fr = self._frontier
            if fr is not None and fr.finalize(final) is True:
                res = {"valid": True, "algorithm": "wgl-online",
                       "op-count": int(final.n)}
                self._verdicts[None] = {"digest": d, "res": res}
                _memo_put(d, res)
            return
        if self.mode != self.MODE_KEYED:
            return
        # Close every builder: in-flight ops become indeterminate rows,
        # exactly as pack_history will see them post-hoc.
        finals = {k: b.finish() for k, b in self._builders.items()}
        self._changed.clear()
        # Frontier keys first: their carry already covers most blocks,
        # the finalize pass only runs the tail.
        for k, fr in self._frontiers.items():
            final = finals[k]
            if fr.finalize(final) is True:
                d = _settle_digest(final, self.pm)
                res = {"valid": True, "algorithm": "wgl-online"}
                self._verdicts[k] = {"digest": d, "res": res}
                _memo_put(d, res)
        # One last stream batch over every unproven key, on the FINAL
        # packs (mid-run proofs recorded snapshot digests; for a key
        # that stayed quiet those equal the final digest, so its
        # verdict already matches and is skipped here).  Keys whose
        # final pack is byte-identical to their last witness attempt
        # are skipped too: the witness is deterministic, so the answer
        # can only repeat — and invalid keys in particular restart the
        # stream engine on every attempt, which would otherwise land
        # squarely in the verdict lag.
        rest, packs = [], []
        for k in self._builders:
            if k in self._verdicts or k in self._frontiers:
                continue
            d = _settle_digest(finals[k], self.pm)
            if self._attempted.get(k) == d:
                continue
            rest.append(k)
            packs.append(finals[k])
        # Chunk sizing: by default HALF the mid-run high-water mark —
        # every mid-run batch already compiled its shape buckets, and
        # the window the witness buckets by scales with rows for
        # concatenated independent keys; a chunk at exactly the
        # high-water mark sits on the bucket edge, where one extra
        # indeterminate row tips into the next power of two and pays a
        # fresh XLA compile seconds before the verdict.  With planning
        # on and a trained cost model whose roofline-annotated stream
        # records cover the candidate shape buckets, the model picks
        # the chunk rows instead (plan/costmodel.py
        # choose_finalize_chunk_rows); out of support it falls back to
        # the same halving formula.
        total_rows = sum(p.n for p in packs)
        from ..plan import enabled as _plan_enabled
        if _plan_enabled():
            from ..plan import costmodel
            cap, cap_src = costmodel.choose_finalize_chunk_rows(
                len(rest), total_rows, self._stream_rows_hwm
            )
            if cap_src == "model":
                telemetry.count("wgl.plan.finalize-chunk-model")
            else:
                telemetry.count("wgl.plan.finalize-chunk-heuristic")
        else:
            cap = max(192, self._stream_rows_hwm // 2)
        i = 0
        while i < len(rest):
            j, rows = i, 0
            while j < len(rest) and (j == i or rows + packs[j].n <= cap):
                rows += packs[j].n
                j += 1
            self._stream_batch(rest[i:j], packs[i:j])
            i = j

    # -- consumers (post-hoc checkers, analyze, bench) -------------------------

    def consume(self, key: Any, digest: str) -> Optional[dict]:
        """The online verdict for `key` (None = single-stream), iff its
        proof covers exactly the packed history whose digest the caller
        re-derived.  Returns a result dict or None."""
        v = self._verdicts.get(key)
        if v is None or v["digest"] != digest:
            return None
        telemetry.count("wgl.online.consumed")
        return dict(v["res"])

    def remote_ticket(self, addr: str, keys: list, model_spec: Any,
                      algorithm: str, budget_s: Any,
                      time_limit_s: Any) -> Optional[str]:
        """The checkerd ticket for this run's streamed upload, iff the
        upload completed and covered the same keys/config the caller
        would submit.  Lets RemoteChecker skip re-uploading a history
        the daemon already holds."""
        if self._remote is None:
            return None
        return self._remote.ticket_for(addr, keys, model_spec, algorithm,
                                       budget_s, time_limit_s)

    @property
    def proven(self) -> int:
        return len(self._verdicts)

    def stats(self) -> dict:
        """The results["streaming"] block."""
        out = {
            "mode": self.mode,
            "ops-ingested": self._ops_ingested,
            "swaps": self._swaps,
            "keys": (len(self._builders) if self.mode == self.MODE_KEYED
                     else (1 if self._builder is not None else 0)),
            "proven-online": len(self._verdicts),
            "rechecks": self._rechecks,
            "verdict-lag-s": self.verdict_lag_s,
        }
        if self.broken:
            out["broken"] = self.broken_reason
        fr = self._frontier
        if fr is not None:
            out["frontier"] = {
                "blocks": fr.blocks_done, "bars": fr.bars_done,
                "chunks": fr.chunks, "device-s": round(fr.device_s, 3),
                "dead": fr.dead_reason,
            }
        if self._frontiers:
            out["key-frontiers"] = len(self._frontiers)
        if self._remote is not None:
            out["remote"] = self._remote.stats()
        return out
