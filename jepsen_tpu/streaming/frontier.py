"""Incremental frontier state for the online witness search.

The witness engine (ops/wgl_witness.py) already runs as a chunked scan
whose inter-chunk carry — member window, beam states, alive mask — IS a
frontier: every config alive after block b is a legal linearization
witness of the first (b+1)*K barriers.  `FrontierCarry` generalizes the
PR 3 stream witness into an *online* consumer: each `advance()` call
extends that carry over the barriers that have become decidable since
the last call, instead of restarting the search from op 0.

Soundness — which rows and barriers may be consumed mid-run
-----------------------------------------------------------

Let s be the builder's stable bound (history/packed.py PackedBuilder:
the minimum invocation event index over in-flight ops).  Two facts make
incremental consumption exact:

1. **Row-prefix stability.**  Rows with inv < s are final: every future
   row belongs either to an in-flight op (inv >= s) or to an op not yet
   invoked (inv >= the event counter >= s), so new rows only ever
   append AFTER the inv-sorted prefix.  Row indices, contents and order
   of the prefix never change — the carried window (row indices in
   `prev_active`) stays valid.

2. **Barrier-prefix stability.**  A barrier (ok row) with ret < s is
   final in the ret-sorted barrier order: any future completion gets an
   event index past every current one, and any in-flight op has
   inv >= s hence ret > s.  So the first `n_stable_bars` barriers —
   exactly those with ret < s — have final ranks, and a block whose K
   barriers are all stable has a final window too (its entrants are
   rows with inv < end_ret < s, all in the stable prefix).

`advance()` therefore processes only FULL blocks of K barriers whose
barriers all have ret < s.  Rows inside those windows whose own barrier
is still unstable carry a PROVISIONAL rank — but any such rank is
>= n_stable_bars, and inside a processed block (every k_rank <
n_stable_bars) the engine only tests `rank < k_rank` (implied
membership) and `rank >= k0` (window retention): both are decided
identically by the provisional and the final value.  Replanning on a
longer prefix is thus guaranteed to reproduce the already-processed
blocks bit-for-bit, which is why the carry composes across calls.

The window width W grows monotonically as the history lengthens; the
member matrix is re-embedded by padding False rows (window positions
past the previous width were never occupied), and the between-chunk
re-gather permutation only indexes positions < len(prev_active), so it
maps correctly after padding.

Death and fallback: a died frontier — or any planner/device error —
marks the carry dead.  Dead means "the witness cannot prove this
stream online"; the caller falls back to the ordinary post-hoc ladder
(whole-history recheck), so a death costs latency, never soundness.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

import numpy as np

from .. import telemetry
from ..history.packed import ST_OK, PackedOps
from ..models.base import PackedModel
from ..ops.wgl import _bucket, window_regather
from ..ops.wgl_witness import (
    INF,
    NARROW_INFO_WINDOW,
    NO_BAR,
    _chunk_fn_cache,
    _make_chunk_fn,
    _plan_blocks,
)

log = logging.getLogger(__name__)


class FrontierCarry:
    """Carries the witness search's device state across stable-prefix
    snapshots of one packed stream.

    Lifecycle: `advance(packed, s)` after each ingest swap with the
    builder's current stable snapshot; `finalize(packed)` once with the
    finished pack.  finalize returns True when a witness linearization
    survives the whole stream (exact: the stream is linearizable) or
    None when the frontier died / overflowed / errored — escalate to
    the post-hoc engines, never report invalid from here.
    """

    def __init__(
        self,
        pm: PackedModel,
        *,
        beam: int = 8,
        bars_per_block: int = 1024,
        blocks_per_call: int = 8,
        depth: int = 5,
        info_window: Optional[int] = NARROW_INFO_WINDOW,
        max_window: int = 32768,
    ):
        self.pm = pm
        self.B = _bucket(beam, lo=8)
        self.K = bars_per_block
        self.NB = blocks_per_call
        self.D = depth
        self.info_window = info_window
        self.max_window = max_window

        self.dead = False
        self.dead_reason: Optional[str] = None
        self.blocks_done = 0
        self.bars_done = 0
        self.chunks = 0
        self.device_s = 0.0

        self._W = 0
        self._member = None      # (W, B) bool device array
        self._states = None      # (B, SW) i32
        self._alive = None       # (B,) bool
        self._prev_active: Optional[np.ndarray] = None

    # -- internals ----------------------------------------------------------

    def _die(self, reason: str) -> None:
        self.dead = True
        self.dead_reason = reason
        # Free the device carry eagerly; a dead frontier never resumes.
        self._member = self._states = self._alive = None
        telemetry.count("wgl.online.frontier-deaths")
        log.info("online frontier died: %s (after %d blocks)",
                 reason, self.blocks_done)

    def _ensure_width(self, W: int) -> None:
        """Grows the window bucket, re-embedding the carried member
        matrix by padding False rows (positions past the old width were
        never occupied)."""
        import jax.numpy as jnp

        if W <= self._W:
            return
        if self._member is not None:
            old = np.asarray(self._member)
            grown = np.zeros((W, self.B), dtype=bool)
            grown[: old.shape[0]] = old
            self._member = jnp.asarray(grown)
        self._W = W

    def _init_carry(self) -> None:
        import jax.numpy as jnp

        self._member = jnp.zeros((self._W, self.B), dtype=bool)
        self._states = jnp.tile(
            jnp.asarray(np.asarray(self.pm.init_state, dtype=np.int32)),
            (self.B, 1),
        )
        alive_np = np.zeros(self.B, dtype=bool)
        alive_np[0] = True
        self._alive = jnp.asarray(alive_np)

    def _chunk_fn(self):
        """The compiled NB-block chunk entry for the current width.
        Shares ops/wgl_witness.py's cache (same key scheme) so a
        post-hoc witness run at the same shape reuses the compile."""
        W = self._W
        compact = max(64, min(
            W // 2,
            self.info_window if self.info_window is not None else W // 8,
        ))
        key = (self.B, W, self.pm.state_width, self.K, self.D, self.NB,
               self.pm.jax_step, "off", compact)
        fns = _chunk_fn_cache.get(key)
        if fns is None:
            fns = _make_chunk_fn(
                self.B, W, self.pm.state_width, self.K, self.D, self.NB,
                self.pm.jax_step, pallas_mode="off",
                jax_step_rows=self.pm.jax_step_rows, compact=compact,
            )
            _chunk_fn_cache[key] = fns
        return fns[0]  # transfer="full" entry

    def _run_blocks(self, packed: PackedOps, blocks, ret32, inv32,
                    bar_rank, upto: int) -> bool:
        """Runs blocks [blocks_done, upto) through the chunk fn,
        chaining the carry.  Returns False when the frontier died
        (carry marked dead)."""
        import jax.numpy as jnp

        if upto <= self.blocks_done:
            return True
        W_need = _bucket(max(
            self._W, 1,
            max(len(a) for _, _, a in blocks[self.blocks_done:upto]),
        ))
        if W_need > self.max_window:
            self._die(f"window {W_need} exceeds max {self.max_window}")
            return False
        self._ensure_width(W_need)
        if self._member is None:
            self._init_carry()
        fn = self._chunk_fn()
        W, B, K, NB = self._W, self.B, self.K, self.NB
        identity_perm = np.arange(W, dtype=np.int32)
        prev_active = self._prev_active
        failed = jnp.bool_(False)
        member, states, alive = self._member, self._states, self._alive

        for c0 in range(self.blocks_done, upto, NB):
            chunk_blocks = blocks[c0: min(c0 + NB, upto)]
            # Host tables, transfer="full" (the streaming pipeline runs
            # host-adjacent; pre-gathered tables are the fast path on
            # CPU and fine over PCIe).
            bars_np = np.zeros((NB, 6, K), dtype=np.int32)
            bars_np[:, 1, :] = INF
            tab_np = np.zeros((NB, 5, W), dtype=np.int32)
            perm_np = np.tile(identity_perm, (NB, 1))
            present_np = np.ones((NB, W), dtype=bool)
            k0s_np = np.zeros(NB, dtype=np.int32)
            for bi, (k0, block_bars, active) in enumerate(chunk_blocks):
                nw = len(active)
                nb = len(block_bars)
                k0s_np[bi] = k0
                bars_np[bi, 0, :nb] = np.searchsorted(active, block_bars)
                bars_np[bi, 1, :nb] = ret32[block_bars]
                bars_np[bi, 2, :nb] = 1
                bars_np[bi, 3, :nb] = packed.f[block_bars]
                bars_np[bi, 4, :nb] = packed.a0[block_bars]
                bars_np[bi, 5, :nb] = packed.a1[block_bars]
                row = tab_np[bi]
                row[0, :] = INF
                row[0, :nw] = inv32[active]
                row[1, :nw] = packed.f[active]
                row[2, :nw] = packed.a0[active]
                row[3, :nw] = packed.a1[active]
                row[4, :] = NO_BAR
                row[4, :nw] = np.minimum(bar_rank[active], NO_BAR)
                if prev_active is None:
                    present_np[bi, :] = False
                    perm_np[bi, :] = 0
                else:
                    perm, present = window_regather(prev_active, active)
                    perm_np[bi, :nw] = perm
                    perm_np[bi, nw:] = 0
                    present_np[bi, :nw] = present
                    present_np[bi, nw:] = False
                prev_active = active

            t0 = time.monotonic()
            try:
                with telemetry.span("wgl.online.chunk",
                                    blocks=len(chunk_blocks)):
                    member, states, alive, failed, died = fn(
                        member, states, alive, failed,
                        jnp.asarray(bars_np), jnp.asarray(tab_np),
                        jnp.asarray(perm_np), jnp.asarray(present_np),
                        jnp.asarray(k0s_np),
                    )
                    failed_now = bool(failed)
            except Exception as e:  # noqa: BLE001
                # Any device/compile failure mid-run: mark dead and let
                # the post-hoc ladder (with its own degradation rungs)
                # decide the stream.  Online checking must never cost
                # the verdict.
                self._die(f"device error: {type(e).__name__}: {e}")
                return False
            self.device_s += time.monotonic() - t0
            self.chunks += 1
            telemetry.count("wgl.online.chunks")
            self.blocks_done = c0 + len(chunk_blocks)
            self.bars_done = sum(len(b[1]) for b in blocks[:self.blocks_done])
            self._prev_active = prev_active
            if failed_now:
                self._die("frontier died (witness cannot prove)")
                return False

        self._member, self._states, self._alive = member, states, alive
        return True

    def _plan(self, packed: PackedOps):
        try:
            return _plan_blocks(packed, self.K, self.info_window)
        except OverflowError:
            self._die("timeline exceeds int32")
            return None

    # -- API ----------------------------------------------------------------

    def rebase(self, rows_dropped: int, bars_dropped: int) -> None:
        """Shifts the carry after the builder discarded a stable prefix
        (PackedBuilder.discard_stable_prefix): row indices fall by
        `rows_dropped`, barrier ranks by `bars_dropped`.  Sound because
        the discard conditions guarantee (a) dropped rows are a
        row-index prefix with the lowest `bars_dropped` barrier ranks,
        so every retained rank/index shifts uniformly, (b) at least the
        most recent processed block stays resident, so the carried
        window (`_prev_active`) references only retained rows — the
        device-side member/states/alive arrays hold no row indices or
        event values and carry over untouched."""
        if self.dead or rows_dropped <= 0:
            return
        if bars_dropped % self.K != 0:
            self._die(
                f"rebase of {bars_dropped} bars misaligned to K={self.K}"
            )
            return
        blocks_gone = bars_dropped // self.K
        if blocks_gone >= self.blocks_done:
            self._die(
                f"rebase would drop {blocks_gone} of "
                f"{self.blocks_done} processed blocks"
            )
            return
        self.blocks_done -= blocks_gone
        self.bars_done -= bars_dropped
        if self._prev_active is not None:
            if self._prev_active.size and int(self._prev_active.min()) < rows_dropped:
                self._die("rebase dropped a row still in the carry window")
                return
            self._prev_active = self._prev_active - rows_dropped
        telemetry.count("wgl.online.rebase")
        telemetry.count("wgl.online.rebase-bars", bars_dropped)

    def advance(self, packed: PackedOps, s: int) -> None:
        """Consumes the newly decidable barriers of a stable-prefix
        snapshot (`packed`, stable bound `s` — see PackedBuilder).
        Only FULL blocks whose K barriers all have ret < s run; the
        rest wait for the next call or finalize()."""
        if self.dead or packed.n == 0 or packed.n_ok == 0:
            return
        with telemetry.span("wgl.online.advance", rows=packed.n):
            plan = self._plan(packed)
            if plan is None:
                return
            bars, bar_rank, inv32, ret32, blocks, _ = plan
            n_stable_bars = int(np.count_nonzero(
                (packed.status == ST_OK) & (packed.ret < s)
            ))
            upto = min(n_stable_bars // self.K, len(blocks))
            self._run_blocks(packed, blocks, ret32, inv32, bar_rank, upto)

    def finalize(self, packed: PackedOps) -> Optional[bool]:
        """Runs the remaining blocks over the FINISHED pack and
        concludes: True = a witness survives (the stream is proven
        linearizable), None = escalate post-hoc."""
        if self.dead:
            return None
        if packed.n == 0 or packed.n_ok == 0:
            return True  # no barriers: trivially linearizable
        plan = self._plan(packed)
        if plan is None:
            return None
        bars, bar_rank, inv32, ret32, blocks, _ = plan
        if not self._run_blocks(packed, blocks, ret32, inv32, bar_rank,
                                len(blocks)):
            return None
        if self._alive is None or not bool(self._alive.any()):
            self._die("frontier empty at finalize")
            return None
        return True
