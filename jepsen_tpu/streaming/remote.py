"""Streamed checkerd upload: ship the history to the daemon WHILE the
run generates it.

Reuses the existing SUBMIT/CHUNK/COMMIT frames (checkerd/protocol.py)
on one long-lived connection: SUBMIT goes out with `streaming: true`
and a deferred key count, routed per-key op dicts ride CHUNK frames as
the run produces them, and COMMIT at finish() carries the final
`n-keys` — by which time the daemon already holds the whole history,
so the ticket is poll-ready almost immediately.  RemoteChecker then
consumes the ticket at analyze (`ticket_for`) instead of re-uploading,
iff the submission it WOULD have made matches what was streamed (same
address, keys, model spec, algorithm, budget); any mismatch or feed
death just means the ordinary post-hoc submission happens — streaming
the upload can cost bandwidth, never the verdict.

Reconnect: the SUBMIT carries a client-minted session token and the
feed retains every op dict it has handed to the socket.  When the
connection dies mid-run, a RESUME on a fresh connection re-attaches to
the daemon's parked submission and learns its stable bound — the
per-key op counts that made it into FULL frames server-side — and the
feed re-sends only each key's tail past that bound
(`wgl.online.remote-resumed`), instead of abandoning the upload and
falling back to a whole-history post-hoc submit.  No encoder interner
state crosses the wire for this: ops mode re-encodes deterministically
daemon-side, so the received-op counts ARE the snapshot.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Optional

from .. import telemetry
from ..history.core import Op

log = logging.getLogger(__name__)

#: Ops accumulated before a CHUNK frame goes out (smaller than the
#: client's bulk CHUNK_OPS: mid-run frames should flow, not pool).
FLUSH_OPS = 1024
#: ...and at least this often while ops trickle.
FLUSH_INTERVAL_S = 0.25


def remote_feed_for(addr: str, test: dict, model: Any) -> Optional["RemoteFeed"]:
    """A RemoteFeed mirroring exactly the submission RemoteChecker
    would make for this test, or None when the checker tree has no
    per-key remotable piece (then there is nothing to stream)."""
    from ..checker.core import Compose
    from ..checker.linearizable import Linearizable
    from ..checkerd.protocol import model_to_spec
    from ..parallel.independent import IndependentChecker

    def find_lin(c: Any) -> Optional[Linearizable]:
        if isinstance(c, IndependentChecker) and \
                isinstance(c.base, Linearizable):
            return c.base
        if isinstance(c, Compose):
            for child in c.checkers.values():
                lin = find_lin(child)
                if lin is not None:
                    return lin
        return None

    lin = find_lin(test.get("checker"))
    if lin is None:
        return None
    spec = model_to_spec(lin.model or model)
    if spec is None:
        return None
    return RemoteFeed(
        addr,
        run=str(test.get("name") or "run"),
        model_spec=spec,
        algorithm=lin.algorithm,
        budget_s=test.get("checker_budget"),
        time_limit_s=lin.time_limit_s,
    )


class RemoteFeed:
    """One streamed submission.  `put(key, op)` from the session's
    checker thread; `commit(keys)` once at finish; `ticket_for(...)`
    from RemoteChecker at analyze."""

    def __init__(self, addr: str, *, run: str, model_spec: dict,
                 algorithm: str, budget_s: Optional[float],
                 time_limit_s: Optional[float]):
        import uuid

        self.addr = addr
        self.run = run
        self.model_spec = model_spec
        self.algorithm = algorithm
        self.budget_s = budget_s
        self.time_limit_s = time_limit_s

        self.dead: Optional[str] = None
        self.ticket: Optional[str] = None
        self.ops_sent = 0
        self.resumes = 0
        self.ops_resent = 0
        #: Resume token minted per feed; the daemon parks the
        #: submission under it when our connection dies.
        self.session = uuid.uuid4().hex
        #: Everything handed to the socket, per key index — the local
        #: half of the resume protocol.  The dicts are the same objects
        #: the queue held, so the cost is one list slot per op.
        self._sent_ops: dict[int, list[dict]] = {}

        self._client = None
        self._keys: list = []            # first-seen order == key index
        self._index: dict = {}
        self._lock = threading.Lock()
        self._queue: list = []           # (key index, op dict)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="streaming-remote", daemon=True
        )
        self._thread.start()

    # -- session side --------------------------------------------------------

    def put(self, key: Any, op: Op) -> None:
        """Enqueues one routed per-key op for upload."""
        i = self._index.get(key)
        if i is None:
            i = self._index[key] = len(self._keys)
            self._keys.append(key)
        with self._lock:
            if self.dead:
                return
            self._queue.append((i, op.to_dict()))
            if len(self._queue) >= FLUSH_OPS:
                self._wake.set()

    def put_many(self, key: Any, ops: list) -> None:
        """put() for a per-key batch: one key lookup, the dict
        conversion outside the lock, one lock acquisition."""
        i = self._index.get(key)
        if i is None:
            i = self._index[key] = len(self._keys)
            self._keys.append(key)
        ods = [(i, op.to_dict()) for op in ops]
        with self._lock:
            if self.dead:
                return
            self._queue.extend(ods)
            if len(self._queue) >= FLUSH_OPS:
                self._wake.set()

    def commit(self, keys: list) -> None:
        """Drains the queue, finalizes the key count, collects the
        ticket.  `keys` is the session's first-seen key order — it must
        match what was streamed or the upload is abandoned."""
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=60.0)
        with self._lock:
            if self.dead:
                return
        if keys != self._keys:
            self._die("key order diverged from the session's")
            return
        try:
            self.ticket = self._commit_once()
        except Exception as e:  # noqa: BLE001
            # One reconnect attempt before giving up: COMMIT rides the
            # resumed connection, which already holds the full upload.
            if self._sent_ops and self._resume(f"{type(e).__name__}: {e}"):
                try:
                    self.ticket = self._commit_once()
                except Exception as e2:  # noqa: BLE001
                    self._die(f"{type(e2).__name__}: {e2}")
                    return
            else:
                self._die(f"{type(e).__name__}: {e}")
                return
        telemetry.count("wgl.online.remote-committed")
        with self._lock:
            sent = self.ops_sent
        log.info("streamed %d ops / %d keys to %s (ticket %s)",
                 sent, len(self._keys), self.addr, self.ticket)

    def _commit_once(self) -> str:
        from ..checkerd.protocol import F_COMMIT, F_TICKET

        self._flush()
        with self._lock:
            c = self._client
        if c is None:
            raise RuntimeError("nothing was streamed")
        c._send(F_COMMIT, {"n-keys": len(self._keys)})
        ftype, payload = c._recv()
        if ftype != F_TICKET:
            raise RuntimeError(f"expected TICKET, got {ftype}")
        return payload["ticket"]

    def ticket_for(self, addr: str, keys: list, model_spec: dict,
                   algorithm: str, budget_s: Any,
                   time_limit_s: Any) -> Optional[str]:
        """The ticket, iff this feed streamed the submission the caller
        is about to make."""
        if self.ticket is None:
            return None
        if (addr, keys, model_spec, algorithm, budget_s, time_limit_s) != \
                (self.addr, self._keys, self.model_spec, self.algorithm,
                 self.budget_s, self.time_limit_s):
            return None
        return self.ticket

    def stats(self) -> dict:
        with self._lock:
            out: dict = {"addr": self.addr, "ops-sent": self.ops_sent,
                         "keys": len(self._keys)}
            if self.ticket is not None:
                out["ticket"] = self.ticket
            if self.resumes:
                out["resumes"] = self.resumes
                out["ops-resent"] = self.ops_resent
            if self.dead:
                out["dead"] = self.dead
        return out

    # -- uploader thread -----------------------------------------------------

    def _die(self, reason: str) -> None:
        with self._lock:
            self.dead = reason
            self._queue = []
            c, self._client = self._client, None
        telemetry.count("wgl.online.remote-dead")
        log.info("streaming upload abandoned (post-hoc submit will "
                 "cover it): %s", reason)
        if c is not None:
            c.close()

    def _ensure_client(self) -> None:
        with self._lock:
            if self._client is not None:
                return
        from ..checkerd import overload
        from ..checkerd.client import CheckerdClient
        from ..checkerd.protocol import F_SUBMIT

        # Same process-wide breaker RemoteChecker consults: a daemon
        # that keeps refusing connections shouldn't cost this feed a
        # connect timeout per flush interval — abandon the stream (the
        # post-hoc submit covers it) until the breaker half-opens.
        br = overload.breaker_for(self.addr)
        if not br.allow():
            telemetry.count("checkerd.breaker-skip")
            raise RuntimeError(
                f"circuit open for {self.addr} (recent failures)"
            )
        try:
            c = CheckerdClient(self.addr)
        except Exception:
            br.record_failure()
            raise
        br.record_success()
        c._send(F_SUBMIT, {
            "run": self.run,
            "model": self.model_spec,
            "algorithm": self.algorithm,
            "n-keys": 0,
            "packed": False,
            "streaming": True,
            "session": self.session,
            "budget-s": self.budget_s,
            "time-limit-s": self.time_limit_s,
            # The run's trace context rides the streamed submission
            # too, so daemon spans for a mid-run feed still nest under
            # the run that generated the ops.
            "trace": telemetry.trace_context()
            if telemetry.enabled() else None,
        })
        c.wf.flush()
        with self._lock:
            self._client = c

    def _flush(self) -> None:
        from ..checkerd.protocol import F_CHUNK

        with self._lock:
            batch, self._queue = self._queue, []
        if not batch:
            return
        # Record intent before the socket sees anything: whatever the
        # send loses, the daemon's RESUME_OK counts tell us where in
        # these lists to restart from.
        for i, od in batch:
            self._sent_ops.setdefault(i, []).append(od)
        self._ensure_client()
        with self._lock:
            c = self._client
        # Coalesce runs of same-key ops into one CHUNK frame each.
        i0, ops = batch[0][0], []
        runs = []
        for i, od in batch:
            if i != i0:
                runs.append((i0, ops))
                i0, ops = i, []
            ops.append(od)
        runs.append((i0, ops))
        with telemetry.span("ingest.frame", frames=len(runs),
                            ops=len(batch)):
            for i, ops in runs:
                c._send(F_CHUNK, {"key": i, "ops": ops})
            c.wf.flush()
        with self._lock:
            self.ops_sent += len(batch)
        telemetry.count("wgl.online.remote-ops", len(batch))
        telemetry.count("ingest.frame.frames", len(runs))
        telemetry.count("ingest.frame.ops", len(batch))

    def _resume(self, why: str) -> bool:
        """Reconnects, re-attaches to the parked daemon-side submission
        via the session token, and re-sends each key's tail past the
        daemon's stable bound.  False means the fallback path (post-hoc
        submit) takes over."""
        from ..checkerd import overload
        from ..checkerd.client import CHUNK_OPS, CheckerdClient
        from ..checkerd.protocol import F_CHUNK, F_RESUME, F_RESUME_OK

        telemetry.count("wgl.online.remote-resume")
        log.info("streamed upload to %s interrupted (%s); resuming "
                 "session %s", self.addr, why, self.session[:8])
        with self._lock:
            c_old, self._client = self._client, None
        if c_old is not None:
            c_old.close()
        br = overload.breaker_for(self.addr)
        if not br.allow():
            telemetry.count("checkerd.breaker-skip")
            log.info("resume of session %s skipped: circuit open for "
                     "%s", self.session[:8], self.addr)
            return False
        c = None
        try:
            c = CheckerdClient(self.addr)
            c._send(F_RESUME, {"session": self.session})
            ftype, payload = c._recv()
            if ftype != F_RESUME_OK:
                raise RuntimeError(f"expected RESUME_OK, got {ftype}")
            received = payload.get("received") or {}
            resent = 0
            for i, ops in sorted(self._sent_ops.items()):
                have = int(received.get(str(i)) or 0)
                for lo in range(have, len(ops), CHUNK_OPS):
                    c._send(F_CHUNK, {
                        "key": i, "ops": ops[lo:lo + CHUNK_OPS],
                    })
                    resent += len(ops[lo:lo + CHUNK_OPS])
            c.wf.flush()
        except Exception as e:  # noqa: BLE001
            br.record_failure()
            if c is not None:
                c.close()
            log.info("resume of session %s failed (%s); abandoning the "
                     "stream", self.session[:8], e)
            return False
        br.record_success()
        with self._lock:
            self._client = c
            self.resumes += 1
            self.ops_resent += resent
        telemetry.count("wgl.online.remote-resumed")
        log.info("resumed session %s: re-sent %d of %d ops",
                 self.session[:8], resent,
                 sum(len(o) for o in self._sent_ops.values()))
        return True

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(FLUSH_INTERVAL_S)
            self._wake.clear()
            with self._lock:
                if self.dead:
                    return
            try:
                self._flush()
            except Exception as e:  # noqa: BLE001
                if self._sent_ops and \
                        self._resume(f"{type(e).__name__}: {e}"):
                    continue
                self._die(f"{type(e).__name__}: {e}")
                return
