"""Online (streaming) checking: consume the history while the run is
still generating it.

The interpreter's journal tees each op into a `StreamingSession`
(pipeline.py): a double-buffered ingest where one buffer fills on the
interpreter threads while the checker thread drains the other into
per-key appendable packed builders (history/packed.py PackedBuilder)
and advances device-side witness work — either an incremental
`FrontierCarry` (frontier.py) over a single stream, or batched
stream-witness passes over keys that have gone quiet.  By the time the
run ends, most keys already carry a proven verdict; `analyze` consumes
them by packed-digest match and only the remainder pays the post-hoc
ladder — verdict latency decouples from run length.

Enable with `--streaming` / `JEPSEN_STREAMING=1`.  See design.md
"Online checking" for the pipeline diagram and soundness argument.
"""

from __future__ import annotations

import logging
import os
from typing import Any, Optional

from .pipeline import StreamingSession

log = logging.getLogger(__name__)

__all__ = ["StreamingSession", "maybe_session", "streaming_enabled"]


def streaming_enabled(test: dict) -> bool:
    """Whether this run asked for online checking (--streaming flag or
    the JEPSEN_STREAMING env var)."""
    if test.get("streaming"):
        return True
    env = os.environ.get("JEPSEN_STREAMING", "")
    return env not in ("", "0", "false", "no")


def maybe_session(test: dict) -> Optional[StreamingSession]:
    """Builds a StreamingSession for this run, or None when the test
    has no packable model (online checking needs the packed/device
    form; host-only models stay post-hoc)."""
    model = test.get("model")
    if model is None:
        log.info("streaming requested but the test has no model; "
                 "checking stays post-hoc")
        return None
    try:
        pm = model.packed()
    except (NotImplementedError, AttributeError):
        log.info("streaming requested but model %s has no packed form; "
                 "checking stays post-hoc", type(model).__name__)
        return None
    remote = None
    addr = test.get("checkerd") or os.environ.get("JEPSEN_CHECKERD")
    if addr:
        try:
            from .remote import remote_feed_for
            remote = remote_feed_for(str(addr), test, model)
        except Exception as e:  # noqa: BLE001
            log.info("streaming remote feed unavailable: %s", e)
    run_id: Any = test.get("name") or "run"
    return StreamingSession(pm, remote=remote, run_id=str(run_id))
