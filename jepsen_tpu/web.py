"""Web dashboard: browse stored test runs over HTTP.

Equivalent of /root/reference/jepsen/src/jepsen/web.clj: an index of
runs with name, time, and validity (:51-66 cached rows), per-run file
listings, and file serving.  Stdlib http.server instead of
http-kit/hiccup; no external deps.
"""

from __future__ import annotations

import html
import http.server
import json
import logging
import os
import urllib.parse
from typing import Any, Optional

from . import store

log = logging.getLogger(__name__)

_STYLE = """
body { font-family: sans-serif; margin: 2em; }
table { border-collapse: collapse; }
td, th { padding: 0.3em 1em; border-bottom: 1px solid #ddd; text-align: left; }
.valid-true { color: #0a0; } .valid-false { color: #a00; }
.valid-unknown { color: #a60; }
a { text-decoration: none; }
.spark-row { display: flex; align-items: center; margin: 2px 0; }
.spark-label { width: 22em; font-family: monospace; font-size: 0.85em;
               overflow: hidden; text-overflow: ellipsis; }
.spark-val { margin-left: 0.8em; font-family: monospace;
             font-size: 0.85em; color: #555; }
.spark-row canvas { border-bottom: 1px solid #eee; }
"""


#: The /monitor page's client: one canvas sparkline per series,
#: bootstrapped from /api/series, then appended live from the SSE
#: stream.  Vanilla JS, no external assets — the dashboard must render
#: inside an airgapped pod.
_MONITOR_JS = """
<script>
(function () {
  var qs = new URLSearchParams(location.search);
  var dirq = qs.get('dir') ? '&dir=' + encodeURIComponent(qs.get('dir')) : '';
  var tier = qs.get('tier') || '0';
  var PIN = [
    'monitor.verdict-lag-s', 'wgl.online.verdict-lag-s.p95',
    'monitor.ops-per-s', 'monitor.ingest-ops-per-s',
    'checkerd.queue-depth',
    'monitor.resident-history-bytes', 'monitor.series-disk-bytes',
    'monitor.cost-drift-ratio', 'monitor.epoch-restarts',
    'monitor.discards', 'chip.health'
  ];
  var MAXPTS = 300;
  var charts = {};
  function rank(n) {
    var i = PIN.indexOf(n);
    if (i >= 0) return i;
    if (n.indexOf('monitor.') === 0) return 100;
    if (n.indexOf('slo.') === 0) return 200;
    return 300;
  }
  function fmt(v) {
    if (v == null) return '-';
    if (Math.abs(v) >= 1048576) return (v / 1048576).toFixed(1) + 'M';
    if (Math.abs(v) >= 1024) return (v / 1024).toFixed(1) + 'k';
    return (Math.round(v * 1000) / 1000).toString();
  }
  function addChart(name) {
    if (charts[name]) return charts[name];
    var row = document.createElement('div');
    row.className = 'spark-row';
    var label = document.createElement('span');
    label.className = 'spark-label';
    label.textContent = name;
    var canvas = document.createElement('canvas');
    canvas.width = 320; canvas.height = 40;
    var val = document.createElement('span');
    val.className = 'spark-val';
    row.appendChild(label); row.appendChild(canvas); row.appendChild(val);
    var box = document.getElementById('charts');
    var rows = box.children, r = rank(name), inserted = false;
    for (var i = 0; i < rows.length; i++) {
      var other = rows[i].getAttribute('data-rank');
      if (r < +other ||
          (r === +other && name < rows[i].getAttribute('data-name'))) {
        box.insertBefore(row, rows[i]); inserted = true; break;
      }
    }
    if (!inserted) box.appendChild(row);
    row.setAttribute('data-rank', r);
    row.setAttribute('data-name', name);
    charts[name] = {pts: [], canvas: canvas, val: val};
    return charts[name];
  }
  function draw(name) {
    var c = charts[name], ctx = c.canvas.getContext('2d');
    var w = c.canvas.width, h = c.canvas.height, pts = c.pts;
    ctx.clearRect(0, 0, w, h);
    if (!pts.length) { c.val.textContent = '-'; return; }
    var lo = Infinity, hi = -Infinity;
    for (var i = 0; i < pts.length; i++) {
      if (pts[i][1] < lo) lo = pts[i][1];
      if (pts[i][1] > hi) hi = pts[i][1];
    }
    if (hi === lo) { hi += 1; lo -= 1; }
    ctx.strokeStyle = '#47a'; ctx.lineWidth = 1.5; ctx.beginPath();
    for (var j = 0; j < pts.length; j++) {
      var x = pts.length > 1 ? j * (w - 4) / (pts.length - 1) + 2 : w / 2;
      var y = h - 3 - (pts[j][1] - lo) * (h - 6) / (hi - lo);
      if (j === 0) ctx.moveTo(x, y); else ctx.lineTo(x, y);
    }
    ctx.stroke();
    c.val.textContent = fmt(pts[pts.length - 1][1]) +
      ' (' + fmt(lo) + '..' + fmt(hi) + ')';
  }
  function push(name, t, v) {
    if (v && typeof v === 'object') {
      v = v.mean != null ? v.mean : v.last;
    }
    if (typeof v !== 'number' || !isFinite(v)) return;
    var c = addChart(name);
    if (c.pts.length && c.pts[c.pts.length - 1][0] >= t) return;
    c.pts.push([t, v]);
    if (c.pts.length > MAXPTS) c.pts.shift();
    draw(name);
  }
  fetch('/api/series?tier=' + tier + dirq)
    .then(function (r) { return r.json(); })
    .then(function (d) {
      (d.names || []).forEach(function (n) {
        addChart(n);
        fetch('/api/series?name=' + encodeURIComponent(n) +
              '&tier=' + tier + '&limit=' + MAXPTS + dirq)
          .then(function (r) { return r.json(); })
          .then(function (s) {
            charts[n].pts = (s.points || []).concat(charts[n].pts);
            if (charts[n].pts.length > MAXPTS) {
              charts[n].pts = charts[n].pts.slice(-MAXPTS);
            }
            draw(n);
          });
      });
      var es = new EventSource('/api/series/stream?tier=' + tier + dirq);
      es.onmessage = function (ev) {
        var p = JSON.parse(ev.data);
        var s = p.s || {};
        Object.keys(s).forEach(function (n) { push(n, p.t, s[n]); });
      };
    });
})();
</script>
"""

_FLEET_JS = """
<script>
(function () {
  'use strict';
  function fmtBytes(b) {
    if (b > 1048576) { return (b / 1048576).toFixed(1) + 'M'; }
    if (b > 1024) { return (b / 1024).toFixed(1) + 'K'; }
    return String(b);
  }
  function drawSpark(canvas, pts) {
    var ctx = canvas.getContext('2d');
    var W = canvas.width, H = canvas.height;
    ctx.clearRect(0, 0, W, H);
    if (!pts.length) { return; }
    var vs = pts.map(function (p) { return p[1]; });
    var mn = Math.min.apply(null, vs), mx = Math.max.apply(null, vs);
    var span = (mx - mn) || 1;
    ctx.strokeStyle = '#069';
    ctx.beginPath();
    pts.forEach(function (p, i) {
      var x = i / Math.max(1, pts.length - 1) * (W - 2) + 1;
      var y = H - 2 - (p[1] - mn) / span * (H - 4);
      if (i === 0) { ctx.moveTo(x, y); } else { ctx.lineTo(x, y); }
    });
    ctx.stroke();
  }
  var sparks = {};   // tenant -> points
  var streams = {};  // tenant -> EventSource
  function ensureStream(name, dir) {
    if (streams[name]) { return; }
    var es = new EventSource(
      '/api/series/stream?dir=' + encodeURIComponent(dir));
    es.onmessage = function (ev) {
      var p = JSON.parse(ev.data);
      var v = (p.s || {})['monitor.ops-per-s'];
      if (v === undefined) { return; }
      var pts = sparks[name] = sparks[name] || [];
      pts.push([p.t, v]);
      if (pts.length > 60) { pts.shift(); }
      var row = document.getElementById('t-' + name);
      if (row) { drawSpark(row.querySelector('.spark'), pts); }
    };
    streams[name] = es;
  }
  function refresh() {
    fetch('/api/fleet')
      .then(function (r) { return r.json(); })
      .then(function (d) {
        Object.keys(d.tenants || {}).forEach(function (name) {
          var t = d.tenants[name];
          var row = document.getElementById('t-' + name);
          if (!row) { return; }
          var sup = t.supervisor || {};
          var state = (t.spec || {}).state || '?';
          if (sup.alive === false && state === 'running') {
            state += ' (down)';
          }
          row.querySelector('.state').textContent = state;
          var firing = t['slo-firing'] || [];
          var slo = row.querySelector('.slo');
          slo.textContent = firing.length ? firing.join(', ') : 'ok';
          slo.style.color = firing.length ? '#b00' : '#080';
          row.querySelector('.restarts').textContent =
            String(sup.restarts || 0);
          row.querySelector('.shed').textContent =
            firing.indexOf('tenant-shed-backoff-rate') >= 0 ?
            'backing off' : 'ok';
          row.querySelector('.disk').textContent =
            fmtBytes(t['disk-bytes'] || 0);
          if (!sparks[name] && (t.spark || []).length) {
            sparks[name] = t.spark.slice(-60);
            drawSpark(row.querySelector('.spark'), sparks[name]);
          }
          ensureStream(name, t.dir);
        });
      });
  }
  refresh();
  setInterval(refresh, 5000);
})();
</script>
"""

#: {run_dir: (jtpu mtime, validity)} so the index doesn't re-scan every
#: test file on every page load (web.clj:51-66 caches its rows too).
_validity_cache: dict[str, tuple[float, str]] = {}


def _validity(run_dir: str) -> str:
    jtpu = os.path.join(run_dir, store.TEST_FILE)
    try:
        mtime = os.path.getmtime(jtpu)
    except OSError:
        return "?"
    cached = _validity_cache.get(run_dir)
    if cached is not None and cached[0] == mtime:
        return cached[1]
    try:
        tf = store.load(run_dir)
        try:
            res = tf.results
            v = "?" if res is None else str(res.get("valid"))
        finally:
            tf.close()
    except Exception:  # noqa: BLE001
        v = "?"
    _validity_cache[run_dir] = (mtime, v)
    return v


def _slo_panel() -> str:
    """Per-rule SLO status table (telemetry/slo.py) for the index and
    /fleet pages.  Renders this process's engine — the dashboard
    co-hosted with runs or a daemon shows live state; a detached one
    shows the stock rules all-clear."""
    try:
        from .telemetry import slo

        rows = slo.status()
    except Exception:  # noqa: BLE001 — render, don't 500
        return ""
    if not rows:
        return ""
    trs = "".join(
        f"<tr><td>{html.escape(str(r['rule']))}</td>"
        f"<td>{html.escape(str(r['kind']))}</td>"
        f"<td>{html.escape(str(r['target']))}</td>"
        f"<td>{html.escape(str(r['threshold']))}</td>"
        f"<td class='valid-{'false' if r['firing'] else 'true'}'>"
        f"{'FIRING' if r['firing'] else 'ok'}</td>"
        f"<td>{html.escape(str(r['value']))}</td></tr>"
        for r in rows
    )
    return (
        "<h2>SLOs</h2><table><tr><th>rule</th><th>kind</th>"
        "<th>target</th><th>threshold</th><th>state</th><th>last value"
        "</th></tr>" + trs + "</table>"
    )


def _fmt_rate(v: Any) -> str:
    """Engineering-notation rate for roofline cells (1.2e9 -> 1.2 G)."""
    if not isinstance(v, (int, float)):
        return "-"
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"),
                          (1e3, "k")):
        if abs(v) >= scale:
            return f"{v / scale:.2f} {suffix}"
    return f"{v:.3f}"


def _roofline_panel(summary: Any) -> str:
    """Per-pass roofline table (telemetry/roofline.summarize shape):
    achieved FLOP/s and bytes/s vs device peak, arithmetic intensity
    against the memory/compute knee, and which side each pass lands
    on.  Shared by /fleet (from checkerd STATS) and /monitor (from the
    store's profiles.jsonl)."""
    if not isinstance(summary, dict) or not summary:
        return ""
    knee = None
    trs = ""
    for name, s in sorted(summary.items()):
        if not isinstance(s, dict):
            continue
        if knee is None and isinstance(s.get("knee_intensity"),
                                       (int, float)):
            knee = s["knee_intensity"]
        ratio = s.get("median_flops_ratio")
        pct = f"{ratio * 100:.4f}%" if isinstance(
            ratio, (int, float)) else "-"
        bound = s.get("bound") or "-"
        trs += (
            f"<tr><td>{html.escape(str(name))}</td>"
            f"<td>{s.get('n')}</td><td>{s.get('with_cost')}</td>"
            f"<td>{_fmt_rate(s.get('median_flops'))}</td>"
            f"<td>{_fmt_rate(s.get('median_achieved_flops_per_s'))}</td>"
            f"<td>{pct}</td>"
            f"<td>{_fmt_rate(s.get('median_achieved_bytes_per_s'))}</td>"
            f"<td>{_fmt_rate(s.get('median_arithmetic_intensity'))}</td>"
            f"<td>{html.escape(str(bound))}</td></tr>"
        )
    if not trs:
        return ""
    kneenote = (
        f" · knee at intensity {knee:.2f} FLOP/byte (left of the knee "
        "is memory-bound, right is compute-bound)"
        if isinstance(knee, (int, float)) else ""
    )
    return (
        "<h2>roofline (telemetry/roofline.py)</h2>"
        f"<p>per-pass medians vs device peak{kneenote}</p>"
        "<table><tr><th>pass</th><th>n</th><th>with cost</th>"
        "<th>flops</th><th>achieved FLOP/s</th><th>% of peak</th>"
        "<th>achieved B/s</th><th>intensity</th><th>bound</th></tr>"
        + trs + "</table>"
    )


def _page(title: str, body: str) -> bytes:
    return (
        f"<!doctype html><html><head><meta charset='utf-8'>"
        f"<title>{html.escape(title)}</title><style>{_STYLE}</style>"
        f"</head><body><h1>{html.escape(title)}</h1>{body}</body></html>"
    ).encode()


class Handler(http.server.BaseHTTPRequestHandler):
    store_dir = "store"

    def log_message(self, fmt: str, *args) -> None:  # quiet
        log.debug("web: " + fmt, *args)

    def _send(self, code: int, body: bytes, ctype: str = "text/html") -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self) -> None:  # noqa: N802 (stdlib API)
        raw_path, _, raw_query = self.path.partition("?")
        path = urllib.parse.unquote(raw_path)
        self._query = urllib.parse.parse_qs(raw_query)
        try:
            if path in ("/", ""):
                self._index()
            elif path.startswith("/files/"):
                self._file(path[len("/files/"):])
            elif path.startswith("/zip/"):
                self._zip(path[len("/zip/"):])
            elif path.startswith("/telemetry/"):
                self._telemetry(path[len("/telemetry/"):])
            elif path.startswith("/search/"):
                self._search(path[len("/search/"):])
            elif path.rstrip("/") == "/fleet":
                self._fleet()
            elif path.rstrip("/") == "/metrics":
                self._metrics()
            elif path.rstrip("/") == "/monitor":
                self._monitor()
            elif path.rstrip("/") == "/api/series/stream":
                self._series_stream()
            elif path.rstrip("/") == "/api/series":
                self._series_api()
            elif path.rstrip("/") == "/api/fleet":
                self._fleet_api()
            else:
                self._send(404, _page("404", "<p>not found</p>"))
        except (BrokenPipeError, ConnectionResetError):
            pass
        except Exception as e:  # noqa: BLE001
            log.exception("web error")
            self._send(500, _page("error", f"<pre>{html.escape(repr(e))}</pre>"))

    def _index(self) -> None:
        # Fault-search dirs (`jepsen search` state under
        # <store>/<name>-search/) get their own coverage-panel links
        # and stay out of the test-run table — their subdirs are
        # corpus/cells/runs, not timestamped runs.
        search_names = set()
        root = self.store_dir
        if os.path.isdir(root):
            for name in sorted(os.listdir(root)):
                d = os.path.join(root, name)
                if os.path.isfile(os.path.join(d, "search.json")):
                    search_names.add(name)
        searches = [
            f"<li><a href='/search/{urllib.parse.quote(n)}'>"
            f"{html.escape(n)}</a></li>"
            for n in sorted(search_names)
        ]
        rows = []
        for name, runs in sorted(store.tests(self.store_dir).items()):
            if name in search_names:
                continue
            for t, d in sorted(runs.items(), reverse=True):
                v = _validity(d)
                rel = os.path.relpath(d, self.store_dir)
                q = urllib.parse.quote(rel)
                tel = (
                    f"<a href='/telemetry/{q}'>telemetry</a>"
                    if os.path.isfile(os.path.join(d, "telemetry.json"))
                    else ""
                )
                forens = (
                    f"<a href='/files/{q}/forensics/'>forensics</a>"
                    if os.path.isdir(os.path.join(d, "forensics"))
                    else ""
                )
                rows.append(
                    f"<tr><td><a href='/files/{q}/'>"
                    f"{html.escape(name)}</a></td>"
                    f"<td>{html.escape(t)}</td>"
                    f"<td class='valid-{html.escape(v.lower())}'>{html.escape(v)}</td>"
                    f"<td>{tel}</td>"
                    f"<td>{forens}</td>"
                    f"<td><a href='/zip/{q}'>zip</a></td></tr>"
                )
        body = (
            "<p><a href='/fleet'>checker fleet</a> · "
            "<a href='/monitor'>monitor observatory</a></p>"
            + (
                "<h2>fault searches</h2><ul>" + "".join(searches)
                + "</ul>" if searches else ""
            )
            + "<table><tr><th>test</th><th>time</th><th>valid?</th>"
            "<th></th><th></th><th></th></tr>"
            + "".join(rows)
            + "</table>"
            + _slo_panel()
        )
        self._send(200, _page("jepsen-tpu store", body))

    def _fleet(self) -> None:
        """Live stats of a checkerd daemon (checkerd/scheduler.py
        stats()): per-run queue depth, cohort merge ratio, device
        utilization, verdict latency.  The daemon address comes from
        ?addr=host:port, the JEPSEN_CHECKERD env var, or the default
        port on localhost."""
        from .checkerd import ADDR_ENV, DEFAULT_PORT

        addr = (
            (self._query.get("addr") or [None])[0]
            or os.environ.get(ADDR_ENV)
            or f"127.0.0.1:{DEFAULT_PORT}"
        )
        hint = (
            "<p>point this page elsewhere with <code>?addr=host:port"
            "</code>; start a daemon with <code>jepsen checkerd</code>"
            " and route runs through it with <code>--remote</code></p>"
        )
        lint_tbl = ""
        try:
            from .analysis.core import read_store_summary

            summary = read_store_summary(self.store_dir)
        except Exception:  # noqa: BLE001 — render, don't 500
            summary = None
        if summary:
            counts = summary.get("counts") or {}
            families = summary.get("families") or {}
            lrows = "".join(
                f"<tr><td>{html.escape(str(k))}</td>"
                f"<td>{html.escape(str(v))}</td></tr>"
                for k, v in [
                    ("last run", summary.get("at")),
                    ("clean", summary.get("clean")),
                    ("unbaselined", summary.get("unbaselined")),
                    ("baselined", summary.get("baselined")),
                    ("errors", counts.get("error")),
                    ("warnings", counts.get("warning")),
                    ("advice", counts.get("advice")),
                    ("files", summary.get("files")),
                    ("duration s", summary.get("duration_s")),
                ]
            )
            frows = "".join(
                f"<tr><td>{html.escape(str(fam))}</td>"
                f"<td>{sevs.get('error', 0)}</td>"
                f"<td>{sevs.get('warning', 0)}</td>"
                f"<td>{sevs.get('advice', 0)}</td></tr>"
                for fam, sevs in sorted(families.items())
                if isinstance(sevs, dict)
            )
            fam_tbl = (
                "<h3>by family</h3><table><tr><th>family</th>"
                "<th>errors</th><th>warnings</th><th>advice</th></tr>"
                f"{frows}</table>" if frows else ""
            )
            lint_tbl = (
                "<h2>static analysis (jepsenlint)</h2>"
                f"<table>{lrows}</table>" + fam_tbl
            )
        try:
            from .checkerd.client import fetch_stats

            stats = fetch_stats(addr, timeout=2.0)
        except Exception as e:  # noqa: BLE001 — render, don't 500
            self._send(200, _page(
                "checker fleet",
                f"<p>checkerd at <code>{html.escape(addr)}</code> "
                f"is unreachable: <code>{html.escape(repr(e))}</code>"
                f"</p>" + _slo_panel() + lint_tbl + hint,
            ))
            return
        if stats.get("router"):
            # The address is a federation router (checkerd/router.py):
            # render the fleet-wide panel instead of single-daemon stats.
            self._send(200, _page(
                "checker federation",
                self._federation_panel(addr, stats)
                + _slo_panel() + lint_tbl + hint,
            ))
            return
        devs = stats.get("devices") or {}
        lat = stats.get("verdict-latency") or {}
        overview = [
            ("daemon", addr),
            ("uptime s", stats.get("uptime-s")),
            ("devices", f"{devs.get('count')} x {devs.get('platform')}"),
            ("device utilization", stats.get("utilization")),
            ("queue depth", stats.get("queue-depth")),
            ("requests", stats.get("requests")),
            ("keys", stats.get("keys")),
            ("cohorts", stats.get("cohorts")),
            ("cohorts merged (>1 run)", stats.get("cohorts-merged")),
            ("merge ratio", stats.get("merge-ratio")),
            ("models cached", stats.get("models-cached")),
            ("chip health", stats.get("chip-health")),
            ("profile records", stats.get("profile-records")),
            ("verdict latency mean s", lat.get("mean-s")),
            ("verdict latency max s", lat.get("max-s")),
        ]
        orows = "".join(
            f"<tr><td>{html.escape(str(k))}</td>"
            f"<td>{html.escape(str(v))}</td></tr>"
            for k, v in overview
        )
        rrows = ""
        for run, d in sorted((stats.get("runs") or {}).items()):
            rrows += (
                f"<tr><td>{html.escape(str(run))}</td>"
                f"<td>{d.get('queued')}</td><td>{d.get('running')}</td>"
                f"<td>{d.get('submitted')}</td><td>{d.get('done')}</td>"
                f"<td>{d.get('merged')}</td>"
                f"<td>{d.get('last-latency-s')}</td></tr>"
            )
        runs_tbl = (
            "<h2>runs</h2><table><tr><th>run</th><th>queued</th>"
            "<th>running</th><th>submitted</th><th>done</th>"
            "<th>merged</th><th>last latency s</th></tr>"
            + rrows + "</table>"
        ) if rrows else "<p>no runs have submitted yet</p>"
        plan_tbl = ""
        plan = stats.get("plan") or {}
        if plan:
            cache = plan.get("cache") or {}
            memo = cache.get("memo") or {}
            cm = plan.get("costmodel") or {}
            prows = "".join(
                f"<tr><td>{html.escape(str(k))}</td>"
                f"<td>{html.escape(str(v))}</td></tr>"
                for k, v in [
                    ("plan executor", "on" if plan.get("enabled")
                     else "off"),
                    ("cache dir", cache.get("dir") or "(not configured)"),
                    ("memo entries", memo.get("entries")),
                    ("memo hits", memo.get("hits")),
                    ("memo misses", memo.get("misses")),
                    ("memo stores", memo.get("puts")),
                    ("xla cache files", cache.get("xla_files")),
                    ("cost model", "trained" if cm.get("loaded")
                     else "heuristics"),
                    ("cost model passes", ", ".join(cm.get("passes") or [])
                     or "-"),
                ]
            )
            plan_tbl = (
                "<h2>plan cache (plan/cache.py)</h2>"
                f"<table>{prows}</table>"
            )
        ov_tbl = ""
        ov = stats.get("overload") or {}
        if ov:
            weights = ov.get("weights") or {}
            head = "".join(
                f"<tr><td>{html.escape(str(k))}</td>"
                f"<td>{html.escape(str(v))}</td></tr>"
                for k, v in [
                    ("brownout level", ov.get("brownout-level")),
                    ("sheds total", ov.get("shed")),
                    ("fair-queue quantum (key-credits)",
                     ov.get("quantum")),
                ]
            )
            trows = "".join(
                f"<tr><td>{html.escape(str(t))}</td>"
                f"<td>{weights.get(t, 1.0)}</td>"
                f"<td>{d.get('served')}</td><td>{d.get('shed')}</td>"
                f"<td>{d.get('queue-wait-p95-s')}</td></tr>"
                for t, d in sorted((ov.get("tenants") or {}).items())
                if isinstance(d, dict)
            )
            tenants_tbl = (
                "<h3>tenants (deficit round-robin)</h3><table>"
                "<tr><th>tenant</th><th>weight</th><th>served</th>"
                "<th>shed</th><th>queue-wait p95 s</th></tr>"
                + trows + "</table>"
            ) if trows else ""
            ov_tbl = (
                "<h2>overload control (checkerd/overload.py)</h2>"
                f"<table>{head}</table>" + tenants_tbl
            )
        self._send(200, _page(
            "checker fleet",
            f"<table>{orows}</table>" + runs_tbl + ov_tbl + plan_tbl
            + _roofline_panel(stats.get("roofline"))
            + _slo_panel() + lint_tbl + hint,
        ))

    def _federation_panel(self, addr: str, stats: dict) -> str:
        """The /fleet body for a federation router: router overview
        (placement, failover, admission counters) plus one row per
        daemon with its health state, queue depth and cache warmth."""
        quota = stats.get("quota") or {}
        qj = stats.get("queue-journal") or {}
        overview = [
            ("router", addr),
            ("uptime s", stats.get("uptime-s")),
            ("daemons", len(stats.get("daemons") or {})),
            ("fleet queue depth", stats.get("queue-depth")),
            ("tickets in flight", stats.get("inflight")),
            ("submits placed", stats.get("submits")),
            ("results relayed", stats.get("results")),
            ("failovers", stats.get("failovers")),
            ("admission rejected", stats.get("admission-rejected")),
            ("replayed from journal", stats.get("replayed")),
            ("tenant quota", quota.get("tenant-quota") or "unlimited"),
            ("max in-flight", quota.get("max-inflight") or "unlimited"),
            ("ticket journal", qj.get("path") or "(not configured)"),
        ]
        orows = "".join(
            f"<tr><td>{html.escape(str(k))}</td>"
            f"<td>{html.escape(str(v))}</td></tr>"
            for k, v in overview
        )
        health = stats.get("health") or {}
        # Model-cache affinity inverted: daemon -> spec count (which
        # caches placement considers warm there).
        warm: dict = {}
        for _spec, d in (stats.get("affinity") or {}).items():
            warm[d] = warm.get(d, 0) + 1
        drows = ""
        for d, st in sorted((stats.get("daemons") or {}).items()):
            h = health.get(str(d)) or {}
            if not isinstance(st, dict) or st.get("unreachable"):
                drows += (
                    f"<tr><td>{html.escape(str(d))}</td>"
                    f"<td>{html.escape(str(h.get('state') or '?'))}</td>"
                    f"<td colspan=4>unreachable</td></tr>"
                )
                continue
            drows += (
                f"<tr><td>{html.escape(str(d))}</td>"
                f"<td>{html.escape(str(h.get('state') or 'healthy'))}</td>"
                f"<td>{html.escape(str(st.get('queue-depth')))}</td>"
                f"<td>{html.escape(str(st.get('requests')))}</td>"
                f"<td>{html.escape(str(st.get('models-cached')))}</td>"
                f"<td>{html.escape(str(warm.get(str(d), 0)))}</td></tr>"
            )
        daemons_tbl = (
            "<h2>daemons</h2><table><tr><th>daemon</th><th>health</th>"
            "<th>queue depth</th><th>requests</th><th>models cached</th>"
            "<th>affinity specs</th></tr>" + drows + "</table>"
        )
        shed_tbl = ""
        sheds = stats.get("shed-by-tenant") or {}
        if sheds:
            srows = "".join(
                f"<tr><td>{html.escape(str(t))}</td>"
                f"<td>{html.escape(str(n))}</td></tr>"
                for t, n in sorted(sheds.items())
            )
            shed_tbl = (
                "<h2>admission sheds by tenant</h2><table>"
                "<tr><th>tenant</th><th>sheds</th></tr>"
                + srows + "</table>"
            )
        return f"<table>{orows}</table>" + daemons_tbl + shed_tbl

    def _metrics(self) -> None:
        """Prometheus text scrape surface: this process's telemetry
        counters/gauges/span totals plus the chip-health one-hot.  The
        dashboard usually runs in a different process from the test
        runs, so the interesting numbers here are the daemon-side ones
        when the dashboard and checkerd are co-hosted — checkerd also
        exposes its own /metrics (see checkerd.server.make_metrics_server)
        for the common split deployment."""
        from . import telemetry
        from .ops import degrade

        extra = {}
        try:
            from .checkerd.client import fetch_stats

            stats = fetch_stats(
                self._query.get("addr", ["127.0.0.1:7462"])[0],
                timeout=2.0,
            )
            for key in ("queue-depth", "utilization", "uptime-s",
                        "requests", "cohorts", "merge-ratio",
                        "profile-records"):
                if stats.get(key) is not None:
                    extra[f"checkerd.{key}"] = float(stats[key])
        except Exception:  # noqa: BLE001 — scrape must not 500
            pass
        lint_counts = None
        try:
            from .analysis.core import read_store_summary

            summary = read_store_summary(self.store_dir)
            if summary:
                # Prefer the per-family breakdown (adds the `family`
                # label); older summaries only carry flat counts.
                lint_counts = (summary.get("families")
                               or summary.get("counts"))
        except Exception:  # noqa: BLE001 — scrape must not 500
            pass
        # Evaluate the SLO rules with the freshest samples this scrape
        # gathered (daemon gauges resolve through `extra`), so the
        # exported jepsen_slo_firing family reflects this instant.
        try:
            from .telemetry import slo

            slo.evaluate(extra, degrade.chip_state())
        except Exception:  # noqa: BLE001 — scrape must not 500
            pass
        body = telemetry.prometheus_text(
            extra_gauges=extra, chip_state=degrade.chip_state(),
            lint_findings=lint_counts,
        ).encode()
        self._send(200, body, ctype="text/plain; version=0.0.4")

    def _series_root(self) -> Optional[str]:
        """Directory holding the monitor's series-t*.jtpu files: the
        store dir itself (a co-hosted `jepsen monitor --serve-port`),
        an explicit ?dir= subdir, or the first subdir that has them (a
        detached `jepsen serve store` over `store/monitor`)."""
        from .telemetry import timeseries

        root = os.path.realpath(self.store_dir)
        sub = (self._query.get("dir") or [""])[0].strip("/")
        if sub:
            cand = os.path.realpath(os.path.join(root, sub))
            if cand == root or cand.startswith(root + os.sep):
                return cand
            return None
        if os.path.isfile(timeseries.series_path(root, 0)):
            return root
        if os.path.isdir(root):
            for name in sorted(os.listdir(root)):
                d = os.path.join(root, name)
                if os.path.isfile(timeseries.series_path(d, 0)):
                    return d
        return None

    def _series_api(self) -> None:
        """JSON read API over the durable series store, straight from
        disk so it works cross-process and across monitor restarts.
        Without ?name= lists series names; with it returns points."""
        from .telemetry import timeseries

        root = self._series_root()
        if root is None:
            self._send(404, b'{"error": "no series store found"}',
                       "application/json")
            return
        q = self._query
        try:
            tier = min(2, max(0, int((q.get("tier") or ["0"])[0])))
        except ValueError:
            tier = 0
        name = (q.get("name") or [""])[0]
        if not name:
            body: dict = {
                "tier": tier,
                "names": timeseries.read_disk_names(root, tier),
            }
        else:
            try:
                since = (float(q["since"][0])
                         if q.get("since") else None)
            except ValueError:
                since = None
            try:
                limit = int((q.get("limit") or ["0"])[0])
            except ValueError:
                limit = 0
            body = {
                "name": name,
                "tier": tier,
                "points": timeseries.read_disk_series(
                    root, name, tier=tier, since=since, limit=limit
                ),
            }
        self._send(200, json.dumps(body).encode(), "application/json")

    def _series_stream(self) -> None:
        """Server-sent events: tails the tier file and pushes each new
        sample payload ({"t": ..., "s": {name: value}}) as one event.
        The dashboard's EventSource reconnects on its own, so each
        connection is capped rather than held forever."""
        import time as _time

        from .telemetry import timeseries

        root = self._series_root()
        if root is None:
            self._send(404, b'{"error": "no series store found"}',
                       "application/json")
            return
        try:
            tier = min(2, max(0, int(
                (self._query.get("tier") or ["0"])[0]
            )))
        except ValueError:
            tier = 0
        tail = timeseries.SeriesTail(timeseries.series_path(root, tier))
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.end_headers()
        try:
            deadline = _time.monotonic() + 3600.0
            while _time.monotonic() < deadline:
                for payload in tail.poll():
                    self.wfile.write(
                        b"data: " + json.dumps(payload).encode() + b"\n\n"
                    )
                # Keepalive comment doubles as disconnect detection.
                self.wfile.write(b": keepalive\n\n")
                self.wfile.flush()
                _time.sleep(2.0)
        finally:
            tail.close()

    def _fleet_root(self) -> Optional[str]:
        """Directory holding a fleet registry (fleet.json): the store
        dir itself or a contained ?dir= subdir."""
        root = os.path.realpath(self.store_dir)
        sub = (self._query.get("dir") or [""])[0].strip("/")
        if sub:
            cand = os.path.realpath(os.path.join(root, sub))
            if not (cand == root or cand.startswith(root + os.sep)):
                return None
            root = cand
        from .monitor.fleet import FLEET_FILE
        if os.path.isfile(os.path.join(root, FLEET_FILE)):
            return root
        return None

    def _fleet_api(self) -> None:
        """JSON fleet overview: registry + supervisor status + one
        summary/SLO/sparkline row per tenant, read straight from each
        tenant's own store dir (crash-safe files only, so this works
        with the supervisor dead)."""
        from .monitor import fleet as mfleet
        from .monitor.retention import disk_bytes
        from .telemetry import slo as tslo
        from .telemetry import timeseries

        root = self._fleet_root()
        if root is None:
            self._send(404, b'{"error": "no fleet registry found"}',
                       "application/json")
            return
        registry = mfleet.FleetRegistry(root).load()
        status = mfleet.read_status(root)
        sup_rows = status.get("tenants") or {}
        tenants = {}
        for name, spec in sorted(registry.items()):
            tstore = mfleet.tenant_store_dir(root, name)
            row: dict = {
                "spec": spec.to_json(),
                "supervisor": sup_rows.get(name) or {},
                "dir": os.path.relpath(
                    tstore, os.path.realpath(self.store_dir)),
            }
            for fname, key in (("monitor-summary.json", "summary"),
                               ("live-status.json", "live")):
                try:
                    with open(os.path.join(tstore, fname)) as f:
                        row[key] = json.load(f)
                except (OSError, ValueError):
                    row[key] = {}
            last: dict = {}
            for rec in tslo.read(tslo.slo_path(tstore)):
                last[rec.get("rule")] = rec
            row["slo-firing"] = sorted(
                r for r, rec in last.items()
                if rec.get("rec") == "firing")
            row["disk-bytes"] = (disk_bytes(tstore)
                                 if os.path.isdir(tstore) else 0)
            try:
                row["spark"] = timeseries.read_disk_series(
                    tstore, "monitor.ops-per-s", limit=60)
            except OSError:
                row["spark"] = []
            tenants[name] = row
        body = {"t": status.get("t"), "root": root,
                "endpoint": status.get("endpoint"),
                "tenants": tenants}
        self._send(200, json.dumps(body).encode(), "application/json")

    def _monitor(self) -> None:
        """Live observatory for a `jepsen monitor` run: one sparkline
        per stored series (pinned ones first), bootstrapped from
        /api/series and updated over the SSE stream.  When the store
        dir is a fleet root (fleet.json) and no ?dir= selects a
        tenant, renders the fleet-scale view instead: one row per
        tenant, linking into each tenant's own dashboard."""
        if not (self._query.get("dir") or [""])[0].strip("/"):
            froot = self._fleet_root()
            if froot is not None:
                self._fleet_view(froot)
                return
        root = self._series_root()
        if root is None:
            # No series yet — the roofline panel still renders off any
            # profiles.jsonl under the store dir.
            self._send(200, _page(
                "monitor observatory",
                "<p>no series store found under "
                f"<code>{html.escape(self.store_dir)}</code> — start "
                "one with <code>jepsen monitor --store-dir "
                f"{html.escape(self.store_dir)}/monitor</code> or point "
                "this page at a subdir with <code>?dir=name</code></p>"
                + self._monitor_roofline(
                    os.path.realpath(self.store_dir)),
            ))
            return
        summ_html = ""
        spath = os.path.join(root, "monitor-summary.json")
        try:
            with open(spath) as f:
                summ = json.load(f)
            rows = "".join(
                f"<tr><td>{html.escape(str(k))}</td>"
                f"<td>{html.escape(str(summ.get(k)))}</td></tr>"
                for k in ("ops", "duration_s", "rate_measured",
                          "ok_keys", "unknown_keys", "verdict_lag_s",
                          "series_disk_bytes")
                if k in summ
            )
            summ_html = (
                "<h2>last completed run</h2>"
                f"<table>{rows}</table>"
            )
        except (OSError, ValueError):
            pass
        rel = os.path.relpath(root, os.path.realpath(self.store_dir))
        if rel == ".":
            rel = ""
        body = (
            f"<p>series store: <code>{html.escape(root)}</code> · "
            f"<a href='/api/series?dir={urllib.parse.quote(rel)}'>"
            "series API</a> · tiers: "
            + " ".join(
                f"<a href='/monitor?tier={t}&dir="
                f"{urllib.parse.quote(rel)}'>t{t}</a>"
                for t in (0, 1, 2)
            )
            + "</p><div id='charts'></div>"
            + _MONITOR_JS
            + summ_html
            + self._monitor_faults(root)
            + self._monitor_roofline(root)
            + _slo_panel()
        )
        self._send(200, _page("monitor observatory", body))

    def _fleet_view(self, froot: str) -> None:
        """Fleet-scale /monitor: one row per tenant (state, verdict
        sparkline, SLO state, restarts, shed backoffs, disk bytes),
        bootstrapped from /api/fleet (polled) with the sparkline kept
        live over each tenant's own SSE series stream."""
        from .monitor.fleet import FleetRegistry

        names = sorted(FleetRegistry(froot).load())
        rows = "".join(
            f"<tr id='t-{html.escape(n)}'>"
            f"<td><a href='/monitor?dir=tenants/"
            f"{urllib.parse.quote(n)}/store'>{html.escape(n)}</a></td>"
            "<td class='state'>–</td>"
            "<td><canvas class='spark' width='180' height='28'>"
            "</canvas></td>"
            "<td class='slo'>–</td><td class='restarts'>–</td>"
            "<td class='shed'>–</td><td class='disk'>–</td></tr>"
            for n in names
        )
        body = (
            f"<p>fleet root: <code>{html.escape(froot)}</code> · "
            f"{len(names)} tenant(s) · "
            "<a href='/api/fleet'>fleet API</a> · "
            "<a href='/metrics'>metrics</a></p>"
            "<table><tr><th>tenant</th><th>state</th>"
            "<th>ops/s</th><th>SLO</th><th>restarts</th>"
            "<th>shed</th><th>disk</th></tr>"
            f"{rows}</table>"
            + _FLEET_JS
        )
        self._send(200, _page("fleet observatory", body))

    def _monitor_faults(self, root: str) -> str:
        """Fault-timeline panel for a live (`--suite`) monitor:
        live-status.json's recent windows as a table — family mix,
        outcome fingerprint, novelty, epoch restarts, outstanding
        intent — plus the coverage-search totals."""
        path = os.path.join(root, "live-status.json")
        try:
            with open(path) as f:
                st = json.load(f)
        except (OSError, ValueError):
            return ""
        rows = "".join(
            f"<tr><td>{w.get('window')}</td>"
            f"<td>{html.escape(','.join(w.get('families') or []))}</td>"
            f"<td><code>{html.escape(str(w.get('fingerprint')))}"
            "</code></td>"
            f"<td>{len(w.get('novel') or [])}</td>"
            f"<td>{w.get('epoch-restarts')}</td>"
            f"<td>{w.get('outstanding')}</td>"
            f"<td>{html.escape(str(w.get('error') or ''))}</td></tr>"
            for w in (st.get("recent") or [])
        )
        return (
            "<h2>live fault windows</h2>"
            f"<p>{st.get('windows')} windows, "
            f"{st.get('novel-windows')} novel, "
            f"{st.get('coverage')} coverage features, "
            f"frontier {st.get('frontier')} "
            f"(families: {html.escape(','.join(st.get('families') or []))})"
            "</p><table><tr><th>#</th><th>families</th>"
            "<th>fingerprint</th><th>novel</th><th>epochs</th>"
            "<th>outstanding</th><th>error</th></tr>"
            f"{rows}</table>"
        )

    def _monitor_roofline(self, root: str) -> str:
        """Roofline panel for /monitor: summarizes the profiles.jsonl
        co-located with the series store (the monitored run's profile
        records), or the store dir's own when the subdir has none."""
        try:
            from .telemetry import profile, roofline

            for d in (root, os.path.realpath(self.store_dir)):
                p = os.path.join(d, profile.PROFILE_FILE)
                if os.path.isfile(p):
                    recs = profile.read(p)[-2000:]
                    return _roofline_panel(roofline.summarize(recs))
        except Exception:  # noqa: BLE001 — render, don't 500
            pass
        return ""

    def _telemetry(self, rel: str) -> None:
        """Renders a run's telemetry.json (written by a
        JEPSEN_TELEMETRY=1 run — see jepsen_tpu/telemetry) as a
        spans-by-total-time table with counters and gauges, linking
        the raw JSON and the Perfetto-loadable trace.json."""
        root = os.path.realpath(self.store_dir)
        run_dir = os.path.realpath(os.path.join(root, rel.strip("/")))
        tpath = os.path.join(run_dir, "telemetry.json")
        if not (run_dir.startswith(root + os.sep)
                and os.path.isfile(tpath)):
            self._send(404, _page("404", "<p>no telemetry for this run"
                                         "</p>"))
            return
        try:
            with open(tpath) as f:
                summ = json.load(f)
        except (OSError, ValueError) as e:
            self._send(500, _page("error",
                                  f"<pre>{html.escape(repr(e))}</pre>"))
            return
        spans = sorted(
            (summ.get("spans") or {}).items(),
            key=lambda kv: kv[1].get("total_s", 0), reverse=True,
        )
        rows = "".join(
            f"<tr><td>{html.escape(name)}</td>"
            f"<td>{st.get('count')}</td>"
            f"<td>{st.get('total_s')}</td>"
            f"<td>{st.get('mean_s')}</td>"
            f"<td>{st.get('max_s')}</td></tr>"
            for name, st in spans
        )
        extras = []
        # Resilience counters (op timeouts, blown checker budgets,
        # degradation-ladder steps) get their own table above the
        # generic counters: a regression in robustness should be as
        # visible on this page as one in throughput.
        from . import telemetry

        counters = summ.get("counters") or {}
        resil = {
            k: v for k, v in counters.items()
            if any(k.startswith(p)
                   for p in telemetry.RESILIENCE_COUNTER_PREFIXES)
        }
        # Per-node availability (results["resilience"]["nodes"], written
        # by the health monitor when any node went suspect): state plus
        # the quarantine/re-admission timeline.
        node_health: dict = {}
        streaming: dict = {}
        try:
            tf = store.load(run_dir)
            try:
                results = tf.results or {}
                node_health = (
                    results.get("resilience") or {}
                ).get("nodes") or {}
                streaming = results.get("streaming") or {}
            finally:
                tf.close()
        except Exception:  # noqa: BLE001 — no stored results: skip
            node_health = {}
            streaming = {}
        if node_health:
            nrows = ""
            for n, d in sorted(node_health.items()):
                probes = d.get("probes") or {}
                timeline = ", ".join(
                    "{}→{} ({})".format(
                        e.get("from"), e.get("to"), e.get("reason")
                    )
                    for e in d.get("timeline") or []
                ) or "-"
                nrows += (
                    f"<tr><td>{html.escape(str(n))}</td>"
                    f"<td>{html.escape(str(d.get('state')))}</td>"
                    f"<td>{d.get('signals')}</td>"
                    f"<td>{probes.get('pass')}/{probes.get('fail')}</td>"
                    f"<td>{html.escape(timeline)}</td></tr>"
                )
            extras.append(
                "<h2>node availability</h2><table><tr><th>node</th>"
                "<th>state</th><th>signals</th><th>probes ok/fail</th>"
                "<th>timeline</th></tr>" + nrows + "</table>"
            )
        # Online-checking panel (results["streaming"], written by a
        # --streaming run): how far behind the run the verdict was.
        # Verdict lag is the subsystem's whole point, so it leads.
        if streaming:
            lag = streaming.get("verdict-lag-s")
            lag_txt = "?" if lag is None else f"{lag:.3f} s"
            keys = streaming.get("keys") or 0
            proven = streaming.get("proven-online") or 0
            srows = "".join(
                f"<tr><td>{html.escape(str(k))}</td>"
                f"<td>{html.escape(json.dumps(v))}</td></tr>"
                for k, v in sorted(streaming.items())
                if k != "verdict-lag-s"
            )
            extras.append(
                "<h2>online checking</h2>"
                f"<p><b>verdict lag: {lag_txt}</b> — "
                f"{proven}/{keys} keys proven online"
                + (" · <b>broken:</b> "
                   + html.escape(str(streaming.get("broken")))
                   if streaming.get("broken") else "")
                + f"</p><table>{srows}</table>"
            )
        for title, d in (("resilience", resil),
                         ("counters", counters),
                         ("gauges", summ.get("gauges") or {})):
            if d:
                items = "".join(
                    f"<tr><td>{html.escape(str(k))}</td>"
                    f"<td>{html.escape(json.dumps(v))}</td></tr>"
                    for k, v in sorted(d.items())
                )
                extras.append(f"<h2>{title}</h2><table>{items}</table>")
        q = urllib.parse.quote(rel.strip("/"))
        links = (
            f"<p><a href='/files/{q}/telemetry.json'>telemetry.json"
            f"</a> · <a href='/files/{q}/trace.json'>trace.json</a> "
            f"(load in <a href='https://ui.perfetto.dev'>Perfetto</a>)"
            f"</p>"
        )
        body = (
            links
            + "<h2>spans</h2><table><tr><th>span</th><th>count</th>"
              "<th>total s</th><th>mean s</th><th>max s</th></tr>"
            + rows + "</table>" + "".join(extras)
        )
        self._send(200, _page(f"telemetry: {rel}", body))

    def _search(self, rel: str) -> None:
        """Coverage-growth panel for a `jepsen search` dir: the
        search.json checkpoint's per-iteration coverage as inline
        bars, the nemesis.search.* counters, and the shrunk
        reproducer cells with links into corpus/cells files."""
        root = os.path.realpath(self.store_dir)
        search_dir = os.path.realpath(os.path.join(root, rel.strip("/")))
        spath = os.path.join(search_dir, "search.json")
        if not (search_dir.startswith(root + os.sep)
                and os.path.isfile(spath)):
            self._send(404, _page("404", "<p>no search state here</p>"))
            return
        try:
            with open(spath) as f:
                state = json.load(f)
        except (OSError, ValueError) as e:
            self._send(500, _page("error",
                                  f"<pre>{html.escape(repr(e))}</pre>"))
            return
        q = urllib.parse.quote(rel.strip("/"))
        iters = state.get("iterations") or []
        peak = max((h.get("coverage") or 0 for h in iters), default=1)
        irows = ""
        for h in iters:
            cov = h.get("coverage") or 0
            width = int(300 * cov / max(1, peak))
            why = ", ".join(h.get("interesting") or []) or "-"
            irows += (
                f"<tr><td>{h.get('i')}</td>"
                f"<td>{html.escape(str(h.get('label')))}</td>"
                f"<td>{h.get('events')}</td>"
                f"<td>{html.escape(','.join(h.get('families') or []))}"
                f"</td><td>+{h.get('new_features')}</td>"
                f"<td><div style='background:#47a;height:0.8em;"
                f"width:{width}px;display:inline-block'></div> "
                f"{cov}</td>"
                f"<td>{html.escape(why)}</td></tr>"
            )
        crows = "".join(
            f"<tr><td><a href='/files/{q}/cells/"
            f"{urllib.parse.quote(c.get('name', ''))}.json'>"
            f"{html.escape(str(c.get('name')))}</a></td>"
            f"<td>{html.escape(str(c.get('reason')))}</td>"
            f"<td>{c.get('events')}</td><td>{c.get('from_events')}</td>"
            f"<td>{c.get('shrink_runs')}</td></tr>"
            for c in state.get("cells") or []
        )
        counters = state.get("counters") or {}
        overview = [
            ("families", ", ".join(state.get("families") or [])),
            ("seed", state.get("seed")),
            ("nodes / floor",
             f"{state.get('n_nodes')} / {state.get('min_nodes')}"),
            ("budget s", state.get("budget_s")),
            ("coverage features", state.get("coverage")),
            ("corpus entries", len(state.get("corpus") or [])),
        ] + sorted(counters.items())
        orows = "".join(
            f"<tr><td>{html.escape(str(k))}</td>"
            f"<td>{html.escape(str(v))}</td></tr>"
            for k, v in overview
        )
        body = (
            f"<p><a href='/files/{q}/search.json'>search.json</a> · "
            f"<a href='/files/{q}/corpus/'>corpus</a> · "
            f"<a href='/files/{q}/cells/'>cells</a> · "
            f"<a href='/files/{q}/runs/'>runs</a></p>"
            f"<table>{orows}</table>"
            + (
                "<h2>shrunk reproducers</h2><table><tr><th>cell</th>"
                "<th>reason</th><th>events</th><th>from</th>"
                "<th>shrink runs</th></tr>" + crows + "</table>"
                if crows else "<p>no reproducer cells yet</p>"
            )
            + "<h2>coverage growth</h2><table><tr><th>#</th>"
              "<th>label</th><th>events</th><th>families</th>"
              "<th>new</th><th>coverage</th><th>interesting</th></tr>"
            + irows + "</table>"
        )
        self._send(200, _page(f"fault search: {rel}", body))

    def _zip(self, rel: str) -> None:
        """Streams a test dir as a zip (web.clj's zip download).  Built
        in a spooled temp file (large runs would double in RSS as a
        BytesIO) and each member is realpath-checked like _file so a
        symlink inside a run dir can't pull outside files into the
        archive."""
        import shutil
        import tempfile
        import zipfile

        root = os.path.realpath(self.store_dir)
        target = os.path.realpath(os.path.join(root, rel.strip("/")))
        if not (target.startswith(root + os.sep) and os.path.isdir(target)):
            self._send(404, _page("404", "<p>not found</p>"))
            return
        with tempfile.TemporaryFile() as buf:
            with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
                for dirpath, _dirs, files in os.walk(target):
                    for fn in files:
                        full = os.path.join(dirpath, fn)
                        real = os.path.realpath(full)
                        if not real.startswith(root + os.sep):
                            continue  # symlink escaping the store
                        z.write(real, os.path.relpath(full, target))
            size = buf.tell()
            buf.seek(0)
            self.send_response(200)
            self.send_header("Content-Type", "application/zip")
            self.send_header("Content-Length", str(size))
            self.end_headers()
            shutil.copyfileobj(buf, self.wfile)

    def _file(self, rel: str) -> None:
        # Resolve inside the store dir only.
        root = os.path.realpath(self.store_dir)
        target = os.path.realpath(os.path.join(root, rel))
        if not target.startswith(root + os.sep) and target != root:
            self._send(403, _page("403", "<p>forbidden</p>"))
            return
        if os.path.isdir(target):
            entries = []
            for e in sorted(os.listdir(target)):
                q = urllib.parse.quote(os.path.join(rel, e).strip("/"))
                entries.append(f"<li><a href='/files/{q}'>{html.escape(e)}</a></li>")
            self._send(200, _page(rel or "store", f"<ul>{''.join(entries)}</ul>"))
        elif os.path.isfile(target):
            with open(target, "rb") as f:
                data = f.read()
            ctype = (
                "application/json"
                if target.endswith(".json")
                else "text/plain; charset=utf-8"
            )
            self._send(200, data, ctype)
        else:
            self._send(404, _page("404", "<p>not found</p>"))


def make_server(
    store_dir: str = "store", host: str = "127.0.0.1", port: int = 8080
) -> http.server.ThreadingHTTPServer:
    handler = type("BoundHandler", (Handler,), {"store_dir": store_dir})
    return http.server.ThreadingHTTPServer((host, port), handler)


def serve(store_dir: str = "store", *, host: str = "0.0.0.0", port: int = 8080) -> None:
    srv = make_server(store_dir, host, port)
    log.info("serving %s on http://%s:%d/", store_dir, host, port)
    print(f"Serving {store_dir} on http://{host}:{port}/")
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        srv.server_close()
