"""Anomaly forensics: every invalid verdict ships a dossier.

The reference framework never leaves a bad verdict bare — checkers
render reports, knossos draws why each linearization path dies, and the
timeline shows the offending window (PAPER.md §0 step 5).  This module
is that assembly step for us: after `core.analyze` merges the checker
tree's results, `assemble` walks them for anomalies (any `valid` of
False or "unknown", per key or whole-history), and builds one
self-contained bundle per anomaly under ``store/<run>/forensics/<key>/``:

  * ``counterexample.json`` / ``.txt`` — the *minimal* counterexample
    subhistory: the per-key history delta-debugged host-side with the
    generic two-pass greedy shrinker (nemesis/search.py, PR 8) using
    the exact CPU engine as the oracle, so the shrunk history is
    re-proven non-linearizable before it is written.  The JSON is
    deliberately timestamp-free: a remote (checkerd) verdict and an
    in-process one over the same history produce byte-identical files.
  * ``linear.svg`` — the linviz death chart for the violating window,
    drawn from the oracle's own WGL result over the minimal history.
  * ``timeline.html`` — the per-key timeline with the crashed op
    highlighted.
  * ``death.json`` — the WGL death state: the per-key result verbatim
    (deepest configs, refutation certificates, which degradation-ladder
    tier produced the verdict and why, checkerd RESULT meta when the
    verdict came from the daemon).
  * ``profiles.json`` / ``trace-slice.json`` — the per-pass cost
    records and Chrome-trace slice for the passes that decided it
    (filtered to this run's trace id / checking categories).
  * ``flight.json`` — the flight-recorder ring as of assembly.
  * ``nemesis.json`` — fault windows from the durable ledger that
    overlapped the violating ops' invoke→return intervals (advisory:
    correlation, not causation).

Each dossier carries a stable **anomaly signature** — a short hash over
the semantic content of the violation (key, verdict, crashed op,
refutation screens) and *not* over which tier found it — which the
coverage-guided nemesis search consumes as a fitness dimension
(`nemesis.search.signature` adds ``x:<sig>`` features), so the fuzzer
is rewarded for finding *new kinds* of anomalies, not re-finding one.

Everything here is fail-open side output: a forensics failure must
never change the verdict it documents.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from datetime import datetime
from typing import Any, Optional

from . import telemetry
from .history.core import History
from .history.packed import pack_history
from .telemetry import flight, profile
from .utils import sanitize_path_part

log = logging.getLogger(__name__)

#: Subdirectory of a run's store dir holding one dir per anomaly.
FORENSICS_DIR = "forensics"

#: Dossier budget per run: anomalies beyond it are counted and listed
#: in the summary but get no bundle (a pathological run can fail every
#: key; the first few dossiers carry all the signal).
MAX_DOSSIERS = 16

#: Shrinker budget: oracle calls per anomaly (two greedy passes), and
#: the exact engine's per-call wall-clock budget.  The oracle runs on
#: an already-refuted per-key history, so calls are typically fast.
SHRINK_MAX_ATTEMPTS = 64
ORACLE_TIME_LIMIT_S = 10.0
ORACLE_MAX_CONFIGS = 2_000_000

#: Span-name prefixes that belong in the dossier's trace slice.
TRACE_PREFIXES = ("checker", "wgl", "checkerd", "lifecycle", "stream",
                  "settle")


# ---------------------------------------------------------------------------
# Anomaly discovery: walk the merged checker-results tree
# ---------------------------------------------------------------------------


_BAD = (False, "unknown")

#: Result keys that are attachments, not child checker results.
_SKIP_KEYS = frozenset((
    "resilience", "streaming", "forensics", "checkerd", "degradations",
    "results", "final-configs", "failures", "crashed-op", "key-results",
))


def _is_linearizable_result(node: dict) -> bool:
    """A leaf verdict from the linearizable checker (any tier)."""
    return "algorithm" in node or "final-configs" in node or (
        "configs-explored" in node
    )


def find_anomalies(results: Any, depth: int = 0) -> list[dict]:
    """Every bad verdict in a merged results tree, flattened to
    ``{"key", "result", "path"}`` entries.  Recognizes the independent
    checker's per-key shape (``results`` dict + ``key-count``), plain
    linearizable leaves, and Compose's named sub-dicts; bounded depth
    so a hostile results value cannot recurse forever."""
    out: list[dict] = []
    if not isinstance(results, dict) or depth > 6:
        return out
    if "key-count" in results and isinstance(results.get("results"), dict):
        for k, r in results["results"].items():
            if isinstance(r, dict) and r.get("valid") in _BAD:
                out.append({"key": k, "result": r, "path": "independent"})
        return out
    if results.get("valid") in _BAD and _is_linearizable_result(results):
        out.append({"key": None, "result": results, "path": "linearizable"})
        return out
    # Compose-style: named children that are themselves result dicts.
    for name, child in results.items():
        if name in _SKIP_KEYS or not isinstance(child, dict):
            continue
        if "valid" not in child:
            continue
        for entry in find_anomalies(child, depth + 1):
            entry["path"] = f"{name}.{entry['path']}"
            out.append(entry)
    return out


def _find_model(checker: Any, test: Optional[dict] = None) -> Any:
    """The model behind a checker tree: unwraps RemoteChecker.base,
    IndependentChecker.base, Compose children, down to a Linearizable's
    ``.model``; falls back to test["model"]."""
    seen: set[int] = set()
    stack = [checker]
    while stack:
        c = stack.pop()
        if c is None or id(c) in seen:
            continue
        seen.add(id(c))
        model = getattr(c, "model", None)
        if model is not None:
            return model
        for attr in ("base", "inner"):
            stack.append(getattr(c, attr, None))
        kids = getattr(c, "checkers", None)
        if isinstance(kids, dict):
            stack.extend(kids.values())
        elif isinstance(kids, (list, tuple)):
            stack.extend(kids)
    return (test or {}).get("model")


# ---------------------------------------------------------------------------
# Minimal counterexample: delta-debug with the exact CPU oracle
# ---------------------------------------------------------------------------


def _op_units(history: History) -> list[tuple]:
    """Groups a history into shrinkable units: one (invoke, completion)
    pair per finished op, a bare (invoke,) for unfinished ones.  Units
    are what the shrinker drops whole — removing an invocation but not
    its completion would fabricate histories no run could produce."""
    units: list[tuple] = []
    open_unit: dict[Any, int] = {}  # process -> index into units
    for op in history:
        if op.is_invoke:
            open_unit[op.process] = len(units)
            units.append((op,))
        else:
            i = open_unit.pop(op.process, None)
            if i is not None:
                units[i] = units[i] + (op,)
            # A completion with no pending invoke (trimmed window):
            # not a unit on its own; drop it from shrinking.
    return units


def _rebuild(units: tuple) -> History:
    ops = sorted((op for u in units for op in u), key=lambda o: o.index)
    return History(ops, reindex=False)


def _simplify_unit(unit: tuple):
    """Second shrink pass: forget an ok completion, making the op
    indeterminate.  That only ever *relaxes* the history (an
    indeterminate op may linearize anywhere or nowhere), so a history
    still refuted afterwards is a strictly stronger counterexample."""
    if len(unit) == 2 and unit[1].is_ok:
        return (unit[0],)
    return None


def minimize(history: History, model: Any, *,
             max_attempts: int = SHRINK_MAX_ATTEMPTS) -> Optional[dict]:
    """Delta-debugs `history` down to a minimal subhistory the exact
    CPU engine still refutes.  Returns ``{"history", "packed", "pm",
    "result", "original-op-count", "op-count", "attempts",
    "algorithm"}`` or None (with a logged reason) when the original is
    not oracle-refutable — e.g. the bad verdict was "unknown", or the
    model has no packed form."""
    from .checker.wgl_cpu import check_wgl_cpu
    from .checker.wgl_event import check_wgl_event
    from .nemesis.search import greedy_shrink

    try:
        pm = model.packed()
    except (NotImplementedError, AttributeError):
        log.info("forensics: model %r has no packed form; skipping "
                 "counterexample minimization", type(model).__name__)
        return None

    def oracle(h: History):
        """(WGLResult, packed, engine) via the exact host search —
        called directly (not through a Checker) so the shrinker's
        oracle is the engine itself, with a hard per-call budget."""
        packed = pack_history(h, pm.encode)
        if packed.n > packed.n_ok:
            res = check_wgl_event(
                packed, pm, max_configs=ORACLE_MAX_CONFIGS,
                time_limit_s=ORACLE_TIME_LIMIT_S)
            return res, packed, "event"
        res = check_wgl_cpu(
            packed, pm, max_configs=ORACLE_MAX_CONFIGS,
            time_limit_s=ORACLE_TIME_LIMIT_S)
        return res, packed, "wgl"

    try:
        res0, _, _ = oracle(history)
    except Exception as e:  # noqa: BLE001 — pack/encode may raise
        log.info("forensics: oracle failed on original history: %r", e)
        return None
    if res0.valid is not False:
        # An "unknown" or budget-blown verdict has no refutation to
        # shrink toward; the dossier still ships the death state.
        log.info("forensics: original history not refuted by exact "
                 "engine (valid=%r); no counterexample", res0.valid)
        return None

    units = _op_units(history)
    original_ops = len(history)

    def interesting(h: History) -> bool:
        try:
            res, _, _ = oracle(h)
        except Exception:  # noqa: BLE001 — a bad candidate is boring
            return False
        return res.valid is False

    with profile.capture("forensics-shrink", ops=original_ops,
                         units=len(units)) as cap:
        kept, attempts = greedy_shrink(
            units, _rebuild, interesting,
            simplify=_simplify_unit, max_attempts=max_attempts)
        cap.knob(max_attempts=max_attempts)
        cap.feature(attempts=attempts, kept_units=len(kept))
        minimal = _rebuild(kept)
        # One final oracle run over the artifact itself: the re-proof
        # the dossier's claims rest on, and the WGLResult linviz draws.
        res, packed, engine = oracle(minimal)
        if res.valid is not False:  # pragma: no cover — shrink invariant
            telemetry.count("forensics.shrink-failed")
            log.warning("forensics: shrunk history no longer refuted; "
                        "falling back to the original")
            minimal = history
            res, packed, engine = oracle(history)
        cap.outcome = res.valid
    telemetry.count("forensics.shrink-attempts", attempts)
    return {
        "history": minimal,
        "packed": packed,
        "pm": pm,
        "result": res,
        "original-op-count": original_ops,
        "op-count": len(minimal),
        "attempts": attempts,
        "algorithm": engine,
    }


# ---------------------------------------------------------------------------
# Anomaly signature: semantic content, not the tier that found it
# ---------------------------------------------------------------------------


def anomaly_signature(key: Any, result: dict,
                      crashed_desc: Optional[str] = None) -> str:
    """A short stable hash of *what* went wrong: the key, the verdict,
    the op the search died on, and any refutation screens — and
    deliberately NOT the algorithm/tier, so the same anomaly found by
    the streaming witness and the settle cohort maps to one coverage
    feature."""
    screens = sorted({
        c.get("screen") for c in result.get("final-configs") or ()
        if isinstance(c, dict) and c.get("screen")
    })
    if crashed_desc is None:
        crashed = result.get("crashed-op")
        if isinstance(crashed, dict):
            crashed_desc = crashed.get("op")
    payload = json.dumps({
        "key": repr(key),
        "valid": result.get("valid"),
        "crashed": crashed_desc,
        "screens": screens,
    }, sort_keys=True)
    return hashlib.sha256(payload.encode()).hexdigest()[:12]


def window_fingerprint(sig: Any) -> str:
    """A short stable hash of a live fault window's coverage signature
    (nemesis.search.signature's feature frozenset): the label the
    monitor's fault-timeline panel and window dossiers carry, so two
    windows with the same observable outcome share one name."""
    payload = json.dumps(sorted(str(f) for f in sig or ()))
    return hashlib.sha256(payload.encode()).hexdigest()[:12]


# ---------------------------------------------------------------------------
# Nemesis correlation: fault windows vs violating op intervals
# ---------------------------------------------------------------------------


def _wall_anchor(test: dict) -> Optional[float]:
    """Wall-clock epoch of the run's t=0 (op times are ns since test
    start; the store stamps start-time with local time_str)."""
    st = (test or {}).get("start-time")
    if not st:
        return None
    try:
        return datetime.strptime(st, "%Y%m%dT%H%M%S.%f").timestamp()
    except ValueError:
        return None


def nemesis_correlation(test: dict, history: History,
                        directory: Optional[str] = None) -> dict:
    """Fault windows from the durable ledger that overlapped any of
    `history`'s invoke→return wall-clock intervals.  Advisory by
    design: an overlapping partition is the first suspect, not a
    conviction."""
    from .nemesis import ledger as fault_ledger

    d = directory
    if d is None:
        try:
            from . import store
            d = store.test_dir(test)
        except (ValueError, KeyError):
            return {"windows": [], "note": "no store dir"}
    path = fault_ledger.ledger_path(d)
    records = fault_ledger.read_records(path)
    if not records:
        return {"windows": [], "note": "no fault ledger"}
    anchor = _wall_anchor(test)
    if anchor is None:
        return {"windows": [], "note": "no start-time anchor"}

    healed_t = {r["id"]: r.get("t") for r in records
                if r.get("rec") == "healed"}
    windows = []
    for r in records:
        if r.get("rec") != "intent":
            continue
        windows.append({
            "id": r.get("id"),
            "fault": r.get("fault"),
            "nodes": r.get("nodes") or [],
            "params": r.get("params") or {},
            "t0": r.get("t"),
            "t1": healed_t.get(r.get("id")),  # None = never healed
        })

    intervals = []
    for inv, comp in _invoke_return_pairs(history):
        t0 = anchor + inv.time / 1e9
        t1 = anchor + comp.time / 1e9 if comp is not None else None
        intervals.append((t0, t1, inv))

    overlapping = []
    for w in windows:
        w0 = w["t0"] or 0.0
        w1 = w["t1"]
        hits = []
        for t0, t1, inv in intervals:
            lo = max(w0, t0)
            hi = min(w1 if w1 is not None else float("inf"),
                     t1 if t1 is not None else float("inf"))
            if lo <= hi:
                hits.append({"index": inv.index, "process": inv.process,
                             "f": str(inv.f)})
        if hits:
            overlapping.append({**w, "overlapping-ops": hits[:32],
                                "overlap-count": len(hits)})
    return {
        "windows": overlapping,
        "window-count": len(windows),
        "note": "advisory: fault windows overlapping violating ops' "
                "invoke-to-return wall intervals",
    }


def _invoke_return_pairs(history: History):
    pending: dict[Any, Any] = {}
    for op in history:
        if op.is_invoke:
            pending[op.process] = op
        else:
            inv = pending.pop(op.process, None)
            if inv is not None:
                yield inv, op
    for inv in pending.values():
        yield inv, None


# ---------------------------------------------------------------------------
# The dossier bundle
# ---------------------------------------------------------------------------


def _safe_key_dir(key: Any, used: set) -> str:
    safe = sanitize_path_part(key if key is not None else "history")[:80]
    if safe in used:
        digest = hashlib.sha1(repr(key).encode()).hexdigest()[:10]
        safe = f"{safe[:69]}-{digest}"
    used.add(safe)
    return safe


def _write_json(path: str, obj: Any, *, sort_keys: bool = True) -> int:
    with open(path, "w") as f:
        json.dump(obj, f, indent=2, sort_keys=sort_keys, default=repr)
        f.write("\n")
    return os.path.getsize(path)


def _trace_slice() -> list[dict]:
    try:
        trace = telemetry.chrome_trace()
    except Exception:  # noqa: BLE001 — trace is optional context
        return []
    evs = trace.get("traceEvents") or []
    return [e for e in evs
            if str(e.get("name", "")).startswith(TRACE_PREFIXES)]


def _profile_records() -> list[dict]:
    path = profile.store_path()
    if not path:
        return []
    tid = telemetry.trace_id()
    recs = profile.read(path)
    mine = [r for r in recs if r.get("trace_id") == tid]
    return mine if mine else recs[-64:]


def build_dossier(test: dict, key: Any, entry: dict, history: History,
                  directory: str, model: Any = None) -> Optional[dict]:
    """Assembles one anomaly's bundle under `directory` (the dossier
    dir itself, already unique per key).  Returns the summary dict for
    the manifest / results attachment, or None on failure."""
    result = entry["result"]
    os.makedirs(directory, exist_ok=True)
    files: dict[str, int] = {}
    summary: dict[str, Any] = {
        "key": repr(key) if key is not None else None,
        "verdict": result.get("valid"),
        "path": entry.get("path"),
        "dir": directory,
    }

    # 1. Minimal counterexample (refuted verdicts with a model only).
    mini = None
    if model is not None and result.get("valid") is False:
        try:
            mini = minimize(history, model)
        except Exception:  # noqa: BLE001 — fail-open
            telemetry.count("forensics.shrink-failed")
            log.warning("forensics: minimization failed for key %r",
                        key, exc_info=True)
    crashed_desc = None
    if mini is not None:
        res, packed, pm = mini["result"], mini["packed"], mini["pm"]
        a = res.crashed_at
        if a is not None and pm.describe_op is not None:
            crashed_desc = pm.describe_op(
                int(packed.f[a]), int(packed.a0[a]), int(packed.a1[a]))
        sig = anomaly_signature(key, result, crashed_desc)
        # Timestamp-free by contract: a checkerd verdict and an
        # in-process one over the same history write identical bytes.
        counterexample = {
            "key": repr(key) if key is not None else None,
            "signature": sig,
            "verdict": False,
            "original-op-count": mini["original-op-count"],
            "op-count": mini["op-count"],
            "attempts": mini["attempts"],
            "oracle": {
                "algorithm": mini["algorithm"],
                "configs-explored": int(res.configs_explored),
                "crashed-op": {
                    "history-index": (int(packed.src_index[a])
                                      if a is not None else None),
                    "op": crashed_desc,
                },
            },
            "ops": [o.to_dict() for o in mini["history"]],
        }
        p = os.path.join(directory, "counterexample.json")
        files["counterexample.json"] = _write_json(p, counterexample)
        with open(os.path.join(directory, "counterexample.txt"), "w",
                  errors="replace") as f:
            for o in mini["history"]:
                f.write(str(o) + "\n")
        files["counterexample.txt"] = os.path.getsize(
            os.path.join(directory, "counterexample.txt"))
        summary.update({
            "original-op-count": mini["original-op-count"],
            "op-count": mini["op-count"],
            "shrink-attempts": mini["attempts"],
        })
        # 2. The linviz death chart over the minimal history.
        try:
            from .checker.linviz import render_analysis
            svg = render_analysis(
                packed, pm, res, os.path.join(directory, "linear.svg"))
            if svg:
                files["linear.svg"] = os.path.getsize(svg)
        except Exception:  # noqa: BLE001
            log.warning("forensics: linviz render failed", exc_info=True)
    else:
        sig = anomaly_signature(key, result)

    summary["signature"] = sig

    # 3. Timeline of the per-key history, crashed op highlighted.
    try:
        from .checker import timeline as tl
        crashed = result.get("crashed-op") or {}
        highlight = crashed.get("history-index")
        if mini is not None:
            ce = counterexample["oracle"]["crashed-op"]
            highlight = ce.get("history-index", highlight)
        html_doc = tl.render(test, history, highlight=highlight)
        with open(os.path.join(directory, "timeline.html"), "w") as f:
            f.write(html_doc)
        files["timeline.html"] = os.path.getsize(
            os.path.join(directory, "timeline.html"))
    except Exception:  # noqa: BLE001
        log.warning("forensics: timeline render failed", exc_info=True)

    # 4. Death state: the verdict verbatim, plus how it was reached.
    death = {
        "result": result,
        "degradations": result.get("degradations"),
        "checkerd": result.get("checkerd"),
    }
    files["death.json"] = _write_json(
        os.path.join(directory, "death.json"), death)

    # 5-7. Cost records, trace slice, flight ring.
    files["profiles.json"] = _write_json(
        os.path.join(directory, "profiles.json"), _profile_records())
    files["trace-slice.json"] = _write_json(
        os.path.join(directory, "trace-slice.json"), _trace_slice())
    files["flight.json"] = _write_json(
        os.path.join(directory, "flight.json"), flight.events())

    # 8. Nemesis correlation over the (minimal, else full) history.
    try:
        corr = nemesis_correlation(
            test, mini["history"] if mini is not None else history)
    except Exception:  # noqa: BLE001
        corr = {"windows": [], "note": "correlation failed"}
    files["nemesis.json"] = _write_json(
        os.path.join(directory, "nemesis.json"), corr)
    if corr.get("windows"):
        summary["nemesis-windows"] = len(corr["windows"])

    # 9. Manifest last: its presence marks a complete dossier.  The
    # only timestamps in the bundle live here.
    manifest = dict(summary)
    manifest["files"] = files
    manifest["created-at"] = datetime.now().isoformat(timespec="seconds")
    _write_json(os.path.join(directory, "dossier.json"), manifest)
    return summary


def assemble(test: dict, results: dict, history: History,
             directory: str, checker: Any = None) -> Optional[dict]:
    """The analyze-time entry point: finds every anomaly in `results`,
    builds capped dossiers under ``<directory>/forensics/``, and
    returns the summary block `core.analyze` attaches as
    ``results["forensics"]`` (None when the run is clean)."""
    anomalies = find_anomalies(results)
    if not anomalies:
        return None
    telemetry.count("forensics.anomalies", len(anomalies))
    root = os.path.join(directory, FORENSICS_DIR)
    model = _find_model(checker, test)

    from .parallel.independent import subhistories
    subs = None
    dossiers: list[dict] = []
    skipped = 0
    used: set = set()
    with telemetry.span("forensics.assemble", anomalies=len(anomalies)):
        for entry in anomalies:
            if len(dossiers) >= MAX_DOSSIERS:
                skipped += 1
                continue
            key = entry["key"]
            if key is None:
                sub = history
            else:
                if subs is None:
                    sub = None
                    try:
                        subs = subhistories(history)
                    except Exception:  # noqa: BLE001
                        subs = {}
                sub = subs.get(key)
                if sub is None:
                    skipped += 1
                    continue
            d = os.path.join(root, _safe_key_dir(key, used))
            try:
                summary = build_dossier(test, key, entry, sub, d,
                                        model=model)
            except Exception:  # noqa: BLE001 — fail-open per anomaly
                log.warning("forensics: dossier for key %r failed",
                            key, exc_info=True)
                summary = None
            if summary is not None:
                dossiers.append(summary)
                telemetry.count("forensics.dossiers")
                flight.note("forensics-dossier", key=repr(key),
                            signature=summary.get("signature"),
                            dir=d)
    if skipped:
        telemetry.count("forensics.skipped", skipped)
    return {
        "dir": root,
        "dossiers": dossiers,
        "anomaly-count": len(anomalies),
        "skipped": skipped,
    }
