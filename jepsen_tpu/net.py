"""Network manipulation: partitions and packet shaping.

Equivalent of /root/reference/jepsen/src/jepsen/net.clj (+ net/proto.clj):
the `Net` protocol (drop!/heal!/slow!/flaky!/fast!/shape!,
net.clj:15-29), the iptables implementation (:177-233, including the
bulk `PartitionAll` drop :223-233), and tc/netem shaping with
delay/loss/corrupt/duplicate/reorder/rate behaviors (:73-164).

All methods act via the control-plane sessions bound in
``test["sessions"]`` (the reference's dynamic `c/on-nodes` binding).

Addressing: iptables rules on a node name the PEER's address.  Node
names of the form "host:port" (localhost clusters, where the host part
is the control node's view — e.g. 127.0.0.1 with a published ssh
port) are NOT usable as peer addresses inside the cluster; supply
``test["node-addresses"] = {node-name: in-cluster address}`` (e.g. the
compose service hostnames n1..n5) and the helpers below resolve
through it, falling back to the bare host part.
"""

from __future__ import annotations

from typing import Any, Mapping, Optional, Sequence

from .control import Session, on_nodes
from .control.core import split_host_port
from .nemesis import ledger as fault_ledger


def node_address(test: dict, node: str) -> str:
    """The address peers use to reach `node` inside the cluster."""
    alias = (test.get("node-addresses") or {}).get(node)
    if alias:
        return alias
    host, port = split_host_port(node)
    if port is not None and host in ("127.0.0.1", "localhost", "::1"):
        # A loopback host:port name is the CONTROL node's view; as a
        # peer address it would blackhole the node's own loopback
        # instead of partitioning anything — fail loudly rather than
        # inject the wrong fault.
        raise ValueError(
            f"node {node!r} is a control-side loopback view; supply "
            f'test["node-addresses"] with in-cluster addresses'
        )
    return host


class Net:
    """net/proto.clj:5-12 + net.clj:15-29."""

    def drop(self, test: dict, src: str, dest: str) -> None:
        """Cuts the link src -> dest (dest stops hearing src)."""
        raise NotImplementedError

    def drop_all(self, test: dict, grudge: Mapping[str, Any]) -> None:
        """Applies a whole grudge {node: nodes-it-stops-hearing} at
        once (PartitionAll, net.clj:223-233)."""
        for node, cut in grudge.items():
            for src in cut:
                self.drop(test, src, node)

    def heal(self, test: dict) -> None:
        raise NotImplementedError

    def slow(self, test: dict, **opts: Any) -> None:
        """Delays all traffic (mean 50 ms ± 10 ms, net.clj:50-56)."""
        raise NotImplementedError

    def flaky(self, test: dict) -> None:
        """Drops packets probabilistically (20%, net.clj:58-61)."""
        raise NotImplementedError

    def fast(self, test: dict) -> None:
        """Removes shaping (not partitions)."""
        raise NotImplementedError

    def shape(self, test: dict, behavior: Optional[dict], nodes: Optional[Sequence[str]] = None) -> None:
        """Applies a tc/netem behavior dict: keys delay {time,jitter,
        correlation,distribution}, loss {percent,correlation},
        corrupt/duplicate/reorder {percent,correlation}, rate
        (net.clj:73-164).  None removes shaping."""
        raise NotImplementedError


class NoopNet(Net):
    """For dummy remotes and in-memory tests."""

    def drop(self, test: dict, src: str, dest: str) -> None:
        pass

    def drop_all(self, test: dict, grudge: Mapping[str, Any]) -> None:
        pass

    def heal(self, test: dict) -> None:
        pass

    def slow(self, test: dict, **opts: Any) -> None:
        pass

    def flaky(self, test: dict) -> None:
        pass

    def fast(self, test: dict) -> None:
        pass

    def shape(self, test: dict, behavior, nodes=None) -> None:
        pass


def _netem_args(behavior: Mapping[str, Any]) -> list[str]:
    """Renders a behavior map to netem arguments (net.clj:93-146)."""
    args: list[str] = []
    delay = behavior.get("delay")
    if delay:
        args += ["delay", f"{delay.get('time', 50)}ms"]
        if "jitter" in delay:
            args += [f"{delay['jitter']}ms"]
        if "correlation" in delay:
            args += [f"{delay['correlation']}%"]
        if delay.get("distribution"):
            args += ["distribution", str(delay["distribution"])]
    for kind in ("loss", "corrupt", "duplicate", "reorder"):
        spec = behavior.get(kind)
        if spec:
            args += [kind, f"{spec.get('percent', 20)}%"]
            if "correlation" in spec:
                args += [f"{spec['correlation']}%"]
    if behavior.get("rate"):
        args += ["rate", f"{behavior['rate']}kbit"]
    return args


class TcShapingNet(Net):
    """Shared tc/netem shaping half of the Net protocol
    (net.clj:73-164): subclasses supply the partition mechanism and
    inherit slow/flaky/fast/shape.  `dev` is the qdisc device —
    eth0 by default, which is also what NetnsCluster names every
    node's interface."""

    def __init__(self, dev: str = "eth0"):
        self.dev = dev

    def _shaping_intent(self, test: dict, params: dict,
                        nodes: Optional[Sequence[str]] = None) -> None:
        """Journals a netem/tbf shaping fault; the compensator is always
        the same qdisc delete, whatever the behavior was."""
        targets = list(nodes) if nodes else list(test.get("nodes") or [])
        fault_ledger.intent(
            test, "netem", nodes=[str(n) for n in targets],
            params=params,
            compensator={"type": "tc-del", "dev": self.dev,
                         "nodes": [str(n) for n in targets]},
        )

    def slow(self, test: dict, **opts: Any) -> None:
        mean = opts.get("mean", 50)
        variance = opts.get("variance", 10)
        dist = opts.get("distribution", "normal")
        self._shaping_intent(
            test, {"f": "slow", "mean": mean, "variance": variance}
        )

        def do(sess: Session, node: str) -> None:
            with sess.su():
                sess.exec(
                    "tc", "qdisc", "add", "dev", self.dev, "root",
                    "netem", "delay", f"{mean}ms", f"{variance}ms",
                    "distribution", dist,
                )

        on_nodes(test, do)

    def flaky(self, test: dict) -> None:
        self._shaping_intent(test, {"f": "flaky", "loss": "20%"})

        def do(sess: Session, node: str) -> None:
            with sess.su():
                sess.exec(
                    "tc", "qdisc", "add", "dev", self.dev, "root",
                    "netem", "loss", "20%", "75%",
                )

        on_nodes(test, do)

    def fast(self, test: dict) -> None:
        if fault_ledger.heal_guard():
            return

        def do(sess: Session, node: str) -> None:
            with sess.su():
                # Deleting a nonexistent qdisc fails; ignore like the
                # reference (net.clj:69-71).
                res = sess.exec_star(
                    "tc", "qdisc", "del", "dev", self.dev, "root"
                )
                del res

        on_nodes(test, do)
        fault_ledger.healed(test, fault="netem")

    def shape(self, test: dict, behavior, nodes=None) -> None:
        if not behavior:
            self.fast(test)
            return
        self._shaping_intent(
            test, {"f": "shape", "behavior": dict(behavior)}, nodes
        )
        args = self._shape_args(behavior)

        def do(sess: Session, node: str) -> None:
            with sess.su():
                sess.exec_star("tc", "qdisc", "del", "dev", self.dev,
                               "root")
                sess.exec(
                    "tc", "qdisc", "add", "dev", self.dev, "root",
                    *args,
                )

        on_nodes(test, do, nodes)

    def _shape_args(self, behavior: Mapping[str, Any]) -> list[str]:
        return ["netem", *_netem_args(behavior)]


class IptablesNet(TcShapingNet):
    """iptables + tc/netem implementation (net.clj:177-233)."""

    def drop(self, test: dict, src: str, dest: str) -> None:
        def do(sess: Session, node: str) -> None:
            with sess.su():
                sess.exec(
                    "iptables", "-A", "INPUT", "-s",
                    node_address(test, src), "-j", "DROP", "-w",
                )

        on_nodes(test, do, [dest])

    def drop_all(self, test: dict, grudge: Mapping[str, Any]) -> None:
        # One command per node, not per edge: comma-joined sources
        # (PartitionAll, net.clj:223-233).
        targets = {n: sorted(cut) for n, cut in grudge.items() if cut}

        def do(sess: Session, node: str) -> None:
            srcs = ",".join(
                node_address(test, s) for s in targets[node]
            )
            with sess.su():
                sess.exec(
                    "iptables", "-A", "INPUT", "-s", srcs,
                    "-j", "DROP", "-w",
                )

        on_nodes(test, do, list(targets.keys()))

    def heal(self, test: dict) -> None:
        def do(sess: Session, node: str) -> None:
            with sess.su():
                sess.exec("iptables", "-F", "-w")
                sess.exec("iptables", "-X", "-w")

        on_nodes(test, do)


class RouteNet(TcShapingNet):
    """Kernel-level partitions without a packet-filter userspace:
    blackhole routes + tc shaping.

    Some hosts (including this repo's CI kernel) ship neither iptables
    nor nftables binaries, but `ip route` always works.  Routing can
    only drop a node's OWN egress, so `drop(src, dest)` — "dest stops
    hearing src" (net/proto.clj:5-12) — installs a blackhole route
    for dest's address ON SRC: src's packets toward dest die in src's
    routing table and dest genuinely never hears src, for TCP and
    datagrams alike.  The residual asymmetry is on the REVERSE path:
    dest's datagrams still reach src (dest was not asked to stop
    being heard), while reverse TCP stalls because src can't
    acknowledge — iptables `INPUT -s src -j DROP` on dest has the
    mirror-image residue (src's datagrams die at dest but dest's
    still reach src).  Partition packages emit symmetric grudges, on
    which both mechanisms produce identical full cuts.

    Shaping (inherited TcShapingNet, net.clj:73-164) uses the netem
    qdisc where the kernel has it, plus a tbf fallback for rate-only
    behaviors — tbf is compiled into kernels that lack sch_netem."""

    @staticmethod
    def _blackhole_prefix(test: dict, node: str) -> str:
        """node -> an iproute2 prefix.  iproute2 takes only literal
        prefixes, so hostnames resolve on the control side (same
        resolver split_host_port topologies already rely on) and
        IPv6 literals get /128."""
        import ipaddress
        import socket

        addr = node_address(test, node)
        try:
            ip = ipaddress.ip_address(addr)
        except ValueError:
            addr = socket.getaddrinfo(addr, None)[0][4][0]
            ip = ipaddress.ip_address(addr)
        return f"{addr}/{128 if ip.version == 6 else 32}"

    def drop(self, test: dict, src: str, dest: str) -> None:
        prefix = self._blackhole_prefix(test, dest)

        def do(sess: Session, node: str) -> None:
            with sess.su():
                # replace = idempotent: overlapping grudges re-drop
                # the same edge without erroring.
                sess.exec("ip", "route", "replace", "blackhole",
                          prefix)

        on_nodes(test, do, [src])

    def drop_all(self, test: dict, grudge: Mapping[str, Any]) -> None:
        # The grudge maps dest -> the srcs it stops hearing; routes
        # must be installed on each SRC (see class doc), so invert to
        # src -> dest-prefixes and run one shell per src node — still
        # the bulk PartitionAll shape (net.clj:223-233).
        by_src: dict[str, list[str]] = {}
        for dest, cut in grudge.items():
            for src in cut:
                by_src.setdefault(src, []).append(
                    self._blackhole_prefix(test, dest)
                )

        def do(sess: Session, node: str) -> None:
            script = "; ".join(
                f"ip route replace blackhole {prefix}"
                for prefix in sorted(by_src[node])
            )
            with sess.su():
                sess.exec("bash", "-c", script)

        on_nodes(test, do, list(by_src.keys()))

    def heal(self, test: dict) -> None:
        def do(sess: Session, node: str) -> None:
            with sess.su():
                sess.exec("bash", "-c",
                          "ip route flush type blackhole || true")

        on_nodes(test, do)

    def _shape_args(self, behavior: Mapping[str, Any]) -> list[str]:
        if set(behavior) == {"rate"}:
            # tbf fallback: netem-free kernels can still rate-limit.
            return ["tbf", "rate", f"{behavior['rate']}kbit",
                    "burst", "32kbit", "latency", "400ms"]
        return super()._shape_args(behavior)


class IpfilterNet(IptablesNet):
    """IPFilter implementation for SmartOS/illumos nodes
    (net.clj:235-270): partitions via `ipf` rules fed on stdin, heal
    via `ipf -Fa`; shaping inherits the tc/netem path (the reference's
    ipfilter impl shells out to tc for slow/flaky/fast/shape too)."""

    def drop(self, test: dict, src: str, dest: str) -> None:
        def do(sess: Session, node: str) -> None:
            with sess.su():
                sess.exec(
                    "ipf", "-f", "-",
                    stdin=f"block in from {node_address(test, src)} to any\n",
                )

        on_nodes(test, do, [dest])

    def drop_all(self, test: dict, grudge: Mapping[str, Any]) -> None:
        # One ipf invocation per node with the whole rule set on stdin
        # (the bulk analogue of iptables' comma-joined PartitionAll).
        targets = {n: sorted(cut) for n, cut in grudge.items() if cut}

        def do(sess: Session, node: str) -> None:
            rules = "".join(
                f"block in from {node_address(test, s)} to any\n"
                for s in targets[node]
            )
            with sess.su():
                sess.exec("ipf", "-f", "-", stdin=rules)

        on_nodes(test, do, list(targets.keys()))

    def heal(self, test: dict) -> None:
        def do(sess: Session, node: str) -> None:
            with sess.su():
                sess.exec("ipf", "-Fa")

        on_nodes(test, do)


iptables = IptablesNet()
ipfilter = IpfilterNet()
route = RouteNet()
noop = NoopNet()
