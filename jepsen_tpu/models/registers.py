"""Register-family models: register, cas-register, multi-register.

Semantics mirror knossos.model's registers as used by the reference
(`knossos.model/cas-register` at tests/linearizable_register.clj:22-53;
protocol in doc/tutorial/04-checker.md): a read of `nil` is unconstrained
(unknown return), reads must otherwise match the current value, writes
always succeed, cas succeeds iff the old value matches.
"""

from __future__ import annotations

from typing import Any, Optional

from ..history.core import OK, Op
from ..history.packed import NIL, Interner
from .base import Inconsistent, Model, PackedModel, inconsistent, intern_value

F_READ, F_WRITE, F_CAS = 0, 1, 2
_F_NAMES = {F_READ: "read", F_WRITE: "write", F_CAS: "cas"}


class Register(Model):
    """A single read/write register."""

    __slots__ = ("value", "_packed_cache")
    fs = ("read", "write")

    def __init__(self, value: Any = None):
        self.value = value

    def step(self, op: Op):
        if op.f == "read":
            if op.value is None or op.value == self.value:
                return self
            return inconsistent(
                f"read {op.value!r} but register held {self.value!r}"
            )
        if op.f == "write":
            return type(self)(op.value)
        return inconsistent(f"unknown op f {op.f!r}")

    def __eq__(self, other):
        return type(other) is type(self) and other.value == self.value

    def __hash__(self):
        return hash((type(self).__name__, self.value))

    def __repr__(self):
        return f"{type(self).__name__}({self.value!r})"

    # -- packed -----------------------------------------------------------

    def _compile_packed(self) -> PackedModel:
        return _register_packed(self, allow_cas=False)


class CASRegister(Register):
    """A register with read/write/compare-and-set — the canonical
    linearizability workload (BASELINE.json configs 1 and 4)."""

    fs = ("read", "write", "cas")

    def step(self, op: Op):
        if op.f == "cas":
            old, new = op.value
            if self.value == old:
                return CASRegister(new)
            return inconsistent(
                f"cas from {old!r} but register held {self.value!r}"
            )
        return super().step(op)

    def _compile_packed(self) -> PackedModel:
        return _register_packed(self, allow_cas=True)


def _register_packed(model: Register, allow_cas: bool) -> PackedModel:
    interner = Interner()
    nil_code = interner.intern(None)  # id 0
    init = (intern_value(interner, model.value),)

    def encode(inv: Op, comp: Optional[Op]):
        f = inv.f
        if f == "read":
            if comp is None or comp.type != OK:
                return None  # indeterminate read: no effect, droppable
            if comp.value is None:
                return None  # unknown return: unconstrained, droppable
            return (F_READ, intern_value(interner, comp.value), NIL)
        if f == "write":
            return (F_WRITE, intern_value(interner, inv.value), NIL)
        if f == "cas" and allow_cas:
            old, new = inv.value
            return (
                F_CAS,
                intern_value(interner, old),
                intern_value(interner, new),
            )
        raise ValueError(f"register model can't encode op f {f!r}")

    def encode_many(items):
        # Columnar-ingest hook (PackedBuilder.append_many): encode() over
        # a [(inv, comp)] batch with the interner inlined — one loop,
        # no per-op intern_value/intern call frames.  MUST stay
        # semantically in lockstep with encode(): same interner dicts,
        # same drops, same codes, so the packed bytes are identical.
        ids = interner._ids
        vals = interner.values
        out = []
        add = out.append
        for inv, comp in items:
            f = inv.f
            if f == "read":
                if comp is None or comp.type != OK:
                    add(None)
                    continue
                v = comp.value
                if v is None:
                    add(None)
                    continue
            elif f == "write":
                v = inv.value
            elif f == "cas" and allow_cas:
                old, new = inv.value
                if isinstance(old, list):
                    old = tuple(old)
                if isinstance(new, list):
                    new = tuple(new)
                i0 = ids.get(old)
                if i0 is None:
                    i0 = len(vals)
                    ids[old] = i0
                    vals.append(old)
                i1 = ids.get(new)
                if i1 is None:
                    i1 = len(vals)
                    ids[new] = i1
                    vals.append(new)
                add((F_CAS, i0, i1))
                continue
            else:
                raise ValueError(
                    f"register model can't encode op f {f!r}"
                )
            if isinstance(v, list):
                v = tuple(v)
            i = ids.get(v)
            if i is None:
                i = len(vals)
                ids[v] = i
                vals.append(v)
            add((F_READ if f == "read" else F_WRITE, i, NIL))
        return out

    encode.many = encode_many

    def py_step(state, f, a0, a1):
        s = state[0]
        if f == F_READ:
            return state, s == a0
        if f == F_WRITE:
            return (a0,), True
        # cas
        return (a1,), s == a0

    def jax_step(state, f, a0, a1):
        import jax.numpy as jnp

        s = state[0]
        is_write = f == F_WRITE
        is_cas = f == F_CAS
        legal = is_write | (s == a0)
        new = jnp.where(is_write, a0, jnp.where(is_cas, a1, s))
        return state.at[0].set(new), legal

    def jax_step_rows(states, f, a0, a1):
        # Scatter-free lane-major form for the Pallas sweep (states
        # is (1, B); the single row IS the register).
        import jax.numpy as jnp

        s = states[0]
        is_write = f == F_WRITE
        is_cas = f == F_CAS
        legal = is_write | (s == a0)
        new = jnp.where(is_write, a0, jnp.where(is_cas, a1, s))
        return new[None, :], legal

    def describe_op(f: int, a0: int, a1: int) -> str:
        if f == F_READ:
            return f"read -> {interner.value(a0)!r}"
        if f == F_WRITE:
            return f"write {interner.value(a0)!r}"
        return f"cas {interner.value(a0)!r} -> {interner.value(a1)!r}"

    def refute_view(packed):
        import numpy as np

        from ..checker.refute import RefuteView
        from ..history.packed import NIL as _NIL

        f = packed.f
        return RefuteView(
            key=np.zeros(packed.n, dtype=np.int32),
            # reads assert the returned value; ok cas asserts the
            # expected old value at its linearization point
            asserts=np.where(f == F_READ, packed.a0,
                             np.where(f == F_CAS, packed.a0, _NIL)),
            # writes force their value; an :ok cas's new value is a
            # forced effect (it returned success)
            produces=np.where(f == F_WRITE, packed.a0,
                              np.where(f == F_CAS, packed.a1, _NIL)),
            init=np.array(init, dtype=np.int32),
        )

    return PackedModel(
        name="cas-register" if allow_cas else "register",
        state_width=1,
        init_state=init,
        encode=encode,
        py_step=py_step,
        jax_step=jax_step,
        interner=interner,
        describe_op=describe_op,
        jax_step_rows=jax_step_rows,
        refute_view=refute_view,
    )


class MultiRegister(Model):
    """A fixed set of named registers; ops read/write a single (k, v) pair
    (knossos.model/multi-register restricted to unit txns — the
    per-key-WGL benchmark config in BASELINE.json uses
    jepsen.independent to shard keys instead of packing them here)."""

    __slots__ = ("values", "_packed_cache")

    def __init__(self, values: dict[Any, Any]):
        self.values = dict(values)

    def step(self, op: Op):
        k, v = op.value
        if k not in self.values:
            return inconsistent(f"no such register {k!r}")
        if op.f == "read":
            if v is None or self.values[k] == v:
                return self
            return inconsistent(
                f"read {v!r} from {k!r} which held {self.values[k]!r}"
            )
        if op.f == "write":
            nv = dict(self.values)
            nv[k] = v
            return MultiRegister(nv)
        return inconsistent(f"unknown op f {op.f!r}")

    def __eq__(self, other):
        return type(other) is MultiRegister and other.values == self.values

    def __hash__(self):
        return hash(tuple(sorted(self.values.items(), key=repr)))

    def __repr__(self):
        return f"MultiRegister({self.values!r})"

    def _compile_packed(self) -> PackedModel:
        interner = Interner()
        interner.intern(None)
        keys = list(self.values.keys())
        key_idx = {k: i for i, k in enumerate(keys)}
        init = tuple(intern_value(interner, self.values[k]) for k in keys)

        def encode(inv: Op, comp: Optional[Op]):
            if inv.f == "read":
                if comp is None or comp.type != OK:
                    return None
                k, v = comp.value
                if v is None:
                    return None
                return (F_READ, key_idx[k], intern_value(interner, v))
            if inv.f == "write":
                k, v = inv.value
                return (F_WRITE, key_idx[k], intern_value(interner, v))
            raise ValueError(f"multi-register can't encode op f {inv.f!r}")

        def py_step(state, f, a0, a1):
            if f == F_READ:
                return state, state[a0] == a1
            s = list(state)
            s[a0] = a1
            return tuple(s), True

        def jax_step(state, f, a0, a1):
            import jax.numpy as jnp

            cur = state[a0]
            is_write = f == F_WRITE
            legal = is_write | (cur == a1)
            new = jnp.where(is_write, a1, cur)
            return state.at[a0].set(new), legal

        def jax_step_rows(states, f, a0, a1):
            # Scatter-free lane-major form for the Pallas sweep
            # (states is (n_keys, B)): the written key row is selected
            # by mask, not scatter.
            import jax
            import jax.numpy as jnp

            nk = states.shape[0]
            key_mask = (
                jax.lax.broadcasted_iota(jnp.int32, (nk, 1), 0) == a0
            )
            cur = jnp.where(key_mask, states, 0).sum(axis=0)  # (B,)
            is_write = f == F_WRITE
            legal = is_write | (cur == a1)
            out = jnp.where(key_mask & is_write, a1, states)
            return out, legal

        def describe_op(f: int, a0: int, a1: int) -> str:
            verb = "read" if f == F_READ else "write"
            return f"{verb} {keys[a0]!r} {interner.value(a1)!r}"

        def refute_view(packed):
            import numpy as np

            from ..checker.refute import RefuteView
            from ..history.packed import NIL as _NIL

            f = packed.f
            return RefuteView(
                key=packed.a0.astype(np.int32),
                asserts=np.where(f == F_READ, packed.a1, _NIL),
                produces=np.where(f == F_WRITE, packed.a1, _NIL),
                init=np.array(init, dtype=np.int32),
            )

        return PackedModel(
            name="multi-register",
            state_width=len(keys),
            init_state=init,
            encode=encode,
            py_step=py_step,
            jax_step=jax_step,
            interner=interner,
            describe_op=describe_op,
            jax_step_rows=jax_step_rows,
            refute_view=refute_view,
        )


def register(value: Any = None) -> Register:
    return Register(value)


def cas_register(value: Any = None) -> CASRegister:
    return CASRegister(value)


def multi_register(values: dict[Any, Any]) -> MultiRegister:
    return MultiRegister(values)
