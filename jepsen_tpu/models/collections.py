"""Collection models: set, unordered-queue, FIFO queue.

Host-only knossos.model equivalents (SURVEY.md §2.4).  These back the
generic `linearizable` checker for collection workloads; the cheap
specialized checkers (checker.set / checker.queue / checker.total_queue)
don't need a model at all, mirroring the reference split
(checker.clj:235-287, 648-708).

These models carry unbounded Python collections, so they have no packed
int32 form yet; `packed()` raises, and the linearizable checker falls back
to the CPU search for them.
"""

from __future__ import annotations

from typing import Any, FrozenSet, Tuple

from ..history.core import Op
from .base import Model, inconsistent


def _freeze(v: Any) -> Any:
    if isinstance(v, list):
        return tuple(v)
    if isinstance(v, set):
        return frozenset(v)
    return v


class SetModel(Model):
    """A grow-only set: `add` elements, `read` the full contents."""

    __slots__ = ("items",)

    def __init__(self, items: FrozenSet[Any] = frozenset()):
        self.items = frozenset(items)

    def step(self, op: Op):
        if op.f == "add":
            return SetModel(self.items | {_freeze(op.value)})
        if op.f == "read":
            if op.value is None:
                return self
            got = frozenset(_freeze(x) for x in op.value)
            if got == self.items:
                return self
            return inconsistent(
                f"read {sorted(map(repr, got))} but set contained "
                f"{sorted(map(repr, self.items))}"
            )
        return inconsistent(f"unknown op f {op.f!r}")

    def __eq__(self, other):
        return type(other) is SetModel and other.items == self.items

    def __hash__(self):
        return hash(("SetModel", self.items))

    def __repr__(self):
        return f"SetModel({sorted(map(repr, self.items))})"


class UnorderedQueue(Model):
    """A queue where dequeue may return any enqueued-but-not-dequeued
    element (knossos.model/unordered-queue)."""

    __slots__ = ("pending",)

    def __init__(self, pending: Tuple[Any, ...] = ()):
        self.pending = tuple(pending)

    def step(self, op: Op):
        v = _freeze(op.value)
        if op.f == "enqueue":
            return UnorderedQueue(self.pending + (v,))
        if op.f == "dequeue":
            if v in self.pending:
                i = self.pending.index(v)
                return UnorderedQueue(self.pending[:i] + self.pending[i + 1 :])
            return inconsistent(f"can't dequeue {v!r}: not in queue")
        return inconsistent(f"unknown op f {op.f!r}")

    def __eq__(self, other):
        return type(other) is UnorderedQueue and sorted(
            map(repr, other.pending)
        ) == sorted(map(repr, self.pending))

    def __hash__(self):
        return hash(("UnorderedQueue", tuple(sorted(map(repr, self.pending)))))

    def __repr__(self):
        return f"UnorderedQueue({list(self.pending)!r})"


class FIFOQueue(Model):
    """A strict FIFO queue: dequeue must return the head."""

    __slots__ = ("items",)

    def __init__(self, items: Tuple[Any, ...] = ()):
        self.items = tuple(items)

    def step(self, op: Op):
        v = _freeze(op.value)
        if op.f == "enqueue":
            return FIFOQueue(self.items + (v,))
        if op.f == "dequeue":
            if not self.items:
                return inconsistent(f"can't dequeue {v!r} from empty queue")
            if self.items[0] == v:
                return FIFOQueue(self.items[1:])
            return inconsistent(
                f"dequeued {v!r} but head was {self.items[0]!r}"
            )
        return inconsistent(f"unknown op f {op.f!r}")

    def __eq__(self, other):
        return type(other) is FIFOQueue and other.items == self.items

    def __hash__(self):
        return hash(("FIFOQueue", self.items))

    def __repr__(self):
        return f"FIFOQueue({list(self.items)!r})"


def set_model() -> SetModel:
    return SetModel()


def unordered_queue() -> UnorderedQueue:
    return UnorderedQueue()


def fifo_queue() -> FIFOQueue:
    return FIFOQueue()
