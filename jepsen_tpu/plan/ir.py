"""The checking-plan IR: pass families, plan nodes, and the plan DAG.

A **pass family** is a checking engine registered with its contract:
which verdict direction it can settle (`can-prove-valid` passes like
the stream witness only ever return True; `can-refute` screens only
False; `exact` engines both), and which resource class it occupies
(`device` passes hold the mesh; `host` passes are CPU/numpy).  The
compiler composes family instances — `PassNode`s with chosen knobs and
declared cost features — into a `Plan`: a small DAG whose typed edges
say where a key goes when a pass cannot decide it ("unknown") or when a
classifier fires ("refuted").

Soundness is the load-bearing invariant: an edge never *changes* a
verdict, it only routes undecided work, so any topology the compiler
emits produces the same per-key verdicts — knobs and ordering are pure
performance choices, which is what lets the cost model drive them.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from collections import OrderedDict
from typing import Any, Callable, Iterator, Optional

#: Verdict directions a family may settle.
SOUNDNESS = ("can-prove-valid", "can-refute", "exact")
#: Resource classes (who holds the accelerator while the pass runs).
RESOURCES = ("device", "host")

#: Edge labels: every node has an implicit "decided" exit; these route
#: the rest.  "unknown" is the generic fallback; "refuted" carries keys
#: a classifier marked invalid-but-uncertified toward a detail pass.
EDGE_LABELS = ("unknown", "refuted")


@dataclasses.dataclass(frozen=True)
class PassFamily:
    """One registered checking engine.

    `runner(ctx, node, keys) -> (decided, routed)` where `decided` maps
    key -> result dict and `routed` maps edge label -> keys to forward.
    Runners live in executor.py; registration here keeps the IR import
    cycle-free.
    """

    name: str
    soundness: str
    resource: str
    runner: Callable[..., Any]
    #: Knob names the cost model may choose for nodes of this family.
    knob_spec: tuple = ()
    doc: str = ""

    def __post_init__(self) -> None:
        if self.soundness not in SOUNDNESS:
            raise ValueError(
                f"{self.name}: soundness {self.soundness!r} not in "
                f"{SOUNDNESS}"
            )
        if self.resource not in RESOURCES:
            raise ValueError(
                f"{self.name}: resource {self.resource!r} not in "
                f"{RESOURCES}"
            )


_FAMILIES: "OrderedDict[str, PassFamily]" = OrderedDict()


def register_family(fam: PassFamily) -> PassFamily:
    """Adds (or replaces) a family in the registry.  Replacement is
    deliberate: tests register instrumented doubles under the stock
    names."""
    _FAMILIES[fam.name] = fam
    return fam


def family(name: str) -> PassFamily:
    f = _FAMILIES.get(name)
    if f is None:
        raise KeyError(
            f"unknown pass family {name!r} (known: {list(_FAMILIES)})"
        )
    return f


def known_families() -> list[str]:
    # Importing the executor registers the builtin families; lazy so
    # `import jepsen_tpu.plan.ir` alone stays cheap.
    from . import executor  # noqa: F401

    return list(_FAMILIES)


@dataclasses.dataclass
class PassNode:
    """One pass instance in a plan: a family plus the knobs the
    compiler chose for it and the cost features it declared."""

    id: str
    family: str
    #: Chosen knob values (segment sizes, beams, budget slices...).
    #: None values mean "engine default" and are preserved in the
    #: fingerprint so trained-vs-untrained plans hash apart.
    knobs: dict = dataclasses.field(default_factory=dict)
    #: Declared cost features (key count, op count) — inputs the cost
    #: model predicted from, recorded for the profile store.
    features: dict = dataclasses.field(default_factory=dict)
    #: label -> node id (or None = exit undecided).  Missing labels
    #: fall back to "unknown"'s target.
    edges: dict = dataclasses.field(default_factory=dict)
    #: Nodes inside the digest-dedup scope operate on one
    #: representative per identical subhistory; the executor fans the
    #: verdict out on scope exit (the settle-memo mechanic).
    group: bool = False

    def target(self, label: str) -> Optional[str]:
        if label in self.edges:
            return self.edges[label]
        return self.edges.get("unknown")

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "family": self.family,
            "knobs": dict(self.knobs),
            "features": dict(self.features),
            "edges": dict(self.edges),
            "group": self.group,
        }


class Plan:
    """An ordered DAG of pass nodes.  Node order is topological by
    construction: the compiler emits nodes in execution order and edges
    only point forward (enforced here), so the executor is a single
    forward sweep with work queues — no scheduler needed."""

    def __init__(self, nodes: list[PassNode], *, meta: Optional[dict] = None):
        self.nodes: "OrderedDict[str, PassNode]" = OrderedDict()
        for n in nodes:
            if n.id in self.nodes:
                raise ValueError(f"duplicate plan node id {n.id!r}")
            self.nodes[n.id] = n
        order = {nid: i for i, nid in enumerate(self.nodes)}
        for n in nodes:
            for label, tgt in n.edges.items():
                if tgt is None:
                    continue
                if tgt not in order:
                    raise ValueError(
                        f"node {n.id!r} edge {label!r} -> unknown node "
                        f"{tgt!r}"
                    )
                if order[tgt] <= order[n.id]:
                    raise ValueError(
                        f"node {n.id!r} edge {label!r} -> {tgt!r} points "
                        "backward; plans are forward DAGs"
                    )
        #: Plan-identity facts (model key, algorithm, budget) — part of
        #: the fingerprint, surfaced in telemetry.
        self.meta = dict(meta or {})

    def __iter__(self) -> Iterator[PassNode]:
        return iter(self.nodes.values())

    def __len__(self) -> int:
        return len(self.nodes)

    def node(self, nid: str) -> PassNode:
        return self.nodes[nid]

    def to_dict(self) -> dict:
        return {
            "meta": dict(self.meta),
            "nodes": [n.to_dict() for n in self.nodes.values()],
        }

    def fingerprint(self) -> str:
        """Stable digest of the whole plan — topology, knobs, and
        identity meta.  Two processes compiling the same cohort with
        the same model/budget/knobs agree on it, which is what lets
        the persistent caches key on it."""
        blob = json.dumps(self.to_dict(), sort_keys=True, default=repr)
        return hashlib.sha256(blob.encode()).hexdigest()

    def describe(self) -> str:
        """One-line-per-node rendering for logs and the /fleet panel."""
        out = []
        for n in self.nodes.values():
            fam = _FAMILIES.get(n.family)
            kn = ",".join(f"{k}={v}" for k, v in sorted(n.knobs.items()))
            edges = ",".join(
                f"{label}->{tgt}" for label, tgt in sorted(n.edges.items())
            )
            out.append(
                f"{n.id}[{n.family}"
                + (f"/{fam.soundness}/{fam.resource}" if fam else "")
                + (f" {kn}" if kn else "")
                + (f" {edges}" if edges else "")
                + ("%" if n.group else "")
                + "]"
            )
        return " ; ".join(out)
