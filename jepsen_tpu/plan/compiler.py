"""The plan compiler: packed work + model spec + budget -> Plan.

Three entry points mirror the three call sites that used to wire the
tier ladder by hand:

* `run_cohort`  — IndependentChecker's per-key cohort (subsumes the
  online-consume / long-key split / stream witness / `_settle_cohort`
  pipeline)
* `run_packs`   — checkerd's wire-packed submissions (subsumes
  `_settle_packs`: stream, memo, decide-mode screen, exact CPU —
  no batched tier)
* `run_single`  — one Linearizable history on the auto device paths

Each compiles a Plan whose knobs come from the cost model
(plan/costmodel.py) — the hand heuristics when untrained, in which
case every knob equals the legacy formula and the compiled plan is
behavior-identical to the hand-wired ladder — and executes it through
plan/executor.py.  The persistent-memo node is inserted only when a
cache directory is configured (cache.py), so default runs have no
on-disk state.
"""

from __future__ import annotations

import logging
from typing import Any, Optional

from .. import telemetry
from . import cache as plan_cache
from . import costmodel
from .executor import ExecContext, execute
from .ir import PassNode, Plan

log = logging.getLogger(__name__)


def _identity(lin: Any, pm: Any, kind: str) -> dict:
    """The persistent-memo identity: every fact whose change must MISS
    the journaled verdicts (satellite: model spec, budget, algorithm;
    the packed digest itself is the other key half)."""
    return {
        "kind": kind,
        "model": pm.name,
        "init": [int(v) for v in pm.init_state],
        "width": int(pm.state_width),
        "algorithm": lin.algorithm,
        "budget-s": lin.time_limit_s,
        "max-configs": lin.max_configs,
    }


def _knob_counter(*sources: str) -> None:
    telemetry.count(
        "wgl.plan.knobs-model" if "model" in sources
        else "wgl.plan.knobs-heuristic"
    )


def _brownout_drops() -> tuple:
    """Optional pass ids the checkerd brownout ladder is currently
    dropping (checkerd/overload.py).  Empty outside a daemon or at
    level 0; the dropped tiers only ever prove keys early, so plans
    built without them stay sound — work routes to the exact tiers."""
    try:
        from ..checkerd import overload

        dropped = overload.dropped_passes()
    except Exception:  # noqa: BLE001 — compilation must never fail on
        # an advisory signal
        return ()
    if dropped:
        telemetry.count("wgl.plan.brownout-compile")
    return dropped


# ---------------------------------------------------------------------------
# Cohort plans (IndependentChecker)
# ---------------------------------------------------------------------------


def compile_cohort_plan(
    checker: Any, test: dict, lin: Any, pm: Any,
    n_keys: int, n_ops: int, *,
    has_unpackable: bool,
) -> tuple[Plan, str]:
    """-> (plan, entry-node-id for packable keys).  Node order is the
    legacy ladder's; the cost model only turns knobs (and may drop the
    stream tier when trained data says it loses)."""
    sess = (test or {}).get("streaming-session") \
        if getattr(checker, "streaming", True) else None
    cache_on = plan_cache.cache_dir() is not None
    stream_knobs, s_src = costmodel.choose_stream_knobs(n_keys, n_ops)
    batched_knobs, b_src = costmodel.choose_batched_knobs(
        n_keys, n_ops, lin.beam
    )
    order = costmodel.choose_tier_order(n_keys, n_ops, stream_knobs)
    _knob_counter(s_src, b_src)

    feats = {"keys": n_keys, "ops": n_ops}
    nodes: list[PassNode] = []
    if has_unpackable:
        nodes.append(PassNode("fallback", "host-fallback"))
    # The main chain: each entry's unknown edge points at the next.
    chain: list[PassNode] = []
    if sess is not None:
        chain.append(PassNode("online", "online-consume",
                              features=feats))
    if cache_on:
        chain.append(PassNode("pmemo", "persistent-memo",
                              features=feats))
    router = PassNode("router", "length-router",
                      knobs={"threshold": 2000})
    chain.append(router)
    longdev = PassNode("longdev", "single-device", features=feats)
    dropped = _brownout_drops()
    stream = None
    if order != "skip-stream" and "stream" not in dropped:
        stream = PassNode("stream", "stream-witness",
                          knobs=dict(stream_knobs), features=feats)
    screen = PassNode("screen", "refute-screen",
                      knobs={"mode": "classify"}, features=feats,
                      group=True)
    batched = None
    if "batched" not in dropped:
        batched = PassNode("batched", "batched-bfs",
                           knobs=dict(batched_knobs), features=feats,
                           group=True)
    detail = PassNode("detail", "settle-exact", features=feats,
                      group=True)

    after_router = stream if stream is not None else screen
    for a, b in zip(chain, chain[1:]):
        a.edges["unknown"] = b.id
    router.edges["long"] = longdev.id
    router.edges["unknown"] = after_router.id
    if stream is not None:
        stream.edges["unknown"] = screen.id
    screen.edges["refuted"] = detail.id
    if batched is not None:
        screen.edges["unknown"] = batched.id
        batched.edges["refuted"] = detail.id
        batched.edges["unknown"] = detail.id
    else:
        screen.edges["unknown"] = detail.id

    nodes.extend(chain)
    nodes.append(longdev)
    if stream is not None:
        nodes.append(stream)
    nodes.append(screen)
    if batched is not None:
        nodes.append(batched)
    nodes.append(detail)

    meta = {
        "kind": "cohort",
        "model": pm.name,
        "algorithm": lin.algorithm,
        "budget-s": lin.time_limit_s,
        "keys": n_keys,
        "knobs": "model" if "model" in (s_src, b_src) else "heuristic",
        "order": order,
    }
    if dropped:
        meta["brownout-dropped"] = list(dropped)
    plan = Plan(nodes, meta=meta)
    return plan, chain[0].id


def run_cohort(
    checker: Any, test: dict, subs: dict, packable: list,
    unpackable: list, packs: dict, model: Any, pm: Any, lin: Any,
    opts: dict,
) -> dict:
    """Compiles and executes the cohort plan; drop-in for everything
    after the packing partition in
    IndependentChecker._check_linearizable."""
    from ..parallel.mesh import checker_mesh

    n_ops = int(sum(packs[k].n for k in packable))
    plan, entry = compile_cohort_plan(
        checker, test, lin, pm, len(packable), n_ops,
        has_unpackable=bool(unpackable),
    )
    telemetry.count("wgl.plan.compile")
    telemetry.count("wgl.plan.keys", len(packable) + len(unpackable))
    ctx = ExecContext(
        test=test, subs=subs, packs=packs, model=model, pm=pm, lin=lin,
        opts=opts, bound=checker.bound, mesh=checker_mesh(test),
        checker=checker, mode="cohort",
        identity=_identity(lin, pm, "cohort"),
    )
    seeds: dict = {}
    if unpackable:
        seeds["fallback"] = list(unpackable)
    if packable:
        seeds[entry] = list(packable)
    return execute(plan, ctx, seeds)


# ---------------------------------------------------------------------------
# Wire-packed plans (checkerd)
# ---------------------------------------------------------------------------


def compile_packs_plan(lin: Any, pm: Any, n_keys: int,
                       n_ops: int) -> tuple[Plan, str]:
    cache_on = plan_cache.cache_dir() is not None
    stream_knobs, s_src = costmodel.choose_stream_knobs(n_keys, n_ops)
    _knob_counter(s_src)
    feats = {"keys": n_keys, "ops": n_ops}
    chain: list[PassNode] = []
    if cache_on:
        chain.append(PassNode("pmemo", "persistent-memo",
                              features=feats))
    dropped = _brownout_drops()
    if "stream" not in dropped:
        chain.append(PassNode("stream", "stream-witness",
                              knobs=dict(stream_knobs), features=feats))
    screen = PassNode("screen", "refute-screen",
                      knobs={"mode": "decide"}, features=feats,
                      group=True)
    exact = PassNode("exact", "packs-exact", features=feats,
                     group=True)
    for a, b in zip(chain, chain[1:]):
        a.edges["unknown"] = b.id
    if chain:
        chain[-1].edges["unknown"] = screen.id
    screen.edges["unknown"] = exact.id
    meta = {
        "kind": "packs",
        "model": pm.name,
        "algorithm": lin.algorithm,
        "budget-s": lin.time_limit_s,
        "keys": n_keys,
    }
    if dropped:
        meta["brownout-dropped"] = list(dropped)
    plan = Plan(chain + [screen, exact], meta=meta)
    return plan, chain[0].id if chain else screen.id


def run_packs(packs: dict, model: Any, lin: Any,
              deadline: Optional[float]) -> dict:
    """Drop-in for checkerd's _settle_packs."""
    pm = model.packed()
    out: dict = {}
    live = []
    for k, p in packs.items():
        if p.n == 0:
            out[k] = {"valid": True, "algorithm": "empty"}
        else:
            live.append(k)
    if not live:
        return out
    n_ops = int(sum(packs[k].n for k in live))
    plan, entry = compile_packs_plan(lin, pm, len(live), n_ops)
    telemetry.count("wgl.plan.compile")
    telemetry.count("wgl.plan.keys", len(live))
    ctx = ExecContext(
        test={}, subs={}, packs=packs, model=model, pm=pm, lin=lin,
        opts={}, mode="packs", deadline=deadline,
        identity=_identity(lin, pm, "packs"),
    )
    out.update(execute(plan, ctx, {entry: live}))
    return out


# ---------------------------------------------------------------------------
# Single-history plans (Linearizable auto paths)
# ---------------------------------------------------------------------------

_SINGLE = "_history"


def run_single(lin: Any, packed: Any, pm: Any, model: Any,
               algorithm: str, test: dict, opts: dict) -> dict:
    """One history through the executor: a persistent-memo probe (when
    a cache dir is configured) in front of the device-first ladder.
    With no cache the plan is the single device-ladder node, whose
    runner IS the legacy ladder."""
    cache_on = plan_cache.cache_dir() is not None
    nodes: list[PassNode] = []
    feats = {"ops": int(packed.n), "ok": int(packed.n_ok)}
    ladder = PassNode("ladder", "device-ladder", features=feats,
                      knobs={"beam": lin.beam, "max_beam": lin.max_beam,
                             "block": lin.block})
    if cache_on:
        pmemo = PassNode("pmemo", "persistent-memo", features=feats,
                         edges={"unknown": "ladder"})
        nodes.append(pmemo)
    nodes.append(ladder)
    plan = Plan(nodes, meta={
        "kind": "single",
        "model": pm.name,
        "algorithm": algorithm,
        "budget-s": lin.time_limit_s,
    })
    telemetry.count("wgl.plan.compile")
    identity = _identity(lin, pm, "single")
    # Search-shape knobs join the identity: they cannot flip a verdict,
    # but a memo entry must describe the plan that produced it.
    identity["beam"] = lin.beam
    identity["max-beam"] = lin.max_beam
    ctx = ExecContext(
        test=test, subs={}, packs={_SINGLE: packed}, model=model,
        pm=pm, lin=lin, opts=opts, mode="single", identity=identity,
    )
    results = execute(plan, ctx, {nodes[0].id: [_SINGLE]})
    r = results[_SINGLE]
    if cache_on and not r.get("memo-hit") \
            and r.get("valid") in (True, False):
        from ..parallel.independent import _sanitize_settle

        pmemo_store = plan_cache.active_memo()
        if pmemo_store is not None:
            pmemo_store.put(
                plan_cache.memo_key(
                    ctx.digest(_SINGLE), identity
                ),
                _sanitize_settle(r),
            )
    return r


# ---------------------------------------------------------------------------
# Elle plans (dependency-graph cycle pass)
# ---------------------------------------------------------------------------


def plan_cycle_fn(device: str) -> Any:
    """A `cycle_fn` for elle's analyses (checker/elle/append.py, wr.py)
    that routes the cycle pass through a one-node plan, registering the
    device SCC screen as the `elle-cycles` pass family.  Returns None
    for the host default (elle's own Tarjan path)."""
    if device == "off":
        return None

    def run(g: Any) -> Any:
        plan = Plan(
            [PassNode("cycles", "elle-cycles",
                      knobs={"device": device},
                      features={"vertices": len(getattr(g, "adj", ()))})],
            meta={"kind": "elle", "device": device},
        )
        telemetry.count("wgl.plan.compile")
        ctx = ExecContext(
            test={}, subs={}, packs={_SINGLE: g}, model=None, pm=None,
            lin=None, opts={}, mode="single",
        )
        return execute(plan, ctx, {"cycles": [_SINGLE]})[_SINGLE]["cycles"]

    return run
