"""The plan executor: one engine that runs any checking plan.

`execute(plan, ctx, seeds)` is a single forward sweep over the plan's
nodes (the IR guarantees edges point forward): each node's pass family
runner decides keys, routes the rest along the node's typed edges, and
the sweep carries work queues node to node.  The contiguous tail of
`group=True` nodes is the **digest-dedup scope** — the settle-memo
mechanic of `IndependentChecker._settle_cohort` hoisted into the
executor: on entry, keys collapse to one representative per packed
digest (memo hits — in-memory settle memo first, then the persistent
plan memo — skip the scope entirely); on exit, each representative's
verdict fans out to its group, sanitized of positional certificates.

The family runners call the *same* engine helpers the legacy ladder
calls (`check_wgl_witness_stream`, `check_refute`, `check_wgl_batched`,
the `"settle"`-algorithm Linearizable, `_memo_get`/`_memo_put`), emit
the same `wgl.settle.*` counters, and wrap the group scope in the same
`profile.capture("settle")` record — so `JEPSEN_PLAN=1` and `=0`
produce identical verdicts, counters, and training records by
construction.  Plan-level telemetry lands under `wgl.plan.*`.
"""

from __future__ import annotations

import contextlib
import dataclasses
import logging
import time
from collections import OrderedDict
from typing import Any, Callable, Optional

from .. import telemetry
from ..telemetry import profile
from . import cache as plan_cache
from .ir import PassFamily, PassNode, Plan, family, register_family

log = logging.getLogger(__name__)


@dataclasses.dataclass
class ExecContext:
    """Everything a runner needs: the cohort's data, the checker
    template whose knobs seed the engines, shared budget state, and
    per-key scratch notes (device verdicts, screen outcomes)."""

    test: dict
    subs: dict
    packs: dict
    model: Any
    pm: Any
    lin: Any
    opts: dict
    bound: Optional[int] = None
    mesh: Any = None
    checker: Any = None
    #: "cohort" (IndependentChecker), "packs" (checkerd wire-packed),
    #: or "single" (one Linearizable history).
    mode: str = "cohort"
    #: packs mode: absolute monotonic deadline (checkerd budget).
    deadline: Optional[float] = None
    #: Plan-identity facts for the persistent memo key: model name /
    #: init state / algorithm / budgets.  Changing any of them misses.
    identity: dict = dataclasses.field(default_factory=dict)
    notes: dict = dataclasses.field(default_factory=dict)
    counts: dict = dataclasses.field(default_factory=dict)
    _digests: dict = dataclasses.field(default_factory=dict)
    _t0: Optional[float] = None

    # -- shared tier budget (the legacy t_tiers clock) ----------------------

    def start_clock(self) -> None:
        if self._t0 is None:
            self._t0 = time.monotonic()

    def budget_left(self) -> Optional[float]:
        if self.mode == "packs":
            if self.deadline is None:
                return None
            return max(1.0, self.deadline - time.monotonic())
        if self.lin.time_limit_s is None:
            return None
        self.start_clock()
        return max(
            1.0, self.lin.time_limit_s - (time.monotonic() - self._t0)
        )

    # -- per-key helpers ----------------------------------------------------

    def digest(self, k: Any) -> str:
        d = self._digests.get(k)
        if d is None:
            from ..parallel.independent import _settle_digest

            d = self._digests[k] = _settle_digest(self.packs[k], self.pm)
        return d

    def pmemo_key(self, k: Any) -> str:
        return plan_cache.memo_key(self.digest(k), self.identity)

    def note(self, k: Any) -> dict:
        n = self.notes.get(k)
        if n is None:
            n = self.notes[k] = {}
        return n

    def count(self, name: str, n: int = 1) -> None:
        self.counts[name] = self.counts.get(name, 0) + n


# ---------------------------------------------------------------------------
# Family runners — each reuses the exact legacy engine call.
# ---------------------------------------------------------------------------


def _run_host_fallback(ctx: ExecContext, node: PassNode, keys: list):
    """Keys with no packed form: the single-key checker under
    bounded_pmap, exactly the legacy unpackable path."""
    from ..checker.core import check_safe
    from ..utils import bounded_pmap

    lin = ctx.lin
    rs = bounded_pmap(
        lambda k: check_safe(
            lin, ctx.test, ctx.subs[k], {**ctx.opts, "history_key": k}
        ),
        keys,
        bound=ctx.bound,
    )
    return dict(zip(keys, rs)), {}


def _run_online(ctx: ExecContext, node: PassNode, keys: list):
    """Digest-gated consumption of a streaming session's online proofs
    (can-prove-valid: a consumed verdict was proven while the run was
    still generating)."""
    from ..parallel.independent import _online_digest

    sess = (ctx.test or {}).get("streaming-session")
    decided: dict = {}
    if sess is not None:
        for k in keys:
            d = _online_digest(sess, ctx.pm, ctx.subs[k])
            r = sess.consume(k, d) if d is not None else None
            if r is not None:
                decided[k] = r
    if decided and telemetry.enabled():
        telemetry.count("wgl.settle.online-proven", len(decided))
    rest = [k for k in keys if k not in decided]
    return decided, ({"unknown": rest} if rest else {})


def _run_pmemo(ctx: ExecContext, node: PassNode, keys: list):
    """Persistent plan-memo lookup (cache.py): a restarted process
    re-checking byte-identical work replays the journaled verdict."""
    pmemo = plan_cache.active_memo()
    if pmemo is None or not keys:
        return {}, ({"unknown": list(keys)} if keys else {})
    decided, rest = {}, []
    for k in keys:
        hit = pmemo.get(ctx.pmemo_key(k))
        if hit is not None:
            hit["memo-hit"] = True
            decided[k] = hit
        else:
            rest.append(k)
    return decided, ({"unknown": rest} if rest else {})


def _run_length_router(ctx: ExecContext, node: PassNode, keys: list):
    """Routes long keys (batched-kernel compile/pad cost scales with
    the LONGEST key) to the per-key device ladder; decides nothing."""
    thr = node.knobs.get("threshold", 2000)
    long_keys = [k for k in keys if ctx.packs[k].n > thr]
    short = [k for k in keys if ctx.packs[k].n <= thr]
    routed: dict = {}
    if long_keys:
        routed["long"] = long_keys
    if short:
        routed["unknown"] = short
    return {}, routed


def _run_single_device(ctx: ExecContext, node: PassNode, keys: list):
    """Per-key witness-first device ladder (check_wgl_device) for keys
    too long for the batched kernel."""
    from ..checker.core import check_safe
    from ..checker.linearizable import Linearizable
    from ..utils import bounded_pmap

    lin = ctx.lin
    long_chk = Linearizable(
        ctx.model, "wgl-tpu",
        beam=lin.beam, max_beam=lin.max_beam,
        time_limit_s=lin.time_limit_s,
        max_configs=lin.max_configs,
    )
    rs = bounded_pmap(
        lambda k: check_safe(
            long_chk, ctx.test, ctx.subs[k], {**ctx.opts, "history_key": k}
        ),
        keys,
        bound=ctx.bound,
    )
    return dict(zip(keys, rs)), {}


def _run_stream(ctx: ExecContext, node: PassNode, keys: list):
    """Cohort-wide witness stream (ops/wgl_stream.py): proves keys
    only; everything else falls through the unknown edge."""
    from ..ops.wgl_stream import check_wgl_witness_stream

    ctx.start_clock()
    kw: dict = {}
    if node.knobs.get("segment") is not None:
        kw["segment_keys"] = node.knobs["segment"]
    if node.knobs.get("max_restarts") is not None:
        kw["max_restarts"] = node.knobs["max_restarts"]
    limit = (ctx.lin.time_limit_s if ctx.mode == "cohort"
             else ctx.budget_left())
    try:
        stream_v = check_wgl_witness_stream(
            [ctx.packs[k] for k in keys], ctx.pm,
            time_limit_s=limit, **kw,
        )
    except Exception:  # noqa: BLE001 — sound fallback exists
        log.warning(
            "stream witness failed; falling back to the batched "
            "search for all keys", exc_info=True,
        )
        stream_v = [None] * len(keys)
    decided: dict = {}
    rest = []
    for k, v in zip(keys, stream_v):
        if v is True:
            decided[k] = {
                "valid": True,
                "algorithm": "wgl-tpu-stream",
                "configs-explored": int(ctx.packs[k].n_ok),
            }
        else:
            rest.append(k)
    if ctx.mode == "cohort" and telemetry.enabled():
        telemetry.count("wgl.settle.stream-proven", len(decided))
    pmemo = plan_cache.active_memo()
    if pmemo is not None and decided:
        from ..parallel.independent import _sanitize_settle

        for k, r in decided.items():
            pmemo.put(ctx.pmemo_key(k), _sanitize_settle(r))
    return decided, ({"unknown": rest} if rest else {})


def _run_screen(ctx: ExecContext, node: PassNode, keys: list):
    """Refutation screens (checker/refute.py).  Two modes: "classify"
    (cohort — a firing screen routes the key to the detail pass for a
    certificate) and "decide" (packs — the screen's exact refutation IS
    the verdict, no detail pass follows)."""
    from ..checker.refute import check_refute
    from ..utils import bounded_pmap

    decide = node.knobs.get("mode") == "decide"

    def screen_one(k):
        b = ctx.budget_left()
        try:
            return check_refute(
                ctx.packs[k], ctx.pm,
                time_limit_s=30.0 if b is None else min(b, 30.0),
            )
        except Exception:  # noqa: BLE001 — a screen bug must not
            log.warning("refutation screen failed for key %r", k,
                        exc_info=True)
            return None  # change a verdict; the search tiers decide

    screened = dict(zip(keys, bounded_pmap(screen_one, keys,
                                           bound=ctx.bound)))
    decided: dict = {}
    refuted, unknown = [], []
    for k in keys:
        ref = screened[k]
        if ref is None:
            unknown.append(k)
        elif decide:
            r: dict = {
                "valid": ref.valid,
                "algorithm": "refute-screen",
                "configs-explored": int(ref.configs_explored),
            }
            if ref.valid == "unknown" and ref.reason:
                r["reason"] = ref.reason
            decided[k] = r
        else:
            ctx.note(k)["screen_fired"] = True
            refuted.append(k)
    routed: dict = {}
    if refuted:
        routed["refuted"] = refuted
    if unknown:
        routed["unknown"] = unknown
    return decided, routed


def _run_batched(ctx: ExecContext, node: PassNode, keys: list):
    """Batched frontier BFS (ops/wgl_batched.py) over screen
    survivors.  True is proven; False is an exact device refutation
    routed to the detail pass; None (overflow/budget) falls through."""
    from ..ops.wgl_batched import check_wgl_batched

    if not keys:
        return {}, {}
    lin = ctx.lin
    beam = node.knobs.get("beam") or min(lin.beam, 32)
    batch = check_wgl_batched(
        [ctx.packs[k] for k in keys],
        ctx.pm,
        beam=beam,
        max_beam=max(lin.max_beam, lin.beam),
        mesh=ctx.mesh,
        time_limit_s=ctx.budget_left(),
    )
    decided: dict = {}
    refuted, unknown = [], []
    n_proven = 0
    for i, k in enumerate(keys):
        v = batch.valid[i]
        n = ctx.note(k)
        n["device_verdict"] = v
        n["device_explored"] = int(batch.explored[i])
        if v is True:
            decided[k] = {
                "valid": True,
                "algorithm": "wgl-tpu-batched",
                "configs-explored": int(batch.explored[i]),
            }
            n_proven += 1
        elif v is False:
            refuted.append(k)
        else:
            unknown.append(k)
    ctx.count("batched-proven", n_proven)
    routed: dict = {}
    if refuted:
        routed["refuted"] = refuted
    if unknown:
        routed["unknown"] = unknown
    return decided, routed


def _run_settle_exact(ctx: ExecContext, node: PassNode, keys: list):
    """The parallel CPU settle: screen-refuted keys re-derive their
    certificate, device-refuted keys get a small detail slice (the
    exact device verdict stands if it expires), unknowns go to the
    exact engine — the legacy settle_one, verbatim."""
    from ..checker.core import check_safe
    from ..checker.linearizable import Linearizable
    from ..utils import bounded_pmap

    lin, model = ctx.lin, ctx.model
    detail_budget = getattr(
        ctx.checker, "REFUTED_DETAIL_BUDGET_S", 10.0
    )

    def settle_one(k):
        n = ctx.notes.get(k) or {}
        dv = n.get("device_verdict")
        budget = ctx.budget_left()
        if dv is False:
            budget = (detail_budget if budget is None
                      else min(budget, detail_budget))
        single = Linearizable(
            model, "settle",
            time_limit_s=budget,
            max_configs=lin.max_configs,
        )
        r = check_safe(single, ctx.test, ctx.subs[k],
                       {**ctx.opts, "history_key": k})
        if dv is not None:
            r["device-verdict"] = dv
        if dv is False:
            if r.get("valid") == "unknown":
                # The detail slice expired; the device refutation is
                # exact (search exhausted without overflow) and
                # settles the verdict on its own.
                r = {
                    "valid": False,
                    "algorithm": "wgl-tpu-batched",
                    "configs-explored": n.get("device_explored", 0),
                    "device-verdict": False,
                }
            elif r.get("valid") is True:
                # Exact engines disagreeing is a checker bug, not a
                # history property; surface it loudly and keep the
                # CPU verdict (parity with per-key exact checking).
                log.error(
                    "device/CPU verdict mismatch on key %r: batched"
                    " kernel proved invalid, exact engine proved "
                    "valid — keeping the CPU verdict", k,
                )
        return r

    decided = dict(zip(keys, bounded_pmap(settle_one, keys,
                                          bound=ctx.bound)))
    for k in decided:
        n = ctx.notes.get(k) or {}
        if n.get("device_verdict") is False:
            ctx.count("batched-refuted")
        elif n.get("screen_fired"):
            ctx.count("screen-refuted")
        else:
            ctx.count("cpu-settled")
    return decided, {}


def _run_packs_exact(ctx: ExecContext, node: PassNode, keys: list):
    """Exact CPU engine over wire-packed submissions (the checkerd
    `_settle_packs` tail: no subs, no batched tier)."""
    decided = {}
    for k in keys:
        res, engine = ctx.lin._cpu_exact(
            ctx.packs[k], ctx.pm, "auto", time_limit_s=ctx.budget_left()
        )
        r: dict = {
            "valid": res.valid,
            "algorithm": engine,
            "configs-explored": int(res.configs_explored),
        }
        if res.valid == "unknown" and res.reason:
            r["reason"] = res.reason
        decided[k] = r
    return decided, {}


def _run_device_ladder(ctx: ExecContext, node: PassNode, keys: list):
    """The whole single-history device-first ladder of
    Linearizable._device_first (witness + frontier search, degradation
    safety nets, exact settling) as one exact pass."""
    decided = {}
    for k in keys:
        decided[k] = ctx.lin._device_first(
            ctx.packs[k], ctx.pm, ctx.model, ctx.lin.algorithm,
            ctx.test, ctx.opts,
        )
    return decided, {}


def _run_elle_cycles(ctx: ExecContext, node: PassNode, keys: list):
    """Elle dependency-cycle pass (checker/elle/graph.py), device-
    screened via the MXU transitive closure when asked.  `ctx.packs`
    carries DepGraphs; a found cycle refutes, an empty result proves
    acyclicity — exact, but registered can-refute because the anomaly
    interpretation belongs to the calling analysis."""
    decided = {}
    for k in keys:
        g = ctx.packs[k]
        if node.knobs.get("device") == "off":
            from ..checker.elle.graph import check_cycles

            decided[k] = {"cycles": check_cycles(g)}
        else:
            from ..ops.scc import check_cycles_device

            decided[k] = {"cycles": check_cycles_device([g])[0]}
    return decided, {}


def _register_builtins() -> None:
    for fam in (
        PassFamily("host-fallback", "exact", "host", _run_host_fallback,
                   doc="host-model search for unpackable keys"),
        PassFamily("online-consume", "can-prove-valid", "host",
                   _run_online,
                   doc="digest-gated streaming-session verdicts"),
        PassFamily("persistent-memo", "exact", "host", _run_pmemo,
                   doc="journaled plan-memo replay (cache.py)"),
        PassFamily("length-router", "exact", "host", _run_length_router,
                   knob_spec=("threshold",),
                   doc="routes only; decides nothing"),
        PassFamily("single-device", "exact", "device",
                   _run_single_device,
                   doc="per-key wgl-tpu ladder for long keys"),
        PassFamily("stream-witness", "can-prove-valid", "device",
                   _run_stream, knob_spec=("segment", "max_restarts"),
                   doc="ops/wgl_witness over one barrier stream "
                       "(ops/wgl_stream frontier)"),
        PassFamily("refute-screen", "can-refute", "host", _run_screen,
                   knob_spec=("mode",),
                   doc="checker/refute.py sound screens"),
        PassFamily("batched-bfs", "exact", "device", _run_batched,
                   knob_spec=("beam",),
                   doc="ops/wgl_batched vmapped frontier BFS"),
        PassFamily("settle-exact", "exact", "host", _run_settle_exact,
                   doc="wgl_cpu / wgl_event via the settle algorithm"),
        PassFamily("packs-exact", "exact", "host", _run_packs_exact,
                   doc="exact CPU engine over wire-packed tensors"),
        PassFamily("device-ladder", "exact", "device",
                   _run_device_ladder,
                   doc="single-history device-first ladder"),
        PassFamily("elle-cycles", "can-refute", "device",
                   _run_elle_cycles, knob_spec=("device",),
                   doc="elle SCC/cycle pass (ops/scc.py MXU closure)"),
    ):
        register_family(fam)


_register_builtins()


# ---------------------------------------------------------------------------
# Group scope: the settle-memo mechanic
# ---------------------------------------------------------------------------


class _GroupState:
    def __init__(self) -> None:
        self.groups: "OrderedDict[str, list]" = OrderedDict()
        self.group_result: dict[str, dict] = {}
        self.key_digest: dict[Any, str] = {}
        self.reps: list = []
        self.n_memo = 0


def _enter_group(ctx: ExecContext, keys: list) -> _GroupState:
    """Digest-groups the keys and replays memoized verdicts: the
    in-memory settle memo first (exactly the legacy ladder), then the
    persistent plan memo (which also warms the in-memory one)."""
    from ..parallel.independent import _memo_get, _memo_put

    gs = _GroupState()
    for k in keys:
        gs.groups.setdefault(ctx.digest(k), []).append(k)
    pmemo = plan_cache.active_memo()
    for d, members in gs.groups.items():
        hit = _memo_get(d)
        if hit is None and pmemo is not None:
            ph = pmemo.get(plan_cache.memo_key(d, ctx.identity))
            if ph is not None:
                hit = ph
                _memo_put(d, ph)
        if hit is not None:
            gs.group_result[d] = hit
        else:
            rep = members[0]
            gs.key_digest[rep] = d
            gs.reps.append(rep)
    gs.n_memo = sum(len(gs.groups[d]) for d in gs.group_result)
    return gs


def _memo_store(ctx: ExecContext, digest: str, r: dict) -> None:
    from ..parallel.independent import _memo_put, _sanitize_settle

    _memo_put(digest, r)
    if r.get("valid") in (True, False):
        pmemo = plan_cache.active_memo()
        if pmemo is not None:
            pmemo.put(plan_cache.memo_key(digest, ctx.identity),
                      _sanitize_settle(r))


def _fanout(ctx: ExecContext, gs: _GroupState) -> dict:
    """Every group's verdict to every member: the representative keeps
    the full result (its positional certificates cite ITS history
    slice); other members share the sanitized verdict."""
    from ..parallel.independent import _sanitize_settle

    live = set(gs.key_digest.values())
    settled: dict = {}
    for d, members in gs.groups.items():
        r = gs.group_result.get(d)
        if r is None:  # defensive: unreachable
            continue
        if d in live:
            settled[members[0]] = r
            extra = members[1:]
            gs.n_memo += len(extra)
        else:
            extra = members  # cross-call memo hit: all share
        for k2 in extra:
            shared = _sanitize_settle(r)
            shared["memo-hit"] = True
            settled[k2] = shared
    return settled


# ---------------------------------------------------------------------------
# The sweep
# ---------------------------------------------------------------------------


def execute(plan: Plan, ctx: ExecContext,
            seeds: Optional[dict] = None) -> dict:
    """Runs a plan to completion; returns {key: result}."""
    telemetry.count("wgl.plan.execute")
    results: dict = {}
    work: dict[str, list] = {nid: [] for nid in plan.nodes}
    for nid, ks in (seeds or {}).items():
        work[nid].extend(ks)

    nodes = list(plan)
    pre = [n for n in nodes if not n.group]
    grp = [n for n in nodes if n.group]

    def route(node: PassNode, routed: dict) -> None:
        for label, ks in routed.items():
            if not ks:
                continue
            tgt = node.target(label)
            if tgt is None:
                # A plan without a fallback edge leaves keys
                # undecided — sound, but worth recording.
                for k in ks:
                    results[k] = {
                        "valid": "unknown",
                        "error": f"plan: no {label!r} route out of "
                                 f"node {node.id!r}",
                    }
                telemetry.count("wgl.plan.unrouted", len(ks))
            else:
                work[tgt].extend(ks)

    for node in pre:
        keys = work.get(node.id) or []
        if not keys:
            continue
        telemetry.count("wgl.plan.pass-runs")
        decided, routed = family(node.family).runner(ctx, node, keys)
        results.update(decided)
        route(node, routed)

    if grp:
        gkeys = work.get(grp[0].id) or []
        if gkeys:
            results.update(
                _execute_group(ctx, grp, gkeys, work, route)
            )
    return results


def _execute_group(ctx: ExecContext, grp: list, gkeys: list,
                   work: dict, route: Callable) -> dict:
    # One cost record for the whole settle pipeline (cohort mode only —
    # the legacy packs path records no settle-level profile either);
    # the chained span hook folds the batched children's compile/
    # execute time into this record, keeping the cost-model training
    # set shape identical across JEPSEN_PLAN values.
    cap = (
        profile.capture(
            "settle", keys=len(gkeys),
            ops=int(sum(ctx.packs[k].n for k in gkeys)),
        )
        if ctx.mode == "cohort"
        else contextlib.nullcontext(None)
    )
    with cap as _ps:
        gs = _enter_group(ctx, gkeys)
        work[grp[0].id] = list(gs.reps)
        for node in grp:
            keys = work.get(node.id) or []
            if not keys:
                continue
            telemetry.count("wgl.plan.pass-runs")
            decided, routed = family(node.family).runner(ctx, node, keys)
            for k, r in decided.items():
                d = gs.key_digest[k]
                gs.group_result[d] = r
                _memo_store(ctx, d, r)
            route(node, routed)
        settled = _fanout(ctx, gs)
        if ctx.mode == "cohort":
            n_screen = ctx.counts.get("screen-refuted", 0)
            n_bp = ctx.counts.get("batched-proven", 0)
            n_br = ctx.counts.get("batched-refuted", 0)
            n_cpu = ctx.counts.get("cpu-settled", 0)
            if telemetry.enabled():
                telemetry.count("wgl.settle.screen-refuted", n_screen)
                telemetry.count("wgl.settle.batched-proven", n_bp)
                telemetry.count("wgl.settle.batched-refuted", n_br)
                telemetry.count("wgl.settle.cpu-settled", n_cpu)
                telemetry.count("wgl.settle.memo-hit", gs.n_memo)
            if _ps is not None:
                _ps.outcome = {
                    "screen-refuted": n_screen,
                    "batched-proven": n_bp,
                    "batched-refuted": n_br,
                    "cpu-settled": n_cpu,
                    "memo-hit": gs.n_memo,
                }
    return settled
