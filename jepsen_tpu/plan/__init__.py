"""Checking-plan IR: compile histories to pass DAGs, execute them once.

The checker tier zoo (witness / stream / frontier / batched / BFS /
settle / exact-CPU, plus the elle SCC path) grew point-to-point: every
caller — `Linearizable`, `IndependentChecker._settle_cohort`, the
checkerd scheduler, the streaming pipeline — wired the degradation
ladder by hand and re-taught it its special cases.  This package is the
compile-then-execute split (TVM's architecture, PAPERS.md) applied to
checking:

  * `ir.py`        — `PassFamily` declarations (soundness direction,
                     resource class) and `PassNode`/`Plan` DAGs with
                     typed fallback edges
  * `compiler.py`  — packed cohort + model + budget -> `Plan`; the
                     existing engines are registered as pass families
                     instead of hard-coded ladder rungs
  * `executor.py`  — one engine runs any plan under the existing
                     budget / degradation / profile.capture machinery,
                     fusing compatible passes across keys and runs and
                     memoizing per plan node
  * `costmodel.py` — a featurized regressor trained offline from
                     profiles.jsonl (`tools/costmodel_train.py`) picks
                     knobs; the hand heuristics are the explicit
                     untrained fallback
  * `cache.py`     — persistent plan memo (store/format.py framing) +
                     JAX's on-disk compilation cache, so fresh
                     processes and restarted daemons skip recompilation

Routing is behind `JEPSEN_PLAN` (default on); `JEPSEN_PLAN=0` keeps
the legacy point-to-point ladder, which the parity suites diff against.
The persistent caches activate only when `JEPSEN_PLAN_CACHE=<dir>` (or
`checkerd --plan-cache`) names a directory — in-memory behavior is
byte-identical either way.
"""

from __future__ import annotations

import os

#: Routing flag: "0"/"false"/"off" disables the plan path.
PLAN_ENV = "JEPSEN_PLAN"
#: Persistent cache directory (plan memo + XLA compile cache); unset
#: means no on-disk state.
CACHE_ENV = "JEPSEN_PLAN_CACHE"


def enabled() -> bool:
    """Whether checking routes through the plan compiler/executor."""
    return os.environ.get(PLAN_ENV, "1").lower() not in ("0", "false", "off")


from .ir import (  # noqa: E402
    Plan,
    PassFamily,
    PassNode,
    family,
    known_families,
    register_family,
)

__all__ = [
    "CACHE_ENV",
    "PLAN_ENV",
    "Plan",
    "PassFamily",
    "PassNode",
    "enabled",
    "family",
    "known_families",
    "register_family",
]
