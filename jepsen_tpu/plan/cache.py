"""Persistent plan memo + XLA compile cache.

Two layers, both rooted in one directory (`JEPSEN_PLAN_CACHE=<dir>` or
`checkerd --plan-cache <dir>`; no directory = no on-disk state, the
in-memory settle memo behaves exactly as before):

* **Plan memo** — `plan-memo.jtpu`, an append-only journal of settled
  plan-node verdicts in store/format.py framing (`BLOCK_PLAN` blocks).
  The key is `sha256(packed-digest | plan identity)` where the identity
  covers model key, algorithm, and budget — so changing any of those
  MISSES while a byte-identical resubmission HITS, and a restarted
  daemon re-checking the same history skips the whole settle ladder.
  Crash safety comes free from BlockWriter's torn-tail truncation.

* **XLA compile cache** — JAX's on-disk compilation cache pointed at
  `<dir>/xla/`, so the second process pays no tracing/lowering for the
  kernels the first one compiled.

Only *decisive, sanitized* verdicts may be journaled: callers strip
positional certificates (final-configs, crashed-op, counterexample
files) before `put`, the same rule the in-memory settle memo enforces.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
import time
from typing import Any, Optional

from .. import telemetry
from ..store import format as fmt

log = logging.getLogger(__name__)

MEMO_FILE = "plan-memo.jtpu"
XLA_SUBDIR = "xla"

#: Journal entries larger than this are not memoized — a plan memo is a
#: verdict cache, not a certificate store.
MAX_ENTRY_BYTES = 1 << 20


def memo_key(digest: str, identity: dict) -> str:
    """Cache key for one settled unit of work.  `digest` is the packed
    subhistory digest (independent._settle_digest / checkerd pack
    digest); `identity` carries every plan knob that must invalidate:
    model key, algorithm, budget, plan fingerprint."""
    blob = json.dumps(identity, sort_keys=True, separators=(",", ":"),
                      default=repr)
    return hashlib.sha256(f"{digest}|{blob}".encode()).hexdigest()


class PlanMemo:
    """The journaled verdict memo.  Thread-safe; one instance per
    process per cache directory."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._mem: dict[str, dict] = {}
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.loaded = 0
        self._writer: Optional[fmt.BlockWriter] = None
        self._load()

    def _load(self) -> None:
        """Replays the journal (last write per key wins).  The
        BlockWriter constructor below re-validates and truncates any
        torn tail before we append."""
        if os.path.exists(self.path):
            try:
                with open(self.path, "rb") as f:
                    if f.read(len(fmt.MAGIC)) == fmt.MAGIC:
                        size = os.path.getsize(self.path)
                        while True:
                            rec = fmt._read_block(f, size)
                            if rec is None:
                                break
                            _, btype, payload = rec
                            if btype != fmt.BLOCK_PLAN:
                                continue
                            k = payload.get("k")
                            v = payload.get("v")
                            if isinstance(k, str) and isinstance(v, dict):
                                self._mem[k] = v
            except OSError as e:
                log.warning("plan memo %s unreadable: %r", self.path, e)
        self.loaded = len(self._mem)
        self._writer = fmt.BlockWriter(self.path)

    def get(self, key: str) -> Optional[dict]:
        with self._lock:
            v = self._mem.get(key)
            if v is None:
                self.misses += 1
                telemetry.count("wgl.plan.memo-miss")
                return None
            self.hits += 1
        telemetry.count("wgl.plan.memo-hit")
        return json.loads(json.dumps(v))  # caller-owned copy

    def put(self, key: str, verdict: dict) -> None:
        entry = {"k": key, "v": verdict, "ts": round(time.time(), 3)}
        try:
            blob = json.dumps(verdict, default=repr)
        except (TypeError, ValueError):
            return
        if len(blob) > MAX_ENTRY_BYTES:
            telemetry.count("wgl.plan.memo-oversize")
            return
        with self._lock:
            if key in self._mem:
                return
            self._mem[key] = json.loads(json.dumps(verdict, default=repr))
            self.puts += 1
            if self._writer is not None:
                try:
                    self._writer.append(fmt.BLOCK_PLAN, entry)
                    self._writer.sync()
                except OSError as e:
                    log.warning("plan memo append failed: %r", e)
        telemetry.count("wgl.plan.memo-store")

    def __len__(self) -> int:
        with self._lock:
            return len(self._mem)

    def stats(self) -> dict:
        with self._lock:
            return {
                "path": self.path,
                "entries": len(self._mem),
                "loaded": self.loaded,
                "hits": self.hits,
                "misses": self.misses,
                "puts": self.puts,
            }

    def close(self) -> None:
        with self._lock:
            if self._writer is not None:
                self._writer.close()
                self._writer = None


# ---------------------------------------------------------------------------
# Process-wide activation
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_memo: Optional[PlanMemo] = None
_dir: Optional[str] = None
_configured = False
_xla_enabled = False


def configure(cache_dir: Optional[str]) -> None:
    """Points the process at a cache directory (both layers), or at
    None to run purely in-memory.  checkerd's --plan-cache flag and the
    smoke tool call this; everyone else inherits JEPSEN_PLAN_CACHE."""
    global _memo, _dir, _configured
    with _lock:
        if _memo is not None:
            _memo.close()
        _memo = None
        _dir = cache_dir
        _configured = True
    if cache_dir:
        enable_xla_cache(cache_dir)


def cache_dir() -> Optional[str]:
    with _lock:
        if _configured:
            return _dir
    from . import CACHE_ENV

    return os.environ.get(CACHE_ENV) or None


def active_memo() -> Optional[PlanMemo]:
    """The process's plan memo, or None when no cache dir is set."""
    global _memo
    d = cache_dir()
    if not d:
        return None
    if not _xla_enabled:
        # Env-var activation (JEPSEN_PLAN_CACHE with no configure()
        # call) must wire the compile cache too, not just the memo.
        enable_xla_cache(d)
    with _lock:
        if _memo is not None and _memo.path == os.path.join(d, MEMO_FILE):
            return _memo
        try:
            os.makedirs(d, exist_ok=True)
            _memo = PlanMemo(os.path.join(d, MEMO_FILE))
        except OSError as e:
            log.warning("plan cache dir %s unusable: %r", d, e)
            _memo = None
        return _memo


def enable_xla_cache(cache_dir_: str) -> Optional[str]:
    """Wires JAX's persistent compilation cache under the plan cache
    dir.  Idempotent; thresholds zeroed so even the sub-second CPU
    kernels of the test suite land in it (the smoke tool counts files
    here to assert compile-cache warm start)."""
    global _xla_enabled
    xdir = os.path.join(cache_dir_, XLA_SUBDIR)
    try:
        os.makedirs(xdir, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", xdir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _xla_enabled = True
        return xdir
    except Exception as e:  # jax missing/old: plan memo still works
        log.warning("XLA persistent cache unavailable: %r", e)
        return None


def xla_cache_files(cache_dir_: Optional[str] = None) -> int:
    """How many compiled executables the XLA cache holds — the smoke
    tool's 'no new compilations on run 2' probe."""
    d = cache_dir_ or cache_dir()
    if not d:
        return 0
    xdir = os.path.join(d, XLA_SUBDIR)
    try:
        return sum(1 for n in os.listdir(xdir)
                   if not n.startswith("."))
    except OSError:
        return 0


def stats() -> dict:
    """Aggregate cache view for checkerd stats() and /fleet."""
    d = cache_dir()
    m = active_memo() if d else None
    return {
        "dir": d,
        "memo": m.stats() if m else None,
        "xla_files": xla_cache_files(d) if d else 0,
        "xla_enabled": _xla_enabled,
    }


def reset_for_tests() -> None:
    """Drops process-wide cache state (tests re-point the cache dir
    between cases)."""
    global _memo, _dir, _configured
    with _lock:
        if _memo is not None:
            _memo.close()
        _memo = None
        _dir = None
        _configured = False
