"""Learned cost model over the per-pass profile store.

PR 9's `profiles.jsonl` records every WGL pass with shape features,
plan knobs, and the measured compile/execute split — the training set
named by ROADMAP item 1 and the approach of "A Learned Performance
Model for TPUs" (PAPERS.md), scaled to this repo: a small per-pass
ridge regressor over log-transformed shape + knob features predicting
log cost.  `tools/costmodel_train.py` fits it offline and writes a
JSON model file; at runtime the compiler asks `choose_*` for knobs.

The contract with correctness: knobs and tier order are *performance*
choices — every pass family is sound in its declared direction
regardless of knob values — so a bad model can only waste time, never
flip a verdict.  The hand heuristics (the exact formulas the legacy
ladder used: ~K/8 stream segments, `max(8, K//2)` restarts, beam-32
batched starts) remain the explicit fallback whenever no model file is
loaded, the model lacks the pass, or prediction fails.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
from typing import Any, Iterable, Optional

log = logging.getLogger(__name__)

MODEL_ENV = "JEPSEN_COSTMODEL"
MODEL_VERSION = 1

#: Minimum records per pass before a fit is trusted.
MIN_SAMPLES = 4


# ---------------------------------------------------------------------------
# Hand heuristics — the untrained fallback, verbatim from the ladder.
# ---------------------------------------------------------------------------


def heuristic_stream_knobs(n_keys: int) -> dict:
    """The legacy formulas from ops/wgl_stream.py: first-pass spans
    every key, post-death segments ~K/8, restart cap half the keys."""
    return {
        "segment": max(8, -(-n_keys // 8)),
        "max_restarts": max(8, n_keys // 2),
    }


def heuristic_batched_knobs(beam: int) -> dict:
    """parallel/independent.py's batched start: the kernel's smallest
    beam bucket so narrow keys settle in cheap passes."""
    return {"beam": min(beam, 32)}


def heuristic_witness_block_knobs() -> dict:
    """The witness chunk shape when no trained model covers the pass:
    2048 bars/block x 32 blocks/call.  Re-measured with the packed
    lanes on the scale workload (4M ops, procs=16, info 5%):
    2048x32 runs 1.28x the old 1024x32 default (169.6k vs 132.9k
    ops/s) and still wins at 200k ops; 4096 regresses (working set
    falls out of cache) — see doc/design.md "Bit-packed kernels"."""
    return {"bars_per_block": 2048, "blocks_per_call": 32}


def _candidate_witness_blocks() -> list:
    """Witness block-shape grid: bars/block x blocks/call buckets the
    scan kernel compiles cleanly at; the chooser ranks only those the
    trained witness predictor has support for."""
    return [(512, 32), (1024, 32), (1024, 64),
            (2048, 16), (2048, 32), (4096, 16)]


def choose_witness_block_knobs(n_ops: int, n_ok: int,
                               model: "Optional[CostModel]" = None
                               ) -> tuple:
    """(knobs, source) for the witness chunk shape
    ({bars_per_block, blocks_per_call}): model-argmin over the bucket
    grid when a trained witness predictor covers the candidates, else
    the measured heuristic default."""
    heur = heuristic_witness_block_knobs()
    if model is None:
        model = active_model()
    if model is None or not model.has("witness"):
        return heur, "heuristic"
    feats = {"ops": n_ops, "ok": n_ok}
    best, best_cost = None, None
    for bars, nb in _candidate_witness_blocks():
        knobs = {"bars_per_block": bars, "blocks_per_call": nb}
        if not _in_support(model, "witness", knobs, heur):
            continue
        cost = model.predict_s("witness", feats, knobs)
        if cost is None:
            return heur, "heuristic"
        if best_cost is None or cost < best_cost:
            best, best_cost = knobs, cost
    return (best, "model") if best else (heur, "heuristic")


#: Floor for streaming-finalize chunk rows (PR 7's constant).
FINALIZE_MIN_ROWS = 192


def heuristic_finalize_rows(hwm: int) -> int:
    """PR 7's HWM-halving formula, verbatim from the streaming
    pipeline's finalize: chunk caps at half the high-water mark the
    steady-state stream batches reached, floored at 192 rows."""
    return max(FINALIZE_MIN_ROWS, int(hwm) // 2)


# ---------------------------------------------------------------------------
# Featurization
# ---------------------------------------------------------------------------

#: Shape features (from record["features"]) and knobs (from
#: record["plan"]) the regressor may see, all log1p-transformed.
#: Unknown keys are ignored; missing ones contribute 0 — schema drift
#: between client- and daemon-side records degrades gracefully.
SHAPE_KEYS = ("keys", "ops", "ok")
KNOB_KEYS = ("segment", "max_restarts", "beam", "max_beam", "block")


#: Roofline cost features a v2 profile record can contribute
#: (telemetry/roofline.py): log-scaled like everything else, and absent
#: (0.0 via x.get default) when a record predates the roofline block or
#: the backend could not report cost analysis — so mixed v1/v2 stores
#: train and predict without special-casing.
COST_KEYS = ("flops", "bytes_accessed")


def featurize(features: dict, plan: dict,
              cost: Optional[dict] = None) -> dict[str, float]:
    x: dict[str, float] = {}
    cvals: dict[str, float] = {}
    for k in COST_KEYS:
        v = (cost or {}).get(k)
        if isinstance(v, (int, float)) and v >= 0:
            cvals[k] = float(v)
            x[f"log_{k}"] = math.log1p(float(v))
    if cvals.get("bytes_accessed"):
        x["log_intensity"] = math.log1p(
            cvals.get("flops", 0.0) / cvals["bytes_accessed"])
    for k in SHAPE_KEYS:
        v = features.get(k)
        if isinstance(v, (int, float)) and v >= 0:
            x[f"log_{k}"] = math.log1p(float(v))
    ks = features.get("keys")
    ops = features.get("ops")
    if isinstance(ks, (int, float)) and isinstance(ops, (int, float)) \
            and ks and ks > 0:
        x["log_ops_per_key"] = math.log1p(float(ops) / float(ks))
    for k in KNOB_KEYS:
        v = plan.get(k)
        if isinstance(v, (int, float)) and v >= 0:
            lv = math.log1p(float(v))
            x[f"log_knob_{k}"] = lv
            # The squared term lets the fit bend: knob cost curves are
            # U-shaped (tiny segments pay per-restart overhead, huge
            # ones pay per-death replay), and a purely linear-in-log
            # model could only ever pick an endpoint of the grid.
            x[f"log_knob_{k}_sq"] = lv * lv
    return x


def record_cost_s(rec: dict) -> float:
    """Cost target: device execute seconds, falling back to wall total
    (same rule as tools/profile_diff.py's cost_of)."""
    t = rec.get("timing") or {}
    ex = t.get("execute_s") or 0.0
    return float(ex if ex > 0 else t.get("total_s") or 0.0)


# ---------------------------------------------------------------------------
# The model
# ---------------------------------------------------------------------------


class CostModel:
    """Per-pass linear predictors over the featurized records.
    `passes[name] = {"names": [...], "coef": [...], "n": int}` with an
    implicit intercept at coef[0]."""

    def __init__(self, passes: dict[str, dict], *, meta: Optional[dict] = None):
        self.passes = passes
        self.meta = dict(meta or {})

    # -- inference ----------------------------------------------------------

    def has(self, pass_name: str) -> bool:
        return pass_name in self.passes

    def predict_s(self, pass_name: str, features: dict,
                  plan: dict, cost: Optional[dict] = None
                  ) -> Optional[float]:
        p = self.passes.get(pass_name)
        if p is None:
            return None
        try:
            x = featurize(features, plan, cost)
            coef = p["coef"]
            y = float(coef[0])
            for name, c in zip(p["names"], coef[1:]):
                y += float(c) * x.get(name, 0.0)
            # Target is log1p(cost): invert, clamp to sane seconds.
            cost = math.expm1(min(y, 25.0))
            return max(cost, 0.0)
        except (KeyError, TypeError, ValueError, IndexError):
            return None

    # -- persistence --------------------------------------------------------

    def to_dict(self) -> dict:
        return {"v": MODEL_VERSION, "meta": self.meta,
                "passes": self.passes}

    def save(self, path: str) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=2, sort_keys=True)
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str) -> Optional["CostModel"]:
        """None on any problem — a broken model file must degrade to
        the heuristics, never break checking."""
        try:
            with open(path) as f:
                d = json.load(f)
            if not isinstance(d, dict) or d.get("v") != MODEL_VERSION:
                log.warning("cost model %s: unsupported version %r",
                            path, d.get("v") if isinstance(d, dict) else d)
                return None
            passes = d.get("passes")
            if not isinstance(passes, dict):
                return None
            return cls(passes, meta=d.get("meta") or {})
        except (OSError, ValueError) as e:
            log.warning("cost model %s unreadable: %r", path, e)
            return None


def fit(records: Iterable[dict], *,
        min_samples: int = MIN_SAMPLES) -> CostModel:
    """Ridge-fits one predictor per pass name over the records.  Pure
    numpy; passes with too few intact records are skipped (the runtime
    then falls back to the heuristics for them)."""
    import numpy as np

    by_pass: dict[str, list[tuple[dict[str, float], float]]] = {}
    support: dict[str, dict[str, list[float]]] = {}
    shape_support: dict[str, dict[str, list[float]]] = {}
    for rec in records:
        name = rec.get("pass") or "unknown"
        cost = record_cost_s(rec)
        if cost < 0:
            continue
        plan = rec.get("plan") or {}
        feats = rec.get("features") or {}
        xla_cost = rec.get("cost")
        x = featurize(feats, plan,
                      xla_cost if isinstance(xla_cost, dict) else None)
        by_pass.setdefault(name, []).append((x, cost))
        sup = support.setdefault(name, {})
        for k in KNOB_KEYS:
            v = plan.get(k)
            if isinstance(v, (int, float)) and v >= 0:
                lo, hi = sup.get(k, (v, v))
                sup[k] = [min(lo, float(v)), max(hi, float(v))]
        ssup = shape_support.setdefault(name, {})
        for k in SHAPE_KEYS:
            v = feats.get(k)
            if isinstance(v, (int, float)) and v >= 0:
                lo, hi = ssup.get(k, (v, v))
                ssup[k] = [min(lo, float(v)), max(hi, float(v))]

    passes: dict[str, dict] = {}
    for name, rows in by_pass.items():
        if len(rows) < min_samples:
            continue
        names = sorted({k for x, _ in rows for k in x})
        if not names:
            continue
        X = np.array(
            [[1.0] + [x.get(n, 0.0) for n in names] for x, _ in rows]
        )
        y = np.array([math.log1p(c) for _, c in rows])
        # Ridge via augmented rows: tiny L2 keeps collinear knob
        # features (e.g. segment == f(keys) in heuristic-only stores)
        # from blowing up the solve.
        lam = 1e-3
        aug = math.sqrt(lam) * np.eye(X.shape[1])
        aug[0, 0] = 0.0  # never shrink the intercept
        Xa = np.vstack([X, aug])
        ya = np.concatenate([y, np.zeros(X.shape[1])])
        coef, *_ = np.linalg.lstsq(Xa, ya, rcond=None)
        pred = X @ coef
        rmse = float(np.sqrt(np.mean((pred - y) ** 2)))
        passes[name] = {
            "names": names,
            "coef": [float(c) for c in coef],
            "n": len(rows),
            "rmse_log": round(rmse, 6),
            # Observed knob ranges: the choosers never rank a knob
            # value the training data has no support for — a linear
            # fit extrapolates confidently and wrongly.
            "support": support.get(name, {}),
            # Observed SHAPE buckets (keys/ops/ok ranges): the
            # finalize-chunk chooser only ranks chunk sizes whose
            # per-chunk shape the store has actually recorded.
            # Additive field — models without it simply keep every
            # shape-gated chooser on the legacy formulas.
            "shape_support": shape_support.get(name, {}),
        }
    return CostModel(passes)


# ---------------------------------------------------------------------------
# The active model (process-wide, lazily loaded)
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_model: Optional[CostModel] = None
_model_path: Optional[str] = None
_loaded = False


def set_model_path(path: Optional[str]) -> None:
    """Points the process at a model file (None reverts to the
    heuristics).  The env var JEPSEN_COSTMODEL is the CLI spelling."""
    global _model, _model_path, _loaded
    with _lock:
        _model_path = path
        _model = None
        _loaded = False


def active_model() -> Optional[CostModel]:
    global _model, _loaded
    with _lock:
        if not _loaded:
            path = _model_path or os.environ.get(MODEL_ENV)
            _model = CostModel.load(path) if path else None
            _loaded = True
        return _model


def model_info() -> dict:
    """Status line for stats()/the /fleet panel."""
    m = active_model()
    if m is None:
        return {"loaded": False, "fallback": "heuristic"}
    return {
        "loaded": True,
        "passes": sorted(m.passes),
        "samples": {k: v.get("n") for k, v in m.passes.items()},
    }


# ---------------------------------------------------------------------------
# Knob choice — model when trained, heuristics otherwise.
# ---------------------------------------------------------------------------


def _candidate_segments(n_keys: int) -> list[int]:
    h = heuristic_stream_knobs(n_keys)["segment"]
    cands = {h, max(1, -(-n_keys // 4)), max(1, -(-n_keys // 16)),
             2, max(1, n_keys)}
    return sorted(c for c in cands if 1 <= c <= max(1, n_keys))


def _candidate_restarts(n_keys: int) -> list[int]:
    h = heuristic_stream_knobs(n_keys)["max_restarts"]
    return sorted({h, max(8, n_keys // 4), max(8, n_keys)})


def _in_support(model: CostModel, pass_name: str, knobs: dict,
                heur: dict) -> bool:
    """A candidate is rankable iff every knob sits inside the pass's
    trained range; a knob the training data never recorded is only
    acceptable at its heuristic value (the fit extrapolates confidently
    and wrongly outside its support)."""
    sup = model.passes.get(pass_name, {}).get("support") or {}
    for k, v in knobs.items():
        rng = sup.get(k)
        if rng is None:
            if v != heur.get(k):
                return False
            continue
        try:
            lo, hi = float(rng[0]), float(rng[1])
        except (TypeError, ValueError, IndexError):
            return False
        if not lo <= float(v) <= hi:
            return False
    return True


def choose_stream_knobs(n_keys: int, n_ops: int,
                        model: Optional[CostModel] = None
                        ) -> tuple[dict, str]:
    """(knobs, source): stream segment size + restart cap, model-argmin
    over a bounded candidate grid when a trained predictor covers the
    stream pass, else the legacy formulas."""
    if model is None:
        model = active_model()
    heur = heuristic_stream_knobs(n_keys)
    if model is None or not model.has("stream"):
        return heur, "heuristic"
    feats = {"keys": n_keys, "ops": n_ops}
    best, best_cost = None, None
    for seg in _candidate_segments(n_keys):
        for mr in _candidate_restarts(n_keys):
            knobs = {"segment": seg, "max_restarts": mr}
            if not _in_support(model, "stream", knobs, heur):
                continue
            cost = model.predict_s("stream", feats, knobs)
            if cost is None:
                return heur, "heuristic"
            if best_cost is None or cost < best_cost:
                best, best_cost = knobs, cost
    return (best, "model") if best else (heur, "heuristic")


def choose_batched_knobs(n_keys: int, n_ops: int, beam: int,
                         model: Optional[CostModel] = None
                         ) -> tuple[dict, str]:
    if model is None:
        model = active_model()
    heur = heuristic_batched_knobs(beam)
    if model is None or not model.has("batched"):
        return heur, "heuristic"
    feats = {"keys": n_keys, "ops": n_ops}
    best, best_cost = None, None
    for b in sorted({heur["beam"], 32, 64, min(128, beam), beam}):
        if b < 1 or not _in_support(model, "batched", {"beam": b}, heur):
            continue
        cost = model.predict_s("batched", feats, {"beam": b})
        if cost is None:
            return heur, "heuristic"
        if best_cost is None or cost < best_cost:
            best, best_cost = {"beam": b}, cost
    return (best, "model") if best else (heur, "heuristic")


def _shape_in_support(model: CostModel, pass_name: str,
                      feats: dict) -> bool:
    """True iff every shape feature sits inside the pass's recorded
    shape bucket range.  Models fitted before shape_support existed
    have no ranges -> nothing is rankable -> legacy formulas hold."""
    sup = model.passes.get(pass_name, {}).get("shape_support") or {}
    if not sup:
        return False
    for k, v in feats.items():
        rng = sup.get(k)
        if rng is None:
            return False
        try:
            lo, hi = float(rng[0]), float(rng[1])
        except (TypeError, ValueError, IndexError):
            return False
        if not lo <= float(v) <= hi:
            return False
    return True


def _candidate_chunk_rows(hwm: int, total_rows: int) -> list[int]:
    """Finalize-chunk candidates: the two legacy formulas plus
    power-of-two buckets up to the backlog (capped — a cap beyond the
    backlog is equivalent to one chunk)."""
    cands = {heuristic_finalize_rows(hwm),
             max(FINALIZE_MIN_ROWS, int(hwm) // 4)}
    b = 256
    while b <= max(total_rows, FINALIZE_MIN_ROWS) and b <= (1 << 16):
        cands.add(b)
        b *= 2
    return sorted(c for c in cands if c >= FINALIZE_MIN_ROWS)


def choose_finalize_chunk_rows(n_keys: int, total_rows: int, hwm: int,
                               model: Optional[CostModel] = None
                               ) -> tuple[int, str]:
    """(chunk_rows, source) for the streaming pipeline's finalize
    backlog: the generalization of PR 7's HWM-halving.  When the
    trained stream predictor has roofline-annotated records whose
    shape buckets cover a candidate chunk size, the model ranks the
    candidates by predicted total finalize cost (per-chunk pass cost x
    number of chunks); out of support — or with no model at all — the
    legacy `max(192, hwm // 2)` formula holds verbatim."""
    heur = heuristic_finalize_rows(hwm)
    if total_rows <= 0:
        return heur, "heuristic"
    if model is None:
        model = active_model()
    if model is None or not model.has("stream"):
        return heur, "heuristic"
    sknobs = heuristic_stream_knobs(n_keys)
    best, best_cost = None, None
    for cap in _candidate_chunk_rows(hwm, total_rows):
        n_chunks = max(1, -(-total_rows // cap))
        keys_per_chunk = max(1, -(-n_keys // n_chunks))
        feats = {"keys": keys_per_chunk, "ops": min(cap, total_rows)}
        if not _shape_in_support(model, "stream", feats):
            continue
        per = model.predict_s("stream", feats, sknobs)
        if per is None:
            return heur, "heuristic"
        cost = per * n_chunks
        if best_cost is None or cost < best_cost:
            best, best_cost = cap, cost
    return (best, "model") if best else (heur, "heuristic")


def choose_tier_order(n_keys: int, n_ops: int, stream_knobs: dict,
                      model: Optional[CostModel] = None) -> str:
    """"stream-first" (the default ladder) or "skip-stream" when the
    model predicts the witness stream costs more than twice the batched
    sweep it is supposed to short-circuit.  Sound either way: the
    stream only ever *proves* keys, every key it would have proven is
    still decided downstream by the exact tiers."""
    if model is None:
        model = active_model()
    if model is None or not model.has("stream") or not model.has("batched"):
        return "stream-first"
    feats = {"keys": n_keys, "ops": n_ops}
    s = model.predict_s("stream", feats, stream_knobs)
    b = model.predict_s("batched", feats,
                        heuristic_batched_knobs(32))
    if s is None or b is None:
        return "stream-first"
    return "skip-stream" if s > 2.0 * max(b, 1e-6) else "stream-first"
