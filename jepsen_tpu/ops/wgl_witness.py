"""Device witness search for linearizability — the valid-verdict fast path.

Round-1 finding: the level-synchronous BFS in ops/wgl.py carries every
reachable subset of absorbed indeterminate (:info) ops as a distinct
configuration, so frontier width grows ~2^k with accumulated info ops
(the deliberately adversarial BASELINE.json 100k-op high-:info config).
This module is the algorithmic answer: an *event-walk* formulation of
Wing–Gong (the just-in-time linearization strategy of Lowe's "Testing
for Linearizability" — the same algorithm family knossos's
`knossos.wgl/analysis` implements, consumed by the reference at
jepsen/src/jepsen/checker.clj:214-233):

* Walk :ok operations in completion order.  By induction every :ok op
  returning before the current barrier is linearized in every surviving
  config, so the WGL candidate rule — `a` may be linearized iff
  inv(a) < min ret over non-members — collapses to "invoked before the
  current barrier's return".
* At the barrier for op `a`, each config must contain `a`: configs pass
  (a already linearized as an earlier helper), linearize `a` directly
  (one model step per beam lane), or linearize a *chain* of helper ops
  ending in `a`.  Helpers are ops still open at the barrier:
  indeterminate ops (ret = ∞, never forced) and :ok ops returning later.
* Chains are found just-in-time, vectorized: a targeted round evaluates
  every (lane, helper) pair `h·a` in one batched model step; an
  escalation round expands by any *productive* single helper
  (state-changing — an unproductive helper child is dominated by its
  parent), deduplicates children by resulting model state, and retries.
  Info ops are therefore only linearized at the barrier that needs
  their effect — the frontier never enumerates subsets of irrelevant
  info ops.

Execution is shaped by two measured costs (round-2 profiling):

* XLA recompilation: anything shape-polymorphic per block (window
  width, re-gather permutations) recompiles hundreds of times.  The
  window width W is therefore fixed for the whole run (the max over
  blocks, bucketed), so exactly one chunk kernel is compiled, and the
  between-block member re-layout is a static-shape device gather driven
  by per-block permutation tensors.
* Dispatch latency (~20 ms/call over a tunneled TPU): barriers are
  grouped into blocks of `bars_per_block`, and `blocks_per_call` blocks
  ship per device call — a 100k-op history runs in ~3 calls.  Inside a
  call, an outer `lax.scan` over blocks re-lays the window and scans
  the block's barriers once: the body does the pass/direct step inline
  (membership of ops whose barrier passed is *implied by barrier rank*,
  so direct linearizations write no member bits) and enters the heavy
  chain-search round behind a `lax.cond` only at barriers where the
  frontier would die.  (An earlier fast-scan/heavy/re-scan split spent
  ~85% of device time re-walking blocks after each heavy round.)

Soundness: every transition is a legal WGL linearization step, so any
config alive after the final barrier is a witness — `valid=True` is
exact.  The search is *not* exhaustive (beam + chain-depth bounded, and
direct success suppresses early-linearization branches), so a dead
frontier proves nothing: callers fall back to the exact frontier BFS
(ops/wgl.py) / CPU DFS (checker/wgl_cpu.py) for invalid/unknown.
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import Any, Optional

import numpy as np

from .. import telemetry
from ..telemetry import profile, roofline
from ..checker.wgl_cpu import WGLResult
from ..history.packed import ST_OK, PackedOps
from ..models.base import PackedModel
from . import degrade, packing
from .wgl import _bucket, packed_enabled, window_regather

INF = np.int32(2**31 - 1)
NO_BAR = np.iinfo(np.int32).max

#: Default per-block bound on indeterminate-op window columns.  Narrow
#: on purpose: W buckets to 2048 on the bench config (1.8 s vs 3.2 s at
#: 4096 — round-2 measurement).  check_wgl_device escalates to
#: WIDE_INFO_WINDOW when a narrow attempt that actually dropped columns
#: finds no witness.  bench.py's warm-up precompiles via plan_width,
#: which shares this default — keep them coupled through this constant.
NARROW_INFO_WINDOW = 512
WIDE_INFO_WINDOW = 4096

_chunk_fn_cache: dict[tuple, Any] = {}

#: transfer="device" entries, keyed (chunk-fn key, span-slice bucket):
#: separate from _chunk_fn_cache so the span bucket never fragments
#: the eager (fn, fn_idx) build or its _BUILD_FAILED negative cache.
_chunk_dev_cache: dict[tuple, Any] = {}

#: Negative-cache sentinel: a key mapping to this means Mosaic
#: deterministically rejected the kernel build for that config —
#: subsequent checks go straight to the scan sweep without re-paying
#: the lowering probe (one redundant probe + traceback per key per
#: analysis pass under IndependentChecker's thread pool otherwise).
#: Transient runtime flakes use cache EVICTION instead, so the next
#: check re-attempts the kernel.
_BUILD_FAILED = object()


#: Minimum elapsed seconds before a checkpoint is worth writing: short
#: searches finish in milliseconds and would pay a device->host carry
#: transfer + npz write per chunk for a file that is deleted moments
#: later.  A blown budget saves regardless — that is precisely the
#: run whose progress a resume recovers.
CKPT_MIN_ELAPSED_S = 5.0


def _ckpt_key(packed: PackedOps, pm: PackedModel, B: int, W: int,
              SW: int, K: int, NB: int,
              info_window: Optional[int]) -> str:
    """Digest binding a checkpoint to one (history, model, search
    shape) triple.  The FULL packed arrays are hashed — a collision
    here would resume the wrong search and corrupt a verdict, so no
    sampling shortcuts (~0.25 s at 10M rows, microseconds at bench
    sizes, amortized over minutes of resumable work).  The model's
    identity and initial state are in the key because the carry's
    beam states only mean anything under the transition function
    that computed them."""
    h = hashlib.sha256()
    h.update(np.int64(
        [packed.n, B, W, SW, K, NB, -1 if info_window is None
         else info_window]
    ).tobytes())
    h.update(getattr(pm, "name", type(pm).__name__).encode())
    h.update(np.ascontiguousarray(
        np.asarray(pm.init_state, dtype=np.int64)
    ).tobytes())
    for name in ("inv", "ret", "process", "status", "f", "a0", "a1"):
        h.update(np.ascontiguousarray(getattr(packed, name)).tobytes())
    return h.hexdigest()


def _ckpt_load(path: str, key: str):
    """-> (next_chunk_c0, member, states, alive) or None."""
    import zipfile

    try:
        with np.load(path, allow_pickle=False) as z:
            if str(z["key"]) != key:
                return None
            return (int(z["c0"]), z["member"], z["states"], z["alive"])
    except (FileNotFoundError, OSError, KeyError, ValueError,
            zipfile.BadZipFile):
        # Missing, foreign, or torn (np.savez never fsyncs, so a hard
        # kill mid-save can install a partial zip): restart from
        # block zero rather than crash the analysis.
        return None


def _ckpt_save(path: str, key: str, c0: int, member: np.ndarray,
               states: np.ndarray, alive: np.ndarray) -> None:
    # NB: np.savez appends ".npz" to names that lack it — the tmp
    # name must already end in .npz or os.replace misses the real
    # file and the except clause eats the evidence.
    tmp = path + ".tmp.npz"
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        np.savez(tmp, key=key, c0=np.int64(c0), member=member,
                 states=states, alive=alive)
        os.replace(tmp, path)
    except OSError:
        # Checkpointing is best-effort: a full disk must not cost
        # the verdict.
        pass


def _ckpt_remove(path: Optional[str]) -> None:
    if path is None:
        return
    try:
        os.remove(path)
    except OSError:
        pass


def _state_hash_vec(sw: int, seed: int = 0xA11CE) -> np.ndarray:
    rng = np.random.default_rng(seed)
    return rng.uniform(1.0, 2.0, size=(sw,)).astype(np.float32)


def _plan_blocks(packed: PackedOps, bars_per_block: int,
                 info_window: Optional[int] = None,
                 rank_override: Optional[np.ndarray] = None):
    """Host-side plan: barrier order, per-block active windows.

    `info_window` keeps only the most recently invoked N indeterminate
    ops in each block's window.  Dropping an info column is SOUND for
    the witness tier regardless of its membership state — an
    unlinearized one merely stops being a helper candidate
    (completeness loss only), and a linearized one keeps its state
    contribution while becoming un-relinearizable.  Without the bound,
    info ops accumulate for the whole run (ret = ∞) and the window —
    hence heavy-round cost — grows linearly with history length: the
    1M-op bench config reaches W = 65536 unbounded.

    The per-block window is maintained INCREMENTALLY: rows are
    invocation-ordered, so each block's entrants are the contiguous
    index range invoked since the previous block (one searchsorted),
    and its leavers are exactly the barriers that passed in the
    previous block plus the oldest info rows beyond the bound — both
    O(window) merges.  A fresh full-history mask per block (the
    round-1..3 implementation) made planning O(n_blocks * n): at 10M
    ops it dominated end-to-end time (measured 43.7k ops/s vs 190k at
    1M, i.e. the checker itself was linear but the planner wasn't).

    Returns (bars, bar_rank, inv32, ret32, blocks, any_dropped);
    `any_dropped` reports whether any block actually lost info columns
    to the bound — when False, a wider retry would plan identically.

    Raises OverflowError when any real event index is >= int32 INF:
    the int32 casts below would otherwise WRAP (negative inv) or clamp
    a real return to the info sentinel — either silently corrupts the
    barrier order.  Reachable via the stream checker's concatenated
    timeline (ops/wgl_stream.py accumulates E+2 per key); callers
    treat it as "witness tier unusable, escalate"."""
    status = packed.status
    if packed.n:
        t_max = int(packed.inv.max())
        okm = status == ST_OK
        if okm.any():
            t_max = max(t_max, int(packed.ret[okm].max()))
        if t_max >= int(INF):
            raise OverflowError(
                f"event timeline exceeds int32: max index {t_max} >= "
                f"{int(INF)}; witness tier cannot represent this history"
            )
    inv32 = packed.inv.astype(np.int32)
    ret32 = np.minimum(packed.ret, np.int64(INF)).astype(np.int32)
    ok_rows = np.nonzero(status == ST_OK)[0]
    bars = ok_rows[np.argsort(ret32[ok_rows], kind="stable")]
    bar_rank = np.full(packed.n, NO_BAR, dtype=np.int64)
    bar_rank[bars] = np.arange(len(bars))
    if rank_override is not None:
        # Stream semantics (ops/wgl_stream.py): a non-barrier row may
        # carry a synthetic rank — once that rank passes, the row is
        # treated exactly like a retired barrier (implied membership,
        # excluded from helper candidacy, dropped from later windows).
        # Barrier rows keep their real ranks: overriding one would
        # corrupt the sweep order.
        ov = (rank_override >= 0) & (status != ST_OK)
        bar_rank[ov] = rank_override[ov]
    is_info = status != ST_OK
    blocks = []
    any_dropped = False
    # active: sorted row indices currently in the window; hi: rows
    # [0, hi) have entered (inv32 is strictly increasing row-wise).
    active = np.empty(0, dtype=np.int64)
    hi = 0
    for k0 in range(0, len(bars), bars_per_block):
        block_bars = bars[k0 : k0 + bars_per_block]
        end_ret = int(ret32[block_bars[-1]])
        # Leavers: rows whose rank passed at block start — real
        # barriers from the previous block, plus override rows whose
        # synthetic rank passed (equivalent to the previous isin()
        # against the passed-barrier list: any active barrier with
        # rank < k0 was by construction in that list).
        if k0:
            active = active[bar_rank[active] >= k0]
        # Entrants: invoked before this block's last barrier.  New
        # rows have larger indices than everything already active, so
        # concatenation preserves sortedness.
        # np.int32 key: a python-int key makes numpy CAST THE WHOLE
        # 10M-row array per call (measured 50 ms vs 6 µs — it was 76%
        # of end-to-end time at 8M ops).
        hi_new = int(np.searchsorted(inv32, np.int32(end_ret),
                                     side="left"))
        if hi_new > hi:
            entering = np.arange(hi, hi_new, dtype=np.int64)
            # Rows whose barrier already passed never join.
            entering = entering[bar_rank[entering] >= k0]
            active = np.concatenate([active, entering])
            hi = hi_new
        if info_window is not None:
            info_mask = is_info[active]
            n_info = int(info_mask.sum())
            if n_info > info_window:
                # Keep the newest N info rows; the drop is permanent
                # ("newest N" is monotone as rows only get newer),
                # matching the per-block criterion of the full-mask
                # implementation.
                drop_pos = np.nonzero(info_mask)[0][: n_info - info_window]
                active = np.delete(active, drop_pos)
                any_dropped = True
        blocks.append((k0, block_bars, active))
    return bars, bar_rank, inv32, ret32, blocks, any_dropped


def plan_width(packed: PackedOps, bars_per_block: Optional[int] = None,
               info_window: Optional[int] = NARROW_INFO_WINDOW) -> int:
    """The window width a witness run over `packed` will use — lets a
    warm-up run pre-compile the same kernel via `width_hint`."""
    if packed.n == 0 or packed.n_ok == 0:
        return 0
    if bars_per_block is None:
        from ..plan.costmodel import choose_witness_block_knobs

        bars_per_block = choose_witness_block_knobs(
            packed.n, int(packed.n_ok))[0]["bars_per_block"]
    try:
        _, _, _, _, blocks, _ = _plan_blocks(packed, bars_per_block,
                                             info_window)
    except OverflowError:
        return 0  # witness tier can't run this history; nothing to warm
    return _bucket(max(max(len(a) for _, _, a in blocks), 1))


def plan_drops(packed: PackedOps, bars_per_block: Optional[int] = None,
               info_window: Optional[int] = NARROW_INFO_WINDOW) -> bool:
    """Whether a witness plan at this info_window would drop any info
    columns — when False, a wider window plans identically and an
    escalation retry is pointless."""
    if packed.n == 0 or packed.n_ok == 0 or info_window is None:
        return False
    if packed.n - packed.n_ok <= info_window:
        return False  # cheap bound: fewer info ops than the window
    if bars_per_block is None:
        from ..plan.costmodel import choose_witness_block_knobs

        bars_per_block = choose_witness_block_knobs(
            packed.n, int(packed.n_ok))[0]["bars_per_block"]
    try:
        return _plan_blocks(packed, bars_per_block, info_window)[5]
    except OverflowError:
        return False  # no witness run happens at all, so no drops


def _make_pallas_sweep(B: int, W: int, SW: int, K: int, jax_step_rows,
                       interpret: bool, unroll: int = 8):
    """The easy-path barrier sweep as a Pallas TPU kernel.

    The XLA `lax.scan` version pays ~30 µs of small-op critical path
    per barrier (round-2 measurement: 1.36 s for a 47k-barrier 0-info
    history).  Here the whole sweep runs inside one kernel whose state
    (member bits, beam states, alive mask) stays on-chip, with a
    `while_loop` that exits at the first barrier the easy path cannot
    survive — the heavy chain search stays in XLA and resumes the
    sweep afterwards.

    Mosaic constraints shape the layout: dynamic per-barrier scalar
    reads must come from SMEM (VMEM vector loads need statically
    aligned indices), so the barrier table lives in SMEM and the
    member matrix is BIT-PACKED to one int32 word per window row
    ((W,) in SMEM; lane b of the beam is bit b — arithmetic
    right-shift + &1 extracts bits for any B <= 32).  All vector
    state is LANE-MAJOR (beam lanes on the 128-lane axis: states
    (SW, B), masks (1, B)) and 32-bit, because sub-32-bit relayouts
    and lane<->sublane reshapes don't lower.

    Outputs: states', alive', death (1,1) SMEM i32 — death == K means
    the block completed; any smaller value is the barrier index whose
    pass/direct step would have killed the frontier (state/alive
    returned are from just BEFORE that barrier).  Identical
    transition semantics to the `easy` branch of the scan path."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    UNROLL = max(1, unroll)

    def kernel(start_ref, bars_ref, mbits_ref, states_ref, alive_ref,
               states_out, alive_out, death_ref):
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, B), 1)
        start = start_ref[0, 0]
        states0 = states_ref[:]          # (SW, B) i32
        alive0 = alive_ref[:]            # (1, B) i32 0/1

        # All VECTOR masks are int32 0/1 — Mosaic fails to legalize
        # selects that produce bool vectors; scalar bools (loop
        # control) are fine.
        def cond(c):
            k, _, _, died = c
            return jnp.logical_and(k < K, jnp.logical_not(died))

        # One barrier's transition, guarded so a finished (dead or
        # past-the-end) carry passes through unchanged.  The guard is
        # what lets the while body UNROLL U barriers per iteration:
        # the live-chip measurement behind it is ~5.2 us/barrier at
        # U=1 — Mosaic's per-iteration loop machinery (cond eval +
        # carry) costs more than the barrier math itself, the same
        # finding as the round-2 XLA-scan measurement, one level down.
        def step1(k, states, alive, died):
            kk = jnp.minimum(k, K - 1)
            a = bars_ref[0, kk]
            valid = jnp.logical_and(k < K, jnp.logical_not(died))
            real = jnp.logical_and(valid, bars_ref[2, kk] != 0)
            bf = bars_ref[3, kk]
            ba0 = bars_ref[4, kk]
            ba1 = bars_ref[5, kk]
            bits = mbits_ref[a]
            has = (bits >> lane) & 1                   # (1, B) i32
            ns, legal_b = jax_step_rows(states, bf, ba0, ba1)
            legal = legal_b.reshape(1, B).astype(jnp.int32)
            surv_pass = alive & has
            surv_dir = alive & (1 - has) & legal
            new_alive = surv_pass | surv_dir
            died_k = real & (new_alive.max() == 0)     # scalar bool
            commit_i = jnp.where(real & ~died_k, 1, 0)  # scalar i32
            take = commit_i * surv_dir                 # (1, B) i32
            st = jnp.where(take != 0, ns, states)
            al = commit_i * new_alive + (1 - commit_i) * alive
            k2 = jnp.where(valid & ~died_k, k + 1, k)
            return k2, st, al, died | died_k

        def body(c):
            k, states, alive, died = c
            for _ in range(UNROLL):
                k, states, alive, died = step1(k, states, alive, died)
            return (k, states, alive, died)

        k, states, alive, died = jax.lax.while_loop(
            cond, body, (start, states0, alive0, jnp.bool_(False))
        )
        states_out[:] = states
        alive_out[:] = alive
        death_ref[0, 0] = jnp.where(died, k, K)

    call = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((SW, B), jnp.int32),
            jax.ShapeDtypeStruct((1, B), jnp.int32),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ),
        in_specs=[
            pl.BlockSpec((1, 1), memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), memory_space=pltpu.SMEM),
        ),
        interpret=interpret,
    )

    def sweep(start_k, bars, member, states, alive):
        start = jnp.asarray(start_k, jnp.int32).reshape(1, 1)
        # Pack each member row to one int32 word (lane b -> bit b).
        mbits = (
            member.astype(jnp.int32)
            << jnp.arange(B, dtype=jnp.int32)[None, :]
        ).sum(axis=1).astype(jnp.int32)
        s2, al2, dk = call(
            start, bars, mbits, states.T,
            alive[None, :].astype(jnp.int32),
        )
        return s2.T, al2[0] != 0, dk[0, 0]

    return sweep


def _make_chunk_fn(B: int, W: int, SW: int, K: int, D: int, NB: int,
                   jax_step, pallas_mode: str = "off",
                   jax_step_rows=None, compact: int = 0,
                   packed: bool = False):
    """One call runs NB blocks of up to K barriers each.

    Args: member (W, B) bool — window-major so the per-barrier
    membership lookup member[a] is a fast major-axis row slice (a
    (B, W) layout makes it a minor-axis dynamic gather) —, states
    (B, SW) i32, alive (B,) bool, failed () bool, and per-block
    tensors — bars (NB, 6, K) i32 (rows: window col, ret, real, and
    the barrier op's f/a0/a1 pre-gathered on host so the hot scan does
    no table lookups), tab (NB, 5, W) i32 (rows: inv, f, a0, a1,
    bar_rank — the heavy round's helper tables), perm (NB, W) i32 +
    present (NB, W) bool (member re-layout from the previous block's
    window), k0s (NB,) i32 (global rank of each block's first
    barrier).  Padding blocks pass identity perm/present and zero
    `real` flags and are no-ops.

    The heavy chain search runs INSIDE the barrier scan behind a
    lax.cond — round-2 profiling showed the earlier design (fast scan
    to the death point, heavy round, masked re-scan) spent ~85% of
    device time re-scanning: each of the ~458 heavy rounds on the
    100k-op bench re-walked up to K barriers.  Inline, every barrier
    is visited exactly once.

    Flat (helper, lane) pair indexing is helper-major: i = h*B + lane.

    `compact` (static, 0 = off) is the candidate-compaction tile width:
    round-3 profiling measured 50-90% of the (W, B) pair lanes masked
    out by `avail` in the chain rounds (which are 85-89% of witness
    time).  When the number of window rows with ANY available lane fits
    in `compact`, the heavy round gathers just those rows into a
    (compact, B) tile — the batched pair-step and the argsort dedup
    then run over compact*B candidates instead of W*B — and maps the
    winners back to window columns through the gather index.  Overflow
    falls back to the uncompacted path behind a lax.cond (the engine's
    standard escalation pattern), so results are bit-identical.
    """
    import jax
    import jax.numpy as jnp

    col = jnp.arange(W)
    hv = jnp.asarray(_state_hash_vec(SW))
    BIG = jnp.float32(3.0e38)
    M = B * W
    WC = compact if 0 < compact < W else 0

    # `packed`: the (W, B) member window rides the inter-block scan
    # carry — and the per-block re-gather, the engine's hottest
    # relayout — as ceil(B/32) uint32 beam lanes (ops/packing.py).
    # run_block itself still sees the bool window (unpack on entry,
    # pack on exit), so block semantics are bit-identical; only the
    # carried/gathered bytes shrink.
    Bp = packing.n_words(B)
    zero_m = jnp.uint32(0) if packed else False

    def _pack_m(m):
        return packing.pack_bits(m, Bp) if packed else m

    def _unpack_m(mw):
        return packing.unpack_bits(mw, B) if packed else mw

    pallas_sweep = (
        _make_pallas_sweep(
            B, W, SW, K, jax_step_rows,
            interpret=(pallas_mode == "interpret"),
        )
        if pallas_mode != "off"
        else None
    )

    def run_block(member, states, alive, bars, tab, k0):
        inv_w, f_w, a0_w, a1_w, bar_rank_w = (
            tab[0], tab[1], tab[2], tab[3], tab[4],
        )

        def pair_steps(states_rep, f_r, a0_r, a1_r):
            # helper-major: rows h*B+lane pair helper h with lane's state
            return jax.vmap(jax_step)(
                states_rep,
                jnp.repeat(f_r, B),
                jnp.repeat(a0_r, B),
                jnp.repeat(a1_r, B),
            )

        def select_children(member, child_states, good, row_map):
            """Dedup (helper, lane) children by model state, keep <= B.

            Selection happens over flat-pair scalars FIRST; member
            columns are materialized only for the <= B winners —
            building (M, W) child-member matrices up front costs
            ~B*W*W bytes.  Hash-sort + exact adjacent compare: equal
            states always hash equal; collisions only cost beam slots.
            `row_map` maps tile rows back to window columns (identity
            for the uncompacted path)."""
            h = jnp.where(good, child_states.astype(jnp.float32) @ hv, BIG)
            order = jnp.argsort(h)
            hs = h[order]
            ss = child_states[order]
            same = (hs == jnp.roll(hs, 1)) & (
                ss == jnp.roll(ss, 1, axis=0)
            ).all(axis=1)
            same = same.at[0].set(False)
            uniq = (hs < BIG) & ~same
            n_child = jnp.minimum(uniq.sum(), B)
            pos = order[jnp.nonzero(uniq, size=B, fill_value=0)[0]]
            hcol = row_map[pos // B]
            lane = pos % B
            new_member = member[:, lane] | (col[:, None] == hcol[None, :])
            new_alive = jnp.arange(B) < n_child
            return new_member, child_states[pos], new_alive

        def heavy(member, states, alive, a, r, bf, ba0, ba1, k_rank):
            """Chain search at one barrier: direct -> targeted h·a ->
            expand-any, bounded by chain depth D."""
            # Membership of ops whose barrier already passed is implied.
            implied = bar_rank_w < k_rank

            def step_bar(s):
                return jax_step(s, bf, ba0, ba1)

            def helper_avail(member, alive):
                # (W, B): helper rows x lanes
                return (
                    alive[None, :]
                    & ~member
                    & ~implied[:, None]
                    & (inv_w[:, None] < r)
                    & (col[:, None] != a)
                )

            def try_direct(member, states, alive):
                ns, legal = jax.vmap(step_bar)(states)
                has = member[a]
                surv_pass = alive & has
                surv_dir = alive & ~has & legal
                new_alive = surv_pass | surv_dir
                new_states = jnp.where(surv_dir[:, None], ns, states)
                return member, new_states, new_alive

            def run_tile(member, states, avail, row_map, f_r, a0_r,
                         a1_r):
                """One fused escalation over a (R, B) candidate tile:
                the helper pair-step is evaluated ONCE and feeds both
                the targeted test (helper+barrier legal -> done) and
                the expand-any fallback (any productive helper -> keep
                searching).  Round-2's split version recomputed
                pair_steps and ran select_children twice behind an
                extra lax.cond — the chain rounds are ~88% of witness
                time (see tools/profile_witness.py), so the duplicated
                work was the engine's single hottest redundancy."""
                R = row_map.shape[0]
                flat = avail.reshape(-1)
                states_rep = jnp.tile(states, (R, 1))
                s1, legal1 = pair_steps(states_rep, f_r, a0_r, a1_r)
                s2, legal2 = jax.vmap(step_bar)(s1)
                good_t = flat & legal1 & legal2
                ok2 = good_t.any()
                productive = legal1 & (s1 != states_rep).any(axis=1)
                good_e = flat & productive
                child = jnp.where(ok2, s2, s1)
                good = jnp.where(ok2, good_t, good_e)
                cm, cs, ca = select_children(member, child, good,
                                             row_map)
                return cm, cs, ca, ok2

            def targeted_or_expand(member, states, alive):
                """Chain-round escalation with candidate compaction:
                gather the window rows that still have an available
                (helper, lane) pair into a (WC, B) tile when they fit
                (the 50-90%-masked common case measured in round 3),
                else run the full (W, B) tile.  Candidate order is
                preserved by the ascending gather, so both branches
                select identical children — the cond trades nothing
                but compile time."""
                avail_full = helper_avail(member, alive)  # (W, B)
                if WC == 0:
                    return run_tile(member, states, avail_full, col,
                                    f_w, a0_w, a1_w)

                row_any = avail_full.any(axis=1)
                n_av = row_any.sum()

                def compact_path(_):
                    idx = jnp.nonzero(row_any, size=WC,
                                      fill_value=0)[0]
                    avail_c = avail_full[idx] & (
                        jnp.arange(WC) < n_av
                    )[:, None]
                    return run_tile(member, states, avail_c, idx,
                                    f_w[idx], a0_w[idx], a1_w[idx])

                def full_path(_):
                    return run_tile(member, states, avail_full, col,
                                    f_w, a0_w, a1_w)

                return jax.lax.cond(n_av <= WC, compact_path,
                                    full_path, None)

            def cond(c):
                _, _, alive, done, d = c
                return (~done) & (d < D) & alive.any()

            def body(c):
                member, states, alive, _, d = c
                m1, s1, al1 = try_direct(member, states, alive)

                def on_direct(_):
                    return m1, s1, al1, True

                def no_direct(_):
                    return targeted_or_expand(member, states, alive)

                mN, sN, alN, done = jax.lax.cond(
                    al1.any(), on_direct, no_direct, None
                )
                return mN, sN, alN, done, d + 1

            member, states, alive, done, _ = jax.lax.while_loop(
                cond, body, (member, states, alive, False, 0)
            )
            return member, states, alive, done

        if pallas_sweep is not None:
            # ---- pallas hybrid: VMEM sweep to the next death point,
            # heavy in XLA, resume — all under one while_loop ----
            def cond_w(c):
                k, _, _, _, failed, _ = c
                return (k < K) & ~failed

            def body_w(c):
                k, member, states, alive, failed, died = c
                s2, al2, dk = pallas_sweep(k, bars, member, states, alive)

                def clean(_):
                    return jnp.int32(K), member, s2, al2, failed, died

                def death(_):
                    colv = jax.lax.dynamic_slice(
                        bars, (jnp.int32(0), dk), (6, 1)
                    )[:, 0]
                    m, s, al, done = heavy(
                        member, s2, al2, colv[0], colv[1], colv[3],
                        colv[4], colv[5], k0 + dk,
                    )
                    d2 = jnp.where(~done & (died == NO_BAR),
                                   k0 + dk, died)
                    return dk + 1, m, s, al, failed | ~done, d2

                return jax.lax.cond(dk >= K, clean, death, None)

            _, member, states, alive, failed, died = jax.lax.while_loop(
                cond_w, body_w,
                (jnp.int32(0), member, states, alive, jnp.bool_(False),
                 jnp.int32(NO_BAR)),
            )
            return member, states, alive, failed, died

        # ---- barrier scan: pass/direct inline, heavy behind a cond ----
        def body(carry, xs):
            member, states, alive, failed, died = carry
            a, r, real, bf, ba0, ba1, k = xs
            has = member[a]
            ns, legal = jax.vmap(
                lambda s: jax_step(s, bf, ba0, ba1)
            )(states)
            surv_pass = alive & has
            surv_dir = alive & ~has & legal
            new_alive = surv_pass | surv_dir
            active = (real != 0) & ~failed

            def easy(_):
                commit = active & new_alive.any()
                st = jnp.where((commit & surv_dir)[:, None], ns, states)
                al = jnp.where(commit, new_alive, alive)
                return member, st, al, failed, died

            def hard(_):
                m, s, al, done = heavy(
                    member, states, alive, a, r, bf, ba0, ba1, k0 + k
                )
                d2 = jnp.where(~done & (died == NO_BAR), k0 + k, died)
                return m, s, al, failed | ~done, d2

            out = jax.lax.cond(
                active & ~new_alive.any(), hard, easy, None
            )
            return out, None

        carry0 = (member, states, alive, jnp.bool_(False),
                  jnp.int32(NO_BAR))
        (member, states, alive, failed, died), _ = jax.lax.scan(
            body, carry0,
            (bars[0], bars[1], bars[2], bars[3], bars[4], bars[5],
             jnp.arange(K, dtype=jnp.int32)),
        )
        return member, states, alive, failed, died

    def chunk(member, states, alive, failed, bars, tab, perm, present,
              k0s):
        def body(carry, xs):
            member, states, alive, failed, died = carry
            bars_b, tab_b, perm_b, present_b, k0 = xs
            member = jnp.where(present_b[:, None], member[perm_b],
                               zero_m)

            def run(_):
                m, s, al, f2, d2 = run_block(
                    _unpack_m(member), states, alive, bars_b, tab_b, k0
                )
                return _pack_m(m), s, al, f2, d2

            def skip(_):
                return (member, states, alive, jnp.bool_(False),
                        jnp.int32(NO_BAR))

            m, s, al, f2, d2 = jax.lax.cond(~failed, run, skip, None)
            died = jnp.where((d2 != NO_BAR) & (died == NO_BAR), d2, died)
            return (m, s, al, failed | f2, died), None

        (member, states, alive, failed, died), _ = jax.lax.scan(
            body,
            (_pack_m(member), states, alive, failed, jnp.int32(NO_BAR)),
            (bars, tab, perm, present, k0s),
        )
        return _unpack_m(member), states, alive, failed, died

    jcol = jnp.arange(K, dtype=jnp.int32)
    wcol = jnp.arange(W, dtype=jnp.int32)

    def idx_block_step(member, states, alive, failed, died,
                       bar_b, act_b, nb, nw, perm_b, present_b,
                       k0, fA, a0A, a1A, retA, invA, rankA):
        """One block: regather member (packed lanes when enabled),
        build bar/tab tables on device from row indices, run.  Shared
        by the "indices" and "device" transfer modes; member arrives
        and leaves in carry form (_pack_m)."""
        member = jnp.where(present_b[:, None], member[perm_b],
                           zero_m)
        real = (jcol < nb).astype(jnp.int32)
        bars_b = jnp.stack([
            jnp.searchsorted(act_b, bar_b).astype(jnp.int32),
            retA[bar_b],
            real,
            fA[bar_b],
            a0A[bar_b],
            a1A[bar_b],
        ])
        valid_w = wcol < nw
        tab_b = jnp.stack([
            jnp.where(valid_w, invA[act_b], INF),
            jnp.where(valid_w, fA[act_b], 0),
            jnp.where(valid_w, a0A[act_b], 0),
            jnp.where(valid_w, a1A[act_b], 0),
            jnp.where(valid_w, rankA[act_b], NO_BAR),
        ])

        def run(_):
            m, s, al, f2, d2 = run_block(
                _unpack_m(member), states, alive, bars_b, tab_b, k0
            )
            return _pack_m(m), s, al, f2, d2

        def skip(_):
            return (member, states, alive, jnp.bool_(False),
                    jnp.int32(NO_BAR))

        m, s, al, f2, d2 = jax.lax.cond(~failed, run, skip, None)
        died = jnp.where((d2 != NO_BAR) & (died == NO_BAR), d2, died)
        return m, s, al, failed | f2, died

    def chunk_idx(member, states, alive, failed, bar_idx, act_idx,
                  nbars, nws, perm, present, k0s,
                  fA, a0A, a1A, retA, invA, rankA):
        """transfer="indices" entry: identical semantics to `chunk`,
        but the (NB, 6, K) bars and (NB, 5, W) tab tables are built
        ON DEVICE from per-block row-index arrays + the once-uploaded
        per-row tables (fA/a0A/a1A/retA/invA/rankA) — ~3x less
        host->device traffic per chunk, which is what the tunneled
        chip's ~50 MB/s uplink actually charges for.

        Padding contracts: bar_idx pads with 0 (masked by j >= nb:
        real=0 rows commit nothing), act_idx pads with packed.n
        (> every real row index, so searchsorted stays monotone;
        gathers clamp under jit and the nw mask discards the lanes).
        """
        def body(carry, xs):
            member, states, alive, failed, died = carry
            bar_b, act_b, nb, nw, perm_b, present_b, k0 = xs
            out = idx_block_step(
                member, states, alive, failed, died,
                bar_b, act_b, nb, nw, perm_b, present_b, k0,
                fA, a0A, a1A, retA, invA, rankA,
            )
            return out, None

        (member, states, alive, failed, died), _ = jax.lax.scan(
            body,
            (_pack_m(member), states, alive, failed, jnp.int32(NO_BAR)),
            (bar_idx, act_idx, nbars, nws, perm, present, k0s),
        )
        return _unpack_m(member), states, alive, failed, died

    def make_chunk_dev(S: int):
        """Builds the transfer="device" entry for span-slice width S.
        Separate from the eager (fn, fn_idx) pair so the Pallas sweep
        build — and its _BUILD_FAILED negative cache — is keyed
        independently of S: two histories sharing every other shape
        must not re-pay the Mosaic lowering probe because their spans
        bucket differently."""
        return roofline.instrument(jax.jit(_chunk_dev_for(S)))

    def _chunk_dev_for(S: int):
        def chunk_dev(member, states, alive, failed, prev_act,
                      k0s, end_rets, los, nbars, cuts, n_total,
                      fA, a0A, a1A, retA, invA, rankA, icumA, barsA):
            return _chunk_dev_impl(
                S, member, states, alive, failed, prev_act,
                k0s, end_rets, los, nbars, cuts, n_total,
                fA, a0A, a1A, retA, invA, rankA, icumA, barsA,
            )
        return chunk_dev

    def _chunk_dev_impl(S, member, states, alive, failed, prev_act,
                        k0s, end_rets, los, nbars, cuts, n_total,
                        fA, a0A, a1A, retA, invA, rankA, icumA, barsA):
        """transfer="device" entry: the per-block index arrays the
        "indices" mode ships from the host (~0.7 MB/chunk) are
        PLANNED ON DEVICE from the once-uploaded row tables — the
        per-chunk H2D payload shrinks to five (NB,) scalars (~640 B).
        The host's _plan_blocks stays authoritative for the STATIC
        facts (W, S buckets, chunk boundaries, per-block scalars);
        the device reproduces its row sets exactly:

          mask(r) = r entered (inv < end_ret) & rank not passed
                    (>= k0) & info retention (info_cum > cut)

        over the (lo, lo+S) slice host planning proved covers the
        window.  `prev_act` (the previous block's window rows, padded
        with n_total) is carried on device across blocks AND chunk
        calls, so the member re-gather needs no host round trip.
        """
        scol = jnp.arange(S, dtype=jnp.int32)

        def body(carry, xs):
            member, states, alive, failed, died, prev_act = carry
            k0, er, lo, nb, cut = xs
            rows = lo + scol
            rows_c = jnp.minimum(rows, n_total - 1)
            inv_r = invA[rows_c]
            rank_r = rankA[rows_c]
            icum_r = icumA[rows_c]
            is_info = rank_r == NO_BAR
            mask = ((rows < n_total) & (inv_r < er) & (rank_r >= k0)
                    & (~is_info | (icum_r > cut)))
            nw = mask.sum()
            act_local = jnp.nonzero(mask, size=W, fill_value=S)[0]
            valid_w = wcol < nw
            act_b = jnp.where(
                valid_w, lo + jnp.minimum(act_local, S - 1), n_total
            ).astype(jnp.int32)
            pos = jnp.searchsorted(prev_act, act_b)
            pos_c = jnp.clip(pos, 0, W - 1)
            present_b = ((pos < W) & (prev_act[pos_c] == act_b)
                         & (act_b < n_total))
            perm_b = jnp.where(present_b, pos_c, 0)
            bar_b = jax.lax.dynamic_slice(barsA, (k0,), (K,))
            out = idx_block_step(
                member, states, alive, failed, died,
                bar_b, act_b, nb, nw, perm_b, present_b, k0,
                fA, a0A, a1A, retA, invA, rankA,
            )
            # Padding blocks (nb == 0 with er == 0) must not clobber
            # the carried window.
            new_prev = jnp.where(nb > 0, act_b, prev_act)
            return (*out, new_prev), None

        carry, _ = jax.lax.scan(
            body,
            (_pack_m(member), states, alive, failed, jnp.int32(NO_BAR),
             prev_act),
            (k0s, end_rets, los, nbars, cuts),
        )
        return (_unpack_m(carry[0]),) + tuple(carry[1:])

    return (roofline.instrument(jax.jit(chunk)),
            roofline.instrument(jax.jit(chunk_idx)), make_chunk_dev)


def check_wgl_witness(
    packed: PackedOps,
    pm: PackedModel,
    *,
    beam: int = 8,  # 16 -> 8 measured 0.70 -> 0.51 s on the 100k bench;
    # chain diversity above 8 lanes almost never decides a register-
    # class history, and a died witness still escalates to the exact
    # tiers.
    bars_per_block: Optional[int] = None,  # None -> profile-chosen
    blocks_per_call: Optional[int] = None,  # bucket (plan/costmodel)
    depth: int = 5,
    info_window: Optional[int] = NARROW_INFO_WINDOW,
    max_window: int = 32768,
    width_hint: int = 0,
    time_limit_s: Optional[float] = None,
    pallas: str = "auto",
    compact: int = -1,
    checkpoint_dir: Optional[str] = None,
    transfer: str = "auto",
    rank_override: Optional[np.ndarray] = None,
    out_info: Optional[dict] = None,
    packed_lanes: Optional[bool] = None,
    _degraded: bool = False,
) -> Optional[WGLResult]:
    """Runs the witness search on the default JAX device.

    Returns an exact `WGLResult(valid=True)` when a witness linearization
    survives, or None when the search dies / overflows / times out —
    meaning "escalate to the exact search", never "invalid".

    `transfer`: "full" ships the pre-gathered (NB,6,K)+(NB,5,W) block
    tables per chunk call; "indices" uploads the per-row tables once
    and ships only small row-index arrays per chunk, rebuilding the
    tables on device — ~3x less H2D, which matters on the tunneled
    chip (~50 MB/s measured, tools/tunnel_diag.py); "device" (round 5,
    VERDICT r4 #1) also PLANS the blocks on device — the per-chunk
    payload shrinks to five (NB,) scalars and the host's per-block
    numpy table building disappears entirely.  Identical verdicts by
    construction; parity-tested including the death rank.  "auto"
    (default) picks "device" on TPU and "full" elsewhere (on CPU the
    device IS the host's cores, so host-built tables win).

    `checkpoint_dir`: when set, the inter-chunk carry (member window,
    beam states, alive mask + the block cursor) is persisted there
    after every chunk call (~32k barriers), keyed by a digest of the
    packed history and every shape knob.  A later call on the same
    history resumes from the last completed chunk instead of block
    zero — SURVEY.md §5's "checkpoint long searches": a time-limited
    or killed analysis pass doesn't forfeit progress, `analyze`
    re-runs pick up where they stopped.  The file is removed when the
    search concludes (witness found or frontier died); only a
    budget-expiry exit leaves it behind.

    `width_hint` forces at least that window width so a warm-up run can
    pre-compile the kernels a bigger history will use (see plan_width).

    `pallas`: "auto" runs the easy sweep as a Pallas VMEM kernel on TPU
    backends and the XLA scan elsewhere; "on"/"interpret"/"off" force a
    mode ("interpret" is the CPU-testable emulation of the kernel).

    `compact`: chain-round candidate-compaction tile width.  -1 picks
    max(64, min(W // 2, info_window)) — or max(64, W // 8) when
    info_window is None: available helpers at a chain round are
    almost all info columns, which the window bound caps at
    info_window, so a tile of exactly that width fits nearly every
    round (measured on the 100k bench config: compact=512 = the
    narrow window is 2.9x end-to-end vs off, while W//8 = 256
    overflows to the full tile at most barriers and wins only 7%).
    0 disables.

    `rank_override`: optional (n,) int array giving NON-barrier rows a
    synthetic barrier rank (-1 = no override).  Once that rank passes,
    the row behaves like a retired barrier: implied membership,
    excluded from helper candidacy, dropped from later windows.  The
    key-concatenated stream checker (ops/wgl_stream.py) uses this to
    fence each key's indeterminate ops inside its own segment.
    Checkpointing is disabled under an override (the checkpoint key
    does not cover it).

    `out_info`: optional dict the search fills with diagnostics — on
    failure, "died_at_rank" is the global rank of the first barrier
    the chain search could not linearize (None if the death point was
    not localized).
    """
    import jax
    import jax.numpy as jnp

    t0 = time.monotonic()
    n = packed.n
    if n == 0 or packed.n_ok == 0:
        return WGLResult(valid=True, configs_explored=1,
                         elapsed_s=time.monotonic() - t0)

    if bars_per_block is None or blocks_per_call is None:
        # Chunk-shape buckets are profile-chosen (ROADMAP item 1 (c)):
        # the trained cost model ranks the bucket grid when its witness
        # predictor covers the candidates, else the measured heuristic
        # default.  Explicit caller values always win.
        from ..plan.costmodel import choose_witness_block_knobs

        knobs, source = choose_witness_block_knobs(n, int(packed.n_ok))
        if bars_per_block is None:
            bars_per_block = knobs["bars_per_block"]
        if blocks_per_call is None:
            blocks_per_call = knobs["blocks_per_call"]
        telemetry.count(f"wgl.plan.witness-block-{source}")
    # Record the resolved shape on the enclosing pass capture so the
    # cost model can train on what actually ran.
    profile.annotate(bars_per_block=int(bars_per_block),
                     blocks_per_call=int(blocks_per_call))

    if rank_override is not None:
        checkpoint_dir = None  # ckpt key does not cover the override
    try:
        with telemetry.span("wgl.witness.plan", n=n):
            bars, bar_rank, inv32, ret32, blocks, _ = _plan_blocks(
                packed, bars_per_block, info_window, rank_override
            )
    except OverflowError:
        # Timeline past int32 (e.g. a huge concatenated stream): the
        # witness tier can't represent it — escalate, don't crash.
        return None
    n_bars = len(bars)
    if max(len(a) for _, _, a in blocks) > max_window:
        return None

    SW = pm.state_width
    B = _bucket(beam, lo=8)
    K = bars_per_block
    packed_on = packed_enabled(packed_lanes)
    if len(blocks) < blocks_per_call:
        # Short histories (one chunk): trim the call width to a
        # bucket of the real block count — padding blocks are no-ops
        # semantically but still cost K scan iterations each, which
        # DOMINATES small searches (measured on the 200-key stream:
        # 22 padding blocks of 32 ≈ 2x the real barrier work).
        blocks_per_call = _bucket(len(blocks), lo=4)
    D = depth
    NB = blocks_per_call
    W = _bucket(max(max(len(a) for _, _, a in blocks), width_hint, 1))
    if telemetry.enabled():
        telemetry.gauge("wgl.witness.window", W)
        telemetry.gauge("wgl.witness.beam", B)
        telemetry.gauge("wgl.witness.blocks", len(blocks))
        if packed_on:
            telemetry.count("wgl.packed.witness-runs")

    if pallas not in ("auto", "on", "off", "interpret"):
        raise ValueError(f"unknown pallas mode {pallas!r}")
    if pallas == "auto":
        # devices()[0].platform is "tpu" even under tunneled plugin
        # platforms whose backend name differs (e.g. axon).
        pallas = "on" if jax.devices()[0].platform == "tpu" else "off"
    if pm.jax_step_rows is None or B > 32:
        # No Mosaic-safe batched step for this model, or the beam no
        # longer fits the kernel's one-word member bit-packing.
        pallas = "off"

    if compact < 0:
        compact = max(64, min(
            W // 2, info_window if info_window is not None else W // 8
        ))

    if transfer not in ("auto", "full", "indices", "device"):
        raise ValueError(f"unknown transfer mode {transfer!r}")
    if transfer == "auto":
        # Measured split (round 5): on the tunneled TPU the per-chunk
        # H2D (~0.7-2 MB at ~50 MB/s) plus the host's per-block numpy
        # table building (~0.35 s at 100k ops) dominate, so planning
        # on device wins; on CPU the device IS the host's cores, so
        # shipping host-built tables is faster (0.46 s vs 0.91 s
        # best-of-4 on the 100k config).
        transfer = ("device" if jax.devices()[0].platform == "tpu"
                    else "full")
    if transfer == "device" and rank_override is not None:
        # Device planning derives is_info from rank == NO_BAR, which
        # an override breaks; the stream path's payloads are small
        # anyway.  Indices mode keeps the once-uploaded-tables win.
        transfer = "indices"

    dev_slice = 0
    dev_plan = None
    if transfer == "device":
        # Per-block scalars the device planner consumes — all derived
        # from the plan the host already built.  hi = first row not
        # yet invoked at the block's last barrier; lo = the window's
        # first row; S buckets the widest (lo, hi) span.
        nblk_all = len(blocks)
        k0_all = np.empty(nblk_all, dtype=np.int32)
        er_all = np.empty(nblk_all, dtype=np.int32)
        lo_all = np.empty(nblk_all, dtype=np.int32)
        nb_all = np.empty(nblk_all, dtype=np.int32)
        cut_all = np.full(nblk_all, np.iinfo(np.int32).min,
                          dtype=np.int32)
        icum_host = np.cumsum(packed.status != ST_OK).astype(np.int32)
        span_max = 1
        for bi, (k0, block_bars, active) in enumerate(blocks):
            er = int(ret32[block_bars[-1]])
            hi = int(np.searchsorted(inv32, np.int32(er), side="left"))
            lo = int(active[0]) if len(active) else hi
            k0_all[bi] = k0
            er_all[bi] = er
            lo_all[bi] = lo
            nb_all[bi] = len(block_bars)
            if info_window is not None and hi > 0:
                cut_all[bi] = int(icum_host[hi - 1]) - info_window
            span_max = max(span_max, hi - lo)
        dev_slice = _bucket(span_max, lo=min(W, 1024))
        dev_plan = (k0_all, er_all, lo_all, nb_all, cut_all, icum_host)

    def _retry_on_scan(why: str):
        """Shared fallback: log, deduct elapsed budget, restart this
        search on the XLA-scan sweep.  Every caller-visible kwarg is
        reproduced exactly once here — keep it that way so a future
        parameter can't be silently dropped on one fallback path."""
        import logging

        logging.getLogger(__name__).warning(
            "%s; retrying witness on the XLA scan sweep", why,
            exc_info=True,
        )
        if time_limit_s is not None:
            remaining = time_limit_s - (time.monotonic() - t0)
            if remaining <= 0:
                return None  # budget blown: escalate directly
        else:
            remaining = None
        return check_wgl_witness(
            packed, pm, beam=beam, bars_per_block=bars_per_block,
            blocks_per_call=blocks_per_call, depth=depth,
            info_window=info_window, max_window=max_window,
            width_hint=width_hint, time_limit_s=remaining,
            pallas="off", compact=compact,
            checkpoint_dir=checkpoint_dir, transfer=transfer,
            rank_override=rank_override, out_info=out_info,
            packed_lanes=packed_on, _degraded=_degraded,
        )

    def _retry_smaller(e: BaseException):
        """Degradation-ladder fallback for device resource exhaustion
        (XLA RESOURCE_EXHAUSTED / compile failure / injected fault):
        first shed the packed lanes (an optimisation, not a budget),
        then retry ONCE with a halved block plan — the chunk call's
        working set scales with bars_per_block × blocks_per_call —
        then escalate (return None) so the caller falls through to the
        next tier.  Mirrors _retry_on_scan's budget deduction; keep
        every caller-visible kwarg reproduced here too."""
        import logging

        if packed_on:
            degrade.record("witness", "packed-fallback", e)
            telemetry.count("wgl.packed.fallbacks")
            if time_limit_s is not None:
                rem = time_limit_s - (time.monotonic() - t0)
                if rem <= 0:
                    return None
            else:
                rem = None
            return check_wgl_witness(
                packed, pm, beam=beam, bars_per_block=bars_per_block,
                blocks_per_call=blocks_per_call, depth=depth,
                info_window=info_window, max_window=max_window,
                width_hint=width_hint, time_limit_s=rem,
                pallas=pallas, compact=compact,
                checkpoint_dir=checkpoint_dir, transfer=transfer,
                rank_override=rank_override, out_info=out_info,
                packed_lanes=False, _degraded=_degraded,
            )
        if _degraded or bars_per_block <= 64:
            degrade.record("witness", "fall-through", e)
            logging.getLogger(__name__).warning(
                "witness tier out of device resources even after "
                "halving; escalating to the next tier", exc_info=True,
            )
            return None
        degrade.record("witness", "retry-halved", e)
        logging.getLogger(__name__).warning(
            "witness chunk call exhausted device resources; retrying "
            "once at bars_per_block=%d", bars_per_block // 2,
            exc_info=True,
        )
        if time_limit_s is not None:
            remaining = time_limit_s - (time.monotonic() - t0)
            if remaining <= 0:
                return None
        else:
            remaining = None
        return check_wgl_witness(
            packed, pm, beam=beam, bars_per_block=bars_per_block // 2,
            blocks_per_call=max(blocks_per_call // 2, 1), depth=depth,
            info_window=info_window, max_window=max_window,
            width_hint=width_hint, time_limit_s=remaining,
            pallas=pallas, compact=compact,
            checkpoint_dir=checkpoint_dir, transfer=transfer,
            rank_override=rank_override, out_info=out_info,
            packed_lanes=packed_on, _degraded=True,
        )

    # The step fn itself keys the cache (strong ref): an id() key
    # can collide after GC address reuse and serve the wrong
    # model's transition kernel.
    key = (B, W, SW, K, D, NB, pm.jax_step, pallas, compact, packed_on)
    # jax.jit is lazy: a freshly built chunk fn actually compiles on
    # its FIRST call — the trace labels that call "compile".
    fresh_fn = False
    fns = _chunk_fn_cache.get(key)
    if fns is _BUILD_FAILED:
        # Mosaic deterministically rejected this kernel earlier in the
        # process: skip the probe and run the scan sweep directly.
        # Single fetch then compare — a second .get() would race with
        # a concurrent thread storing the sentinel (IndependentChecker
        # pool) and leak it to the tuple unpack below.  "off" keys
        # never hold the sentinel, so this fetch can't see it.
        pallas = "off"
        key = (B, W, SW, K, D, NB, pm.jax_step, pallas, compact,
               packed_on)
        fns = _chunk_fn_cache.get(key)
    if fns is None:
        fresh_fn = True
        try:
            fns = _make_chunk_fn(B, W, SW, K, D, NB, pm.jax_step,
                                 pallas_mode=pallas,
                                 jax_step_rows=pm.jax_step_rows,
                                 compact=compact, packed=packed_on)
        except Exception:
            # Kernel BUILD failures (pallas_call construction, Mosaic
            # lowering probes) need the same safety net as execution
            # failures below: a flaky tunneled chip must not cost the
            # verdict.
            if pallas != "on":
                raise
            _chunk_fn_cache[key] = _BUILD_FAILED
            return _retry_on_scan("pallas kernel build failed")
        _chunk_fn_cache[key] = fns
    fn, fn_idx, make_dev = fns
    fn_dev = None
    if transfer == "device":
        dev_key = (key, dev_slice)
        fn_dev = _chunk_dev_cache.get(dev_key)
        if fn_dev is None:
            fresh_fn = True  # new device-planner entry compiles too
            fn_dev = make_dev(dev_slice)
            _chunk_dev_cache[dev_key] = fn_dev

    row_tables = None
    prev_act_dev = None
    if transfer in ("indices", "device"):
        # One upload per check; subsequent chunk calls pass these
        # already-resident arrays, which jit does NOT re-transfer.
        dev = jax.devices()[0]
        row_tables = tuple(
            jax.device_put(np.ascontiguousarray(a, dtype=np.int32), dev)
            for a in (packed.f, packed.a0, packed.a1, ret32, inv32,
                      np.minimum(bar_rank, NO_BAR))
        )
        if telemetry.enabled():
            telemetry.count("wgl.h2d-bytes",
                            sum(int(a.nbytes) for a in row_tables))
    if transfer == "device":
        # Device planning extras: the info cumsum (retention rule),
        # the barrier array (padded so any k0 slice is in bounds),
        # and the carried previous-window rows.
        icumA = jax.device_put(dev_plan[5], dev)
        bars_pad = np.zeros(_bucket(len(bars) + K, lo=K),
                            dtype=np.int32)
        bars_pad[: len(bars)] = bars
        barsA = jax.device_put(bars_pad, dev)
        prev_act_dev = jnp.asarray(
            np.full(W, packed.n, dtype=np.int32)
        )

    member = jnp.zeros((W, B), dtype=bool)
    states = jnp.tile(
        jnp.asarray(np.asarray(pm.init_state, dtype=np.int32)), (B, 1)
    )
    alive_np = np.zeros(B, dtype=bool)
    alive_np[0] = True
    alive = jnp.asarray(alive_np)
    failed = jnp.bool_(False)

    identity_perm = np.arange(W, dtype=np.int32)
    prev_active: Optional[np.ndarray] = None

    ckpt_path = ckpt_key = None
    c0_start = 0
    if checkpoint_dir is not None:
        ckpt_key = _ckpt_key(packed, pm, B, W, SW, K, NB, info_window)
        # The key prefix in the filename keeps CONCURRENT searches
        # sharing one dir (per-key checks under IndependentChecker's
        # thread pool all get the same opts["dir"]) from clobbering —
        # or tearing — each other's files.
        ckpt_path = os.path.join(
            checkpoint_dir, f"wgl-witness-{ckpt_key[:16]}.ckpt.npz"
        )
        saved = _ckpt_load(ckpt_path, ckpt_key)
        if saved is not None:
            c0_start, member_np, states_np, alive_np2 = saved
            member = jnp.asarray(member_np)
            states = jnp.asarray(states_np)
            alive = jnp.asarray(alive_np2)
            # The resumed chunk's first re-gather keys off the LAST
            # block of the chunk before it; blocks are recomputed
            # deterministically from the packed history, so only the
            # cursor needed saving.  A cursor past the end (the last
            # chunk saved c0 + NB > len) clamps: the loop is skipped
            # and the final alive check concludes from the carry.
            c0_start = min(c0_start, len(blocks))
            if c0_start > 0:
                prev_active = blocks[c0_start - 1][2]
                if transfer == "device":
                    pa = np.full(W, packed.n, dtype=np.int32)
                    pa[: len(prev_active)] = prev_active
                    prev_act_dev = jnp.asarray(pa)

    for c0 in range(c0_start, len(blocks), NB):
        chunk_blocks = blocks[c0 : c0 + NB]
        nblk = len(chunk_blocks)
        if transfer == "device":
            # Five (NB,) scalars per chunk; everything else is planned
            # on device from the resident tables.  Only the call
            # differs from the other modes: the try/except and the
            # post-chunk tail below are shared.
            k0_all, er_all, lo_all, nb_all, cut_all, _ = dev_plan

            def padded(a, fill=0):
                out = np.full(NB, fill, dtype=np.int32)
                out[:nblk] = a[c0 : c0 + nblk]
                return out

            dev_args = (
                jnp.asarray(padded(k0_all)),
                jnp.asarray(padded(er_all)),
                jnp.asarray(padded(lo_all)),
                jnp.asarray(padded(nb_all)),
                jnp.asarray(padded(cut_all, np.iinfo(np.int32).min)),
            )
        else:
            perm_np = np.tile(identity_perm, (NB, 1))
            present_np = np.ones((NB, W), dtype=bool)
            k0s_np = np.zeros(NB, dtype=np.int32)
            if transfer == "indices":
                # Per-chunk payload: row-INDEX arrays only; the tables
                # are rebuilt on device from the once-uploaded
                # row_tables.
                bar_idx_np = np.zeros((NB, K), dtype=np.int32)
                act_idx_np = np.full((NB, W), packed.n, dtype=np.int32)
                nbars_np = np.zeros(NB, dtype=np.int32)
                nws_np = np.zeros(NB, dtype=np.int32)
            else:
                bars_np = np.zeros((NB, 6, K), dtype=np.int32)
                bars_np[:, 1, :] = INF
                tab_np = np.zeros((NB, 5, W), dtype=np.int32)

            for bi, (k0, block_bars, active) in enumerate(chunk_blocks):
                nw = len(active)
                nb = len(block_bars)
                k0s_np[bi] = k0
                if transfer == "indices":
                    bar_idx_np[bi, :nb] = block_bars
                    act_idx_np[bi, :nw] = active
                    nbars_np[bi] = nb
                    nws_np[bi] = nw
                else:
                    bars_np[bi, 0, :nb] = np.searchsorted(active,
                                                          block_bars)
                    bars_np[bi, 1, :nb] = ret32[block_bars]
                    bars_np[bi, 2, :nb] = 1
                    bars_np[bi, 3, :nb] = packed.f[block_bars]
                    bars_np[bi, 4, :nb] = packed.a0[block_bars]
                    bars_np[bi, 5, :nb] = packed.a1[block_bars]
                    row = tab_np[bi]
                    row[0, :] = INF
                    row[0, :nw] = inv32[active]
                    row[1, :nw] = packed.f[active]
                    row[2, :nw] = packed.a0[active]
                    row[3, :nw] = packed.a1[active]
                    row[4, :] = NO_BAR
                    row[4, :nw] = np.minimum(bar_rank[active], NO_BAR)
                if prev_active is None:
                    # Very first block: nothing to re-gather; member
                    # is all-False already, so a full wipe is a no-op.
                    present_np[bi, :] = False
                    perm_np[bi, :] = 0
                else:
                    perm, present = window_regather(prev_active, active)
                    perm_np[bi, :nw] = perm
                    perm_np[bi, nw:] = 0
                    present_np[bi, :nw] = present
                    present_np[bi, nw:] = False
                prev_active = active

        if telemetry.enabled():
            if transfer == "device":
                h2d = sum(int(a.nbytes) for a in dev_args) + 4
            elif transfer == "indices":
                h2d = sum(int(a.nbytes) for a in (
                    bar_idx_np, act_idx_np, nbars_np, nws_np,
                    perm_np, present_np, k0s_np))
            else:
                h2d = sum(int(a.nbytes) for a in (
                    bars_np, tab_np, perm_np, present_np, k0s_np))
            telemetry.count("wgl.h2d-bytes", h2d)
            telemetry.count("wgl.witness.chunks", 1)
            sp = telemetry.span(
                "wgl.witness.compile" if fresh_fn
                else "wgl.witness.chunk", transfer=transfer)
        else:
            sp = telemetry.span("")  # shared no-op
        fresh_fn = False
        try:
            degrade.maybe_fault("witness")
            # The span covers dispatch AND the bool(failed) sync, so
            # its duration is real device time, not async enqueue.
            with sp:
                if transfer == "device":
                    (member, states, alive, failed, died,
                     prev_act_dev) = fn_dev(
                        member, states, alive, failed, prev_act_dev,
                        *dev_args, jnp.int32(packed.n),
                        *row_tables, icumA, barsA,
                    )
                elif transfer == "indices":
                    member, states, alive, failed, died = fn_idx(
                        member, states, alive, failed,
                        jnp.asarray(bar_idx_np), jnp.asarray(act_idx_np),
                        jnp.asarray(nbars_np), jnp.asarray(nws_np),
                        jnp.asarray(perm_np), jnp.asarray(present_np),
                        jnp.asarray(k0s_np), *row_tables,
                    )
                else:
                    member, states, alive, failed, died = fn(
                        member, states, alive, failed,
                        jnp.asarray(bars_np), jnp.asarray(tab_np),
                        jnp.asarray(perm_np), jnp.asarray(present_np),
                        jnp.asarray(k0s_np),
                    )
                # One sync per chunk (~32k barriers): early exit + time
                # budget.  The sync ALSO belongs inside the try — jitted
                # dispatch is asynchronous, so execution-time failures
                # only raise when a result is consumed.
                failed_now = bool(failed)
        except Exception as e:
            if pallas == "on":
                # A Mosaic compile or transient runtime failure on the
                # tunneled chip must not cost the verdict: evict the
                # kernel (transient — the next check may succeed, unlike
                # the deterministic build-failure negative cache above)
                # and restart this search on the XLA-scan sweep.
                _chunk_fn_cache.pop(key, None)
                _chunk_dev_cache.pop((key, dev_slice), None)
                return _retry_on_scan("pallas sweep failed")
            if degrade.is_resource_error(e):
                # The device (not the search) gave out: degradation
                # ladder — evict the possibly-huge compiled entry, retry
                # once halved, then escalate to the next tier.
                _chunk_fn_cache.pop(key, None)
                _chunk_dev_cache.pop((key, dev_slice), None)
                return _retry_smaller(e)
            raise
        if failed_now:
            _ckpt_remove(ckpt_path)  # concluded: a resume can't help
            if out_info is not None:
                d = int(died)
                out_info["died_at_rank"] = d if d != int(NO_BAR) else None
            return None
        budget_blown = (time_limit_s is not None
                        and time.monotonic() - t0 > time_limit_s)
        if ckpt_path is not None and (
            budget_blown or time.monotonic() - t0 > CKPT_MIN_ELAPSED_S
        ):
            _ckpt_save(ckpt_path, ckpt_key, c0 + NB,
                       np.asarray(member), np.asarray(states),
                       np.asarray(alive))
        if budget_blown:
            return None  # budget blown: the checkpoint stays for resume

    _ckpt_remove(ckpt_path)
    if not bool(alive.any()):
        if out_info is not None:
            out_info["died_at_rank"] = None  # not localized
        return None
    return WGLResult(
        valid=True,
        configs_explored=n_bars,
        elapsed_s=time.monotonic() - t0,
    )
