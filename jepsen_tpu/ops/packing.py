"""uint32 lane packing for WGL member/child bitsets.

A member set over a window of ``W`` slots is carried as
``ceil(W / 32)`` uint32 words instead of ``W`` bools.  Bit ``w`` of a
set lives at word ``w // 32``, lane ``w % 32`` (LSB-first).  All step
semantics the engines need reduce to popcount/AND/OR/shift on the
words; padding lanes (``w >= W``) are always zero so full-coverage
tests can OR them away with the complement of the packed ok-mask.

Hash accumulation over packed words is done with wrapping uint32
multiply-adds against fixed odd constants — deterministic across
devices, and exact dedup still compares the words themselves, so the
hashes only have to order duplicates next to each other.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

LANES = 32
FULL_WORD = np.uint32(0xFFFFFFFF)

# Fixed odd multipliers (splitmix-style) for word/state hash lanes.
# Independent hash streams; dedup correctness never depends on them
# (exact word compare backs the hash), only sort clustering does.
_HASH_SEEDS = (
    np.uint32(0x9E3779B1), np.uint32(0x85EBCA77),
    np.uint32(0xC2B2AE3D), np.uint32(0x27D4EB2F),
)


def n_words(W: int) -> int:
    """Words needed for a W-slot window."""
    return max(1, -(-int(W) // LANES))


def word_lane_tables(W: int) -> tuple[np.ndarray, np.ndarray]:
    """(word_idx[W] int32, lane_bit[W] uint32) lookup tables."""
    idx = np.arange(W, dtype=np.int32)
    lane = np.arange(W, dtype=np.uint32) % np.uint32(LANES)
    return idx // LANES, np.uint32(1) << lane


def hash_consts(Wp: int, stream: int = 0) -> np.ndarray:
    """Per-word odd uint32 multipliers for hash stream 0 or 1."""
    seed = _HASH_SEEDS[stream % len(_HASH_SEEDS)]
    k = np.arange(1, Wp + 1, dtype=np.uint32)
    # All-uint32 arithmetic: wraps in-type, no narrowing cast needed.
    return k * seed * np.uint32(2) + np.uint32(1)


def as_u32(x: jnp.ndarray) -> jnp.ndarray:
    """Relabels 32-bit integer lanes as uint32 for wrapping hash
    arithmetic.  Same-width reinterpretation only — the trace-time
    assert keeps a 64-bit value from ever narrowing here."""
    assert x.dtype in (jnp.int32, jnp.uint32), (
        f"as_u32: expected an int32/uint32 lane dtype, got {x.dtype}"
    )
    return x.astype(jnp.uint32)


def pack_bits(x: jnp.ndarray, Wp: int | None = None) -> jnp.ndarray:
    """bool (..., W) -> uint32 (..., ceil(W/32)), LSB-first lanes."""
    W = x.shape[-1]
    wp = Wp if Wp is not None else n_words(W)
    pad = wp * LANES - W
    if pad:
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    xr = x.reshape(x.shape[:-1] + (wp, LANES))
    lanebits = jnp.uint32(1) << jnp.arange(LANES, dtype=jnp.uint32)
    return jnp.where(xr, lanebits, jnp.uint32(0)).sum(
        axis=-1, dtype=jnp.uint32)


def unpack_bits(words: jnp.ndarray, W: int) -> jnp.ndarray:
    """uint32 (..., Wp) -> bool (..., W)."""
    lanes = jnp.arange(LANES, dtype=jnp.uint32)
    bits = (words[..., :, None] >> lanes) & jnp.uint32(1)
    flat = bits.reshape(words.shape[:-1] + (words.shape[-1] * LANES,))
    return flat[..., :W].astype(bool)


def popcount(words: jnp.ndarray) -> jnp.ndarray:
    """Per-element popcount summed over the last (word) axis -> int32."""
    return jax.lax.population_count(words).sum(axis=-1, dtype=jnp.int32)


def set_bit(words: jnp.ndarray, slot: jnp.ndarray) -> jnp.ndarray:
    """OR bit ``slot`` into each row of uint32 (..., Wp) words.

    ``slot`` broadcasts against the leading axes of ``words``.
    """
    wp = words.shape[-1]
    slot = jnp.asarray(slot)
    # Same-width relabels below (slot is already a 32-bit window index
    # by contract): assert at trace time so no int32 narrowing can
    # slip in through a 64-bit slot.
    assert slot.dtype in (jnp.int32, jnp.uint32), (
        f"set_bit: slot must be an int32/uint32 index, got {slot.dtype}"
    )
    widx = (slot // LANES).astype(jnp.int32)
    bit = jnp.uint32(1) << (slot % LANES).astype(jnp.uint32)
    cols = jnp.arange(wp, dtype=jnp.int32)
    hot = jnp.where(cols == widx[..., None], bit[..., None], jnp.uint32(0))
    return words | hot


def covers(child_words: jnp.ndarray, ok_words: jnp.ndarray) -> jnp.ndarray:
    """True where a packed child set covers every ok bit.

    Padding lanes of ``ok_words`` are zero, so their complement is all
    ones and they never block coverage.
    """
    return ((child_words | ~ok_words) == FULL_WORD).all(axis=-1)


def hash_words(words: jnp.ndarray, consts: jnp.ndarray) -> jnp.ndarray:
    """Wrapping uint32 multiply-add over the last axis."""
    return (words * consts).sum(axis=-1, dtype=jnp.uint32)


# -- host-side (numpy) mirrors, for re-gather / snapshots -------------------

def np_pack_bits(x: np.ndarray, Wp: int | None = None) -> np.ndarray:
    W = x.shape[-1]
    wp = Wp if Wp is not None else n_words(W)
    pad = wp * LANES - W
    if pad:
        x = np.pad(x, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    xr = x.reshape(x.shape[:-1] + (wp, LANES))
    lanebits = np.uint32(1) << np.arange(LANES, dtype=np.uint32)
    return np.where(xr, lanebits, np.uint32(0)).sum(
        axis=-1, dtype=np.uint32)


def np_unpack_bits(words: np.ndarray, W: int) -> np.ndarray:
    lanes = np.arange(LANES, dtype=np.uint32)
    bits = (words[..., :, None] >> lanes) & np.uint32(1)
    flat = bits.reshape(words.shape[:-1] + (words.shape[-1] * LANES,))
    return flat[..., :W].astype(bool)
