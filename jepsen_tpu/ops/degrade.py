"""Device degradation ladder support: classify XLA resource/compile
failures, inject them for tests, and record every degradation step.

The WGL tiers (witness → stream → batched → plain device BFS → CPU
exact) each catch resource exhaustion at their device-call sites, retry
once with a halved chunk/batch/beam, and otherwise fall through to the
next tier.  This module is the shared vocabulary: `is_resource_error`
decides what counts as "the device ran out, not the search", `record`
emits the `wgl.degrade.<tier>.<action>` telemetry counter AND appends
to the active capture so checkers can put the ladder in their result
metadata, and `maybe_fault`/JEPSEN_WGL_FAULT is the fault hook the
fault-matrix harness uses to force a tier failure without real
hardware (mirrors how DrJAX keeps host orchestration robust around
device-side JAX failures).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional

from .. import telemetry

#: Comma-separated tier names ("witness", "stream", "batched", "device"),
#: or "all": each named tier raises a synthetic RESOURCE_EXHAUSTED at its
#: device-call site, driving the ladder end-to-end on any backend.
FAULT_ENV = "JEPSEN_WGL_FAULT"

#: Message fragments that mean "the device/compiler gave out", as opposed
#: to a bug in the search itself.  Matched case-insensitively against the
#: stringified exception.
_RESOURCE_MARKERS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "ran out of memory",
    "oom",
    "allocation failure",
    "failed to allocate",
    "compilation failure",
    "xla compilation",
    "mosaic failed",
    "internal: failed to compile",
)


class InjectedFault(RuntimeError):
    """Raised by maybe_fault; message matches the resource markers so the
    production catch sites treat it exactly like a real device failure."""


def fault_tiers() -> set[str]:
    raw = os.environ.get(FAULT_ENV, "")
    return {t.strip() for t in raw.split(",") if t.strip()}


def maybe_fault(tier: str) -> None:
    """Raises a synthetic resource-exhaustion error when JEPSEN_WGL_FAULT
    names this tier (or "all").  Reads the env each call so tests can
    toggle tiers without reimporting; the lookup is two dict hits on a
    path that is about to launch a device program anyway."""
    tiers = fault_tiers()
    if tier in tiers or "all" in tiers:
        raise InjectedFault(
            f"RESOURCE_EXHAUSTED: injected fault for tier {tier!r} "
            f"({FAULT_ENV}={os.environ.get(FAULT_ENV)!r})"
        )


def is_resource_error(e: BaseException) -> bool:
    """True when the exception smells like XLA resource exhaustion or a
    compile failure — the class of errors the ladder may degrade on.
    Anything else (assertion, shape bug, keyboard interrupt) must
    propagate: degrading on a logic error would hide it."""
    if isinstance(e, (MemoryError, InjectedFault)):
        return True
    if isinstance(e, (KeyboardInterrupt, SystemExit)):
        return False
    # XlaRuntimeError lives in jaxlib internals; match by name so this
    # works across jaxlib layouts and on CPU-only builds.
    name = type(e).__name__
    msg = f"{name}: {e}".lower()
    if name == "XlaRuntimeError" and (
        "resource" in msg or "memory" in msg or "compil" in msg
    ):
        return True
    return any(m in msg for m in _RESOURCE_MARKERS)


# ---------------------------------------------------------------------------
# Degradation event capture
# ---------------------------------------------------------------------------

_tls = threading.local()


class capture:
    """Context manager collecting degradation events recorded on this
    thread, so a checker can attach the ladder's path to its result
    metadata:

        with degrade.capture() as steps:
            res = check_wgl_device(...)
        if steps:
            out["degradations"] = steps

    Captures nest: an inner capture sees only its own events; they are
    replayed into the outer capture on exit so nothing is lost."""

    def __enter__(self) -> list[dict]:
        self._outer = getattr(_tls, "events", None)
        _tls.events = []
        return _tls.events

    def __exit__(self, *exc) -> None:
        mine = _tls.events
        _tls.events = self._outer
        if self._outer is not None:
            self._outer.extend(mine)
        return None


def record(tier: str, action: str, error: Optional[Any] = None) -> None:
    """Records one degradation step: a `wgl.degrade.<tier>.<action>`
    telemetry counter plus an event in the active capture (if any)."""
    telemetry.count(f"wgl.degrade.{tier}.{action}")
    events = getattr(_tls, "events", None)
    if events is not None:
        ev = {"tier": tier, "action": action}
        if error is not None:
            ev["error"] = f"{type(error).__name__}: {error}" if isinstance(
                error, BaseException
            ) else str(error)
        events.append(ev)
