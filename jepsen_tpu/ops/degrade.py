"""Device degradation ladder support: classify XLA resource/compile
failures, inject them for tests, and record every degradation step.

The WGL tiers (witness → stream → batched → plain device BFS → CPU
exact) each catch resource exhaustion at their device-call sites, retry
once with a halved chunk/batch/beam, and otherwise fall through to the
next tier.  This module is the shared vocabulary: `is_resource_error`
decides what counts as "the device ran out, not the search", `record`
emits the `wgl.degrade.<tier>.<action>` telemetry counter AND appends
to the active capture so checkers can put the ladder in their result
metadata, and `maybe_fault`/JEPSEN_WGL_FAULT is the fault hook the
fault-matrix harness uses to force a tier failure without real
hardware (mirrors how DrJAX keeps host orchestration robust around
device-side JAX failures).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Optional

from .. import telemetry
from ..telemetry import flight

#: Comma-separated tier names ("witness", "stream", "batched", "device"),
#: or "all": each named tier raises a synthetic RESOURCE_EXHAUSTED at its
#: device-call site, driving the ladder end-to-end on any backend.
FAULT_ENV = "JEPSEN_WGL_FAULT"

#: Message fragments that mean "the device/compiler gave out", as opposed
#: to a bug in the search itself.  Matched case-insensitively against the
#: stringified exception.
_RESOURCE_MARKERS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "ran out of memory",
    "oom",
    "allocation failure",
    "failed to allocate",
    "compilation failure",
    "xla compilation",
    "mosaic failed",
    "internal: failed to compile",
)


class InjectedFault(RuntimeError):
    """Raised by maybe_fault; message matches the resource markers so the
    production catch sites treat it exactly like a real device failure."""


def fault_tiers() -> set[str]:
    raw = os.environ.get(FAULT_ENV, "")
    return {t.strip() for t in raw.split(",") if t.strip()}


def maybe_fault(tier: str) -> None:
    """Raises a synthetic resource-exhaustion error when JEPSEN_WGL_FAULT
    names this tier (or "all").  Reads the env each call so tests can
    toggle tiers without reimporting; the lookup is two dict hits on a
    path that is about to launch a device program anyway."""
    tiers = fault_tiers()
    if tier in tiers or "all" in tiers:
        raise InjectedFault(
            f"RESOURCE_EXHAUSTED: injected fault for tier {tier!r} "
            f"({FAULT_ENV}={os.environ.get(FAULT_ENV)!r})"
        )


def is_resource_error(e: BaseException) -> bool:
    """True when the exception smells like XLA resource exhaustion or a
    compile failure — the class of errors the ladder may degrade on.
    Anything else (assertion, shape bug, keyboard interrupt) must
    propagate: degrading on a logic error would hide it."""
    if isinstance(e, (MemoryError, InjectedFault)):
        return True
    if isinstance(e, (KeyboardInterrupt, SystemExit)):
        return False
    # XlaRuntimeError lives in jaxlib internals; match by name so this
    # works across jaxlib layouts and on CPU-only builds.
    name = type(e).__name__
    msg = f"{name}: {e}".lower()
    if name == "XlaRuntimeError" and (
        "resource" in msg or "memory" in msg or "compil" in msg
    ):
        return True
    return any(m in msg for m in _RESOURCE_MARKERS)


# ---------------------------------------------------------------------------
# Degradation event capture
# ---------------------------------------------------------------------------

_tls = threading.local()


class capture:
    """Context manager collecting degradation events recorded on this
    thread, so a checker can attach the ladder's path to its result
    metadata:

        with degrade.capture() as steps:
            res = check_wgl_device(...)
        if steps:
            out["degradations"] = steps

    Captures nest: an inner capture sees only its own events; they are
    replayed into the outer capture on exit so nothing is lost."""

    def __enter__(self) -> list[dict]:
        self._outer = getattr(_tls, "events", None)
        _tls.events = []
        return _tls.events

    def __exit__(self, *exc) -> None:
        mine = _tls.events
        _tls.events = self._outer
        if self._outer is not None:
            self._outer.extend(mine)
        return None


def record(tier: str, action: str, error: Optional[Any] = None) -> None:
    """Records one degradation step: a `wgl.degrade.<tier>.<action>`
    telemetry counter plus an event in the active capture (if any)."""
    telemetry.count(f"wgl.degrade.{tier}.{action}")
    flight.note(f"degrade.{tier}.{action}")
    events = getattr(_tls, "events", None)
    if events is not None:
        ev = {"tier": tier, "action": action}
        if error is not None:
            ev["error"] = f"{type(error).__name__}: {error}" if isinstance(
                error, BaseException
            ) else str(error)
        events.append(ev)


# ---------------------------------------------------------------------------
# Chip recovery — the rung between "retry smaller" and "surrender to CPU"
# ---------------------------------------------------------------------------

#: Set JEPSEN_CHIP_RESET=0 to disable the reset rung (shared hosts where
#: another process may legitimately hold the libtpu lockfile).
CHIP_RESET_ENV = "JEPSEN_CHIP_RESET"

#: The one wedge cause recoverable from userspace: a stale libtpu
#: lockfile left by a killed process (the runtime spins waiting on it).
LOCKFILE_GLOB = "/tmp/libtpu_lockfile*"

_chip_reset_lock = threading.Lock()
_chip_reset_tried = False

#: Last observed chip health, exported on /metrics as a one-hot
#: `jepsen_chip_health{state=...}` gauge and on the web fleet page.
#: "unprobed" until the first probe_chip()/try_chip_reset() call;
#: "ok-after-reset" distinguishes a chip that needed the lockfile rung
#: from one that was healthy all along.
_chip_state = "unprobed"


def chip_state() -> str:
    """Returns the last observed chip health: one of
    telemetry.CHIP_HEALTH_STATES ("unprobed", "ok", "wedged",
    "ok-after-reset", "absent")."""
    return _chip_state


def _set_chip_state(state: str) -> None:
    global _chip_state
    _chip_state = state


def reset_chip(pattern: str = LOCKFILE_GLOB) -> str:
    """Best-effort chip unwedge: removes stale libtpu lockfiles,
    settles briefly, and returns a note describing what was done
    (bench.py records it in its JSON)."""
    import glob

    removed = []
    for path in glob.glob(pattern):
        try:
            os.remove(path)
            removed.append(path)
        except OSError:
            pass
    time.sleep(2.0)
    if removed:
        return f"removed {len(removed)} stale libtpu lockfile(s)"
    return "no stale lockfiles found"


def probe_chip(timeout_s: float = 90.0) -> str:
    """Chip health probe: one tiny matmul in a subprocess under a short
    timeout.  Returns "ok", "wedged" (hang/timeout), or "absent" (no
    accelerator backend).  90 s covers a cold first compile (~20-40 s
    observed) with slack; a wedged tunnel hangs for hours, so the two
    are cleanly separable.

    Every probe leaves a structured trace in `_last_probe` (timing,
    returncode, trimmed output); a "wedged" or "absent" result
    additionally writes the forensics dossier (`write_chip_dossier`)
    when JEPSEN_CHIP_DOSSIER_DIR points somewhere — machine-readable
    evidence for the still-open wedged-TPU investigation, and for the
    terminal plugin-gone state that succeeded it."""
    import subprocess
    import sys

    code = (
        "import jax\n"
        "x = jax.numpy.ones((8, 8))\n"
        "(x @ x).block_until_ready()\n"
        "print(jax.devices()[0].platform)\n"
    )
    t0 = time.time()
    trace: dict[str, Any] = {"at": t0, "timeout_s": timeout_s,
                             "elapsed_s": None, "returncode": None,
                             "stdout": None, "stderr": None}
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout_s, capture_output=True,
        )
    except subprocess.TimeoutExpired:
        trace["elapsed_s"] = round(time.time() - t0, 3)
        _note_probe("wedged", trace)
        _set_chip_state("wedged")
        _maybe_write_dossier()
        return "wedged"
    trace["elapsed_s"] = round(time.time() - t0, 3)
    trace["returncode"] = proc.returncode
    trace["stdout"] = proc.stdout.decode(errors="replace")[-2000:]
    trace["stderr"] = proc.stderr.decode(errors="replace")[-2000:]
    if proc.returncode != 0:
        _note_probe("absent", trace)
        _set_chip_state("absent")
        _maybe_write_dossier()
        return "absent"
    platform = proc.stdout.decode(errors="replace").strip()
    state = "ok" if platform == "tpu" else "absent"
    _note_probe(state, trace)
    _set_chip_state(state)
    if state == "absent":
        _maybe_write_dossier()
    return state


def try_chip_reset(error: Optional[BaseException] = None) -> bool:
    """The degradation ladder's chip-recovery rung: when a resource
    error looks like a WEDGED CHIP rather than a too-big program, clear
    stale libtpu lockfiles and re-probe ONCE per process before the
    ladder surrenders the device to CPU.  True means the probe came
    back healthy — retry the device tier; False means stay on the
    fall-through path (already tried, disabled, non-TPU backend, or the
    chip stayed wedged)."""
    global _chip_reset_tried
    if os.environ.get(CHIP_RESET_ENV, "") in ("0", "false", "no"):
        return False
    with _chip_reset_lock:
        if _chip_reset_tried:
            return False
        _chip_reset_tried = True
    try:
        import jax

        platform = jax.default_backend()
    except Exception:  # noqa: BLE001 — no backend at all
        return False
    if platform != "tpu":
        return False
    note = reset_chip()
    ok = probe_chip() == "ok"
    if ok:
        _set_chip_state("ok-after-reset")
    global _last_reset
    _last_reset = {
        "at": time.time(),
        "note": note,
        "recovered": ok,
        "after_error": f"{type(error).__name__}: {error}"
        if error else None,
    }
    telemetry.count("wgl.degrade.chip-reset")
    record("chip-reset", "recovered" if ok else "still-wedged",
           f"{note}; probe {'ok' if ok else 'failed'}"
           + (f" (after {type(error).__name__})" if error else ""))
    flight.note("chip-reset", recovered=ok, detail=note)
    if not ok:
        _maybe_write_dossier()
    return ok


# ---------------------------------------------------------------------------
# Chip forensics dossier
# ---------------------------------------------------------------------------

#: When set, every "wedged" probe (and every failed reset rung) writes
#: `chip.json` into this directory — next to CHIP_LOG.md when
#: tools/chip_watch.py is driving.
DOSSIER_ENV = "JEPSEN_CHIP_DOSSIER_DIR"

#: Environment variables worth preserving as evidence (prefix match).
_DOSSIER_ENV_PREFIXES = ("JAX_", "JEPSEN_", "TPU_", "LIBTPU",
                         "XLA_", "PJRT_")

#: Most recent probe_chip trace / reset-rung outcome (None until run).
_last_probe: Optional[dict] = None
_last_reset: Optional[dict] = None


def _note_probe(state: str, trace: dict) -> None:
    global _last_probe
    trace = dict(trace)
    trace["state"] = state
    _last_probe = trace


def chip_dossier() -> dict:
    """The structured forensics snapshot for a wedged-chip report:
    environment, toolchain versions, lockfile state, last probe timing,
    and the reset rung's outcome.  Every field is best-effort — a
    half-broken runtime must still produce evidence."""
    import glob
    import sys

    out: dict[str, Any] = {
        "v": 1,
        "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "chip_state": _chip_state,
        "probe": dict(_last_probe) if _last_probe else None,
        "reset": dict(_last_reset) if _last_reset else None,
        "reset_tried": _chip_reset_tried,
        "env": {k: v for k, v in sorted(os.environ.items())
                if k.startswith(_DOSSIER_ENV_PREFIXES)},
        "versions": {"python": sys.version.split()[0]},
        "lockfiles": [],
    }
    for mod in ("jax", "jaxlib", "numpy"):
        try:
            out["versions"][mod] = __import__(mod).__version__
        except Exception:  # noqa: BLE001 — evidence, not a dependency
            out["versions"][mod] = None
    try:
        for path in sorted(glob.glob(LOCKFILE_GLOB)):
            st = os.stat(path)
            out["lockfiles"].append(
                {"path": path, "mtime": st.st_mtime, "size": st.st_size}
            )
    except OSError:
        pass
    return out


def write_chip_dossier(path: Optional[str] = None) -> Optional[str]:
    """Writes `chip_dossier()` as JSON (atomic tmp+rename).  `path`
    defaults to `$JEPSEN_CHIP_DOSSIER_DIR/chip.json`; returns the path
    written, or None (no destination / write failed — forensics never
    raise)."""
    import json

    if path is None:
        d = os.environ.get(DOSSIER_ENV)
        if not d:
            return None
        path = os.path.join(d, "chip.json")
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(chip_dossier(), f, indent=2, sort_keys=True,
                      default=repr)
            f.write("\n")
        os.replace(tmp, path)
        telemetry.count("wgl.degrade.chip-dossier")
        return path
    except (OSError, TypeError, ValueError):
        return None


def _maybe_write_dossier() -> None:
    if os.environ.get(DOSSIER_ENV):
        write_chip_dossier()
