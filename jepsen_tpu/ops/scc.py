"""Batched device cycle screening for dependency graphs.

The device half of the Elle-equivalent (checker/elle/graph.py): Adya
anomaly detection is cycle detection over per-transaction dependency
graphs, and a test's history shards into many *independent* per-key
graphs (parallel/independent.py), each small.  That shape is a poor fit
for irregular host Tarjan at scale but a great fit for the MXU: pack
each graph as a (V, V) boolean adjacency matrix, batch over keys, and
compute transitive closure by repeated bfloat16 matrix squaring —
log2(V) batched matmuls.  A graph has a cycle iff its closure has a
nonzero diagonal.

The screen is conservative in the cheap direction: it decides *whether*
each key's graph is acyclic (the common, expensive-to-confirm case) on
device; only flagged keys go to the exact host search
(graph.check_cycles) for cycle extraction and Adya classification, so
verdict parity with the host path is structural.  Keys shard across the
mesh axis like the batched WGL kernel (ops/wgl_batched.py).

Equivalent role in the reference stack: elle's cycle search consumed by
jepsen at tests/cycle/{append,wr}.clj (the elle library itself is not
vendored; SURVEY.md §2.4).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from ..checker.elle.graph import DepGraph, check_cycles

_kernel_cache: dict[tuple, Any] = {}


def _bucket(x: int, lo: int) -> int:
    w = lo
    while w < x:
        w *= 2
    return w


def pack_adjacency(
    graphs: Sequence[DepGraph],
    *,
    pad_keys_to: Optional[int] = None,
) -> tuple[np.ndarray, list[list[int]]]:
    """Packs graphs into a (K, V, V) bool adjacency tensor (all edge
    types collapsed — the screen only needs reachability) plus each
    graph's dense-index -> vertex mapping."""
    V = _bucket(max((len(g.vertices) for g in graphs), default=1), 8)
    K = pad_keys_to or len(graphs)
    adj = np.zeros((K, V, V), dtype=bool)
    vertex_maps: list[list[int]] = []
    for k, g in enumerate(graphs):
        verts = sorted(g.vertices)
        idx = {v: i for i, v in enumerate(verts)}
        vertex_maps.append(verts)
        for src, dsts in g.adj.items():
            si = idx[src]
            for dst in dsts:
                adj[k, si, idx[dst]] = True
    return adj, vertex_maps


def _get_kernel(K: int, V: int, mesh=None):
    # Keyed on the mesh object itself (a strong reference): id()
    # keys can collide when a dead object's address is reused,
    # silently serving a kernel compiled for something else.
    key = (K, V, mesh)
    fn = _kernel_cache.get(key)
    if fn is not None:
        return fn

    import jax
    import jax.numpy as jnp

    steps = max(1, int(np.ceil(np.log2(max(V, 2)))))

    def has_cycle(adj):
        # (K, V, V) bool -> (K,) bool.  Repeated squaring in bfloat16:
        # values are clamped to {0, 1} every step, so low precision
        # only ever rounds sums of nonnegative reachability counts,
        # which cannot reach zero — exactness is preserved.
        a = adj.astype(jnp.bfloat16)
        for _ in range(steps):
            a = jnp.minimum(a + jnp.einsum(
                "kij,kjh->kih", a, a,
                preferred_element_type=jnp.bfloat16,
            ), 1.0)
        diag = jnp.diagonal(a, axis1=1, axis2=2)
        return (diag > 0).any(axis=1)

    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import shard_map_compat

        shard_map, rep_kw = shard_map_compat()

        fn = jax.jit(
            shard_map(
                has_cycle, mesh=mesh,
                in_specs=P("keys"), out_specs=P("keys"),
                **rep_kw,
            )
        )
    else:
        fn = jax.jit(has_cycle)
    _kernel_cache[key] = fn
    return fn


def screen_cycles(
    graphs: Sequence[DepGraph], *, mesh=None
) -> np.ndarray:
    """(n_graphs,) bool: True where the graph contains a cycle.  Runs on
    the default JAX device, keys sharded over `mesh` when given."""
    import jax.numpy as jnp

    if not graphs:
        return np.zeros(0, dtype=bool)
    n = len(graphs)
    K = n
    if mesh is not None:
        shards = mesh.devices.size
        K = ((n + shards - 1) // shards) * shards
    adj, _ = pack_adjacency(graphs, pad_keys_to=K)
    flags = np.asarray(_get_kernel(K, adj.shape[1], mesh)(jnp.asarray(adj)))
    return flags[:n]


def check_cycles_device(
    graphs: Sequence[DepGraph], *, mesh=None, max_device_vertices: int = 1024
) -> list[list[dict]]:
    """Anomaly cycles per graph, device-screened: acyclic keys are
    settled by the closure kernel; flagged keys get the exact host
    layered search (same records as graph.check_cycles).  Graphs too
    large for a dense (V, V) matrix fall back to host Tarjan."""
    big = [
        i for i, g in enumerate(graphs)
        if len(g.vertices) > max_device_vertices
    ]
    small_idx = [i for i in range(len(graphs)) if i not in set(big)]
    small = [graphs[i] for i in small_idx]
    out: list[list[dict]] = [[] for _ in graphs]
    if small:
        flags = screen_cycles(small, mesh=mesh)
        for i, flagged in zip(small_idx, flags):
            if flagged:
                out[i] = check_cycles(graphs[i])
    for i in big:
        out[i] = check_cycles(graphs[i])
    return out
