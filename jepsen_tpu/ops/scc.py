"""Batched device cycle screening for dependency graphs.

The device half of the Elle-equivalent (checker/elle/graph.py): Adya
anomaly detection is cycle detection over per-transaction dependency
graphs, and a test's history shards into many *independent* per-key
graphs (parallel/independent.py), each small.  That shape is a poor fit
for irregular host Tarjan at scale but a great fit for the MXU: pack
each graph as a (V, V) boolean adjacency matrix, batch over keys, and
compute transitive closure by repeated bfloat16 matrix squaring —
log2(V) batched matmuls.  A graph has a cycle iff its closure has a
nonzero diagonal.

The screen is conservative in the cheap direction: it decides *whether*
each key's graph is acyclic (the common, expensive-to-confirm case) on
device; only flagged keys go to the exact host search
(graph.check_cycles) for cycle extraction and Adya classification, so
verdict parity with the host path is structural.  Keys shard across the
mesh axis like the batched WGL kernel (ops/wgl_batched.py).

Equivalent role in the reference stack: elle's cycle search consumed by
jepsen at tests/cycle/{append,wr}.clj (the elle library itself is not
vendored; SURVEY.md §2.4).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import numpy as np

from ..checker.elle.graph import DepGraph, check_cycles
from ..telemetry import roofline

_kernel_cache: dict[tuple, Any] = {}


def _bucket(x: int, lo: int) -> int:
    w = lo
    while w < x:
        w *= 2
    return w


def pack_adjacency(
    graphs: Sequence[DepGraph],
    *,
    pad_keys_to: Optional[int] = None,
) -> tuple[np.ndarray, list[list[int]]]:
    """Packs graphs into a (K, V, V) bool adjacency tensor (all edge
    types collapsed — the screen only needs reachability) plus each
    graph's dense-index -> vertex mapping."""
    V = _bucket(max((len(g.vertices) for g in graphs), default=1), 8)
    K = pad_keys_to or len(graphs)
    adj = np.zeros((K, V, V), dtype=bool)
    vertex_maps: list[list[int]] = []
    for k, g in enumerate(graphs):
        verts = sorted(g.vertices)
        idx = {v: i for i, v in enumerate(verts)}
        vertex_maps.append(verts)
        for src, dsts in g.adj.items():
            si = idx[src]
            for dst in dsts:
                adj[k, si, idx[dst]] = True
    return adj, vertex_maps


def _get_kernel(K: int, V: int, mesh=None):
    # Keyed on the mesh object itself (a strong reference): id()
    # keys can collide when a dead object's address is reused,
    # silently serving a kernel compiled for something else.
    key = (K, V, mesh)
    fn = _kernel_cache.get(key)
    if fn is not None:
        return fn

    import jax
    import jax.numpy as jnp

    steps = max(1, int(np.ceil(np.log2(max(V, 2)))))

    def has_cycle(adj):
        # (K, V, V) bool -> (K,) bool.  Repeated squaring in bfloat16:
        # values are clamped to {0, 1} every step, so low precision
        # only ever rounds sums of nonnegative reachability counts,
        # which cannot reach zero — exactness is preserved.
        a = adj.astype(jnp.bfloat16)
        for _ in range(steps):
            a = jnp.minimum(a + jnp.einsum(
                "kij,kjh->kih", a, a,
                preferred_element_type=jnp.bfloat16,
            ), 1.0)
        diag = jnp.diagonal(a, axis1=1, axis2=2)
        return (diag > 0).any(axis=1)

    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import shard_map_compat

        shard_map, rep_kw = shard_map_compat()

        fn = roofline.instrument(jax.jit(
            shard_map(
                has_cycle, mesh=mesh,
                in_specs=P("keys"), out_specs=P("keys"),
                **rep_kw,
            )
        ))
    else:
        fn = roofline.instrument(jax.jit(has_cycle))
    _kernel_cache[key] = fn
    return fn


def screen_cycles(
    graphs: Sequence[DepGraph], *, mesh=None
) -> np.ndarray:
    """(n_graphs,) bool: True where the graph contains a cycle.  Runs on
    the default JAX device, keys sharded over `mesh` when given."""
    import jax.numpy as jnp

    if not graphs:
        return np.zeros(0, dtype=bool)
    n = len(graphs)
    K = n
    if mesh is not None:
        shards = mesh.devices.size
        K = ((n + shards - 1) // shards) * shards
    adj, _ = pack_adjacency(graphs, pad_keys_to=K)
    flags = np.asarray(_get_kernel(K, adj.shape[1], mesh)(jnp.asarray(adj)))
    return flags[:n]


# ---------------------------------------------------------------------------
# Device witness-cycle extraction (VERDICT r2 #8)
# ---------------------------------------------------------------------------


def _get_extract_kernel(K: int, V: int):
    """fn(adj_all (K,V,V) bool, adj_req (K,V,V) bool) ->
    (found (K,), u (K,), v (K,), parent (K,V), scc_size (K,)).

    Finds, per graph, one edge u->v from adj_req that lies on a cycle
    of adj_all (v reaches u), plus parent pointers of a shortest
    v->..->u path — the same parent-pointer reconstruction idea as the
    WGL witness (ops/wgl_witness.py), so only the O(len) backtrack
    happens on host.  adj_req == adj_all asks for any cycle; a
    restricted adj_req (e.g. wr-only edges) asks for a cycle THROUGH
    that edge type, which is exactly the elle layered-search primitive
    (graph.find_cycle_with_edge)."""
    key = ("extract", K, V)
    fn = _kernel_cache.get(key)
    if fn is not None:
        return fn

    import jax
    import jax.numpy as jnp
    from jax import lax

    steps = max(1, int(np.ceil(np.log2(max(V, 2)))))

    def one(adj_all, adj_req):
        a = adj_all.astype(jnp.bfloat16)
        for _ in range(steps):
            a = jnp.minimum(a + a @ a, 1.0)
        reach = a > 0                      # path of length >= 1
        eye = jnp.eye(V, dtype=bool)
        # M[u, v]: required edge u->v whose head v walks back to u
        # (trivially when u == v: a self-loop).
        m = adj_req & (reach | eye).T
        found = m.any()
        flat = jnp.argmax(m.reshape(-1))
        u = flat // V
        v = flat % V
        # SCC size of u (for scc-size reporting): mutually reachable.
        scc = reach[u] & reach[:, u]
        scc_size = jnp.maximum(scc.sum(), 1)

        # Parent BFS v -> u over adj_all.
        src_row = jnp.arange(V) == v
        init_frontier = jnp.where(found, src_row, jnp.zeros(V, bool))

        def cond(s):
            frontier, visited, parent = s
            return frontier.any() & ~visited[u]

        def body(s):
            frontier, visited, parent = s
            nxt = (
                (frontier.astype(jnp.bfloat16) @ adj_all.astype(
                    jnp.bfloat16)) > 0
            ) & ~visited
            # pred[j]: first frontier vertex with an edge to j.
            pred = jnp.argmax(frontier[:, None] & adj_all, axis=0)
            parent = jnp.where(nxt, pred, parent)
            return nxt, visited | nxt, parent

        frontier0 = init_frontier
        visited0 = init_frontier
        parent0 = jnp.where(init_frontier, v, -1).astype(jnp.int32)
        # u == v (self-loop): the trivial path needs no BFS at all.
        _, _, parent = lax.while_loop(
            cond, body,
            (frontier0 & (u != v), visited0, parent0),
        )
        return found, u.astype(jnp.int32), v.astype(jnp.int32), \
            parent, scc_size.astype(jnp.int32)

    fn = roofline.instrument(jax.jit(jax.vmap(one)))
    _kernel_cache[key] = fn
    return fn


def extract_cycles_device(
    graphs: Sequence[DepGraph],
    *,
    require: Optional[Sequence[Optional[set]]] = None,
) -> list[Optional[tuple[list[int], int]]]:
    """Per graph: (cycle as a closed vertex list [v0..v0], scc_size),
    or None when no qualifying cycle exists.  `require[i]` restricts
    graph i's cycle to pass through at least one edge carrying one of
    those types (the elle layer rule); None means any cycle.

    The O(V^3) closure + BFS sweep runs on device; the host only
    backtracks parent pointers."""
    import jax.numpy as jnp

    if not graphs:
        return []
    adj_all, vertex_maps = pack_adjacency(graphs)
    K, V, _ = adj_all.shape
    adj_req = adj_all.copy()
    if require is not None:
        for k, (g, types) in enumerate(zip(graphs, require)):
            if types is None:
                continue
            verts = vertex_maps[k]
            idx = {x: i for i, x in enumerate(verts)}
            req = np.zeros((V, V), dtype=bool)
            for src, dsts in g.adj.items():
                for dst, ts in dsts.items():
                    if ts & set(types):
                        req[idx[src], idx[dst]] = True
            adj_req[k] = req
    found, u, v, parent, scc = (
        np.asarray(x) for x in _get_extract_kernel(K, V)(
            jnp.asarray(adj_all), jnp.asarray(adj_req)
        )
    )
    out: list[Optional[tuple[list[int], int]]] = []
    for k in range(K):
        if not found[k]:
            out.append(None)
            continue
        verts = vertex_maps[k]
        uu, vv = int(u[k]), int(v[k])
        # Path vv -> .. -> uu via parents, then the uu -> vv edge
        # closes it.  Format matches graph.find_cycle_in: closed list.
        path = [uu]
        guard = 0
        while path[-1] != vv and guard <= V:
            path.append(int(parent[k][path[-1]]))
            guard += 1
        if guard > V:  # unreachable (shouldn't happen): be safe
            out.append(None)
            continue
        path.reverse()                    # vv .. uu
        cycle_idx = [vv] if uu == vv else path
        cycle = [verts[i] for i in cycle_idx] + [verts[vv]]
        out.append((cycle, int(scc[k])))
    return out


def _record(g: DepGraph, cycle: list[int], scc_size: int,
            forced: Optional[str]) -> dict:
    from ..checker.elle.graph import classify_cycle, cycle_explanation

    return {
        "type": forced or classify_cycle(g, cycle),
        "cycle": cycle,
        "steps": cycle_explanation(g, cycle),
        "scc-size": scc_size,
    }


#: sentinel forced-type for the leftovers layer (classification is
#: derived from the cycle itself, like graph.check_cycles layer 4)
_LAYER4 = "__leftover__"


def check_cycles_layered_device_batch(
    graphs: Sequence[DepGraph],
) -> list[list[dict]]:
    """graph.check_cycles' layer structure with the cycle search on
    device, batched over graphs: G0 over the ww subgraph, G1c through
    a wr edge over ww+wr, G-single/G2-item through an rw edge over
    everything, and a leftovers layer (any cycle at all — custom or
    realtime/process-only edge types must not pass as valid, exactly
    like the host's layer 4).  Every layer of every graph rides ONE
    extract_cycles_device call.

    One witness record per non-empty layer per graph — the host path
    emits one per SCC per layer; this path exists for graphs whose
    host Tarjan is the bottleneck, where one certificate per anomaly
    class is what the checker consumes (checker/elle reports types +
    examples), at the cost of possibly under-reporting extra SCCs."""
    entries: list[tuple[int, DepGraph, Optional[set], Optional[str]]] = []
    for gi, graph in enumerate(graphs):
        layers = [
            (graph.restricted(["ww", "realtime", "process"]),
             None, "G0"),
            (graph.restricted(["ww", "wr", "realtime", "process"]),
             {"wr"}, "G1c"),
            (graph, {"rw"}, None),
            (graph, None, _LAYER4),
        ]
        for g, req, t in layers:
            if g.vertices:
                entries.append((gi, g, req, t))
    results = extract_cycles_device(
        [e[1] for e in entries], require=[e[2] for e in entries],
    )
    out: list[list[dict]] = [[] for _ in graphs]
    leftovers: list[tuple[int, DepGraph, tuple]] = []
    for (gi, g, _req, forced), res in zip(entries, results):
        if res is None:
            continue
        if forced == _LAYER4:
            leftovers.append((gi, g, res))
            continue
        cycle, scc_size = res
        out[gi].append(_record(g, cycle, scc_size, forced))
    for gi, g, (cycle, scc_size) in leftovers:
        # Report only what the typed layers left unexplained: a cycle
        # sharing vertices with an already-reported one is the same
        # SCC seen again through a looser lens.
        seen = [set(r["cycle"]) for r in out[gi]]
        if any(set(cycle) & s for s in seen):
            continue
        out[gi].append(_record(g, cycle, scc_size, None))
    return out


def check_cycles_layered_device(graph: DepGraph) -> list[dict]:
    return check_cycles_layered_device_batch([graph])[0]


def check_cycles_device(
    graphs: Sequence[DepGraph], *, mesh=None,
    max_device_vertices: int = 1024,
    device_extract_min_vertices: int = 256,
) -> list[list[dict]]:
    """Anomaly cycles per graph, device-screened: acyclic keys are
    settled by the closure kernel; small flagged keys get the exact
    host layered search (same records as graph.check_cycles); LARGE
    flagged keys extract their witness cycles on device too
    (check_cycles_layered_device), so a huge cyclic key no longer
    serializes on host Tarjan.  Graphs too large for a dense (V, V)
    matrix fall back to host entirely."""
    big = [
        i for i, g in enumerate(graphs)
        if len(g.vertices) > max_device_vertices
    ]
    small_idx = [i for i in range(len(graphs)) if i not in set(big)]
    small = [graphs[i] for i in small_idx]
    out: list[list[dict]] = [[] for _ in graphs]
    device_bound: list[int] = []
    if small:
        flags = screen_cycles(small, mesh=mesh)
        for i, flagged in zip(small_idx, flags):
            if not flagged:
                continue
            if len(graphs[i].vertices) >= device_extract_min_vertices:
                device_bound.append(i)
            else:
                out[i] = check_cycles(graphs[i])
    if device_bound:
        # One batched extraction for every large flagged key — not a
        # serial per-key device round-trip.
        recs = check_cycles_layered_device_batch(
            [graphs[i] for i in device_bound]
        )
        for i, r in zip(device_bound, recs):
            out[i] = r
    for i in big:
        out[i] = check_cycles(graphs[i])
    return out
