"""Key-concatenated stream witness checking for many small keys.

The reference checks `jepsen.independent` workloads one key at a time
under a thread pool (/root/reference/jepsen/src/jepsen/independent.clj:
327-377).  Round 4's batched frontier BFS (ops/wgl_batched.py) vmapped
the per-key search, but each key still paid the full frontier machinery
from beam 32 — ~25x per-op slower than the single-history witness
engine on identical hardware (VERDICT r4 'weak' #3).

This module instead feeds ALL keys through the witness engine as ONE
history: per-key packed histories are concatenated on a disjoint
timeline with a synthetic always-legal RESET barrier between keys that
returns the model to its initial state.  The witness sweep then decides
every key in a single device pass — per-key state isolation comes from
three pieces:

  1. **Disjoint timelines**: key i's events occupy event indices
     [seg_i, seg_i + E_i); no cross-key op ever overlaps in real time,
     so no cross-key reordering is even representable.
  2. **RESET barriers**: an ok op with f = F_RESET whose transition is
     (any state) -> init_state, legal from everywhere.  The engine
     treats it like any barrier; every surviving lane steps to
     init_state before the next key's first barrier.
  3. **Rank fencing** (`rank_override` in ops/wgl_witness.py): a key's
     indeterminate ops are given the synthetic barrier rank of their
     key's RESET.  Once that rank passes they are implied/retired —
     they can neither linearize into a later key nor linger in its
     windows.  Within their own key they remain ordinary helper
     candidates, so per-key semantics are exactly those of a
     standalone witness run on that key's subhistory.

A stream verdict of True therefore proves EVERY key linearizable in
one shot — the common case for real workloads.  On failure, the
engine's death rank localizes the first undecidable key: keys wholly
before it are proven (their barriers were all linearized), the dead
key is reported unknown (the caller settles it exactly), and the
stream resumes after it — in SEGMENTS of ~K/8 keys once any key has
died, so each restart re-concatenates O(segment) rows instead of the
whole remainder (invalid-heavy histories pay O(bad * K/segments) host
work, not O(bad * K); see check_wgl_witness_stream).

Throughput: 200 keys x 100 ops decided in one ~10-block device pass
instead of 200 frontier searches — measured ~20x the batched-BFS rate
on the 8-virtual-device CPU suite mesh (tests/test_whole_stack_perf.py
guards the floor).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Optional

import numpy as np

from .. import telemetry
from ..telemetry import profile
from ..history.packed import NO_RET, ST_OK, PackedOps
from ..models.base import PackedModel
from . import degrade
from .wgl import packed_enabled
from .wgl_witness import INF, check_wgl_witness

#: Synthetic f-code for the inter-key reset barrier.  Far above any
#: interner-assigned op code (those are small dense ints), well inside
#: int32.
F_RESET = 1 << 20

_stream_model_cache: dict[tuple, PackedModel] = {}

log = logging.getLogger(__name__)


def stream_model(pm: PackedModel) -> PackedModel:
    """`pm` with every transition function taught the RESET op:
    f == F_RESET maps any state to init_state and is always legal.
    Cached per underlying step functions — a fresh closure per call
    would defeat the witness engine's kernel cache."""
    key = (pm.jax_step, pm.jax_step_rows, tuple(pm.init_state),
           pm.state_width)
    cached = _stream_model_cache.get(key)
    if cached is not None:
        return cached

    import jax.numpy as jnp

    init = tuple(int(v) for v in pm.init_state)
    base_step = pm.jax_step
    base_rows = pm.jax_step_rows
    base_py = pm.py_step

    def jax_step(s, f, a0, a1):
        is_reset = f == F_RESET
        # Clamp f for the base step: a model switching on f must never
        # see the out-of-range synthetic code.
        ns, legal = base_step(s, jnp.where(is_reset, 0, f), a0, a1)
        init_arr = jnp.asarray(init, jnp.int32)
        return (
            jnp.where(is_reset, init_arr, ns),
            jnp.where(is_reset, True, legal),
        )

    jax_step_rows = None
    if base_rows is not None:
        def jax_step_rows(states, f, a0, a1):
            # Lane-major (SW, B); scatter-free (jnp.where only), so the
            # wrap stays Mosaic-safe for the Pallas sweep.
            is_reset = f == F_RESET
            ns, legal = base_rows(states, jnp.where(is_reset, 0, f),
                                  a0, a1)
            init_col = jnp.asarray(init, jnp.int32)[:, None]
            return (
                jnp.where(is_reset, init_col, ns),
                jnp.where(is_reset, jnp.ones_like(legal), legal),
            )

    def py_step(s, f, a0, a1):
        if f == F_RESET:
            return init, True
        return base_py(s, f, a0, a1)

    spm = dataclasses.replace(
        pm,
        name=f"{pm.name}+stream",
        jax_step=jax_step,
        jax_step_rows=jax_step_rows,
        py_step=py_step,
    )
    _stream_model_cache[key] = spm
    return spm


def stream_timeline_len(packs: list[PackedOps]) -> int:
    """The combined timeline length `concat_packs` would produce (an
    exclusive upper bound on every event index): per key, segment
    width E_i (one past the largest event index used) plus 2 for the
    RESET barrier's inv/ret slots.  The witness engine's device
    tables are int32, so a stream past INF must fall back to per-key
    checking (which stays in int64 end to end)."""
    total = 0
    for p in packs:
        if p.n:
            okm = p.status == ST_OK
            e_max = int(p.inv.max())
            if okm.any():
                e_max = max(e_max, int(p.ret[okm].max()))
            total += e_max + 3  # E = e_max + 1, plus the RESET's 2 slots
        else:
            total += 2
    return total


def concat_packs(
    packs: list[PackedOps],
) -> tuple[PackedOps, np.ndarray, np.ndarray]:
    """Concatenates per-key packs onto one disjoint timeline.

    Returns (combined, rank_override, key_of_bar):
      - combined: one PackedOps with a RESET row appended per key;
      - rank_override: (n,) int64, the key's RESET barrier rank for
        its indeterminate rows, -1 elsewhere (see check_wgl_witness);
      - key_of_bar: (n_bars,) int32 mapping global barrier rank ->
        key index (each key contributes its ok rows + its RESET).
    """
    K = len(packs)
    n_rows = sum(p.n for p in packs)
    N = n_rows + K
    inv = np.empty(N, dtype=np.int64)
    ret = np.empty(N, dtype=np.int64)
    process = np.empty(N, dtype=np.int32)
    status = np.empty(N, dtype=np.int32)
    f = np.empty(N, dtype=np.int32)
    a0 = np.zeros(N, dtype=np.int32)
    a1 = np.zeros(N, dtype=np.int32)
    src_index = np.full(N, -1, dtype=np.int64)
    rank_override = np.full(N, -1, dtype=np.int64)
    key_of_bar = np.empty(0, dtype=np.int32)

    kob_parts = []
    seg = 0          # current timeline offset
    row = 0          # current output row
    n_bars_cum = 0   # barriers emitted so far (ok rows + resets)
    for i, p in enumerate(packs):
        n = p.n
        okm = p.status == ST_OK
        n_ok = int(okm.sum())
        if n:
            # Segment width: one past the largest event index used.
            # Gaps (from dropped :fail rows) are harmless — only
            # relative order matters.
            e_max = int(p.inv.max())
            if n_ok:
                e_max = max(e_max, int(p.ret[okm].max()))
            E = e_max + 1
            sl = slice(row, row + n)
            inv[sl] = p.inv + seg
            r = np.where(okm, p.ret + seg, NO_RET)
            ret[sl] = r
            process[sl] = p.process
            status[sl] = p.status
            f[sl] = p.f
            a0[sl] = p.a0
            a1[sl] = p.a1
            src_index[sl] = p.src_index
            # Fence this key's indeterminate ops at its RESET's rank.
            reset_rank = n_bars_cum + n_ok
            rank_override[sl][~okm] = reset_rank
        else:
            E = 0
            reset_rank = n_bars_cum
        # The RESET barrier row.
        j = row + n
        inv[j] = seg + E
        ret[j] = seg + E + 1
        process[j] = -1
        status[j] = ST_OK
        f[j] = F_RESET
        kob_parts.append(np.full(n_ok + 1, i, dtype=np.int32))
        n_bars_cum += n_ok + 1
        seg += E + 2
        row += n + 1

    key_of_bar = (np.concatenate(kob_parts) if kob_parts
                  else np.empty(0, dtype=np.int32))
    combined = PackedOps(
        inv=inv,
        ret=ret,
        process=process,
        status=status,
        f=f,
        a0=a0,
        a1=a1,
        src_index=src_index,
        # Witness-only pack: the BFS's preds/horizon are never read on
        # this path (the stream checker escalates per KEY, not on the
        # combined history).
        preds=np.zeros(N, dtype=np.int64),
        horizon=np.full(N, N - 1, dtype=np.int64),
    )
    return combined, rank_override, key_of_bar


def check_wgl_witness_stream(
    packs: list[PackedOps],
    pm: PackedModel,
    *,
    time_limit_s: Optional[float] = None,
    max_restarts: Optional[int] = None,
    segment_keys: Optional[int] = None,
    **witness_kw: Any,
) -> list[Any]:
    """Per-key verdicts via the concatenated stream: True (proven
    linearizable) or None (witness could not decide — settle exactly).
    Never returns False: like the witness tier itself, failure only
    means escalate.

    Restart cost is bounded by SEGMENTING: the first pass concatenates
    every key (the all-valid common case stays one device pass), but
    once a key dies, the stream resumes in segments of `segment_keys`
    keys (default ~K/8).  A dead key then kills only its segment's
    remainder — each restart re-concatenates and re-plans O(segment)
    rows instead of O(all remaining), so an invalid-heavy history pays
    O(bad * K/segments) host work rather than O(bad * K).  Fixed-size
    segments also share kernel shapes, so the per-restart pass reuses
    the compiled sweep instead of recompiling per remainder length.
    """
    K = len(packs)
    verdicts: list[Any] = [None] * K
    if K == 0:
        return verdicts
    if stream_timeline_len(packs) >= int(INF):
        # The witness engine clamps event indices to int32; a
        # concatenated timeline past INF would wrap on cast (the plan
        # would also raise OverflowError — this precheck just skips
        # building the doomed combined pack).  All-None verdicts send
        # every key to per-key checking, which stays in int64.
        log.info(
            "stream witness: combined timeline exceeds int32; "
            "falling back to per-key checking for %d keys", K,
        )
        return verdicts
    spm = stream_model(pm)
    t0 = time.monotonic()
    if max_restarts is None:
        # Restarts are segment-sized (cheap), so the cap can afford
        # one per bad key up to half the keys; a history where MOST
        # keys defeat the witness should still fall through to the
        # exact engines rather than pay K passes.
        max_restarts = max(8, K // 2)
    seg = max(1, segment_keys) if segment_keys is not None \
        else max(8, -(-K // 8))
    start = 0
    restarts = 0
    passes = 0
    # First pass spans every key; after any death the stream continues
    # segment-sized.
    span = K
    with profile.capture(
        "stream", keys=K, ops=int(stream_timeline_len(packs)),
    ) as _pp, telemetry.span("wgl.stream", keys=K):
        # packed_lanes flows through **witness_kw to the witness
        # engine; the knob is recorded here so stream pass records
        # distinguish packed from wide runs in profiles.jsonl.
        stream_packed = packed_enabled(witness_kw.get("packed_lanes"))
        _pp.knob(segment=seg, max_restarts=max_restarts,
                 packed=stream_packed)
        if stream_packed and telemetry.enabled():
            telemetry.count("wgl.packed.stream-passes")
        while start < K:
            remaining = None
            if time_limit_s is not None:
                remaining = time_limit_s - (time.monotonic() - t0)
                if remaining <= 0:
                    break
            end = min(K, start + span)
            combined, override, key_of_bar = concat_packs(
                packs[start:end]
            )
            info: dict = {}
            passes += 1
            try:
                degrade.maybe_fault("stream")
                r = check_wgl_witness(
                    combined, spm,
                    rank_override=override,
                    out_info=info,
                    time_limit_s=remaining,
                    **witness_kw,
                )
            except Exception as e:  # noqa: BLE001
                if not degrade.is_resource_error(e):
                    raise
                # Degradation ladder: the witness call already retries
                # halved internally, so a resource error surfacing here
                # means the concatenated stream itself is too big —
                # leave the remaining keys None and fall through to the
                # per-key tiers (batched BFS / cohort settle).
                degrade.record("stream", "fall-through", e)
                log.warning(
                    "stream witness exhausted device resources; "
                    "falling through to per-key tiers for %d keys",
                    K - start, exc_info=True,
                )
                break
            if r is not None and r.valid is True:
                for k in range(start, end):
                    verdicts[k] = True
                start = end
                continue
            died = info.get("died_at_rank")
            if died is None:
                break  # budget blown or unlocalized: the rest stay None
            bad = int(key_of_bar[died])
            # Every barrier of keys before the dead one was linearized
            # before the death point: those keys are proven.
            for k in range(bad):
                verdicts[start + k] = True
            start += bad + 1
            span = seg
            restarts += 1
            if restarts >= max_restarts:
                log.info(
                    "stream witness: %d restarts (max %d); %d keys left "
                    "for the exact engines", restarts, max_restarts,
                    K - start,
                )
                break
        _pp.feature(restarts=restarts, passes=passes)
        _pp.outcome = {
            "proven": sum(1 for v in verdicts if v is True),
            "escalated": sum(1 for v in verdicts if v is None),
        }
    if telemetry.enabled():
        telemetry.count("wgl.stream.keys-proven",
                        sum(1 for v in verdicts if v is True))
        telemetry.count("wgl.stream.restarts", restarts)
        telemetry.count("wgl.stream.passes", passes)
    return verdicts
