"""Batched per-key Wing–Gong–Lowe search over a TPU mesh.

This is the TPU-native re-design of `jepsen.independent`'s checker
(/root/reference/jepsen/src/jepsen/independent.clj:327-377): where the
reference runs knossos once per key under a `bounded-pmap` of JVM
threads, here every key's search *is the batch axis* — K independent
histories are padded to a common shape, the WGL frontier search runs
vmapped over keys on one device, and `shard_map` splits the key axis
across the mesh so each device advances its own keys with no
cross-device chatter (per-key searches are embarrassingly parallel; the
collectives-free inner loop rides entirely in VMEM/HBM).

Unlike ops/wgl.py (single giant history, windowed frontier), per-key
histories are short by construction — the reference bounds them
precisely because knossos explodes otherwise
(tests/linearizable_register.clj:39-53) — so the whole history fits in
the member bitset and no windowing is needed.

Soundness contract (same as ops/wgl.py): `accepted` verdicts are always
sound (a witness linearization was found).  `invalid` is only reported
when the search was exact (no beam/candidate overflow); overflow
degrades to "unknown", which the host settles with the exact CPU search.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np

from .. import telemetry
from ..telemetry import profile, roofline
from ..history.packed import ST_OK, PackedOps
from ..models.base import PackedModel
from . import degrade, packing
from .wgl import packed_enabled

INF = np.int32(2**31 - 1)

_kernel_cache: dict[tuple, Any] = {}


def _hash_vectors(n: int, sw: int, seed: int = 0x5EED) -> tuple[np.ndarray, ...]:
    rng = np.random.default_rng(seed)
    return (
        rng.uniform(1.0, 2.0, size=(n,)).astype(np.float32),
        rng.uniform(1.0, 2.0, size=(n,)).astype(np.float32),
        rng.uniform(1.0, 2.0, size=(sw,)).astype(np.float32),
        rng.uniform(1.0, 2.0, size=(sw,)).astype(np.float32),
    )


def _bucket(x: int, lo: int = 32) -> int:
    w = lo
    while w < x:
        w *= 2
    return w


@dataclass
class BatchedPack:
    """K per-key histories padded to a common (K, N) table."""

    ret: np.ndarray  # (K, N) int32, INF for info/padding
    inv: np.ndarray  # (K, N) int32, INF for padding
    f: np.ndarray    # (K, N) int32
    a0: np.ndarray   # (K, N) int32
    a1: np.ndarray   # (K, N) int32
    okv: np.ndarray  # (K, N) bool
    n_ops: np.ndarray  # (K,) int32 live op count per key
    keys: list = field(default_factory=list)

    @property
    def K(self) -> int:
        return int(self.ret.shape[0])

    @property
    def N(self) -> int:
        return int(self.ret.shape[1])


def pack_batch(packs: list[PackedOps], pad_keys_to: Optional[int] = None) -> BatchedPack:
    """Stacks per-key PackedOps into padded (K, N) arrays.  Padding ops
    have inv = ret = INF so they are never order-legal candidates and
    never block anyone; padding *keys* (to fill a mesh) have n_ops = 0
    and accept immediately."""
    K = len(packs)
    Kp = pad_keys_to if pad_keys_to and pad_keys_to > K else K
    N = _bucket(max((p.n for p in packs), default=1))
    ret = np.full((Kp, N), INF, dtype=np.int32)
    inv = np.full((Kp, N), INF, dtype=np.int32)
    f = np.zeros((Kp, N), dtype=np.int32)
    a0 = np.zeros((Kp, N), dtype=np.int32)
    a1 = np.zeros((Kp, N), dtype=np.int32)
    okv = np.zeros((Kp, N), dtype=bool)
    n_ops = np.zeros(Kp, dtype=np.int32)
    for k, p in enumerate(packs):
        n = p.n
        n_ops[k] = n
        if n == 0:
            continue
        inv[k, :n] = p.inv.astype(np.int64).clip(max=int(INF) - 1)
        ret[k, :n] = p.ret.clip(max=int(INF)).astype(np.int64)
        f[k, :n] = p.f
        a0[k, :n] = p.a0
        a1[k, :n] = p.a1
        okv[k, :n] = p.status == ST_OK
    return BatchedPack(ret=ret, inv=inv, f=f, a0=a0, a1=a1, okv=okv, n_ops=n_ops)


def _make_key_fn(B: int, N: int, SW: int, Cmax: int, jax_step,
                 packed: bool = False):
    """One key's full frontier search: (tables…) -> (accepted, alive_end,
    incomplete, explored).  vmap'd over the key axis by the caller.

    With `packed`, the member/child bitsets ride as ceil(N/32) uint32
    lanes between levels (ops/packing.py): word-OR children, packed
    cover test, wrapping-uint32 dedup hashes.  Under the caller's vmap
    the level advances every key's frontier in one dispatch, so the
    unpack + candidate rule is one (K*B, N) operand and the dedup hash
    one (K*Cmax, Np) integer contraction — the batched, matmul-shaped
    step the wide engine only approximates with bool tensors."""
    import jax
    import jax.numpy as jnp

    if packed:
        Np = packing.n_words(N)
        hw1 = jnp.asarray(packing.hash_consts(Np, 0))
        hw2 = jnp.asarray(packing.hash_consts(Np, 1))
        shw1 = jnp.asarray(packing.hash_consts(SW, 2))
        shw2 = jnp.asarray(packing.hash_consts(SW, 3))
    else:
        h1v, h2v, sh1v, sh2v = (
            jnp.asarray(v) for v in _hash_vectors(N, SW)
        )

    def level_step(carry, tables):
        member, states, alive, accepted, incomplete, explored, it = carry
        ret, inv, f, a0, a1, okv, init_state, n_ops = tables
        member_w = member
        if packed:
            member = packing.unpack_bits(member_w, N)

        # Candidate rule: a non-member a may be linearized next iff
        # inv(a) < min ret over the *other* non-members — two masked
        # min-reductions per config (see ops/wgl.py).
        nm_ret = jnp.where(member | ~alive[:, None], INF, ret[None, :])  # (B, N)
        m1 = nm_ret.min(axis=1)
        am1 = jnp.argmin(nm_ret, axis=1)
        nm_ret2 = nm_ret.at[jnp.arange(B), am1].set(INF)
        m2 = nm_ret2.min(axis=1)
        bound = jnp.where(
            jnp.arange(N)[None, :] == am1[:, None], m2[:, None], m1[:, None]
        )
        order_ok = (~member) & alive[:, None] & (inv[None, :] < bound)

        # Compact candidate (config, op) pairs.
        flat = order_ok.reshape(-1)
        count = flat.sum()
        cand_idx = jnp.nonzero(flat, size=Cmax, fill_value=0)[0]
        valid_c = jnp.arange(Cmax) < count
        incomplete = incomplete | (count > Cmax)
        parent = cand_idx // N
        a = cand_idx % N

        # Model transition over survivors.
        new_states, legal = jax.vmap(jax_step)(states[parent], f[a], a0[a], a1[a])
        live_c = valid_c & legal
        if packed:
            # Packed child: word-OR the parent lanes + one hot bit;
            # cover test and dedup hashes run on the uint32 words
            # (okv arrives pre-packed from key_fn).
            child = packing.set_bit(member_w[parent], a)
            cover = packing.covers(child, okv)
            accepted = accepted | jnp.any(live_c & cover)
            su = packing.as_u32(new_states)
            dead = jnp.uint32(0xFFFFFFFF)
            h1 = jnp.where(
                live_c,
                packing.hash_words(child, hw1)
                + packing.hash_words(su, shw1),
                dead,
            )
            h2 = jnp.where(
                live_c,
                packing.hash_words(child, hw2)
                + packing.hash_words(su, shw2),
                dead,
            )
        else:
            child = member[parent].at[jnp.arange(Cmax), a].set(True)

            # Accept when some live child covers every :ok op.
            cover = (child | ~okv[None, :]).all(axis=1)
            accepted = accepted | jnp.any(live_c & cover)

            # Dedup via float-hash sort + exact adjacent compare.
            cf = child.astype(jnp.float32)
            sf = new_states.astype(jnp.float32)
            big = jnp.float32(3.0e38)
            h1 = jnp.where(live_c, cf @ h1v + sf @ sh1v, big)
            h2 = jnp.where(live_c, cf @ h2v + sf @ sh2v, big)
        h1s, h2s, perm = jax.lax.sort((h1, h2, jnp.arange(Cmax)), num_keys=2)
        child_s = child[perm]
        states_s = new_states[perm]
        live_s = live_c[perm]
        same_h = (h1s == jnp.roll(h1s, 1)) & (h2s == jnp.roll(h2s, 1))
        same_h = same_h.at[0].set(False)
        same_full = (
            same_h
            & (child_s == jnp.roll(child_s, 1, axis=0)).all(axis=1)
            & (states_s == jnp.roll(states_s, 1, axis=0)).all(axis=1)
        )
        uniq = live_s & ~same_full
        n_uniq = uniq.sum()
        incomplete = incomplete | (n_uniq > B)

        sel = jnp.nonzero(uniq, size=B, fill_value=0)[0]
        new_alive = jnp.arange(B) < jnp.minimum(n_uniq, B)
        return (
            child_s[sel],
            states_s[sel],
            new_alive,
            accepted,
            incomplete,
            explored + jnp.minimum(n_uniq, B),
            it + 1,
        )

    def key_fn(ret, inv, f, a0, a1, okv, init_state, n_ops):
        if packed:
            member0 = jnp.zeros((B, Np), dtype=jnp.uint32)
        else:
            member0 = jnp.zeros((B, N), dtype=bool)
        states0 = jnp.tile(init_state[None, :], (B, 1))
        alive0 = jnp.arange(B) < 1
        accepted0 = ~okv.any()
        ok_t = packing.pack_bits(okv, Np) if packed else okv
        tables = (ret, inv, f, a0, a1, ok_t, init_state, n_ops)

        def cond(carry):
            _, _, alive, accepted, _, _, it = carry
            return (~accepted) & jnp.any(alive) & (it < n_ops)

        def body(carry):
            return level_step(carry, tables)

        carry = (
            member0,
            states0,
            alive0,
            accepted0,
            jnp.bool_(False),
            jnp.int32(0),
            jnp.int32(0),
        )
        member, states, alive, accepted, incomplete, explored, it = (
            jax.lax.while_loop(cond, body, carry)
        )
        return accepted, jnp.any(alive), incomplete, explored

    return key_fn


def _get_kernel(B: int, N: int, SW: int, Cmax: int, jax_step, mesh=None,
                packed: bool = False):
    """The jitted batched kernel: vmap over keys, shard_map over the mesh
    'keys' axis when a mesh is given (each device runs its slice of keys
    independently — no collectives in the hot loop)."""
    import jax

    # Strong-reference keys: id() collides after GC address reuse.
    key = (B, N, SW, Cmax, jax_step, mesh, packed)
    fn = _kernel_cache.get(key)
    if fn is not None:
        return fn

    key_fn = _make_key_fn(B, N, SW, Cmax, jax_step, packed=packed)
    batched = jax.vmap(key_fn, in_axes=(0, 0, 0, 0, 0, 0, None, 0))
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import shard_map_compat

        shard_map, rep_kw = shard_map_compat()

        pk = P("keys")
        in_specs = (pk, pk, pk, pk, pk, pk, P(None), pk)
        out_specs = (pk, pk, pk, pk)
        batched = shard_map(
            batched, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            **rep_kw,
        )
    fn = roofline.instrument(jax.jit(batched))
    _kernel_cache[key] = fn
    return fn


@dataclass
class BatchedWGLResult:
    #: per-key verdicts: True | False | "unknown" (pre-CPU-fallback)
    valid: list
    explored: np.ndarray
    elapsed_s: float
    beam_used: int


def check_wgl_batched(
    packs: list[PackedOps],
    pm: PackedModel,
    *,
    beam: int = 256,
    max_beam: int = 16384,
    cand_factor: int = 4,
    mesh=None,
    time_limit_s: Optional[float] = None,
    packed_lanes: Optional[bool] = None,
) -> BatchedWGLResult:
    """Runs the WGL search for every key at once on device.  Keys whose
    search overflowed the beam are retried together with a doubled beam;
    at max_beam survivors report "unknown" (the caller settles them on
    CPU).  The time limit is checked between beam-retry rounds (the
    device block itself is uninterruptible); unsettled keys at the
    deadline report "unknown"."""
    import jax.numpy as jnp

    t0 = time.monotonic()
    K = len(packs)
    n_dev = math.prod(mesh.devices.shape) if mesh is not None else 1
    pad_keys = max(K, n_dev) if mesh is None else n_dev * math.ceil(K / n_dev)
    bp = pack_batch(packs, pad_keys_to=pad_keys)
    SW = pm.state_width
    init_state = np.asarray(pm.init_state, dtype=np.int32)

    verdict: list[Any] = [None] * K
    explored = np.zeros(K, dtype=np.int64)
    todo = list(range(K))
    B = _bucket(beam, lo=32)
    packed_on = packed_enabled(packed_lanes)
    batch_retried = False  # one halved-beam retry on resource errors

    # One cost record per batched pass: shape features, the beam plan,
    # and the compile/execute split folded in from the span hook.
    with profile.capture(
        "batched", keys=K, ops=int(sum(p.n for p in packs)),
    ) as _pb:
        _pb.knob(beam=B, max_beam=int(max_beam),
                 cand_factor=int(cand_factor), mesh=mesh is not None,
                 packed=packed_on)
        while todo:
            if mesh is not None:
                pad_t = n_dev * math.ceil(len(todo) / n_dev)
            else:
                pad_t = len(todo)
            sel = np.asarray(todo + [todo[0]] * (pad_t - len(todo)))
            # jax.jit is lazy: a cache-miss kernel pays trace+compile inside
            # its first call, so the span name splits compile vs execute
            # exactly like the witness/BFS tiers (the phase profile and the
            # per-pass cost record both read this convention).
            fresh_fn = (B, bp.N, SW, cand_factor * B, pm.jax_step,
                        mesh, packed_on) not in _kernel_cache
            fn = _get_kernel(B, bp.N, SW, cand_factor * B, pm.jax_step,
                             mesh, packed=packed_on)
            if packed_on and telemetry.enabled():
                telemetry.count("wgl.packed.batched-rounds")
            sp = telemetry.span(
                "wgl.batched.compile" if fresh_fn else "wgl.batched.block",
                keys=len(todo), beam=B,
            ) if telemetry.enabled() else telemetry.span("")
            try:
                degrade.maybe_fault("batched")
                with sp:
                    acc, alive_end, inc, expl = fn(
                        jnp.asarray(bp.ret[sel]),
                        jnp.asarray(bp.inv[sel]),
                        jnp.asarray(bp.f[sel]),
                        jnp.asarray(bp.a0[sel]),
                        jnp.asarray(bp.a1[sel]),
                        jnp.asarray(bp.okv[sel]),
                        jnp.asarray(init_state),
                        jnp.asarray(bp.n_ops[sel]),
                    )
                    # The host transfers stay inside the try: jitted
                    # dispatch is asynchronous, so execution failures raise
                    # at consumption.
                    acc = np.asarray(acc)
                    alive_end = np.asarray(alive_end)
                    inc = np.asarray(inc)
                    expl = np.asarray(expl)
            except Exception as e:  # noqa: BLE001
                if not degrade.is_resource_error(e):
                    raise
                # Degradation ladder: evict the compiled kernel, retry ONCE
                # with a halved beam (and cap the overflow ladder there so
                # it can't climb back into the OOM region); a second
                # failure hands every unsettled key to the CPU settle.
                _kernel_cache.pop(
                    (B, bp.N, SW, cand_factor * B, pm.jax_step, mesh,
                     packed_on), None
                )
                if packed_on:
                    # First rung: shed the packed lanes at the SAME beam
                    # before surrendering any width (see ops/wgl.py).
                    packed_on = False
                    degrade.record("batched", "packed-fallback", e)
                    telemetry.count("wgl.packed.fallbacks")
                    continue
                if batch_retried or B <= 32:
                    degrade.record("batched", "fall-through", e)
                    for k in todo:
                        verdict[k] = "unknown"
                    todo = []
                    continue
                batch_retried = True
                degrade.record("batched", "retry-halved", e)
                B //= 2
                max_beam = min(max_beam, B)
                continue

            retry = []
            for i, k in enumerate(todo):
                explored[k] += int(expl[i])
                if acc[i]:
                    verdict[k] = True
                elif inc[i]:
                    # Inexact (beam/candidate overflow): a wider beam can
                    # genuinely settle it.
                    if B < max_beam:
                        retry.append(k)
                    else:
                        verdict[k] = "unknown"
                elif alive_end[i]:
                    # Defensive guard: an exact search ended with a live
                    # frontier but no acceptance, which shouldn't happen —
                    # re-running with a wider beam can't change an exact
                    # outcome, so don't ride the ladder (round-1 weak #5:
                    # each rung recompiles); report unknown for the CPU
                    # fallback to settle.
                    verdict[k] = "unknown"
                else:
                    verdict[k] = False  # exact search exhausted: invalid
            todo = retry
            if todo:
                if time_limit_s is not None and time.monotonic() - t0 > time_limit_s:
                    for k in todo:
                        verdict[k] = "unknown"
                    todo = []
                else:
                    B *= 2

        _pb.outcome = {
            "proven": sum(1 for v in verdict if v is True),
            "refuted": sum(1 for v in verdict if v is False),
            "unknown": sum(1 for v in verdict if v == "unknown"),
        }
        _pb.degraded = batch_retried or None
    if telemetry.enabled():
        # Tier populations for the cohort-settle ladder: an exact False
        # here is a device REFUTATION the settle tier can accept
        # without an exhaustive CPU search (soundness contract above).
        telemetry.count("wgl.batched.keys", K)
        telemetry.count("wgl.batched.proven",
                        sum(1 for v in verdict if v is True))
        telemetry.count("wgl.batched.refuted",
                        sum(1 for v in verdict if v is False))
        telemetry.count("wgl.batched.unknown",
                        sum(1 for v in verdict if v == "unknown"))
    return BatchedWGLResult(
        valid=verdict,
        explored=explored,
        elapsed_s=time.monotonic() - t0,
        beam_used=B,
    )
