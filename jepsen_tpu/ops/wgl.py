"""Device (TPU) Wing–Gong–Lowe linearizability search.

The BASELINE.json north star: knossos's sequential WGL DFS becomes a
batched breadth-first frontier search over configurations, JIT-compiled
and vmapped on device.  See checker/wgl_cpu.py for the shared formulation;
this module is the SIMD re-design, not a port (SURVEY.md §7 stage 3):

* BFS by linearized-count level: every frontier config has |S| = n, so the
  member-set needs bits only for the *active window* — ops that are
  neither guaranteed-members (horizon < n, must be linearized by level n
  in any valid prefix) nor guaranteed-non-members (preds ≥ n + K, can't be
  linearized within this block of K levels).  The window is recomputed on
  host every K levels and the frontier re-gathered; window size tracks the
  history's concurrency + accumulated indeterminate (:info) ops, not its
  length.
* The candidate rule (op a appendable iff inv(a) < min ret over other
  non-members) becomes two masked min-reductions per config — no per-op
  predecessor masks, no (B, W, W) intermediates.
* Candidate (config, op) pairs are compacted with a static-size nonzero,
  the model transition (models/base.py jax_step) is vmapped over the
  survivors, and children are deduplicated by float-hash sort + exact
  adjacent compare — equal configs always hash equal, so dedup is exact;
  hash collisions only cost beam slots.
* Beam/candidate overflow is detected on device; the host retries the
  block with a doubled beam (frontier state is re-gathered from the block
  start), so completeness is only surrendered at max_beam, where the
  verdict degrades from invalid to :unknown (valid stays sound).

Per-key independent histories batch along a leading axis and shard across
the TPU mesh (parallel/independent.py), turning `jepsen.independent`'s
bounded-pmap (independent.clj:327-377) into data parallelism over devices.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Optional

import numpy as np

from .. import telemetry
from ..telemetry import profile, roofline
from ..checker.wgl_cpu import WGLResult
from ..history.packed import ST_OK, PackedOps
from ..models.base import PackedModel
from . import degrade, packing

INF = np.int32(2**31 - 1)

#: JEPSEN_WGL_PACKED=0 disables the uint32 bit-packed member lanes and
#: falls back to the wide bool (B, W) tensors everywhere.
PACKED_ENV = "JEPSEN_WGL_PACKED"

_block_fn_cache: dict[tuple, Any] = {}


def packed_enabled(packed_lanes: Optional[bool] = None) -> bool:
    """Resolve the packed-lane switch: explicit arg wins, then the
    JEPSEN_WGL_PACKED env (default on)."""
    import os

    if packed_lanes is not None:
        return bool(packed_lanes)
    return os.environ.get(PACKED_ENV, "1") not in ("0", "false", "off")


def _hash_vectors(w: int, sw: int, seed: int = 0x5EED) -> tuple[np.ndarray, ...]:
    rng = np.random.default_rng(seed)
    return (
        rng.uniform(1.0, 2.0, size=(w,)).astype(np.float32),
        rng.uniform(1.0, 2.0, size=(w,)).astype(np.float32),
        rng.uniform(1.0, 2.0, size=(sw,)).astype(np.float32),
        rng.uniform(1.0, 2.0, size=(sw,)).astype(np.float32),
    )



def _expand_level(member, states, alive, tables, n_rows, n_slots,
                  jax_step):
    """One frontier level's expansion, shared by the single-device and
    frontier-sharded block fns: candidate rule (two masked
    min-reductions per config), static-size compaction, vmapped model
    step, child bitsets, acceptance and dedup hashes.  `n_rows` is the
    (local) frontier height, `n_slots` the (local) candidate budget.

    Returns (child, new_states, live_c, h1, h2, accepted_any,
    overflow)."""
    import jax
    import jax.numpy as jnp

    (ret_w, inv_w, f_w, a0_w, a1_w, ok_w, fmin1, f_has_ok,
     h1v, h2v, sh1v, sh2v) = tables
    W = ret_w.shape[0]

    # --- candidate rule ---------------------------------------------
    nm_ret = jnp.where(member | ~alive[:, None], INF, ret_w[None, :])
    m1w = nm_ret.min(axis=1)
    am1 = jnp.argmin(nm_ret, axis=1)
    nm_ret2 = nm_ret.at[jnp.arange(n_rows), am1].set(INF)
    m2w = nm_ret2.min(axis=1)
    # Merge with the (host-precomputed) min over "future" ops outside
    # the window — they are non-members of every config.
    is_w_min = m1w <= fmin1
    total_m1 = jnp.minimum(m1w, fmin1)
    second_for_argmin = jnp.minimum(m2w, fmin1)
    bound = jnp.where(
        (jnp.arange(W)[None, :] == am1[:, None]) & is_w_min[:, None],
        second_for_argmin[:, None],
        total_m1[:, None],
    )
    order_ok = (~member) & alive[:, None] & (inv_w[None, :] < bound)

    # --- compact candidate (config, op) pairs ------------------------
    flat = order_ok.reshape(-1)
    count = flat.sum()
    cand_idx = jnp.nonzero(flat, size=n_slots, fill_value=0)[0]
    valid_c = jnp.arange(n_slots) < count
    overflow = count > n_slots
    parent = cand_idx // W
    a = cand_idx % W

    # --- model transition, vmapped over survivors only ---------------
    new_states, legal = jax.vmap(jax_step)(
        states[parent], f_w[a], a0_w[a], a1_w[a]
    )
    live_c = valid_c & legal

    child = member[parent]
    child = child.at[jnp.arange(n_slots), a].set(True)

    # --- acceptance: some live child covers every :ok op -------------
    cover = (child | ~ok_w[None, :]).all(axis=1)
    accepted_any = jnp.any(live_c & cover & ~f_has_ok)

    # --- dedup hashes ------------------------------------------------
    cf = child.astype(jnp.float32)
    sf = new_states.astype(jnp.float32)
    big = jnp.float32(3.0e38)
    h1 = jnp.where(live_c, cf @ h1v + sf @ sh1v, big)
    h2 = jnp.where(live_c, cf @ h2v + sf @ sh2v, big)
    return child, new_states, live_c, h1, h2, accepted_any, overflow


def _expand_level_packed(member_w, states, alive, tables, n_rows,
                         n_slots, jax_step):
    """Bit-packed twin of _expand_level: the frontier member sets ride
    as uint32 lanes (W bools -> ceil(W/32) words), children are built
    with word-OR + one hot bit, acceptance is a packed cover test, and
    the dedup hashes are wrapping uint32 multiply-adds over the words.
    The candidate rule still needs per-slot ints, so the member bits
    are unpacked once per level — everything carried between levels
    (and gathered over ICI in the sharded path) stays packed."""
    import jax
    import jax.numpy as jnp

    (ret_w, inv_w, f_w, a0_w, a1_w, ok_words, fmin1, f_has_ok,
     hw1, hw2, shw1, shw2) = tables
    W = ret_w.shape[0]
    member = packing.unpack_bits(member_w, W)

    # --- candidate rule (identical to the wide engine) ---------------
    nm_ret = jnp.where(member | ~alive[:, None], INF, ret_w[None, :])
    m1w = nm_ret.min(axis=1)
    am1 = jnp.argmin(nm_ret, axis=1)
    nm_ret2 = nm_ret.at[jnp.arange(n_rows), am1].set(INF)
    m2w = nm_ret2.min(axis=1)
    is_w_min = m1w <= fmin1
    total_m1 = jnp.minimum(m1w, fmin1)
    second_for_argmin = jnp.minimum(m2w, fmin1)
    bound = jnp.where(
        (jnp.arange(W)[None, :] == am1[:, None]) & is_w_min[:, None],
        second_for_argmin[:, None],
        total_m1[:, None],
    )
    order_ok = (~member) & alive[:, None] & (inv_w[None, :] < bound)

    flat = order_ok.reshape(-1)
    count = flat.sum()
    cand_idx = jnp.nonzero(flat, size=n_slots, fill_value=0)[0]
    valid_c = jnp.arange(n_slots) < count
    overflow = count > n_slots
    parent = cand_idx // W
    a = cand_idx % W

    new_states, legal = jax.vmap(jax_step)(
        states[parent], f_w[a], a0_w[a], a1_w[a]
    )
    live_c = valid_c & legal

    child_w = packing.set_bit(member_w[parent], a)

    # --- acceptance: packed cover over the ok-mask words -------------
    cover = packing.covers(child_w, ok_words)
    accepted_any = jnp.any(live_c & cover & ~f_has_ok)

    # --- dedup hashes: uint32 wrap-sum over words + states -----------
    su = packing.as_u32(new_states)
    dead = jnp.uint32(0xFFFFFFFF)
    h1 = jnp.where(
        live_c,
        packing.hash_words(child_w, hw1) + packing.hash_words(su, shw1),
        dead,
    )
    h2 = jnp.where(
        live_c,
        packing.hash_words(child_w, hw2) + packing.hash_words(su, shw2),
        dead,
    )
    return child_w, new_states, live_c, h1, h2, accepted_any, overflow


def _dedup_sort(child, new_states, live_c, h1, h2, n_slots):
    """Hash-sort + exact adjacent compare over candidates: equal
    configs always hash equal, so dedup is exact; collisions only cost
    slots.  Returns (child_s, states_s, uniq, n_uniq) in sort order."""
    import jax
    import jax.numpy as jnp

    h1s, h2s, perm = jax.lax.sort(
        (h1, h2, jnp.arange(n_slots)), num_keys=2
    )
    child_s = child[perm]
    states_s = new_states[perm]
    live_s = live_c[perm]
    same_h = (h1s == jnp.roll(h1s, 1)) & (h2s == jnp.roll(h2s, 1))
    same_h = same_h.at[0].set(False)
    same_full = (
        same_h
        & (child_s == jnp.roll(child_s, 1, axis=0)).all(axis=1)
        & (states_s == jnp.roll(states_s, 1, axis=0)).all(axis=1)
    )
    uniq = live_s & ~same_full
    return child_s, states_s, uniq, uniq.sum()


def _make_block_fn(B: int, W: int, SW: int, Cmax: int, jax_step,
                   packed: bool = False):
    """Builds the jitted block runner for static shapes (B, W, SW, Cmax).

    Carry: member (B, W) bool — or (B, ceil(W/32)) uint32 when
    `packed` — states (B, SW) i32, alive (B,) bool, accepted,
    incomplete (bool), explored (i32), it (i32).
    """
    import jax
    import jax.numpy as jnp

    expand = _expand_level_packed if packed else _expand_level

    def level_step(carry, tables):
        member, states, alive, accepted, incomplete, explored, it = carry
        child, new_states, live_c, h1, h2, acc, overflow = expand(
            member, states, alive, tables, B, Cmax, jax_step
        )
        accepted = accepted | acc
        incomplete = incomplete | overflow
        child_s, states_s, uniq, n_uniq = _dedup_sort(
            child, new_states, live_c, h1, h2, Cmax
        )
        incomplete = incomplete | (n_uniq > B)

        # --- select the next frontier ------------------------------------
        sel = jnp.nonzero(uniq, size=B, fill_value=0)[0]
        new_alive = jnp.arange(B) < jnp.minimum(n_uniq, B)
        new_member = child_s[sel]
        new_states_f = states_s[sel]
        explored = explored + jnp.minimum(n_uniq, B)
        return (
            new_member,
            new_states_f,
            new_alive,
            accepted,
            incomplete,
            explored,
            it + 1,
        )

    def block(member, states, alive, iters, *tables):
        def cond(carry):
            _, _, alive, accepted, _, _, it = carry
            return (~accepted) & jnp.any(alive) & (it < iters)

        def body(carry):
            return level_step(carry, tables)

        carry = (
            member,
            states,
            alive,
            jnp.bool_(False),
            jnp.bool_(False),
            jnp.int32(0),
            jnp.int32(0),
        )
        return jax.lax.while_loop(cond, body, carry)

    return roofline.instrument(jax.jit(block))


def _make_block_fn_sharded(B: int, W: int, SW: int, Cmax: int, jax_step,
                           mesh, packed: bool = False):
    """Frontier-sharded variant of _make_block_fn: ONE search's beam
    splits across the mesh (the within-search axis SURVEY.md §5 frames
    as the ring-attention analog — parallelism over the configuration
    frontier rather than over sequence position).

    Layout per level: the B frontier rows and their candidate
    expansion (the FLOP-heavy part: candidate rule over (B, W),
    Cmax model steps, (Cmax, W) child bitsets) are sharded B/n per
    device; candidates then `all_gather` over ICI (hashes + bitsets +
    states) and the small global dedup-sort runs replicated, after
    which each device keeps its B/n slice of the new frontier.
    Verdict-relevant scalars (accepted / incomplete / n_alive) are
    globalized with `psum`, so control flow stays identical on every
    device.  Verdicts match the single-device search exactly; the one
    behavioral difference is overflow detection — candidate compaction
    is per-shard (Cmax/n slots each), so a lopsided level can trip the
    (sound) beam-retry/unknown path where the global compactor would
    not."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import shard_map_compat

    shard_map, rep_kw = shard_map_compat()

    axis = mesh.axis_names[0]
    n = mesh.devices.size
    assert B % n == 0 and Cmax % n == 0, (B, Cmax, n)
    B_l = B // n
    C_l = Cmax // n
    expand = _expand_level_packed if packed else _expand_level

    def level_step(carry, tables):
        (member, states, alive, accepted, incomplete, explored, it,
         n_alive) = carry

        # --- expansion on the LOCAL frontier rows -----------------------
        # With packed lanes the all_gather below moves uint32 words —
        # 8x fewer ICI bytes per candidate bitset than the bool rows.
        child, new_states, live_c, h1, h2, acc_local, local_overflow = (
            expand(
                member, states, alive, tables, B_l, C_l, jax_step
            )
        )

        # --- globalize: gather candidates, psum flags -------------------
        def gather(x):
            return jax.lax.all_gather(x, axis).reshape(
                (Cmax,) + x.shape[1:]
            )

        child_g = gather(child)
        states_g = gather(new_states)
        live_g = gather(live_c)
        h1_g = gather(h1)
        h2_g = gather(h2)
        accepted = accepted | (
            jax.lax.psum(acc_local.astype(jnp.int32), axis) > 0
        )
        incomplete = incomplete | (
            jax.lax.psum(local_overflow.astype(jnp.int32), axis) > 0
        )

        # --- replicated dedup-sort over the gathered candidates ---------
        child_s, states_s, uniq, n_uniq = _dedup_sort(
            child_g, states_g, live_g, h1_g, h2_g, Cmax
        )
        incomplete = incomplete | (n_uniq > B)

        # --- each device keeps its slice of the new frontier ------------
        sel = jnp.nonzero(uniq, size=B, fill_value=0)[0]
        d = jax.lax.axis_index(axis)
        sel_l = jax.lax.dynamic_slice_in_dim(sel, d * B_l, B_l)
        n_alive = jnp.minimum(n_uniq, B)
        new_alive = (jnp.arange(B_l) + d * B_l) < n_alive
        new_member = child_s[sel_l]
        new_states_f = states_s[sel_l]
        explored = explored + n_alive
        return (
            new_member, new_states_f, new_alive,
            accepted, incomplete, explored, it + 1, n_alive,
        )

    def block_local(member, states, alive, iters, *tables):
        def cond(carry):
            _, _, _, accepted, _, _, it, n_alive = carry
            return (~accepted) & (n_alive > 0) & (it < iters)

        def body(carry):
            return level_step(carry, tables)

        n_alive0 = jax.lax.psum(alive.sum(), axis)
        carry = (
            member, states, alive,
            jnp.bool_(False), jnp.bool_(False),
            jnp.int32(0), jnp.int32(0), n_alive0,
        )
        out = jax.lax.while_loop(cond, body, carry)
        return out[:7]  # drop the internal n_alive

    pb = P(axis)
    pr = P()
    sharded = shard_map(
        block_local, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), pb, pr) + (pr,) * 12,
        out_specs=(P(axis, None), P(axis, None), pb, pr, pr, pr, pr),
        **rep_kw,
    )
    return roofline.instrument(jax.jit(sharded))


def _bucket(x: int, lo: int = 256) -> int:
    w = lo
    while w < x:
        w *= 2
    return w


def window_regather(prev_active: np.ndarray, active: np.ndarray):
    """(perm, present) mapping a new window layout onto the previous
    one: new column j reads old column perm[j] where present[j].  Shared
    by the BFS and witness paths so boundary handling stays in one
    place."""
    pos = np.searchsorted(prev_active, active)
    pos_clip = np.clip(pos, 0, len(prev_active) - 1)
    present = (pos < len(prev_active)) & (prev_active[pos_clip] == active)
    perm = np.where(present, pos_clip, 0)
    return perm, present


def _window_tables(packed: PackedOps, n0: int, K: int, max_window: int):
    """Host-side window computation for levels [n0, n0+K)."""
    preds = packed.preds
    horizon = packed.horizon
    active = np.nonzero((preds < n0 + K) & (horizon >= n0))[0]
    if len(active) > max_window:
        return None  # window overflow
    future = np.nonzero(preds >= n0 + K)[0]
    ret = np.minimum(packed.ret, np.int64(INF)).astype(np.int32)
    if len(future):
        fr = np.sort(ret[future])
        fmin1 = np.int32(fr[0])
        f_has_ok = bool((packed.status[future] == ST_OK).any())
    else:
        fmin1 = INF
        f_has_ok = False
    W = _bucket(max(len(active), 1))
    pad = W - len(active)

    def pad_to(arr, fill):
        return np.concatenate([arr, np.full(pad, fill, dtype=arr.dtype)])

    tables = dict(
        ret_w=pad_to(ret[active], INF),
        inv_w=pad_to(packed.inv[active].astype(np.int32), INF),
        f_w=pad_to(packed.f[active], 0),
        a0_w=pad_to(packed.a0[active], 0),
        a1_w=pad_to(packed.a1[active], 0),
        ok_w=pad_to(packed.status[active] == ST_OK, False),
        fmin1=fmin1,
        f_has_ok=np.bool_(f_has_ok),
    )
    return active, W, tables


def check_wgl_device(
    packed: PackedOps,
    pm: PackedModel,
    *,
    beam: int = 1024,
    max_beam: int = 4096,
    block: int = 256,
    cand_factor: int = 4,
    max_window: int = 16384,
    time_limit_s: Optional[float] = None,
    witness: bool = True,
    width_hint: int = 0,
    mesh: Any = None,
    checkpoint_dir: Optional[str] = None,
    packed_lanes: Optional[bool] = None,
) -> WGLResult:
    """Decides linearizability of one packed history on the default JAX
    device.

    Two tiers: first the just-in-time witness search
    (ops/wgl_witness.py) — exact for valid verdicts and immune to the
    high-:info frontier explosion; if it finds no witness, the exhaustive
    frontier BFS below settles invalid.  The BFS is exact until
    `max_beam`/`max_window` overflow, after which invalid degrades to
    "unknown" (valid verdicts remain sound).  `max_beam` defaults low:
    beyond ~4096 the ladder's recompiles and frontier costs exceed the
    CPU fallback's (round-1 measurement: 65536 hung >280 s where 4096
    finished in 12 s).

    `mesh`: a 1-D `jax.sharding.Mesh` shards the BFS *frontier* of this
    single search across devices (_make_block_fn_sharded) — the
    within-search parallel axis, complementing the across-keys axis of
    ops/wgl_batched.py.  The witness tier stays single-device (its
    frontier is a handful of lanes)."""
    import jax
    import jax.numpy as jnp

    t0 = time.monotonic()
    if mesh is not None:
        # Validate up front, before any search work: the frontier and
        # candidate budget shard evenly only over power-of-two mesh
        # sizes (beam sizes are power-of-two buckets).  NOTE the
        # sharded path also assumes a single-host mesh — the
        # window-boundary re-gather pulls the frontier to the host.
        n_dev = int(mesh.devices.size)
        b0 = _bucket(beam)
        if n_dev < 1 or b0 % n_dev or (cand_factor * b0) % n_dev:
            raise ValueError(
                f"mesh size {n_dev} must evenly divide the beam "
                f"bucket {b0} and its candidate budget"
            )

    N = packed.n
    if N == 0 or packed.n_ok == 0:
        return WGLResult(valid=True, configs_explored=1, elapsed_s=time.monotonic() - t0)

    if witness:
        from .wgl_witness import (
            NARROW_INFO_WINDOW,
            WIDE_INFO_WINDOW,
            check_wgl_witness,
            plan_drops,
        )

        # Window-width ladder: the narrow default first (fastest,
        # covers almost every valid history), then a wide retry whose
        # extra helper columns recover most of the completeness the
        # narrow info_window trades away.  Each rung gets the budget
        # REMAINING after earlier rungs and only pays a compile if its
        # W lands in a new bucket.  The wide rung runs only when the
        # narrow plan actually dropped info columns (checked lazily,
        # off the happy path) — otherwise both plans are identical and
        # the retry would deterministically fail again.
        def remaining() -> Optional[float]:
            if time_limit_s is None:
                return None
            return time_limit_s - (time.monotonic() - t0)

        def timed_out() -> bool:
            r = remaining()
            return r is not None and r <= 0

        with profile.capture(
            "witness", ops=int(N), ok=int(packed.n_ok),
        ) as _pw:
            _pw.knob(info_window=NARROW_INFO_WINDOW,
                     width_hint=width_hint)
            with telemetry.span("wgl.witness"):
                wres = check_wgl_witness(
                    packed, pm, info_window=NARROW_INFO_WINDOW,
                    time_limit_s=remaining(), width_hint=width_hint,
                    checkpoint_dir=checkpoint_dir,
                )
                if wres is None and not timed_out() and plan_drops(
                    packed, info_window=NARROW_INFO_WINDOW
                ):
                    _pw.knob(info_window=WIDE_INFO_WINDOW)
                    wres = check_wgl_witness(
                        packed, pm, info_window=WIDE_INFO_WINDOW,
                        time_limit_s=remaining(), width_hint=width_hint,
                        checkpoint_dir=checkpoint_dir,
                    )
            _pw.outcome = "hit" if wres is not None else "miss"
        if wres is not None:
            telemetry.count("wgl.witness.hit")
            return wres
        telemetry.count("wgl.witness.miss")
        if timed_out():
            return WGLResult(
                valid="unknown",
                configs_explored=0,
                reason="time-limit",
                elapsed_s=time.monotonic() - t0,
            )

    def _bfs() -> WGLResult:
        SW = pm.state_width
        n0 = 0
        B = _bucket(beam, lo=256)
        packed_on = packed_enabled(packed_lanes)
        prev_active: Optional[np.ndarray] = None
        member = None  # device (B, W) bool, or (B, ceil(W/32)) u32 packed
        states = None  # device (B, SW) i32
        alive = None   # device (B,) bool
        explored_total = 0
        soft_incomplete = False  # gave up on exactness somewhere
        device_retried = False   # one halved-beam retry on resource errors

        while n0 < N:
            win = _window_tables(packed, n0, block, max_window)
            if win is None:
                return WGLResult(
                    valid="unknown",
                    configs_explored=explored_total,
                    reason="window-overflow",
                    elapsed_s=time.monotonic() - t0,
                )
            active, W, tables = win
            h1v, h2v, sh1v, sh2v = _hash_vectors(W, SW)
            Wp = packing.n_words(W)

            # Re-gather frontier bits from the previous window layout.
            if prev_active is None:
                if packed_on:
                    base_member = np.zeros((B, Wp), dtype=np.uint32)
                else:
                    base_member = np.zeros((B, W), dtype=bool)
                base_states = np.tile(
                    np.asarray(pm.init_state, dtype=np.int32), (B, 1)
                )
                base_alive = np.zeros(B, dtype=bool)
                base_alive[0] = True
                member = jnp.asarray(base_member)
                states = jnp.asarray(base_states)
                alive = jnp.asarray(base_alive)
            else:
                # Host-side re-gather: device gathers here recompile per
                # distinct (old, new) window shape pair and dominate runtime.
                perm, present = window_regather(prev_active, active)
                member_np = np.asarray(member)
                if packed_on:
                    member_np = packing.np_unpack_bits(
                        member_np, member_np.shape[1] * packing.LANES
                    )
                Bcur = member_np.shape[0]
                new_member = np.zeros((Bcur, W), dtype=bool)
                new_member[:, : len(active)] = np.where(
                    present[None, :], member_np[:, perm], False
                )
                if packed_on:
                    new_member = packing.np_pack_bits(new_member, Wp)
                member = jnp.asarray(new_member)

            iters = min(block, N - n0)
            # Snapshot for beam-overflow retry.
            snap = (member, states, alive)

            while True:
                Cmax = cand_factor * B
                # The step fn itself keys the cache (strong ref): an
                # id() key can collide after GC address reuse and serve
                # the wrong model's transition kernel.
                key = (B, W, SW, Cmax, pm.jax_step, mesh, packed_on)
                fn = _block_fn_cache.get(key)
                fresh_fn = fn is None
                if fn is None:
                    if mesh is not None:
                        fn = _make_block_fn_sharded(
                            B, W, SW, Cmax, pm.jax_step, mesh,
                            packed=packed_on,
                        )
                    else:
                        fn = _make_block_fn(
                            B, W, SW, Cmax, pm.jax_step, packed=packed_on
                        )
                    _block_fn_cache[key] = fn
                if packed_on:
                    # Packed table slots: ok-mask as uint32 words, hash
                    # vectors as odd uint32 multipliers.
                    htabs = [
                        jnp.asarray(packing.np_pack_bits(tables["ok_w"], Wp)),
                        jnp.asarray(tables["fmin1"]),
                        jnp.asarray(tables["f_has_ok"]),
                        jnp.asarray(packing.hash_consts(Wp, 0)),
                        jnp.asarray(packing.hash_consts(Wp, 1)),
                        jnp.asarray(packing.hash_consts(SW, 2)),
                        jnp.asarray(packing.hash_consts(SW, 3)),
                    ]
                else:
                    htabs = [
                        jnp.asarray(tables["ok_w"]),
                        jnp.asarray(tables["fmin1"]),
                        jnp.asarray(tables["f_has_ok"]),
                        jnp.asarray(h1v),
                        jnp.asarray(h2v),
                        jnp.asarray(sh1v),
                        jnp.asarray(sh2v),
                    ]
                targs = [
                    jnp.asarray(tables["ret_w"]),
                    jnp.asarray(tables["inv_w"]),
                    jnp.asarray(tables["f_w"]),
                    jnp.asarray(tables["a0_w"]),
                    jnp.asarray(tables["a1_w"]),
                ] + htabs
                if telemetry.enabled():
                    # Fresh cache entries pay jit trace+compile inside the
                    # first call — "wgl.bfs.compile" vs "wgl.bfs.block" is
                    # the compile/execute split the phase profile reports.
                    telemetry.count(
                        "wgl.h2d-bytes",
                        int(sum(a.nbytes for a in tables.values()
                                if hasattr(a, "nbytes"))),
                    )
                    telemetry.gauge("wgl.bfs.beam", B)
                    telemetry.gauge("wgl.bfs.window", W)
                    if packed_on:
                        telemetry.count("wgl.packed.blocks")
                        telemetry.gauge("wgl.packed.words", Wp)
                    sp = telemetry.span(
                        "wgl.bfs.compile" if fresh_fn else "wgl.bfs.block"
                    )
                else:
                    sp = telemetry.span("")  # shared no-op
                try:
                    degrade.maybe_fault("device")
                    # The bool() syncs stay inside the try: jitted dispatch
                    # is async, so execution failures raise at consumption.
                    with sp:
                        out = fn(member, states, alive, jnp.int32(iters), *targs)
                        member, states, alive, accepted, incomplete, explored, it_done = out
                        accepted_b = bool(accepted)
                        incomplete_b = bool(incomplete)
                except Exception as e:  # noqa: BLE001
                    if not degrade.is_resource_error(e):
                        raise
                    # Degradation ladder: the device (not the search) gave
                    # out.  Evict the compiled block fn, retry ONCE with a
                    # halved beam from the block snapshot, then settle for
                    # "unknown" — the dispatcher's CPU settle takes over.
                    _block_fn_cache.pop(key, None)
                    if packed_on:
                        # First rung: shed the packed lanes and retry the
                        # block wide at the SAME beam — packing is an
                        # optimisation, not a budget, so it goes before
                        # any beam width is surrendered.
                        packed_on = False
                        degrade.record("device", "packed-fallback", e)
                        telemetry.count("wgl.packed.fallbacks")
                        m0, s0, a0_ = snap
                        m0np = np.asarray(m0)
                        member = jnp.asarray(packing.np_unpack_bits(
                            m0np, m0np.shape[1] * packing.LANES
                        )[:, :W])
                        states, alive = s0, a0_
                        snap = (member, states, alive)
                        continue
                    if device_retried or B <= 64:
                        degrade.record("device", "fall-through", e)
                        return WGLResult(
                            valid="unknown",
                            configs_explored=explored_total,
                            reason="device-resource-error",
                            elapsed_s=time.monotonic() - t0,
                        )
                    device_retried = True
                    degrade.record("device", "retry-halved", e)
                    B //= 2
                    m0, s0, a0_ = snap
                    # Frontier rows are packed alive-first; truncating live
                    # rows beyond the new beam forfeits exactness, which
                    # soft_incomplete degrades to "unknown" (never a false
                    # conviction).
                    if bool(a0_[B:].any()):
                        soft_incomplete = True
                    member = m0[:B]
                    states = s0[:B]
                    alive = a0_[:B]
                    snap = (member, states, alive)
                    continue
                if telemetry.enabled():
                    telemetry.count("wgl.bfs.rounds", int(it_done))

                if accepted_b:
                    explored_total += int(explored)
                    return WGLResult(
                        valid=True,
                        configs_explored=explored_total,
                        elapsed_s=time.monotonic() - t0,
                    )
                if time_limit_s is not None and time.monotonic() - t0 > time_limit_s:
                    # The limit must bind inside the retry ladder too —
                    # round-1 bug: a 45 s limit was ignored for 280 s+ while
                    # the ladder doubled and recompiled.
                    return WGLResult(
                        valid="unknown",
                        configs_explored=explored_total + int(explored),
                        reason="time-limit",
                        elapsed_s=time.monotonic() - t0,
                    )
                if incomplete_b and B < max_beam:
                    # Retry this block with a wider beam, exactly.
                    B *= 2
                    m0, s0, a0_ = snap
                    pad = B - m0.shape[0]
                    member = jnp.pad(m0, ((0, pad), (0, 0)))
                    states = jnp.pad(s0, ((0, pad), (0, 0)))
                    alive = jnp.pad(a0_, (0, pad))
                    snap = (member, states, alive)
                    continue
                if incomplete_b:
                    soft_incomplete = True
                explored_total += int(explored)
                break

            if not bool(alive.any()):
                if soft_incomplete:
                    return WGLResult(
                        valid="unknown",
                        configs_explored=explored_total,
                        reason="beam-overflow",
                        elapsed_s=time.monotonic() - t0,
                    )
                return WGLResult(
                    valid=False,
                    configs_explored=explored_total,
                    elapsed_s=time.monotonic() - t0,
                )
            if time_limit_s is not None and time.monotonic() - t0 > time_limit_s:
                return WGLResult(
                    valid="unknown",
                    configs_explored=explored_total,
                    reason="time-limit",
                    elapsed_s=time.monotonic() - t0,
                )
            n0 += int(it_done)
            prev_active = active

        # Ran every level with live configs and never accepted: with an exact
        # search this is unreachable (a full linearization covers all oks);
        # degrade safely.
        return WGLResult(
            valid="unknown" if soft_incomplete else False,
            configs_explored=explored_total,
            reason="exhausted",
            elapsed_s=time.monotonic() - t0,
        )

    # The BFS pass record: shape features + plan knobs + the
    # compile/execute split folded in from the wgl.bfs.compile /
    # wgl.bfs.block spans via the span-exit hook (telemetry/profile.py).
    with profile.capture(
        "bfs", ops=int(N), ok=int(packed.n_ok),
    ) as _pb:
        _pb.knob(
            beam=int(_bucket(beam, lo=256)), block=int(block),
            max_beam=int(max_beam), max_window=int(max_window),
            mesh=mesh is not None,
            packed=packed_enabled(packed_lanes),
        )
        res = _bfs()
        _pb.outcome = (f"unknown:{res.reason}"
                       if res.valid == "unknown" else res.valid)
        _pb.feature(explored=int(res.configs_explored))
    return res
