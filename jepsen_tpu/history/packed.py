"""Packed columnar op tensors — the device-facing history representation.

This is the TPU-native serialization called for by BASELINE.json: a history
becomes packed int32 arrays (process, f, args, type) plus invocation /
completion event indices, ready to ship to device for checker kernels.
Mirrors what `jepsen.history`'s Op records + `knossos`'s history
preprocessing provide to the reference's checkers (SURVEY.md §2.4), but
columnar from the start.

Shapes: for a history with n live operations (invoke/completion pairs from
client ops, certain failures dropped), every column is an `(n,)` numpy
array sorted by invocation order — int32 for op payloads
(process/status/f/a0/a1), int64 for event bookkeeping (inv/ret/src_index/
preds/horizon, since ret uses NO_RET = int64 max; the device path clamps
to int32 INF on transfer).  Precedence structure is reduced to two
counters per op (SURVEY.md §7 stage 3; see ops/wgl.py for how the search
uses them):

  preds[a] = #{y != a : ret(y) < inv(a)}   ops that must precede a
  horizon[a] = #{y != a : inv(y) < ret(a)} last level at which a may remain
                                            un-linearized

Info (indeterminate) ops never complete, so ret = +inf (INT64 max) and
horizon = n-1: they stay optional forever — exactly why high-:info
histories blow up search width (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import numpy as np

from .core import FAIL, INFO, INVOKE, OK, History, Op

from .. import telemetry

#: Sentinel for "never returns" event index.
NO_RET = np.iinfo(np.int64).max

#: Sentinel int32 for missing / nil argument values.
NIL = np.iinfo(np.int32).min

#: Status codes for packed ops.
ST_OK = 1
ST_INFO = 3


class Interner:
    """Dense int interning of arbitrary hashable values (f symbols, large
    or non-int op payloads)."""

    __slots__ = ("values", "_ids")

    def __init__(self) -> None:
        self.values: list[Any] = []
        self._ids: dict[Any, int] = {}

    def intern(self, v: Any) -> int:
        i = self._ids.get(v)
        if i is None:
            i = len(self.values)
            self._ids[v] = i
            self.values.append(v)
        return i

    def value(self, i: int) -> Any:
        return self.values[i]

    def __len__(self) -> int:
        return len(self.values)


#: Column order + dtypes of the binary PackedOps serialization.  The
#: wire/rest format is just these arrays little-endian, concatenated
#: after a magic + u64 row count — no per-element framing, so a packed
#: history round-trips at memcpy speed (checkerd ships these frames).
PACKED_COLUMNS: tuple[tuple[str, Any], ...] = (
    ("inv", np.int64),
    ("ret", np.int64),
    ("process", np.int32),
    ("status", np.int32),
    ("f", np.int32),
    ("a0", np.int32),
    ("a1", np.int32),
    ("src_index", np.int64),
    ("preds", np.int64),
    ("horizon", np.int64),
)

PACKED_MAGIC = b"JPKD1\n"


def packed_to_bytes(p: "PackedOps") -> bytes:
    """Serializes a PackedOps to the columnar binary form."""
    parts = [PACKED_MAGIC, np.int64(p.n).tobytes()]
    for name, dtype in PACKED_COLUMNS:
        col = np.ascontiguousarray(getattr(p, name), dtype=dtype)
        if col.shape != (p.n,):
            raise ValueError(
                f"column {name}: shape {col.shape} != ({p.n},)"
            )
        parts.append(col.tobytes())
    return b"".join(parts)


def packed_from_bytes(buf: bytes) -> "PackedOps":
    """Inverse of packed_to_bytes.  Validates magic and total length so
    a torn or foreign frame raises instead of mis-slicing columns."""
    if buf[: len(PACKED_MAGIC)] != PACKED_MAGIC:
        raise ValueError("not a packed-ops frame (bad magic)")
    off = len(PACKED_MAGIC)
    n = int(np.frombuffer(buf, dtype=np.int64, count=1, offset=off)[0])
    if n < 0:
        raise ValueError(f"packed-ops frame: negative row count {n}")
    off += 8
    want = off + sum(n * np.dtype(dt).itemsize for _, dt in PACKED_COLUMNS)
    if len(buf) != want:
        raise ValueError(
            f"packed-ops frame: {len(buf)} bytes, want {want} for n={n}"
        )
    cols = {}
    for name, dtype in PACKED_COLUMNS:
        # .copy(): frombuffer views are read-only and pin the source
        # buffer; the checker mutates nothing but numpy ops want
        # writable, owned arrays.
        cols[name] = np.frombuffer(
            buf, dtype=dtype, count=n, offset=off
        ).copy()
        off += n * np.dtype(dtype).itemsize
    return PackedOps(**cols)


#: An encoder maps (invocation, completion|None) to packed
#: (f_code, a0, a1) int32 triple, or None to drop the op entirely (e.g.
#: indeterminate reads, which can never affect model state).
OpEncoderFn = Callable[[Op, Optional[Op]], Optional[tuple[int, int, int]]]


@dataclass
class PackedOps:
    """Columnar live-operation table, invocation-ordered."""

    #: (n,) invocation event index within the source history
    inv: np.ndarray
    #: (n,) completion event index, NO_RET when never completed
    ret: np.ndarray
    #: (n,) worker process ids
    process: np.ndarray
    #: (n,) ST_OK / ST_INFO
    status: np.ndarray
    #: (n,) packed op function codes
    f: np.ndarray
    #: (n,) first argument (NIL if absent)
    a0: np.ndarray
    #: (n,) second argument (NIL if absent)
    a1: np.ndarray
    #: (n,) original History index of the invocation (for reporting)
    src_index: np.ndarray
    #: (n,) number of ops that must be linearized before this one
    preds: np.ndarray
    #: (n,) last BFS level at which this op may remain un-linearized
    horizon: np.ndarray

    @property
    def n(self) -> int:
        return int(self.inv.shape[0])

    @property
    def n_ok(self) -> int:
        return int((self.status == ST_OK).sum())

    def op_row(self, a: int) -> dict[str, int]:
        return {
            "inv": int(self.inv[a]),
            "ret": int(self.ret[a]),
            "process": int(self.process[a]),
            "status": int(self.status[a]),
            "f": int(self.f[a]),
            "a0": int(self.a0[a]),
            "a1": int(self.a1[a]),
            "src_index": int(self.src_index[a]),
        }


class PackedBuilder:
    """Incremental `pack_history`: ops append one at a time (the
    interpreter's journal order) and chunks encode without re-packing
    the prefix — the streaming checker's ingest primitive
    (jepsen_tpu/streaming/).

    Equivalence contract (tested byte-for-byte in
    tests/test_histgen_packed.py): for any history h,

        b = PackedBuilder(encode)
        for o in h: b.append(o)
        packed_to_bytes(b.finish()) == packed_to_bytes(pack_history(h, encode))

    The emit/pairing logic below is a line-for-line transcription of
    pack_history's — same client filter, same dense event enumeration,
    same FAIL/None-encode drops, same double-invoke and unfinished-op
    indeterminates — only driven one op at a time instead of over a
    complete list.  Keep the two in lockstep.

    Mid-run, `snapshot()` returns the STABLE ROW PREFIX: rows whose
    invocation event index is < s, where s = min invocation index over
    in-flight ops (ops invoked but not yet completed).  Every future
    row either belongs to an in-flight op (inv >= s) or to an op not
    yet invoked (inv >= the current event counter >= s), so it sorts
    AFTER the prefix — prefix row indices, contents and order are
    final.  That stability is what lets the frontier consumer
    (streaming/frontier.py) carry device state across chunks.
    """

    __slots__ = ("encode", "_e", "_pending", "_rows", "_stable",
                 "_finished", "_counted")

    def __init__(self, encode: OpEncoderFn):
        self.encode = encode
        #: next dense event index over CLIENT ops (pack_history's e).
        self._e = 0
        #: process -> (inv_e, invoke Op), exactly pack_history's pending.
        self._pending: dict[Any, tuple[int, Op]] = {}
        #: every emitted row tuple, in EMIT order (finish() sorts, so
        #: this matches pack_history's pre-sort rows list exactly).
        self._rows: list[tuple[int, int, int, int, int, int, int, int]] = []
        #: inv-sorted prefix of rows proven stable by a past snapshot().
        self._stable: list[tuple[int, int, int, int, int, int, int, int]] = []
        self._finished = False
        #: client events already flushed to the ingest.append.ops
        #: counter (append itself is too hot for per-op telemetry:
        #: deltas flush at snapshot/finish instead).
        self._counted = 0

    # -- introspection ------------------------------------------------------

    @property
    def n_events(self) -> int:
        """Client events consumed so far."""
        return self._e

    @property
    def n_rows(self) -> int:
        """Rows emitted so far (more may follow until finish())."""
        return len(self._rows) + len(self._stable)

    @property
    def in_flight(self) -> int:
        """Ops invoked but not yet completed."""
        return len(self._pending)

    def stable_bound(self) -> int:
        """s: the event index below which rows are final.  Equals the
        minimum in-flight invocation index, or the event counter when
        nothing is in flight (everything so far is stable)."""
        if not self._pending:
            return self._e
        return min(inv_e for inv_e, _ in self._pending.values())

    # -- ingest -------------------------------------------------------------

    def _emit(self, inv_e: int, invoke_op: Op, ret_e: int,
              comp: Optional[Op]) -> None:
        # Mirror of pack_history's emit() — keep in lockstep.
        if comp is not None and comp.type == FAIL:
            return  # certainly never happened
        status = ST_OK if (comp is not None and comp.type == OK) else ST_INFO
        enc = self.encode(invoke_op, comp)
        if enc is None:
            return
        fc, a0, a1 = enc
        self._rows.append(
            (
                inv_e,
                ret_e if status == ST_OK else NO_RET,
                invoke_op.process,
                status,
                fc,
                a0,
                a1,
                invoke_op.index,
            )
        )

    def append(self, o: Op) -> None:
        """Feeds one op in journal order.  Non-client ops are ignored
        without consuming an event index (pack_history's client
        filter)."""
        if self._finished:
            raise RuntimeError("PackedBuilder already finished")
        if not o.is_client_op:
            return
        self._append_client(o)

    def extend(self, ops: "Any") -> None:
        """Feeds a chunk of ops (may be empty)."""
        self.append_many(ops)

    #: Below this many client ops the numpy pairing setup costs more
    #: than it saves; fall back to the scalar loop.
    _MANY_MIN = 16

    def append_many(self, ops: "Any") -> None:
        """Feeds a chunk of ops in journal order — byte-identical to
        calling append() per op (tested in tests/test_wgl_packed.py),
        but with the invoke/completion pairing done columnar in numpy.

        Correctness rests on one invariant of append()'s state machine:
        after any client op on process p, p's pending state is simply
        "that op was an invoke".  So on a per-process event sequence,
        a completion pairs with its immediate predecessor iff that
        predecessor is an invoke, an invoke becomes a double-invoke
        indeterminate iff its successor is another invoke, and only
        each process's FIRST op can interact with pending state carried
        in from before the chunk (handled scalar below).  A stable sort
        by process exposes those predecessor/successor relations as
        shifted boolean masks.  Emit order differs from append()'s, but
        every row has a unique inv event index and each consumer
        (snapshot/finish/discard) sorts or reduces over inv, so the
        serialized bytes cannot tell.
        """
        if self._finished:
            raise RuntimeError("PackedBuilder already finished")
        client = [o for o in ops if isinstance(o.process, int)]
        n = len(client)
        if n < self._MANY_MIN:
            for o in client:
                self._append_client(o)
            return
        e0 = self._e
        self._e = e0 + n
        is_inv = np.array([o.type == INVOKE for o in client], dtype=bool)
        procs = np.array([o.process for o in client], dtype=np.int64)
        order = np.argsort(procs, kind="stable")
        p_sorted = procs[order]
        inv_sorted = is_inv[order]
        same_prev = np.empty(n, dtype=bool)
        same_prev[0] = False
        np.equal(p_sorted[1:], p_sorted[:-1], out=same_prev[1:])
        prev_inv = np.empty(n, dtype=bool)
        prev_inv[0] = False
        prev_inv[1:] = inv_sorted[:-1]
        same_next = np.empty(n, dtype=bool)
        same_next[:-1] = same_prev[1:]
        same_next[-1] = False
        next_inv = np.empty(n, dtype=bool)
        next_inv[:-1] = inv_sorted[1:]
        next_inv[-1] = False
        oi = order.tolist()
        encode = self.encode
        emit_row = self._rows.append
        # Chunk-boundary interactions: each process's first op vs any
        # pending invoke carried in from earlier appends.
        for j in np.nonzero(~same_prev)[0].tolist():
            i = oi[j]
            o = client[i]
            prev = self._pending.pop(o.process, None)
            if prev is None:
                continue
            if is_inv[i]:
                # Double invoke without completion: the carried op is
                # indeterminate (it may still chain into doubles below).
                self._emit(prev[0], prev[1], -1, None)
            else:
                self._emit(prev[0], prev[1], e0 + i, o)
        # Within-chunk pairs: a completion whose in-process predecessor
        # is an invoke.  _emit's logic, inlined: the loop body runs once
        # per live op and the method dispatch is measurable at ingest
        # rates — keep in lockstep with _emit.
        pair_j = np.nonzero(
            (~inv_sorted) & same_prev & prev_inv
        )[0].tolist()
        enc_many = getattr(encode, "many", None)
        if enc_many is not None and pair_j:
            # Batched encode: collect the surviving (inv, comp) pairs,
            # encode in one call (the model inlines its interner), then
            # build rows.  Same drops, same codes as the scalar branch.
            meta = []
            items = []
            for j in pair_j:
                ic = oi[j]
                comp = client[ic]
                t = comp.type
                if t == FAIL:
                    continue  # certainly never happened
                ii = oi[j - 1]
                meta.append((ii, ic, t))
                items.append((client[ii], comp))
            for (ii, ic, t), enc in zip(meta, enc_many(items)):
                if enc is None:
                    continue
                fc, a0, a1 = enc
                inv_op = client[ii]
                if t == OK:
                    emit_row((e0 + ii, e0 + ic, inv_op.process, ST_OK,
                              fc, a0, a1, inv_op.index))
                else:
                    emit_row((e0 + ii, NO_RET, inv_op.process, ST_INFO,
                              fc, a0, a1, inv_op.index))
        else:
            for j in pair_j:
                ii = oi[j - 1]
                inv_op = client[ii]
                comp = client[oi[j]]
                t = comp.type
                if t == FAIL:
                    continue  # certainly never happened
                enc = encode(inv_op, comp)
                if enc is None:
                    continue
                fc, a0, a1 = enc
                if t == OK:
                    emit_row((e0 + ii, e0 + oi[j], inv_op.process, ST_OK,
                              fc, a0, a1, inv_op.index))
                else:
                    emit_row((e0 + ii, NO_RET, inv_op.process, ST_INFO,
                              fc, a0, a1, inv_op.index))
        # Within-chunk double invokes: superseded by the next invoke.
        for j in np.nonzero(inv_sorted & same_next & next_inv)[0].tolist():
            i = oi[j]
            inv_op = client[i]
            enc = encode(inv_op, None)
            if enc is None:
                continue
            fc, a0, a1 = enc
            emit_row((e0 + i, NO_RET, inv_op.process, ST_INFO,
                      fc, a0, a1, inv_op.index))
        # Trailing invokes become the new pending state.
        for j in np.nonzero(inv_sorted & ~same_next)[0].tolist():
            i = oi[j]
            self._pending[client[i].process] = (e0 + i, client[i])

    def _append_client(self, o: Op) -> None:
        """append() minus the client filter (caller already checked)."""
        e = self._e
        self._e = e + 1
        if o.type == INVOKE:
            prev = self._pending.get(o.process)
            if prev is not None:
                self._emit(prev[0], prev[1], -1, None)
            self._pending[o.process] = (e, o)
        else:
            inv = self._pending.pop(o.process, None)
            if inv is None:
                return
            inv_e, inv_op = inv
            self._emit(inv_e, inv_op, e, o)

    # -- snapshots & finish -------------------------------------------------

    def _advance_stable(self, s: int) -> None:
        """Moves rows with inv < s from the unsorted tail into the
        inv-sorted stable prefix.  Sound because every previously
        stable row has inv < the previous s <= every newly stable
        row's inv: sorting the batch and appending keeps the whole
        prefix sorted."""
        if not self._rows:
            return
        fresh = [r for r in self._rows if r[0] < s]
        if not fresh:
            return
        self._rows = [r for r in self._rows if r[0] >= s]
        fresh.sort(key=lambda r: r[0])
        self._stable.extend(fresh)

    def discard_stable_prefix(
        self, *, bars_per_block: int, blocks_done: int
    ) -> tuple[int, int, int]:
        """Rolling-window discard: drops the longest prefix of the
        stable rows that the frontier consumer can never need again,
        renumbers the surviving event indices down to a dense range,
        and returns ``(rows_dropped, bars_dropped, event_shift)`` so
        the caller can `FrontierCarry.rebase()` in lockstep.

        A prefix of length d is discardable when:

          1. every dropped row is ST_OK — an ST_INFO row has
             ret = NO_RET and stays a candidate entrant of every
             future block, so it pins the discard point (documented
             limitation: an indeterminate op early in the run caps how
             much history can ever be dropped before an epoch restart);
          2. max(ret over the prefix) < min(ret over every retained
             stable OK row) — then the prefix's barriers are EXACTLY
             the global barrier ranks [0, d) (bars sort by ret), so
             retained bar ranks shift uniformly by d;
          3. d is a multiple of `bars_per_block` — block boundaries
             stay aligned after the shift;
          4. d <= (blocks_done - 1) * bars_per_block — the most recent
             PROCESSED block must stay resident, because the carried
             frontier window (`_prev_active`) references that block's
             own rows; discarding them would orphan the member matrix.

        Under those conditions every device-side comparison the
        frontier makes (bar rank vs k0, inv vs barrier ret, window
        regather by row index) is invariant under the uniform shift —
        tests/test_monitor.py asserts verdict byte-parity.

        Event renumbering (the returned `event_shift`) subtracts the
        minimum surviving event index from every retained inv/ret and
        from the event counter, so a paced week-long run never walks
        the int32 timeline off its cliff (~2.1e9 events)."""
        if self._finished:
            raise RuntimeError("PackedBuilder already finished")
        K = bars_per_block
        max_bars = max(0, (blocks_done - 1)) * K
        if K <= 0 or max_bars <= 0 or not self._stable:
            return 0, 0, 0
        # Longest all-OK prefix of the stable rows.
        n_ok_prefix = 0
        for r in self._stable:
            if r[3] != ST_OK:
                break
            n_ok_prefix += 1
        if n_ok_prefix == 0:
            return 0, 0, 0
        # Condition 2: the prefix must be ret-closed against every
        # retained OK row — stable tail AND unsorted tail (a row with
        # inv >= s may still have completed before a stable row did,
        # so tail rets compete for low barrier ranks too).  Pending
        # ops complete at future events > every existing ret.
        min_ret_rest = min(
            min(
                (r[1] for r in self._stable[n_ok_prefix:] if r[3] == ST_OK),
                default=NO_RET,
            ),
            min(
                (r[1] for r in self._rows if r[3] == ST_OK),
                default=NO_RET,
            ),
        )
        rets = sorted(r[1] for r in self._stable[:n_ok_prefix])
        d = n_ok_prefix
        while d > 0 and rets[d - 1] >= min_ret_rest:
            d -= 1
        d = min(d, max_bars)
        d -= d % K
        if d <= 0:
            return 0, 0, 0
        # The dropped rows' rets must be exactly ranks [0, d): every
        # retained ret larger than all dropped rets.  After trimming d
        # to ret-order (rets is sorted; rows aren't), re-check that the
        # first d rows *by ret* are a row prefix too — for register
        # workloads rows are emitted completion-ordered so this holds;
        # bail (discard nothing) when it doesn't rather than risk a
        # rank permutation.
        cut = rets[d - 1]
        prefix = self._stable[:d]
        if any(r[1] > cut for r in prefix) or any(
            r[1] <= cut for r in self._stable[d:n_ok_prefix]
        ):
            return 0, 0, 0
        # Event renumbering: shift so the first retained row lands at
        # event 0 (or keep the counter dense when nothing is retained).
        rest = self._stable[d:]
        candidates = [r[0] for r in rest] + [r[0] for r in self._rows]
        candidates += [inv_e for inv_e, _ in self._pending.values()]
        e_shift = min(candidates) if candidates else self._e
        self._stable = [
            (
                r[0] - e_shift,
                r[1] - e_shift if r[1] != NO_RET else NO_RET,
                r[2], r[3], r[4], r[5], r[6], r[7],
            )
            for r in rest
        ]
        self._rows = [
            (
                r[0] - e_shift,
                r[1] - e_shift if r[1] != NO_RET else NO_RET,
                r[2], r[3], r[4], r[5], r[6], r[7],
            )
            for r in self._rows
        ]
        self._pending = {
            p: (inv_e - e_shift, op)
            for p, (inv_e, op) in self._pending.items()
        }
        self._e -= e_shift
        # The ingest flush watermark tracks the (renumbered) counter.
        self._counted = max(0, self._counted - e_shift)
        return d, d, e_shift

    def _flush_ingest(self) -> None:
        """Publishes the client events consumed since the last flush
        (keeps `append` itself telemetry-free — the hot path's cost
        contract)."""
        if not telemetry.enabled():
            return
        d = self._e - self._counted
        if d > 0:
            telemetry.count("ingest.append.ops", d)
        self._counted = self._e

    def snapshot(self) -> tuple["PackedOps", int]:
        """(stable-prefix PackedOps, s).  The pack covers exactly the
        rows with inv < s and is WITNESS-ONLY: preds/horizon are left
        zero (the witness event walk never reads them; a full pack
        comes from finish())."""
        self._flush_ingest()
        with telemetry.span("ingest.snapshot", rows=self.n_rows):
            telemetry.count("ingest.snapshots")
            s = self.stable_bound()
            self._advance_stable(s)
            return _rows_to_packed(self._stable, with_preds=False), s

    def finish(self) -> "PackedOps":
        """Closes the builder: unfinished invocations become
        indeterminate, rows sort by invocation, preds/horizon are
        computed — byte-identical to pack_history on the same ops."""
        if self._finished:
            raise RuntimeError("PackedBuilder already finished")
        self._finished = True
        self._flush_ingest()
        with telemetry.span("ingest.finish", rows=self.n_rows):
            # Unfinished invocations are indeterminate (pending dict
            # order, matching pack_history's final loop).
            for inv_e, inv_op in self._pending.values():
                self._emit(inv_e, inv_op, -1, None)
            self._pending.clear()
            rows = self._stable + self._rows
            rows.sort(key=lambda r: r[0])
            return _rows_to_packed(rows, with_preds=True)


def _require_i32(arr: "np.ndarray") -> None:
    """The process/status/f/a0/a1 columns narrow to int32 on device;
    a0/a1 carry model-encoded op arguments, which nothing bounds.  A
    value past int32 would wrap silently in the cast below and corrupt
    every verdict downstream, so bail loudly first (the
    wgl_witness._plan_blocks idiom)."""
    if not arr.size:
        return
    cols = arr[:, 2:7]
    lo = int(cols.min())
    hi = int(cols.max())
    if lo < -(2 ** 31) or hi >= 2 ** 31:
        raise OverflowError(
            f"packed op column value out of int32 range "
            f"[{lo}, {hi}]: re-encode op arguments (a0/a1) into a "
            f"dense int32 domain before packing"
        )


def _rows_to_packed(rows: list, *, with_preds: bool) -> "PackedOps":
    """Shared row-tuples -> PackedOps tail of pack_history.  `rows`
    must already be inv-sorted.  with_preds=False leaves preds/horizon
    zero for witness-only snapshots."""
    if rows:
        arr = np.array(rows, dtype=np.int64)
    else:
        arr = np.zeros((0, 8), dtype=np.int64)

    inv = arr[:, 0]
    ret = arr[:, 1]
    n = arr.shape[0]
    _require_i32(arr)

    if with_preds:
        ret_sorted = np.sort(ret)
        preds = np.searchsorted(ret_sorted, inv, side="left").astype(np.int64)
        inv_before_ret = np.searchsorted(inv, ret, side="left").astype(np.int64)
        horizon = inv_before_ret - 1
        horizon = np.minimum(horizon, n - 1)
    else:
        preds = np.zeros(n, dtype=np.int64)
        horizon = np.zeros(n, dtype=np.int64)

    return PackedOps(
        inv=inv.astype(np.int64),
        ret=ret,
        process=arr[:, 2].astype(np.int32),
        status=arr[:, 3].astype(np.int32),
        f=arr[:, 4].astype(np.int32),
        a0=arr[:, 5].astype(np.int32),
        a1=arr[:, 6].astype(np.int32),
        src_index=arr[:, 7].astype(np.int64),
        preds=preds,
        horizon=horizon,
    )


def pack_history(h: History, encode: OpEncoderFn) -> PackedOps:
    """Packs the client portion of a history into columnar arrays.

    Pipeline (mirrors knossos's preprocessing as observed through the
    checker API, checker.clj:214-233):
      1. keep client ops only;
      2. pair invocations with completions;
      3. drop certain failures (:fail) — they never happened;
      4. ops whose completion is missing or :info become indeterminate
         (ret = NO_RET);
      5. encode (f, value) via the model's encoder; encoders may drop
         no-effect indeterminate ops (e.g. :info reads).
    """
    client = [o for o in h if o.is_client_op]
    rows: list[tuple[int, int, int, int, int, int, int, int]] = []
    # Re-derive pairing on the client-only event sequence so inv/ret indices
    # are dense event positions in that sequence.
    pending: dict[Any, tuple[int, Op]] = {}
    events: list[tuple[Op, int]] = [(o, e) for e, o in enumerate(client)]

    def emit(inv_e: int, invoke_op: Op, ret_e: int, comp: Op | None) -> None:
        if comp is not None and comp.type == FAIL:
            return  # certainly never happened
        status = ST_OK if (comp is not None and comp.type == OK) else ST_INFO
        enc = encode(invoke_op, comp)
        if enc is None:
            return
        fc, a0, a1 = enc
        rows.append(
            (
                inv_e,
                ret_e if status == ST_OK else NO_RET,
                invoke_op.process,
                status,
                fc,
                a0,
                a1,
                invoke_op.index,
            )
        )

    for o, e in events:
        if o.type == INVOKE:
            prev = pending.get(o.process)
            if prev is not None:
                # Double invoke without completion (torn history): the
                # earlier op is indeterminate, like core pairing keeps it.
                emit(prev[0], prev[1], -1, None)
            pending[o.process] = (e, o)
        else:
            inv = pending.pop(o.process, None)
            if inv is None:
                continue  # completion without invocation: tolerate
            inv_e, inv_op = inv
            emit(inv_e, inv_op, e, o)
    # Unfinished invocations are indeterminate.
    for inv_e, inv_op in pending.values():
        emit(inv_e, inv_op, -1, None)

    rows.sort(key=lambda r: r[0])
    if rows:
        arr = np.array(rows, dtype=np.int64)
    else:
        arr = np.zeros((0, 8), dtype=np.int64)

    inv = arr[:, 0]
    ret = arr[:, 1]
    n = arr.shape[0]
    _require_i32(arr)

    # preds[a] = #{y != a : ret(y) < inv(a)}
    # horizon[a] = #{y != a : inv(y) < ret(a)}
    # O(n log n) via sorted ret values.
    ret_sorted = np.sort(ret)
    preds = np.searchsorted(ret_sorted, inv, side="left").astype(np.int64)
    # inv is sorted ascending already; count invs strictly below each ret.
    inv_before_ret = np.searchsorted(inv, ret, side="left").astype(np.int64)
    # Subtract self when inv(a) < ret(a) (always true for completed ops;
    # for NO_RET ops every other op counts, self too — subtract 1).
    horizon = inv_before_ret - 1
    horizon = np.minimum(horizon, n - 1)

    return PackedOps(
        inv=inv.astype(np.int64),
        ret=ret,
        process=arr[:, 2].astype(np.int32),
        status=arr[:, 3].astype(np.int32),
        f=arr[:, 4].astype(np.int32),
        a0=arr[:, 5].astype(np.int32),
        a1=arr[:, 6].astype(np.int32),
        src_index=arr[:, 7].astype(np.int64),
        preds=preds,
        horizon=horizon,
    )
