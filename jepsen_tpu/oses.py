"""OS protocol: preparing nodes before the DB goes on.

Equivalent of /root/reference/jepsen/src/jepsen/os.clj (:4-8) and the
os/{debian,ubuntu,centos}.clj implementations (package install, hostfile
setup).  Named `oses` to avoid shadowing the stdlib `os` module.
"""

from __future__ import annotations

import logging
from typing import Any, Sequence

from .control import Session, health, on_nodes

log = logging.getLogger(__name__)


class OS:
    """os.clj:4-8."""

    def setup(self, test: dict, sess: Session, node: str) -> None:
        pass

    def teardown(self, test: dict, sess: Session, node: str) -> None:
        pass


class NoopOS(OS):
    pass


noop = NoopOS()


class DebianOS(OS):
    """Debian/Ubuntu node prep (os/debian.clj:14-181): hostname in
    /etc/hosts, apt packages installed on demand."""

    def __init__(self, packages: Sequence[str] = ()):
        self.packages = list(packages)

    def setup(self, test: dict, sess: Session, node: str) -> None:
        self.setup_hostfile(test, sess, node)
        if self.packages:
            self.install(sess, self.packages)

    def setup_hostfile(self, test: dict, sess: Session, node: str) -> None:
        """Ensures every test node resolves (os/debian.clj:14-27)."""
        nodes = test.get("nodes") or []
        lines = ["127.0.0.1 localhost"]
        for n in nodes:
            try:
                ip = sess.exec("getent", "hosts", n).split()[0]
            except Exception:  # noqa: BLE001 - unresolvable: leave to DNS
                continue
            lines.append(f"{ip} {n}")
        with sess.su():
            sess.exec(
                "tee", "/etc/hosts", stdin="\n".join(lines) + "\n"
            )

    def install(self, sess: Session, packages: Sequence[str]) -> None:
        """apt-get install missing packages (os/debian.clj:62-90)."""
        with sess.su():
            sess.exec(
                "env", "DEBIAN_FRONTEND=noninteractive",
                "apt-get", "install", "-y", "--no-install-recommends",
                *packages,
            )

    def teardown(self, test: dict, sess: Session, node: str) -> None:
        pass


debian = DebianOS()


class UbuntuOS(DebianOS):
    """Ubuntu node prep (os/ubuntu.clj): Debian mechanics plus the
    standard package load-out and a net heal."""

    DEFAULT_PACKAGES = (
        "apt-transport-https", "wget", "curl", "vim", "man-db",
        "faketime", "ntpdate", "unzip", "iptables", "psmisc", "tar",
        "bzip2", "iputils-ping", "iproute2", "rsyslog", "sudo",
        "logrotate",
    )

    def __init__(self, packages: Sequence[str] = ()):
        super().__init__(list(packages) or list(self.DEFAULT_PACKAGES))

    def setup(self, test: dict, sess: Session, node: str) -> None:
        super().setup(test, sess, node)
        net = test.get("net")
        if net is not None:
            try:
                net.heal(test)
            except Exception:  # noqa: BLE001 — `meh`, like the reference
                log.debug("net heal during OS setup failed", exc_info=True)


ubuntu = UbuntuOS()


class CentOSOS(OS):
    """CentOS node prep (os/centos.clj): loopback hostname entry, yum
    update at most daily, yum package install."""

    def __init__(self, packages: Sequence[str] = ()):
        self.packages = list(packages)

    def setup(self, test: dict, sess: Session, node: str) -> None:
        self.setup_hostfile(sess)
        self.maybe_update(sess)
        if self.packages:
            self.install(sess, self.packages)

    def setup_hostfile(self, sess: Session) -> None:
        """Appends the hostname to the loopback line
        (os/centos.clj:12-25)."""
        name = sess.exec("hostname")
        hosts = sess.exec("cat", "/etc/hosts") or ""
        out = []
        for line in hosts.splitlines():
            if line.startswith("127.0.0.1") and name not in line:
                line = f"{line} {name}"
            out.append(line)
        with sess.su():
            sess.exec("tee", "/etc/hosts", stdin="\n".join(out) + "\n")

    def maybe_update(self, sess: Session) -> None:
        """yum update unless one ran in the last day
        (os/centos.clj:27-44)."""
        try:
            now = int(sess.exec("date", "+%s"))
            last = int(sess.exec("stat", "-c", "%Y", "/var/log/yum.log"))
            if now - last < 86400:
                return
        except Exception:  # noqa: BLE001 — no yum.log: just update
            pass
        with sess.su():
            sess.exec_star("yum", "-y", "update")

    def install(self, sess: Session, packages: Sequence[str]) -> None:
        with sess.su():
            sess.exec("yum", "install", "-y", *packages)


centos = CentOSOS()


class SmartOSOS(CentOSOS):
    """SmartOS node prep (os/smartos.clj): the CentOS hostfile
    mechanics with pkgin as the package manager."""

    def maybe_update(self, sess: Session) -> None:
        try:
            now = int(sess.exec("date", "+%s"))
            last = int(sess.exec(
                "stat", "-c", "%Y", "/var/db/pkgin/pkgin.db"
            ))
            if now - last < 86400:
                return
        except Exception:  # noqa: BLE001 — no pkgin db yet: update
            pass
        with sess.su():
            sess.exec_star("pkgin", "-y", "update")

    def install(self, sess: Session, packages: Sequence[str]) -> None:
        with sess.su():
            sess.exec("pkgin", "-y", "install", *packages)


smartos = SmartOSOS()


def setup(test: dict) -> None:
    """OS setup across the surviving nodes (core.clj:92-99 with-os);
    per-node failures go through the node-loss policy (abort vs
    quarantine-and-shrink)."""
    osys = test.get("os") or noop
    health.run_phase(test, "os setup", lambda s, n: osys.setup(test, s, n))


def teardown(test: dict) -> None:
    osys = test.get("os") or noop
    on_nodes(test, lambda s, n: osys.teardown(test, s, n))
