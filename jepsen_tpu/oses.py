"""OS protocol: preparing nodes before the DB goes on.

Equivalent of /root/reference/jepsen/src/jepsen/os.clj (:4-8) and the
os/{debian,ubuntu,centos}.clj implementations (package install, hostfile
setup).  Named `oses` to avoid shadowing the stdlib `os` module.
"""

from __future__ import annotations

import logging
from typing import Any, Sequence

from .control import Session, on_nodes

log = logging.getLogger(__name__)


class OS:
    """os.clj:4-8."""

    def setup(self, test: dict, sess: Session, node: str) -> None:
        pass

    def teardown(self, test: dict, sess: Session, node: str) -> None:
        pass


class NoopOS(OS):
    pass


noop = NoopOS()


class DebianOS(OS):
    """Debian/Ubuntu node prep (os/debian.clj:14-181): hostname in
    /etc/hosts, apt packages installed on demand."""

    def __init__(self, packages: Sequence[str] = ()):
        self.packages = list(packages)

    def setup(self, test: dict, sess: Session, node: str) -> None:
        self.setup_hostfile(test, sess, node)
        if self.packages:
            self.install(sess, self.packages)

    def setup_hostfile(self, test: dict, sess: Session, node: str) -> None:
        """Ensures every test node resolves (os/debian.clj:14-27)."""
        nodes = test.get("nodes") or []
        lines = ["127.0.0.1 localhost"]
        for n in nodes:
            try:
                ip = sess.exec("getent", "hosts", n).split()[0]
            except Exception:  # noqa: BLE001 - unresolvable: leave to DNS
                continue
            lines.append(f"{ip} {n}")
        with sess.su():
            sess.exec(
                "tee", "/etc/hosts", stdin="\n".join(lines) + "\n"
            )

    def install(self, sess: Session, packages: Sequence[str]) -> None:
        """apt-get install missing packages (os/debian.clj:62-90)."""
        with sess.su():
            sess.exec(
                "env", "DEBIAN_FRONTEND=noninteractive",
                "apt-get", "install", "-y", "--no-install-recommends",
                *packages,
            )

    def teardown(self, test: dict, sess: Session, node: str) -> None:
        pass


debian = DebianOS()


def setup(test: dict) -> None:
    """OS setup across all nodes (core.clj:92-99 with-os)."""
    osys = test.get("os") or noop
    on_nodes(test, lambda s, n: osys.setup(test, s, n))


def teardown(test: dict) -> None:
    osys = test.get("os") or noop
    on_nodes(test, lambda s, n: osys.teardown(test, s, n))
