"""Synthetic concurrent histories for benchmarks and tests.

The reference benchmarks its stack on generated workloads
(/root/reference/jepsen/test/jepsen/core_test.clj:127-132 runs 1e6
list-append ops; interpreter_test.clj:43-88 asserts >10k ops/s) — this
module provides the checker-side analog: concurrent register histories
that are linearizable *by construction* (every op takes effect at one
instant between its invocation and completion), with controllable
concurrency and indeterminate-op rate, plus optional injected
violations.  These drive bench.py and the BASELINE.json 100k-op config.
"""

from __future__ import annotations

import random
from typing import Optional

from ..history.core import History, Op, history


def random_register_history(
    n_ops: int,
    *,
    procs: int = 16,
    info_rate: float = 0.02,
    cas: bool = True,
    n_values: int = 5,
    seed: int = 45100,
    bad: bool = False,
) -> History:
    """A concurrent cas-register history of ~n_ops operations.

    Each op's effect is applied atomically at completion time, so the
    history is linearizable unless `bad` injects a read of a
    never-written value.  `info_rate` of ops complete as :info
    (indeterminate) — these stay concurrent with everything after them,
    the width driver for WGL search (SURVEY.md §7 "hard parts").  The
    default seed matches the reference's fixed generator-test seed
    (generator/test.clj:48-52)."""
    rng = random.Random(seed)
    value: Optional[int] = None
    ops: list[Op] = []
    # process -> (f, payload, effect_applies) for in-flight ops
    pending: dict[int, tuple] = {}
    started = 0

    def complete(p: int) -> None:
        nonlocal value
        f, payload, as_info = pending.pop(p)
        if as_info:
            # Indeterminate: maybe the effect happened.
            if f == "write" and rng.random() < 0.5:
                value = payload
            elif f == "cas" and rng.random() < 0.5 and value == payload[0]:
                value = payload[1]
            ops.append(Op(type="info", f=f, value=payload, process=p))
            return
        if f == "read":
            ops.append(Op(type="ok", f="read", value=value, process=p))
        elif f == "write":
            value = payload
            ops.append(Op(type="ok", f="write", value=payload, process=p))
        else:  # cas
            if value == payload[0]:
                value = payload[1]
                ops.append(Op(type="ok", f="cas", value=payload, process=p))
            else:
                ops.append(Op(type="fail", f="cas", value=payload, process=p))

    while started < n_ops or pending:
        p = rng.randrange(procs)
        if p in pending:
            complete(p)
        elif started < n_ops:
            fs = ["read", "write", "cas"] if cas else ["read", "write"]
            f = rng.choice(fs)
            if f == "read":
                payload = None
            elif f == "write":
                payload = rng.randrange(n_values)
            else:
                payload = (rng.randrange(n_values), rng.randrange(n_values))
            as_info = f != "read" and rng.random() < info_rate
            pending[p] = (f, payload, as_info)
            ops.append(Op(type="invoke", f=f, value=payload, process=p))
            started += 1
        # else: only pending ops remain; loop drains them.

    if bad:
        ops.append(Op(type="invoke", f="read", value=None, process=0))
        ops.append(Op(type="ok", f="read", value=n_values + 94, process=0))
    return history(ops)
