"""Synthetic concurrent histories for benchmarks and tests.

The reference benchmarks its stack on generated workloads
(/root/reference/jepsen/test/jepsen/core_test.clj:127-132 runs 1e6
list-append ops; interpreter_test.clj:43-88 asserts >10k ops/s) — this
module provides the checker-side analog: concurrent register histories
that are linearizable *by construction* (every op takes effect at one
instant between its invocation and completion), with controllable
concurrency and indeterminate-op rate, plus optional injected
violations.  These drive bench.py and the BASELINE.json 100k-op config.
"""

from __future__ import annotations

import random
from typing import Optional

from ..history.core import History, Op, history


def random_register_history(
    n_ops: int,
    *,
    procs: int = 16,
    info_rate: float = 0.02,
    cas: bool = True,
    n_values: int = 5,
    seed: int = 45100,
    bad: bool = False,
    bad_at: Optional[float] = None,
) -> History:
    """A concurrent cas-register history of ~n_ops operations.

    Each op's effect is applied atomically at completion time, so the
    history is linearizable unless `bad` injects a read of a
    never-written value.  `info_rate` of ops complete as :info
    (indeterminate) — these stay concurrent with everything after them,
    the width driver for WGL search (SURVEY.md §7 "hard parts").  The
    default seed matches the reference's fixed generator-test seed
    (generator/test.clj:48-52)."""
    rng = random.Random(seed)
    value: Optional[int] = None
    ops: list[Op] = []
    # process -> (f, payload, effect_applies) for in-flight ops
    pending: dict[int, tuple] = {}
    started = 0

    def complete(p: int) -> None:
        nonlocal value
        f, payload, as_info = pending.pop(p)
        if as_info:
            # Indeterminate: maybe the effect happened.
            if f == "write" and rng.random() < 0.5:
                value = payload
            elif f == "cas" and rng.random() < 0.5 and value == payload[0]:
                value = payload[1]
            ops.append(Op(type="info", f=f, value=payload, process=p))
            return
        if f == "read":
            ops.append(Op(type="ok", f="read", value=value, process=p))
        elif f == "write":
            value = payload
            ops.append(Op(type="ok", f="write", value=payload, process=p))
        else:  # cas
            if value == payload[0]:
                value = payload[1]
                ops.append(Op(type="ok", f="cas", value=payload, process=p))
            else:
                ops.append(Op(type="fail", f="cas", value=payload, process=p))

    while started < n_ops or pending:
        p = rng.randrange(procs)
        if p in pending:
            complete(p)
        elif started < n_ops:
            fs = ["read", "write", "cas"] if cas else ["read", "write"]
            f = rng.choice(fs)
            if f == "read":
                payload = None
            elif f == "write":
                payload = rng.randrange(n_values)
            else:
                payload = (rng.randrange(n_values), rng.randrange(n_values))
            as_info = f != "read" and rng.random() < info_rate
            pending[p] = (f, payload, as_info)
            ops.append(Op(type="invoke", f=f, value=payload, process=p))
            started += 1
        # else: only pending ops remain; loop drains them.

    if bad:
        ops.append(Op(type="invoke", f="read", value=None, process=0))
        ops.append(Op(type="ok", f="read", value=n_values + 94, process=0))
    if bad_at is not None:
        # A mid-history impossible read (a value no op ever writes), on
        # a process id outside the worker range so it can't collide
        # with an in-flight op.  Unlike `bad`, the violation sits at
        # `bad_at` of the way through: a search in event order has to
        # chew through everything before it — info-op width and all —
        # before the infeasibility is reachable, which is the shape
        # that breaks beam-capped device BFS (VERDICT r2 "missing" #2).
        at = max(0, min(len(ops), int(bad_at * len(ops))))
        ops[at:at] = [
            Op(type="invoke", f="read", value=None, process=procs),
            Op(type="ok", f="read", value=n_values + 73, process=procs),
        ]
    return history(ops)


def stale_read_history(
    n_ops: int,
    *,
    procs: int = 16,
    info_rate: float = 0.05,
    n_values: int = 5,
    seed: int = 45100,
    read_at: float = 0.6,
) -> History:
    """A concurrent register history that is genuinely non-linearizable
    through the async-replication shape (the repkv violation,
    suites/repkv.py): a value S is written and acknowledged early, an
    acknowledged fence write overwrites it, and much later a read still
    returns S.  Every producer of S completes before the fence begins
    and the fence completes before the read is invoked, so no
    linearization order can serve S to the read — the proof obligation
    checker/refute.py's stale-read screen discharges at any scale.

    The body between fence and read is an ordinary linearizable-by-
    construction workload (values 0..n_values-1 < S, so nothing
    re-produces S; info ops welcome)."""
    S = n_values  # retired value: body ops can never produce it
    prologue = [
        Op(type="invoke", f="write", value=S, process=0),
        Op(type="ok", f="write", value=S, process=0),
        # fence: acknowledged overwrite, window disjoint from both the
        # producer above and the stale read below
        Op(type="invoke", f="write", value=0, process=0),
        Op(type="ok", f="write", value=0, process=0),
    ]
    body = list(
        random_register_history(
            n_ops - 3, procs=procs, info_rate=info_rate,
            n_values=n_values, seed=seed,
        )
    )
    at = max(0, min(len(body), int(read_at * len(body))))
    body[at:at] = [
        Op(type="invoke", f="read", value=None, process=procs),
        Op(type="ok", f="read", value=S, process=procs),
    ]
    return history(prologue + body)


def random_register_packed(
    n_ops: int,
    *,
    procs: int = 16,
    info_rate: float = 0.05,
    n_values: int = 5,
    seed: int = 45100,
    model=None,
):
    """A vectorized linearizable-by-construction register workload,
    built DIRECTLY in PackedOps form — the scale-bench generator.

    random_register_history() materializes 2n Op objects through a
    Python state machine (~60k events/s: a 20M-op history costs ~330 s
    to generate and another ~105 s to pack — more than 4x the time the
    checker needs to DECIDE it).  Benchmarking "max history length to
    verdict @ 300 s" (BASELINE.md's second north star) therefore needs
    a generator that is not the bottleneck: this one builds the
    columnar arrays in numpy (~1 s per 10M rows).

    Construction (valid by the same argument as the Op-level
    generator: every op takes effect at one instant inside its
    invocation window):

      * op k runs on proc k % procs; per-proc streams interleave by
        merging per-proc exponential-gap clocks — invocation and
        completion tokens get global dense event ranks via one
        argsort, giving realistic ~`procs`-wide concurrency;
      * the op mix is write/read (no cas — the cas success chain is
        inherently sequential; the checker load driver is barrier
        count + indeterminacy width, not the op flavor);
      * `info_rate` of writes complete :info (ret = NO_RET), each
        applied with probability 1/2 at its completion instant;
      * every read takes effect at its completion instant and returns
        the payload of the latest applied write completing before it
        (or the initial value) — one vectorized searchsorted.

    `model` (default cas_register().packed()) supplies the op
    encoder; codes are learned from a handful of sample encodings, so
    the emitted rows match pack_history() exactly.
    """
    import numpy as np

    from ..history.core import Op
    from ..history.packed import NO_RET, ST_INFO, ST_OK, PackedOps

    if model is None:
        from ..models import cas_register

        model = cas_register().packed()
    encode = model.encode

    rng = np.random.default_rng(seed)
    n = int(n_ops)
    proc = (np.arange(n, dtype=np.int64) % procs).astype(np.int32)

    # --- interleave: per-proc exponential clocks, one global argsort.
    # Token 2k = op k's invocation, 2k+1 its completion.
    gaps = rng.exponential(1.0, size=2 * n)
    tok_proc = np.repeat(proc, 2)
    order_by_proc = np.argsort(tok_proc, kind="stable")
    times = np.empty(2 * n)
    g_sorted = gaps[order_by_proc]
    csum = np.cumsum(g_sorted)
    # Subtract each proc segment's starting offset to restart clocks.
    # Empty segments (procs > n_ops) contribute boundary positions of
    # 0 or 2n — both invalid bases; mask them out.
    seg_starts = np.searchsorted(tok_proc[order_by_proc],
                                 np.arange(procs), side="left")
    base = np.zeros(2 * n)
    pos = seg_starts[1:]
    ok_pos = pos[(pos > 0) & (pos < 2 * n)]
    base[ok_pos] = csum[ok_pos - 1]
    times[order_by_proc] = csum - np.maximum.accumulate(base)
    rank = np.argsort(np.argsort(times, kind="stable"), kind="stable")
    inv_rank = rank[0::2].astype(np.int64)
    ret_rank = rank[1::2].astype(np.int64)

    # --- op mix and outcomes.
    is_write = rng.random(n) < 0.5
    payload = rng.integers(0, n_values, size=n)
    is_info = is_write & (rng.random(n) < info_rate)
    applied = is_write & (~is_info | (rng.random(n) < 0.5))

    # Reads see the latest applied write completing strictly before
    # their own completion instant.
    w_rank = ret_rank[applied]
    w_order = np.argsort(w_rank)
    w_rank_sorted = w_rank[w_order]
    w_payload_sorted = payload[applied][w_order]
    read_rows = np.nonzero(~is_write)[0]
    if len(w_rank_sorted):
        idx = np.searchsorted(w_rank_sorted, ret_rank[read_rows],
                              side="left") - 1
        read_val = np.where(
            idx >= 0, w_payload_sorted[np.maximum(idx, 0)], -1,
        )  # -1 = initial value (reads None)
    else:
        # No applied writes at all (tiny histories): every read sees
        # the initial value.
        read_val = np.full(len(read_rows), -1, dtype=np.int64)

    # --- codes, learned from sample encodings (exactly what
    # pack_history would emit for these rows).
    def code(f, value, typ="ok"):
        inv = Op(type="invoke", f=f,
                 value=None if f == "read" else value, process=0)
        comp = Op(type=typ, f=f, value=value, process=0)
        enc = encode(inv, comp if typ != "none" else None)
        assert enc is not None, (f, value, typ)
        return enc

    wr_codes = np.asarray([code("write", v) for v in range(n_values)],
                          dtype=np.int64)          # (V, 3)
    wr_info_codes = np.asarray(
        [code("write", v, "info") for v in range(n_values)],
        dtype=np.int64,
    )
    rd_codes = np.asarray(
        [code("read", v) for v in range(n_values)], dtype=np.int64,
    )                                               # (V, 3)

    fc = np.empty(n, dtype=np.int32)
    a0 = np.empty(n, dtype=np.int32)
    a1 = np.empty(n, dtype=np.int32)
    wrows = np.nonzero(is_write & ~is_info)[0]
    irows = np.nonzero(is_info)[0]
    fc[wrows] = wr_codes[payload[wrows], 0]
    a0[wrows] = wr_codes[payload[wrows], 1]
    a1[wrows] = wr_codes[payload[wrows], 2]
    fc[irows] = wr_info_codes[payload[irows], 0]
    a0[irows] = wr_info_codes[payload[irows], 1]
    a1[irows] = wr_info_codes[payload[irows], 2]
    seen = read_rows[read_val >= 0]
    seen_val = read_val[read_val >= 0]
    fc[seen] = rd_codes[seen_val, 0]
    a0[seen] = rd_codes[seen_val, 1]
    a1[seen] = rd_codes[seen_val, 2]

    status = np.where(is_info, ST_INFO, ST_OK).astype(np.int32)
    ret = np.where(is_info, NO_RET, ret_rank)

    # Reads of the initial value encode to None (unconstrained) and
    # are dropped, exactly like pack_history with this model's
    # encoder.  Event ranks are NOT renumbered — dropped rows still
    # consumed their event positions, as in the Op-level pipeline.
    keep = np.ones(n, dtype=bool)
    keep[read_rows[read_val < 0]] = False

    # Rows are invocation-ordered, like pack_history's output.
    o = np.nonzero(keep)[0][np.argsort(inv_rank[keep])]
    inv_s = inv_rank[o]
    ret_s = ret[o]
    m = len(o)

    # preds/horizon: same O(n log n) formulas as pack_history.
    ret_sorted = np.sort(ret_s)
    preds = np.searchsorted(ret_sorted, inv_s, side="left").astype(np.int64)
    inv_before_ret = np.searchsorted(inv_s, ret_s, side="left").astype(np.int64)
    horizon = np.minimum(inv_before_ret - 1, m - 1)

    return PackedOps(
        inv=inv_s,
        ret=ret_s,
        process=proc[o],
        status=status[o],
        f=fc[o],
        a0=a0[o],
        a1=a1[o],
        src_index=inv_s.copy(),
        preds=preds,
        horizon=horizon,
    )
