"""General-purpose utilities for jepsen-tpu.

Host-side equivalents of the reference's `jepsen.util`
(/root/reference/jepsen/src/jepsen/util.clj): parallel maps with meaningful
exception selection, time bookkeeping, retry/timeout helpers, majorities,
interval-set rendering.  Everything here is pure Python; no JAX.
"""

from __future__ import annotations

import contextlib
import itertools
import math
import random
import threading
import time as _time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterable, Iterator, Sequence, TypeVar

T = TypeVar("T")
U = TypeVar("U")


# ---------------------------------------------------------------------------
# Parallel maps
# ---------------------------------------------------------------------------


def real_pmap(f: Callable[[T], U], xs: Iterable[T]) -> list[U]:
    """Maps f over xs with one thread per element, returning results in
    order.  If any call throws, raises the first *meaningful* exception
    (preferring non-interrupt errors), like `jepsen.util/real-pmap`
    (util.clj:71-83).  Used for per-node control-plane fan-out."""
    xs = list(xs)
    if not xs:
        return []
    results: list[Any] = [None] * len(xs)
    errors: list[tuple[int, BaseException]] = []
    lock = threading.Lock()

    def run(i: int, x: T) -> None:
        try:
            results[i] = f(x)
        except BaseException as e:  # noqa: BLE001 - re-raised below
            with lock:
                errors.append((i, e))

    threads = [
        threading.Thread(target=run, args=(i, x), daemon=True)
        for i, x in enumerate(xs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        # Prefer a non-KeyboardInterrupt error, like real-pmap prefers
        # non-InterruptedException.
        errors.sort(key=lambda ie: (isinstance(ie[1], KeyboardInterrupt), ie[0]))
        raise errors[0][1]
    return results


def bounded_pmap(f: Callable[[T], U], xs: Iterable[T], bound: int | None = None) -> list[U]:
    """Parallel map over xs with at most `bound` concurrent workers
    (default: cpu count + 2), preserving order.  Mirrors the reference's
    `bounded-pmap` used by `jepsen.independent/checker`
    (independent.clj:346-367)."""
    import os

    xs = list(xs)
    if not xs:
        return []
    if bound is None:
        bound = (os.cpu_count() or 4) + 2
    with ThreadPoolExecutor(max_workers=bound) as pool:
        return list(pool.map(f, xs))


# ---------------------------------------------------------------------------
# Time
# ---------------------------------------------------------------------------

#: Conversions, mirroring util.clj:380-407.
NANOS_PER_MS = 1_000_000
NANOS_PER_SECOND = 1_000_000_000

_relative_time_origin = threading.local()


@contextlib.contextmanager
def with_relative_time() -> Iterator[None]:
    """Binds a nanosecond-resolution time origin for `relative_time_nanos`
    (util.clj:397-407, bound at core.clj:400)."""
    old = getattr(_relative_time_origin, "origin", None)
    _relative_time_origin.origin = _time.monotonic_ns()
    try:
        yield
    finally:
        _relative_time_origin.origin = old


def relative_time_nanos() -> int:
    """Nanoseconds since the enclosing `with_relative_time` (or process-start
    monotonic clock if unbound)."""
    origin = getattr(_relative_time_origin, "origin", None)
    if origin is None:
        return _time.monotonic_ns()
    return _time.monotonic_ns() - origin


def ms_to_nanos(ms: float) -> int:
    return int(ms * NANOS_PER_MS)


def nanos_to_ms(ns: float) -> float:
    return ns / NANOS_PER_MS

def nanos_to_secs(ns: float) -> float:
    return ns / NANOS_PER_SECOND


def sleep_ms(ms: float) -> None:
    """High-resolution-ish sleep (util.clj:409-428)."""
    _time.sleep(ms / 1000.0)


# ---------------------------------------------------------------------------
# Control flow
# ---------------------------------------------------------------------------


class JepsenTimeout(Exception):
    """Raised when a `timeout`-bounded call exceeds its budget."""


def timeout(ms: float, f: Callable[[], T], *, default: Any = JepsenTimeout) -> T:
    """Runs f in a worker thread with a deadline, like the `timeout` macro
    (util.clj:430-441).  On expiry returns `default` (or raises
    JepsenTimeout when no default given).  The worker thread is abandoned
    (Python cannot safely kill threads), matching the advisory nature of
    the reference's thread interrupt."""
    box: list[Any] = []
    err: list[BaseException] = []

    def run() -> None:
        try:
            box.append(f())
        except BaseException as e:  # noqa: BLE001
            err.append(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(ms / 1000.0)
    if t.is_alive():
        if default is JepsenTimeout:
            raise JepsenTimeout(f"timed out after {ms} ms")
        return default
    if err:
        raise err[0]
    return box[0]


class RetryExhausted(Exception):
    pass


class Deadline:
    """A wall-clock budget that can be handed down a call tree, mirroring
    the way the reference threads `timeout` budgets through `jepsen.util`.
    `Deadline(None)` is unbounded: remaining() is inf and it never expires,
    so callers can thread one object without branching on "is there a
    budget at all?".

        d = Deadline(30.0)
        while not d.expired():
            step(timeout_s=d.remaining())
        d.check("drain")          # raises JepsenTimeout when expired
        child = d.capped(5.0)     # sub-budget: min(parent left, 5 s)
    """

    __slots__ = ("seconds", "_t0")

    def __init__(self, seconds: float | None):
        self.seconds = seconds
        self._t0 = _time.monotonic()

    @classmethod
    def never(cls) -> "Deadline":
        return cls(None)

    def elapsed(self) -> float:
        return _time.monotonic() - self._t0

    def remaining(self) -> float:
        """Seconds left (may be negative once expired; inf if unbounded)."""
        if self.seconds is None:
            return float("inf")
        return self.seconds - self.elapsed()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def check(self, what: str = "deadline") -> None:
        """Raises JepsenTimeout when the budget is spent."""
        if self.expired():
            raise JepsenTimeout(
                f"{what} exceeded {self.seconds:.3f} s budget"
            )

    def capped(self, seconds: float | None) -> "Deadline":
        """A fresh sub-budget: at most `seconds`, never more than what's
        left here.  Lets a stage grant children a slice of its own time."""
        left = self.remaining()
        if seconds is None:
            return Deadline(None if left == float("inf") else max(left, 0.0))
        if left == float("inf"):
            return Deadline(seconds)
        return Deadline(max(min(seconds, left), 0.0))

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        if self.seconds is None:
            return "Deadline(unbounded)"
        return f"Deadline({self.remaining():.3f}s of {self.seconds:.3f}s left)"


def with_retry(
    f: Callable[[], T],
    *,
    retries: int = 5,
    backoff_ms: float = 100.0,
    max_backoff_ms: float = 30_000.0,
    jitter: float = 0.5,
    retry_on: tuple[type[BaseException], ...] = (Exception,),
    no_retry_on: tuple[type[BaseException], ...] = (),
    deadline: Deadline | None = None,
    log: Callable[[str], None] | None = None,
) -> T:
    """Calls f, retrying up to `retries` times with exponential backoff +
    jitter, like `with-retry` (util.clj:487-527) and the SSH retry policy
    (control/retry.clj:15-21: 5 retries, ~100 ms base).  Only exceptions
    matching `retry_on` are retried; anything else propagates at once.
    `no_retry_on` wins over `retry_on`, for carving a non-retryable
    subclass out of a retryable base (e.g. RemoteDisconnected under
    RemoteError, where the command may already have applied).
    Sleep for attempt k is `backoff_ms * 2^(k-1)`, capped at
    `max_backoff_ms`, stretched by up to `jitter` fraction.  An optional
    `deadline` bounds the whole loop: when the budget would be exceeded
    the last exception propagates instead of sleeping."""
    attempt = 0
    while True:
        try:
            return f()
        except retry_on as e:
            if no_retry_on and isinstance(e, no_retry_on):
                raise
            attempt += 1
            if attempt > retries:
                raise
            pause = min(backoff_ms * (2 ** (attempt - 1)), max_backoff_ms)
            pause *= 1 + jitter * random.random()
            if deadline is not None and deadline.remaining() < pause / 1000.0:
                raise
            if log:
                log(f"retry {attempt}/{retries} after {type(e).__name__}: {e}")
            _time.sleep(pause / 1000.0)


def await_fn(
    f: Callable[[], T],
    *,
    retry_interval_ms: float = 1000.0,
    timeout_ms: float = 60_000.0,
    log_interval_ms: float | None = 10_000.0,
    log_message: str | None = None,
    log: Callable[[str], None] | None = None,
) -> T:
    """Invokes f until it returns without throwing; throws JepsenTimeout when
    the deadline passes.  Logs progress via `log` every `log_interval_ms`
    (util.clj:443-485; defaults to the stdlib logger)."""
    if log is None:
        import logging

        log = logging.getLogger("jepsen_tpu").info
    deadline = _time.monotonic() + timeout_ms / 1000.0
    last_log = _time.monotonic()
    while True:
        try:
            return f()
        except Exception as e:
            now = _time.monotonic()
            if now > deadline:
                raise JepsenTimeout(
                    log_message or f"await_fn timed out after {timeout_ms} ms"
                ) from e
            if log_interval_ms and (now - last_log) * 1000 >= log_interval_ms:
                last_log = now
                log(log_message or f"waiting for {getattr(f, '__name__', 'fn')}")
            _time.sleep(retry_interval_ms / 1000.0)


# ---------------------------------------------------------------------------
# Math / collections
# ---------------------------------------------------------------------------


def majority(n: int) -> int:
    """Smallest integer strictly greater than half of n; majority(0) == 1
    (util.clj:90-97)."""
    return max(1, n // 2 + 1)


def chunks(xs: Sequence[T], size: int) -> Iterator[Sequence[T]]:
    for i in range(0, len(xs), size):
        yield xs[i : i + size]


def integer_interval_set_str(xs: Iterable[int]) -> str:
    """Renders a set of integers as compact interval notation, e.g.
    #{1..3 5 7..9} (util.clj:691-721)."""
    xs = sorted(set(xs))
    parts: list[str] = []
    i = 0
    while i < len(xs):
        j = i
        while j + 1 < len(xs) and xs[j + 1] == xs[j] + 1:
            j += 1
        if j == i:
            parts.append(str(xs[i]))
        else:
            parts.append(f"{xs[i]}..{xs[j]}")
        i = j + 1
    return "#{" + " ".join(parts) + "}"


def rand_exp(rate: float, rng: random.Random | None = None) -> float:
    """Exponentially-distributed random value with given rate; used by
    stagger-style generators (generator.clj:1346-1361)."""
    r = (rng or random).random()
    return -math.log(1.0 - r) / rate


def nemesis_intervals(history: Iterable[Any], start_fs=("start",), stop_fs=("stop",)) -> list[tuple[Any, Any]]:
    """Pairs of [start-op stop-op] for nemesis activity windows
    (util.clj:780-826).  Like the reference: consecutive ops pair up as
    (invoke, completion) — pairs with mismatched :f are dropped — every
    open start pair is closed by the next stop pair (start1 start2
    start3 start4 stop1 stop2 yields [s1 e1] [s2 e2] [s3 e1] [s4 e2]),
    and unclosed intervals pair with None.

    Like the reference (util.clj:803-805), the input is filtered to
    nemesis ops first — the strict stride-2 pairing would misalign on
    any interleaved client op.  Contract note: callers passing
    synthetic ops without a `process` field (pre-round-2 behavior
    accepted "any objects with .f attributes") fall back to unfiltered
    pairing, so a nemesis-only synthetic history keeps yielding
    intervals instead of silently returning []."""
    history = list(history)
    ops = [
        o for o in history
        if getattr(o, "process", None) == "nemesis"
    ]
    if not ops:
        # Only the process-less ops join the fallback: client ops with
        # real process ids must never enter the stride-2 pairing (the
        # misalignment the nemesis filter exists to prevent).
        ops = [
            o for o in history
            if getattr(o, "process", None) is None and hasattr(o, "f")
        ]
    pairs = [
        (ops[i], ops[i + 1])
        for i in range(0, len(ops) - 1, 2)
        if getattr(ops[i], "f", None) == getattr(ops[i + 1], "f", None)
    ]
    intervals: list[tuple[Any, Any]] = []
    open_starts: list[tuple[Any, Any]] = []
    for a, b in pairs:
        f = getattr(a, "f", None)
        if f in start_fs:
            open_starts.append((a, b))
        elif f in stop_fs:
            for s1, s2 in open_starts:
                intervals.append((s1, a))
                intervals.append((s2, b))
            open_starts = []
    for s1, s2 in open_starts:
        intervals.append((s1, None))
        intervals.append((s2, None))
    return intervals


def name_thread(name: str) -> contextlib.AbstractContextManager[None]:
    """Temporarily renames the current thread (util.clj:723-735), useful in
    log lines."""

    @contextlib.contextmanager
    def ctx() -> Iterator[None]:
        t = threading.current_thread()
        old = t.name
        t.name = name
        try:
            yield
        finally:
            t.name = old

    return ctx()


def coll_str(x: Any, limit: int = 8) -> str:
    """Abbreviated rendering of long collections for log lines."""
    try:
        xs = list(x)
    except TypeError:
        return repr(x)
    if len(xs) <= limit:
        return repr(xs)
    return f"[{', '.join(map(repr, xs[:limit]))}, ... ({len(xs)} total)]"


class Forgettable:
    """A reference that can forget its value, letting the head of a
    long generator chain be GC'd during a run (util.clj:1037-1066)."""

    __slots__ = ("_value", "_forgotten")

    def __init__(self, value: Any):
        self._value = value
        self._forgotten = False

    def deref(self) -> Any:
        if self._forgotten:
            raise ValueError("value has been forgotten")
        return self._value

    def forget(self) -> None:
        self._value = None
        self._forgotten = True


def fraction(num: float, denom: float) -> float:
    """num/denom, but 0 when denom is 0 (checker.clj fraction helper)."""
    return num / denom if denom else 0.0


def sanitize_path_part(part: Any) -> str:
    """One safe filesystem path component from an arbitrary value:
    hostile characters become underscores, and names that are empty or
    all dots (".", "..", "" — which would escape or collapse the
    parent directory) are fully underscored.  Shared by the fs cache
    and per-key artifact writers."""
    import re

    s = re.sub(r"[^A-Za-z0-9._-]", "_", str(part))
    if not s or set(s) <= {"."}:
        return "_" * max(1, len(s))
    return s


def summarize_times(times: Sequence[float]) -> dict:
    """Median/best/spread summary of measured rep times, the shared
    shape every measurement tool records (multi-rep evidence: a
    capture with reps >= 3 is a median, not a mood).  Keys: best_s,
    median_s, spread_s=[min, max], reps."""
    ts = sorted(times)
    if not ts:
        raise ValueError("no measurements")
    return {
        "best_s": round(ts[0], 3),
        "median_s": round(ts[len(ts) // 2], 3),
        "spread_s": [round(ts[0], 3), round(ts[-1], 3)],
        "reps": len(ts),
    }
