"""Kafka checker artifacts: conviction trail + plots in the store dir.

The reference's kafka checker doesn't just return data — it renders
plots of unseen messages over time and per-consumer realtime lag, and
writes the version-order divergences, into the test's store directory
(tests/kafka.clj:99-180 and the plotting code around :1300).  This
module is that half for the repo's kafka checker (VERDICT r3 #6), in
the house style of checker/elle's write_artifacts: JSON + DOT always,
matplotlib SVG plots, every write failure swallowed so a side-output
problem can never downgrade a computed verdict.

Artifacts (under <store>/kafka/):
  anomalies.json     valid / anomaly-types / anomalies (invalid runs)
  cycle-*.dot        one Graphviz file per ww/wr dependency cycle
  version-orders.json the per-key version order for every key named in
                     an inconsistent-offsets divergence
  unseen.json        final unseen values per key + the time series
  unseen.svg         acked-but-never-polled message count over time
  realtime-lag.svg   per-process poll lag over time (version-order
                     indices behind the newest sent value — an
                     index-based analogue of the reference's
                     time-based consumer lag)
"""

from __future__ import annotations

import json
import logging
import os
from collections import defaultdict
from typing import Any, Optional

from ..history.core import History, Op
from .kafka import TXN_FS, op_reads, op_writes, version_orders, reads_by_type

log = logging.getLogger(__name__)

MAX_POINTS = 1024  # downsample plots/series beyond this


def unseen_series(ops: list[Op]) -> list[tuple[float, int]]:
    """(t_seconds, total unseen count) after each completed txn:
    acked sends not yet polled by anyone (the time-resolved version
    of kafka.unseen_final, kafka.clj:1268-1303)."""
    sent: dict[Any, set] = defaultdict(set)
    polled: dict[Any, set] = defaultdict(set)
    series: list[tuple[float, int]] = []
    unseen = 0
    for op in ops:
        if op.type != "ok" or op.f not in TXN_FS:
            continue
        for k, vs in op_writes(op).items():
            for v in vs:
                if v not in sent[k]:
                    sent[k].add(v)
                    if v not in polled[k]:
                        unseen += 1
        for k, vs in op_reads(op).items():
            for v in vs:
                if v not in polled[k]:
                    polled[k].add(v)
                    if v in sent[k]:
                        unseen -= 1
        series.append(((op.time or 0) / 1e9, unseen))
    return _downsample(series)


def lag_series(ops: list[Op], orders: Optional[dict] = None,
               ) -> dict[Any, list[tuple[float, int]]]:
    """{process: [(t_seconds, lag)]} — at each completed poll, how many
    version-order positions the polled value sits behind the newest
    value sent so far on that key; a process's point is its worst key.
    Index-based analogue of the reference's realtime consumer lag.
    `orders` accepts a precomputed version-order map so one analysis
    pass can serve every artifact."""
    if orders is None:
        orders, _ = version_orders(ops, reads_by_type(ops))
    newest: dict[Any, int] = {}
    out: dict[Any, list[tuple[float, int]]] = defaultdict(list)
    for op in ops:
        if op.type != "ok" or op.f not in TXN_FS:
            continue
        for k, vs in op_writes(op).items():
            vo = orders.get(k)
            if vo is None:
                continue
            for v in vs:
                i = vo.by_value.get(v)
                if i is not None and i > newest.get(k, -1):
                    newest[k] = i
        worst: Optional[int] = None
        for k, vs in op_reads(op).items():
            vo = orders.get(k)
            if vo is None or not vs:
                continue
            i = vo.by_value.get(vs[-1])
            if i is None:
                continue
            lag = max(0, newest.get(k, i) - i)
            worst = lag if worst is None else max(worst, lag)
        if worst is not None:
            out[op.process].append(((op.time or 0) / 1e9, worst))
    return {p: _downsample(s) for p, s in out.items()}


def _downsample(series: list) -> list:
    if len(series) <= MAX_POINTS:
        return series
    step = len(series) / MAX_POINTS
    return [series[int(i * step)] for i in range(MAX_POINTS)] + [series[-1]]


def _plot_unseen(series: list, path: str) -> None:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(8, 3))
    if series:
        t0 = series[0][0]
        ax.step([t - t0 for t, _ in series], [u for _, u in series],
                where="post", color="#FFA400")
    ax.set_xlabel("time (s)")
    ax.set_ylabel("unseen messages")
    ax.set_title("acked sends not yet polled")
    fig.tight_layout()
    fig.savefig(path)
    plt.close(fig)


def _plot_lag(lags: dict, path: str) -> None:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(8, 3))
    t0 = min(
        (s[0][0] for s in lags.values() if s), default=0.0
    )
    for p, series in sorted(lags.items(), key=lambda kv: repr(kv[0])):
        ax.plot([t - t0 for t, _ in series], [v for _, v in series],
                label=f"p{p}", linewidth=1)
    ax.set_xlabel("time (s)")
    ax.set_ylabel("poll lag (version-order positions)")
    ax.set_title("realtime lag per process")
    if lags:
        ax.legend(fontsize=7, ncols=4)
    fig.tight_layout()
    fig.savefig(path)
    plt.close(fig)


def write_artifacts(result: dict, opts: Optional[dict],
                    history: History | list[Op]) -> None:
    """Persists the kafka analysis into <store>/kafka/ (see module
    doc).  Never raises: a side-output failure must not let
    check_safe downgrade the computed verdict — same policy as
    checker/elle.write_artifacts."""
    directory = (opts or {}).get("dir")
    if not directory:
        return
    try:
        ops = [o for o in history if o.f in TXN_FS]
        out = os.path.join(directory, "kafka")
        os.makedirs(out, exist_ok=True)

        # One version-order inference serves the lag plot AND the
        # divergence artifact below (each previously recomputed it on
        # top of analyze()'s own pass).
        orders, _ = version_orders(ops, reads_by_type(ops))

        series = unseen_series(ops)
        with open(os.path.join(out, "unseen.json"), "w") as f:
            json.dump(
                {"final": result.get("unseen"), "series": series},
                f, indent=2, default=repr,
            )
        _plot_unseen(series, os.path.join(out, "unseen.svg"))
        _plot_lag(lag_series(ops, orders),
                  os.path.join(out, "realtime-lag.svg"))

        if result.get("valid") is True:
            return
        anomalies = result.get("anomalies") or {}
        with open(os.path.join(out, "anomalies.json"), "w") as f:
            json.dump(
                {
                    "valid": result.get("valid"),
                    "anomaly-types": result.get("anomaly-types"),
                    "anomalies": anomalies,
                },
                f, indent=2, default=repr,
            )

        # Version orders for every key a divergence names
        # (kafka.clj's version-order artifacts).
        divergent = {
            d.get("key")
            for d in anomalies.get("inconsistent-offsets", ())
            if isinstance(d, dict)
        }
        if divergent:
            with open(os.path.join(out, "version-orders.json"),
                      "w") as f:
                json.dump(
                    {
                        repr(k): list(orders[k].by_index)
                        for k in divergent if k in orders
                    },
                    f, indent=2, default=repr,
                )

        # One DOT per dependency cycle, elle-style.
        cycles = [
            c for v in anomalies.values() if isinstance(v, list)
            for c in v if isinstance(c, dict) and "steps" in c
        ]
        for i, c in enumerate(cycles):
            lines = ["digraph cycle {"]
            for step in c.get("steps", []):
                label = ",".join(step.get("types", []))
                lines.append(
                    f'  "T{step["from"]}" -> "T{step["to"]}" '
                    f'[label="{label}"];'
                )
            lines.append("}")
            name = f"cycle-{i}-{c.get('type', 'cycle')}.dot"
            with open(os.path.join(out, name), "w") as f:
                f.write("\n".join(lines) + "\n")
    except Exception as e:  # noqa: BLE001 — side output only
        log.warning("could not write kafka artifacts to %s: %r",
                    directory, e)
