"""Grow-only set workload: unique adds, then a final read.

The client/generator side for the reference's set checkers
(checker.clj:257-287 set, :487-612 set-full); jepsen uses this shape in
most DB suites' "set" workloads.
"""

from __future__ import annotations

import itertools
import threading
from typing import Optional

from .. import client as jc
from ..checker.core import SetChecker, SetFull
from ..generator.core import FnGen, phases, repeat, until_ok
from ..history import OK


class InMemorySetClient(jc.Client):
    def __init__(self, state=None, lock=None):
        self.state = state if state is not None else set()
        self.lock = lock or threading.Lock()

    def open(self, test, node):
        return InMemorySetClient(self.state, self.lock)

    def invoke(self, test, op):
        with self.lock:
            if op.f == "add":
                self.state.add(op.value)
                return op.complete(OK)
            return op.complete(OK, value=sorted(self.state))

    def reusable(self, test):
        return True


def generator(full: bool = False, read_fraction: float = 0.1,
              rng=None):
    """Unique adds, then a final read retried until it succeeds
    (the zookeeper.clj:120-127 shape).  With full=True, reads are
    interleaved throughout at `read_fraction` for the set-full
    checker — staleness-hunting suites want a dense read stream
    (repkv uses 0.5)."""
    import random as _random

    counter = itertools.count()
    adds = FnGen(lambda: {"f": "add", "value": next(counter)})
    if full:
        r = rng or _random

        def step():
            if r.random() < read_fraction:
                return {"f": "read"}
            return {"f": "add", "value": next(counter)}

        return FnGen(step)
    return adds


def final_generator():
    # repeat: dicts are one-shot, and the read must retry until it lands
    # (until-ok over repeat, the zookeeper.clj:120-127 shape).
    return until_ok(repeat({"f": "read"}))


def workload(opts: Optional[dict] = None) -> dict:
    opts = opts or {}
    full = bool(opts.get("full"))
    return {
        "name": "set-full" if full else "set",
        "generator": generator(full),
        "final-generator": final_generator(),
        "checker": SetFull(
            linearizable=opts.get("linearizable", False)
        )
        if full
        else SetChecker(),
        "client": InMemorySetClient(),
    }
