"""Kafka-style totally-ordered-log workload and checker.

Equivalent of /root/reference/jepsen/src/jepsen/tests/kafka.clj — the
reference's largest and most intricate checker.  The system under test
is a set of append-only partitions ("keys"); producers *send* values
which get durable, theoretically monotonically-increasing *offsets*;
consumers *subscribe* (the system assigns partitions) or *assign*
(manual), and *poll* batches of [offset value] pairs, advancing their
position.

Op grammar (kafka.clj:24-98):

    {"f": "subscribe"|"assign", "value": [k1, k2, ...]}
      (assign may carry ext {"seek-to-beginning?": True})
    {"f": "send"|"poll"|"txn", "value": [mop, ...]}
      mop ["send", k, v]            -> completed ["send", k, [offset v]]
      mop ["poll"]                  -> completed ["poll", {k: [[o v] ...]}]

Analyses (kafka.clj:99-180, functions :725-1300, :1791-1878):

  1. version orders per key from every observed (offset, value) —
     divergence at one offset = inconsistent-offsets.
  2. g1a (aborted read): committed poll observes a failed send.
  3. lost-write: every value whose last log index precedes the highest
     *observed* index of its key must be read by someone (with the
     value->first-index / last-index->values bound construction).
  4. ww/wr dependency graph over version orders + elle cycle search
     (G0/G1c... via checker/elle/graph.py; rw edges like the reference's
     disabled rw-graph are omitted).
  5. internal poll/send contiguity: skips and nonmonotonic pairs inside
     one transaction.
  6. cross-op per-process poll/send contiguity (resetting on
     assign/subscribe), duplicates, and unseen counts.

The client here is the reference *pattern* (a real Kafka), realized as
an in-memory total-order log with injectable fault modes so the checker
has real anomalies to find in tests.
"""

from __future__ import annotations

import random
import threading
from collections import defaultdict
from typing import Any, Iterable, Optional

from .. import client as jc
from ..checker.core import Checker
from ..generator.core import PENDING, Generator, fill_in_op, gen_op
from ..history import FAIL, INFO, OK, History, Op

TXN_FS = ("txn", "poll", "send")


# ---------------------------------------------------------------------------
# Micro-op readers (kafka.clj:462-535)
# ---------------------------------------------------------------------------


def op_writes(op: Op) -> dict[Any, list]:
    """{key: [value, ...]} sent by this op, in mop order."""
    out: dict[Any, list] = defaultdict(list)
    if op.f in TXN_FS:
        for mop in op.value or []:
            if mop and mop[0] == "send":
                k, v = mop[1], mop[2]
                if isinstance(v, (list, tuple)):
                    v = v[1]
                out[k].append(v)
    return dict(out)


def op_write_offsets(op: Op) -> dict[Any, list]:
    """{key: [offset, ...]} for sends with known offsets."""
    out: dict[Any, list] = defaultdict(list)
    if op.f in TXN_FS:
        for mop in op.value or []:
            if mop and mop[0] == "send":
                v = mop[2]
                if isinstance(v, (list, tuple)) and v[0] is not None:
                    out[mop[1]].append(v[0])
    return dict(out)


def op_reads(op: Op) -> dict[Any, list]:
    """{key: [value, ...]} polled by this op, in offset order."""
    out: dict[Any, list] = defaultdict(list)
    if op.f in ("txn", "poll"):
        for mop in op.value or []:
            if mop and mop[0] == "poll" and len(mop) > 1 and mop[1]:
                for k, pairs in mop[1].items():
                    for off, v in pairs:
                        out[k].append(v)
    return dict(out)


def op_read_offsets(op: Op) -> dict[Any, list]:
    out: dict[Any, list] = defaultdict(list)
    if op.f in ("txn", "poll"):
        for mop in op.value or []:
            if mop and mop[0] == "poll" and len(mop) > 1 and mop[1]:
                for k, pairs in mop[1].items():
                    for off, v in pairs:
                        if off is not None:
                            out[k].append(off)
    return dict(out)


def _observed_pairs(op: Op) -> Iterable[tuple[Any, int, Any]]:
    """Every (key, offset, value) this op fixes in the log."""
    if op.f not in TXN_FS:
        return
    for mop in op.value or []:
        if not mop:
            continue
        if mop[0] == "send":
            v = mop[2]
            if isinstance(v, (list, tuple)) and v[0] is not None:
                yield (mop[1], v[0], v[1])
        elif mop[0] == "poll" and len(mop) > 1 and mop[1]:
            for k, pairs in mop[1].items():
                for off, v in pairs:
                    if off is not None:
                        yield (k, off, v)


# ---------------------------------------------------------------------------
# Version orders (kafka.clj:738-877)
# ---------------------------------------------------------------------------


def writes_by_type(history: Iterable[Op]) -> dict[str, dict]:
    """{"ok"/"info"/"fail": {key: set(values)}}."""
    out = {"ok": defaultdict(set), "info": defaultdict(set),
           "fail": defaultdict(set)}
    for op in history:
        if op.type in ("ok", "info", "fail") and op.f in TXN_FS:
            for k, vs in op_writes(op).items():
                out[op.type][k].update(vs)
    return {t: dict(d) for t, d in out.items()}


def reads_by_type(history: Iterable[Op]) -> dict[str, dict]:
    out = {"ok": defaultdict(set), "info": defaultdict(set),
           "fail": defaultdict(set)}
    for op in history:
        if op.type in ("ok", "info", "fail") and op.f in ("txn", "poll"):
            for k, vs in op_reads(op).items():
                out[op.type][k].update(vs)
    return {t: dict(d) for t, d in out.items()}


def must_have_committed(rbt: dict, op: Op) -> bool:
    """ok, or info with at least one send proven read
    (kafka.clj:725-737)."""
    if op.type == "ok":
        return True
    if op.type != "info":
        return False
    ok = rbt.get("ok", {})
    for k, vs in op_writes(op).items():
        if set(vs) & set(ok.get(k, ())):
            return True
    return False


class VersionOrder:
    """One key's log reconstruction: `log[offset] = set(values)`,
    `by_index` dense (gap-free) single-value order, `by_value` inverse."""

    __slots__ = ("log", "by_index", "by_value")

    def __init__(self, log: list):
        self.log = log
        self.by_index = [sorted(vs, key=repr)[0] for vs in log if vs]
        self.by_value = {}
        for i, v in enumerate(self.by_index):
            self.by_value.setdefault(v, i)

    def value_to_first_index(self) -> dict:
        out: dict = {}
        i = 0
        for vs in self.log:
            if not vs:
                continue
            for v in vs:
                out.setdefault(v, i)
            i += 1
        return out

    def last_index_to_values(self) -> list:
        latest: dict = {}
        i = 0
        for vs in self.log:
            if not vs:
                continue
            for v in vs:
                latest[v] = i
            i += 1
        out: list = [set() for _ in range(i)]
        for v, idx in latest.items():
            out[idx].add(v)
        return out


def version_orders(history: Iterable[Op], rbt: dict) -> tuple[dict, list]:
    """-> ({key: VersionOrder}, [inconsistency error maps])."""
    logs: dict[Any, list] = defaultdict(list)
    for op in history:
        if op.f in TXN_FS and must_have_committed(rbt, op):
            for k, off, v in _observed_pairs(op):
                log = logs[k]
                while len(log) <= off:
                    log.append(None)
                if log[off] is None:
                    log[off] = {v}
                else:
                    log[off].add(v)
    errors = []
    for k, log in logs.items():
        index = 0
        for off, vs in enumerate(log):
            if not vs:
                continue
            if len(vs) > 1:
                errors.append({
                    "key": k, "offset": off, "index": index,
                    "values": sorted(vs, key=repr),
                })
            index += 1
    return {k: VersionOrder(log) for k, log in logs.items()}, errors


# ---------------------------------------------------------------------------
# Anomaly analyses
# ---------------------------------------------------------------------------


def _writer_of(history: Iterable[Op]) -> dict:
    """{key: {value: op}} over non-invoke sends."""
    out: dict[Any, dict] = defaultdict(dict)
    for op in history:
        if op.type in ("ok", "info", "fail") and op.f in TXN_FS:
            for k, vs in op_writes(op).items():
                for v in vs:
                    out[k].setdefault(v, op)
    return dict(out)


def _readers_of(history: Iterable[Op]) -> dict:
    out: dict[Any, dict] = defaultdict(lambda: defaultdict(list))
    for op in history:
        if op.type == "ok" and op.f in ("txn", "poll"):
            for k, vs in op_reads(op).items():
                for v in vs:
                    out[k][v].append(op)
    return {k: dict(d) for k, d in out.items()}


def g1a_cases(history: list[Op], wbt: dict) -> list[dict]:
    """Committed polls observing failed sends (kafka.clj:877-896)."""
    failed = wbt.get("fail", {})
    out = []
    for op in history:
        if op.type != "ok" or op.f not in ("txn", "poll"):
            continue
        for k, vs in op_reads(op).items():
            for v in vs:
                if v in failed.get(k, ()):
                    out.append({"key": k, "value": v,
                                "reader": op.index})
    return out


def lost_write_cases(history: list[Op], orders: dict, rbt: dict,
                     writer_of: dict) -> list[dict]:
    """kafka.clj:896-991: for each key, values whose last appearance
    precedes the highest observed index must all be read."""
    out = []
    for k, vs in rbt.get("ok", {}).items():
        vo = orders.get(k)
        if vo is None:
            continue
        v2fi = vo.value_to_first_index()
        li2v = vo.last_index_to_values()
        bound = max((v2fi[v] for v in vs if v in v2fi), default=-1)
        must_read: list = []
        for idx in range(bound + 1):
            must_read.extend(li2v[idx])
        lost = [v for v in must_read if v not in vs]
        for v in list(lost):
            w = writer_of.get(k, {}).get(v)
            if w is None or not must_have_committed(rbt, w):
                lost.remove(v)
        for v in lost:
            w = writer_of.get(k, {}).get(v)
            out.append({
                "key": k, "value": v,
                "index": v2fi.get(v),
                "max-read-index": bound,
                "writer": w.index if w is not None else None,
            })
    return out


def duplicate_cases(orders: dict) -> list[dict]:
    """A value at more than one offset (kafka.clj:1252-1267)."""
    out = []
    for k, vo in orders.items():
        counts: dict = defaultdict(int)
        for v in vo.by_index:
            counts[v] += 1
        for v, n in counts.items():
            if n > 1:
                out.append({"key": k, "value": v, "count": n})
    return out


def unseen_final(history: list[Op]) -> dict:
    """Final unseen counts: acked sends never polled by anyone
    (kafka.clj:1268-1303, final element)."""
    sent: dict[Any, set] = defaultdict(set)
    polled: dict[Any, set] = defaultdict(set)
    for op in history:
        if op.type != "ok" or op.f not in TXN_FS:
            continue
        for k, vs in op_writes(op).items():
            sent[k].update(vs)
        for k, vs in op_reads(op).items():
            polled[k].update(vs)
    unseen = {k: vs - polled.get(k, set()) for k, vs in sent.items()}
    return {k: sorted(vs, key=repr) for k, vs in unseen.items() if vs}


def _pair_cases(pairs_by_key: dict, orders: dict, op: Op,
                skipped_limit: int = 16):
    """Shared skip/nonmonotonic detection over consecutive (v1, v2)
    pairs (kafka.clj:997-1088)."""
    skips, nonmono = [], []
    for k, vs in pairs_by_key.items():
        vo = orders.get(k)
        if vo is None:
            continue
        for v1, v2 in zip(vs, vs[1:]):
            i1 = vo.by_value.get(v1)
            i2 = vo.by_value.get(v2)
            delta = (i2 - i1) if (i1 is not None and i2 is not None) else 1
            if delta > 1:
                skips.append({
                    "key": k, "values": [v1, v2], "delta": delta,
                    "skipped": vo.by_index[i1 + 1 : i2][:skipped_limit],
                    "op": op.index,
                })
            elif delta < 1:
                nonmono.append({
                    "key": k, "values": [v1, v2], "delta": delta,
                    "op": op.index,
                })
    return skips, nonmono


def int_poll_cases(history: list[Op], orders: dict) -> dict:
    """Internal read contiguity (kafka.clj:997-1050)."""
    skips, nonmono = [], []
    for op in history:
        if op.type not in ("ok", "info") or op.f not in ("txn", "poll"):
            continue
        rebalanced = set()
        for ev in op.ext.get("rebalance-log") or []:
            rebalanced.update(ev.get("keys") or [])
        reads = {k: vs for k, vs in op_reads(op).items()
                 if k not in rebalanced}
        s, n = _pair_cases(reads, orders, op)
        skips.extend(s)
        nonmono.extend(n)
    return {"skip": skips, "nonmonotonic": nonmono}


def int_send_cases(history: list[Op], orders: dict) -> dict:
    """Internal write contiguity (kafka.clj:1051-1088)."""
    skips, nonmono = [], []
    for op in history:
        if op.type == "invoke" or op.f not in TXN_FS:
            continue
        s, n = _pair_cases(op_writes(op), orders, op)
        skips.extend(s)
        nonmono.extend(n)
    return {"skip": skips, "nonmonotonic": nonmono}


def poll_cases(history: list[Op], orders: dict) -> dict:
    """Cross-op per-process poll contiguity; positions reset on
    assign/subscribe (kafka.clj:1088-1180)."""
    skips, nonmono = [], []
    by_process: dict[Any, list] = defaultdict(list)
    for op in history:
        if op.type in ("ok", "info"):
            by_process[op.process].append(op)
    for process, ops in by_process.items():
        last_seen: dict[Any, Any] = {}
        for op in ops:
            if op.f in ("assign", "subscribe"):
                last_seen.clear()
                continue
            if op.f not in ("txn", "poll"):
                continue
            for k, vs in op_reads(op).items():
                if not vs:
                    continue
                vo = orders.get(k)
                if vo is None:
                    continue
                if k in last_seen:
                    i1 = vo.by_value.get(last_seen[k])
                    i2 = vo.by_value.get(vs[0])
                    if i1 is not None and i2 is not None:
                        delta = i2 - i1
                        if delta > 1:
                            skips.append({
                                "key": k, "process": process,
                                "values": [last_seen[k], vs[0]],
                                "delta": delta, "op": op.index,
                                "skipped": vo.by_index[i1 + 1 : i2][:16],
                            })
                        elif delta < 1:
                            nonmono.append({
                                "key": k, "process": process,
                                "values": [last_seen[k], vs[0]],
                                "delta": delta, "op": op.index,
                            })
                last_seen[k] = vs[-1]
    return {"skip": skips, "nonmonotonic": nonmono}


def nonmonotonic_send_cases(history: list[Op], orders: dict) -> list:
    """Cross-op per-process send order (kafka.clj:1180-1252)."""
    out = []
    by_process: dict[Any, list] = defaultdict(list)
    for op in history:
        if op.type in ("ok", "info"):
            by_process[op.process].append(op)
    for process, ops in by_process.items():
        last_sent: dict[Any, Any] = {}
        for op in ops:
            if op.f not in TXN_FS:
                continue
            for k, vs in op_writes(op).items():
                if not vs:
                    continue
                vo = orders.get(k)
                if vo is not None and k in last_sent:
                    i1 = vo.by_value.get(last_sent[k])
                    i2 = vo.by_value.get(vs[0])
                    if i1 is not None and i2 is not None and i2 - i1 < 1:
                        out.append({
                            "key": k, "process": process,
                            "values": [last_sent[k], vs[0]],
                            "delta": i2 - i1, "op": op.index,
                        })
                last_sent[k] = vs[-1]
    return out


def dependency_cycles(history: list[Op], orders: dict,
                      writer_of: dict, readers_of: dict,
                      rw_edges: bool = False) -> list[dict]:
    """ww/wr graph over version orders (kafka.clj:1791-1878) run through
    the Elle-equivalent layered cycle search (device-screened).

    `rw_edges=True` (round 5, VERDICT r4 #9) also adds
    anti-dependency edges — reader of version i -> writer of version
    i+1 — recovering the G-single/G2 cycles the reference's DISABLED
    rw-graph would have found (kafka.clj keeps the remnants commented
    out because polls make rw edges noisy under rebalances; here the
    flag lets a suite opt in when its client keeps assignments
    stable)."""
    from ..checker.elle.graph import DepGraph
    from ..ops.scc import check_cycles_device

    g = DepGraph()
    for k, v2w in writer_of.items():
        vo = orders.get(k)
        if vo is None:
            continue
        for v2, op2 in v2w.items():
            i2 = vo.by_value.get(v2)
            if i2 is None or i2 == 0:
                continue
            v1 = vo.by_index[i2 - 1]
            op1 = v2w.get(v1)
            if op1 is not None and op1.index != op2.index:
                g.add_edge(op1.index, op2.index, "ww")
    for k, v2rs in readers_of.items():
        for v, readers in v2rs.items():
            w = writer_of.get(k, {}).get(v)
            if w is not None:
                for r in readers:
                    if r.index != w.index:
                        g.add_edge(w.index, r.index, "wr")
        if rw_edges:
            vo = orders.get(k)
            if vo is None:
                continue
            # Anti-dependency fires only from the LAST version each
            # reader observed of the key (its final position): a
            # reader that also polled the successor saw it, so there
            # is no "unread overwrite" to anti-depend on.
            last_read: dict[int, tuple[int, Any]] = {}
            for v, readers in v2rs.items():
                i = vo.by_value.get(v)
                if i is None:
                    continue
                for r in readers:
                    cur = last_read.get(r.index)
                    if cur is None or i > cur[0]:
                        last_read[r.index] = (i, r)
            for r_idx, (i, r) in last_read.items():
                if i + 1 >= len(vo.by_index):
                    continue
                w2 = writer_of.get(k, {}).get(vo.by_index[i + 1])
                if w2 is not None and r_idx != w2.index:
                    g.add_edge(r_idx, w2.index, "rw")
    return check_cycles_device([g])[0]


def analyze(history: History | list[Op], *,
            rw_edges: bool = False) -> dict:
    """Full kafka analysis -> {"valid", "anomaly-types", "anomalies",
    counts} (kafka.clj:1879-1984).  `rw_edges` opts into
    anti-dependency cycle edges (see dependency_cycles)."""
    ops = [o for o in history
           if o.f in TXN_FS + ("assign", "subscribe")]
    wbt = writes_by_type(ops)
    rbt = reads_by_type(ops)
    orders, order_errors = version_orders(ops, rbt)
    writer_of = _writer_of(ops)
    readers_of = _readers_of(ops)

    anomalies: dict[str, Any] = {}
    if order_errors:
        anomalies["inconsistent-offsets"] = order_errors
    g1a = g1a_cases(ops, wbt)
    if g1a:
        anomalies["G1a"] = g1a
    lost = lost_write_cases(ops, orders, rbt, writer_of)
    if lost:
        anomalies["lost-write"] = lost
    dups = duplicate_cases(orders)
    if dups:
        anomalies["duplicate"] = dups
    ip = int_poll_cases(ops, orders)
    if ip["skip"]:
        anomalies["int-poll-skip"] = ip["skip"]
    if ip["nonmonotonic"]:
        anomalies["int-poll-nonmonotonic"] = ip["nonmonotonic"]
    isnd = int_send_cases(ops, orders)
    if isnd["skip"]:
        anomalies["int-send-skip"] = isnd["skip"]
    if isnd["nonmonotonic"]:
        anomalies["int-send-nonmonotonic"] = isnd["nonmonotonic"]
    pc = poll_cases(ops, orders)
    if pc["skip"]:
        anomalies["poll-skip"] = pc["skip"]
    if pc["nonmonotonic"]:
        anomalies["nonmonotonic-poll"] = pc["nonmonotonic"]
    nms = nonmonotonic_send_cases(ops, orders)
    if nms:
        anomalies["nonmonotonic-send"] = nms
    cycles = dependency_cycles(ops, orders, writer_of, readers_of,
                               rw_edges=rw_edges)
    for c in cycles:
        anomalies.setdefault(c["type"], []).append(c)
    unseen = unseen_final(ops)

    info_types = {"unseen"} if unseen else set()
    bad_types = set(anomalies)
    valid: Any = not bad_types or ("unknown" if bad_types <= info_types
                                   else False)
    if valid is True and unseen:
        valid = True  # unseen alone is informational, like the reference
    return {
        "valid": valid if bad_types else True,
        "anomaly-types": sorted(bad_types),
        "anomalies": anomalies,
        "unseen": unseen,
        "key-count": len(orders),
    }


class KafkaChecker(Checker):
    def __init__(self, *, rw_edges: bool = False):
        self.rw_edges = rw_edges

    def check(self, test: dict, history: History, opts: dict) -> dict:
        res = analyze(history.client_ops(), rw_edges=self.rw_edges)
        # Conviction trail into the store dir: unseen/lag plots always,
        # anomalies.json + version orders + cycle DOTs when invalid
        # (tests/kafka.clj:99-180; VERDICT r3 #6).
        from .kafka_viz import write_artifacts

        write_artifacts(res, opts, history.client_ops())
        return res


# ---------------------------------------------------------------------------
# Generator (kafka.clj:195-443)
# ---------------------------------------------------------------------------

SUBSCRIBE_RATIO = 1 / 8  # kafka.clj:236-241


class KafkaGen(Generator):
    """Rewrites list-append txns into send/poll micro-ops and
    interleaves subscribe/assign ops (txn-generator :195 +
    InterleaveSubscribes :219-241)."""

    __slots__ = ("inner", "rng", "sub_via")

    def __init__(self, inner: Any, rng: Optional[random.Random] = None,
                 sub_via: tuple = ("subscribe", "assign")):
        self.inner = inner
        self.rng = rng or random.Random(45100)
        self.sub_via = sub_via

    def op(self, test, ctx):
        res = gen_op(self.inner, test, ctx)
        if res is None:
            return None
        op, inner2 = res
        nxt = KafkaGen(inner2, self.rng, self.sub_via)
        if op is PENDING:
            return (PENDING, self)
        keys = sorted({m[1] for m in (op.value or [])})
        if self.rng.random() < SUBSCRIBE_RATIO:
            f = self.rng.choice(list(self.sub_via))
            sub = fill_in_op({"f": f, "value": keys}, ctx)
            if sub is PENDING:
                return (PENDING, self)
            return (sub, self)  # txn deferred: re-ask inner next time
        mops = [(["send", m[1], m[2]] if m[0] == "append" else ["poll"])
                for m in (op.value or [])]
        fs = {m[0] for m in mops}
        f = "send" if fs == {"send"} else (
            "poll" if fs == {"poll"} else "txn")
        return (op.replace(f=f, value=mops), nxt)


def final_polls(keys: Iterable[Any], polls: int = 10) -> list:
    """Quiesce-phase generator: assign everything, seek to beginning,
    poll repeatedly (kafka.clj:403-431)."""
    ks = sorted(keys)
    return [{"f": "assign", "value": ks, "seek-to-beginning?": True}] + [
        {"f": "poll", "value": [["poll"]]} for _ in range(polls)
    ]


# ---------------------------------------------------------------------------
# In-memory log client (the checker's test double)
# ---------------------------------------------------------------------------


class LogState:
    """A shared broker: per-key append-only logs with fault knobs.

    faults: set of {"lose-acked"(drop an acked send from the log),
    "duplicate"(append twice), "skip-offset"(leave gaps),
    "unseen"(drop tail reads)} with `fault_rate` probability each."""

    def __init__(self, faults: Optional[set] = None,
                 fault_rate: float = 0.1,
                 rng: Optional[random.Random] = None):
        self.logs: dict[Any, list] = defaultdict(list)
        self.lock = threading.Lock()
        self.faults = faults or set()
        self.fault_rate = fault_rate
        self.rng = rng or random.Random(45100)

    def _fault(self, name: str) -> bool:
        return name in self.faults and self.rng.random() < self.fault_rate

    def send(self, k, v) -> Optional[int]:
        with self.lock:
            log = self.logs[k]
            if self._fault("skip-offset"):
                log.append(None)  # burn an offset (txn metadata slot)
            off = len(log)
            log.append(v)
            if self._fault("duplicate"):
                log.append(v)
            if self._fault("lose-acked"):
                log[off] = None  # ack then lose it
            return off

    def read_from(self, k, position: int, limit: int = 32):
        with self.lock:
            log = self.logs[k]
            out = []
            pos = position
            while pos < len(log) and len(out) < limit:
                v = log[pos]
                if v is not None:
                    out.append([pos, v])
                pos += 1
            if out and self._fault("unseen"):
                out = out[: max(1, len(out) // 2)]
                pos = out[-1][0] + 1
            return out, pos


class InMemoryKafkaClient(jc.Client):
    """Producer+consumer against a LogState (kafka.clj's combined
    client shape, :24-43)."""

    def __init__(self, state: Optional[LogState] = None):
        self.state = state or LogState()
        self.assigned: list = []
        self.positions: dict[Any, int] = {}

    def open(self, test, node):
        c = InMemoryKafkaClient(self.state)
        return c

    def invoke(self, test, op):
        if op.f in ("subscribe", "assign"):
            self.assigned = list(op.value or [])
            seek = op.ext.get("seek-to-beginning?")
            self.positions = {
                k: 0 if seek else self.positions.get(k, 0)
                for k in self.assigned
            }
            return op.complete(OK)
        out = []
        for mop in op.value or []:
            if mop[0] == "send":
                _, k, v = mop
                off = self.state.send(k, v)
                out.append(["send", k, [off, v]])
            else:
                polled: dict = {}
                for k in self.assigned:
                    pairs, pos = self.state.read_from(
                        k, self.positions.get(k, 0)
                    )
                    self.positions[k] = pos
                    if pairs:
                        polled[k] = pairs
                out.append(["poll", polled])
        return op.complete(OK, value=out)

    def reusable(self, test):
        return True


def workload(opts: Optional[dict] = None) -> dict:
    """Test-map fragment: generator + client + checker + final reads
    (kafka.clj's `workload`, end of file)."""
    from ..checker.elle import AppendGen
    from ..generator.core import FnGen

    opts = opts or {}
    rng = random.Random(opts.get("seed", 45100))
    la = AppendGen(
        key_count=opts.get("key-count", 4),
        min_txn_length=1,
        max_txn_length=opts.get("max-txn-length", 4),
        max_writes_per_key=opts.get("max-writes-per-key", 128),
        rng=rng,
    )
    keys = list(range(opts.get("key-count", 4)))
    state = LogState(
        faults=opts.get("faults"),
        fault_rate=opts.get("fault-rate", 0.1),
        rng=rng,
    )
    return {
        "name": "kafka",
        "generator": KafkaGen(FnGen(la), rng),
        "final-generator": final_polls(keys,
                                       opts.get("final-polls", 10)),
        "client": InMemoryKafkaClient(state),
        "checker": KafkaChecker(),
        "sub-via": ("subscribe", "assign"),
    }
