"""Long-fork anomaly workload.

Equivalent of /root/reference/jepsen/src/jepsen/tests/long_fork.clj
(spec in its docstring :1-60): writers write each register key exactly
once; readers read a whole group of n keys in one txn.  Under parallel
snapshot isolation, two reads can observe the writes in contradictory
orders — read A sees w1 but not w2 while read B sees w2 but not w1 —
the "long fork" (an instance of G2).
"""

from __future__ import annotations

import itertools
import random
import threading
from collections import defaultdict
from typing import Any, Optional

from .. import client as jc
from ..checker.core import Checker
from ..generator.core import PENDING, Generator, fill_in_op
from ..history import OK, History


def read_txn_mops(op_value) -> Optional[dict]:
    """{k: v} for a read txn's mops, or None for a write txn."""
    if not op_value:
        return None
    if any(m[0] != "r" for m in op_value):
        return None
    return {m[1]: m[2] for m in op_value}


class LongForkChecker(Checker):
    """Finds contradictory read pairs (long_fork.clj:62-250 condensed:
    with single-write-per-key groups, two group reads fork iff each
    sees a write the other missed)."""

    def check(self, test: dict, history: History, opts: dict) -> dict:
        reads_by_group: dict[frozenset, list] = defaultdict(list)
        for op in history:
            if not (op.is_ok and op.f == "txn"):
                continue
            r = read_txn_mops(op.value)
            if r is not None and len(r) > 1:
                reads_by_group[frozenset(r.keys())].append((op.index, r))

        forks = []
        for group, reads in reads_by_group.items():
            for i in range(len(reads)):
                for j in range(i + 1, len(reads)):
                    ia, ra = reads[i]
                    ib, rb = reads[j]
                    # a key A saw written that B didn't, and vice versa
                    a_ahead = any(
                        ra[k] is not None and rb[k] is None for k in group
                    )
                    b_ahead = any(
                        rb[k] is not None and ra[k] is None for k in group
                    )
                    if a_ahead and b_ahead:
                        forks.append(
                            {"ops": [ia, ib], "reads": [ra, rb]}
                        )
        return {
            "valid": not forks,
            "early-read-count": sum(len(v) for v in reads_by_group.values()),
            "fork-count": len(forks),
            "forks": forks[:8],
        }


class InMemoryLongForkClient(jc.Client):
    """Atomic txn store over registers."""

    def __init__(self, state=None, lock=None):
        self.state = state if state is not None else {}
        self.lock = lock or threading.Lock()

    def open(self, test, node):
        return InMemoryLongForkClient(self.state, self.lock)

    def invoke(self, test, op):
        with self.lock:
            out = []
            for f, k, v in op.value:
                if f == "w":
                    self.state[k] = v
                    out.append([f, k, v])
                else:
                    out.append(["r", k, self.state.get(k)])
            return op.complete(OK, value=out)

    def reusable(self, test):
        return True


class LongForkGen(Generator):
    """Write each key of the current group once (value 1), read whole
    groups (long_fork.clj:252-332's invariants).

    Emission is tuned for OBSERVABILITY: each group is a burst of
    `reads_per_group` whole-group reads with the group's writes
    injected back-to-back (shuffled order) at a random point in the
    middle.  A fork needs concurrent readers to overlap the short
    interval between the two write commits from both sides — reads
    scattered across fast-churning groups almost never do (measured:
    2 partial sightings in 522 group reads), while a read burst
    around clustered writes crosses the window every group.

    A proper immutable Generator, NOT a stateful fn: the scheduler may
    ask `op` several times (pending polls, races) and discard results,
    so a side-effecting closure silently drops queue entries — a
    measured run lost 2/3 of its emissions, including most writes."""

    __slots__ = ("group_size", "reads_per_group", "seed", "group",
                 "queue")

    def __init__(self, group_size: int = 2, reads_per_group: int = 16,
                 seed: int = 45100, group: int = 0, queue=()):
        if reads_per_group < 1:
            raise ValueError("reads_per_group must be >= 1 (an empty "
                             "group would recurse forever)")
        self.group_size = group_size
        self.reads_per_group = reads_per_group
        self.seed = seed
        self.group = group
        self.queue = tuple(queue)

    def _refilled(self) -> "LongForkGen":
        rng = random.Random(self.seed * 1_000_003 + self.group)
        g = self.group
        keys = list(range(g * self.group_size,
                          (g + 1) * self.group_size))
        read = {"f": "txn", "value": [["r", k, None] for k in keys]}
        order = keys[:]
        rng.shuffle(order)
        # Clamp into the loop's range: a wpos past the end would
        # silently drop the group's writes at small reads_per_group.
        wpos = min(rng.randrange(2, max(3, self.reads_per_group - 2)),
                   self.reads_per_group - 1)
        q: list = []
        for i in range(self.reads_per_group):
            if i == wpos:
                q += [{"f": "txn", "value": [["w", k, 1]]}
                      for k in order]
            q.append(read)
        return LongForkGen(self.group_size, self.reads_per_group,
                           self.seed, g + 1, q)

    def op(self, test, ctx):
        if not self.queue:
            return self._refilled().op(test, ctx)
        op = fill_in_op(self.queue[0], ctx)
        if op is PENDING:
            return (op, self)
        return (op, LongForkGen(self.group_size, self.reads_per_group,
                                self.seed, self.group, self.queue[1:]))


def generator(group_size: int = 2, rng: Optional[random.Random] = None,
              reads_per_group: int = 16):
    rng = rng or random.Random()
    return LongForkGen(group_size, reads_per_group,
                       seed=rng.randrange(2**32))


def workload(opts: Optional[dict] = None) -> dict:
    opts = opts or {}
    n = opts.get("group-size", 2)
    return {
        "name": "long-fork",
        "generator": generator(n, random.Random(opts.get("seed"))),
        "checker": LongForkChecker(),
        "client": InMemoryLongForkClient(),
    }
