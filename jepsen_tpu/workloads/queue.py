"""Total-queue workload: every acked enqueue must eventually come out.

The reference's queue suites (rabbitmq/disque/chronos-shaped) pair a
mixed enqueue/dequeue generator with `checker.total-queue`
(checker.clj:648-708) and a final DRAIN phase that keeps dequeuing
after faults heal, so "still sitting in the queue at test end" is
never mistaken for "lost".  This module reproduces that shape as a
reusable workload map: `{generator, final-generator, checker}` with
unique integer enqueue values.

Semantics the checker enforces (and the drain makes fair):
  lost        acked enqueue that never came out — CONVICTS
  unexpected  dequeue of a value never even attempted — CONVICTS
  duplicated  redelivery (at-least-once) — reported, allowed
  recovered   indeterminate enqueue that surfaced — reported, allowed
"""

from __future__ import annotations

import itertools
from typing import Optional

from ..checker import core as chk
from ..checker.timeline import Timeline
from ..generator.core import FnGen, clients, limit, mix, stagger


def workload(opts: Optional[dict] = None) -> dict:
    opts = dict(opts or {})
    counter = itertools.count(1)

    def enqueue():
        return {"f": "enqueue", "value": next(counter)}

    def dequeue():
        return {"f": "dequeue", "value": None}

    # 2:1 enqueue:dequeue keeps a backlog building, so a crash window
    # usually holds acked-but-undelivered records — the thing the
    # checker exists to catch.
    gen = mix([FnGen(enqueue), FnGen(enqueue), FnGen(dequeue)])
    rate = opts.get("rate", 150.0)
    if rate:
        gen = stagger(1.0 / rate, gen)

    # Drain budget: every record in the post-heal log needs one
    # successful single-record dequeue, plus EMPTY misses.  Bounded
    # well above any log this workload's op budget can produce
    # (duplicates included: each restart rewinds the shared cursor
    # once, and the log never exceeds total enqueue attempts).
    drain_ops = opts.get("drain-ops", 8000)

    return {
        "name": "total-queue",
        "generator": gen,
        "final-generator": clients(
            limit(drain_ops, FnGen(dequeue))
        ),
        "checker": chk.compose({
            "total-queue": chk.TotalQueue(),
            "timeline": Timeline(),
            "stats": chk.Stats(),
        }),
    }
