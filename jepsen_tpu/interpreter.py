"""The interpreter: turns a generator into an executed history.

Equivalent of /root/reference/jepsen/src/jepsen/generator/interpreter.clj:
one OS thread per logical worker (`spawn-worker` :102-167), a
size-1 in-queue per worker plus one shared completion queue, and a
single-threaded hot loop (:184-337) that owns ALL scheduling state:

  * poll completions; stamp index+time; free the thread; fold the event
    into the generator; on client :info crashes, rotate to a fresh
    process id (context with_next_process, :245-249);
  * else ask the generator for an op: None drains the workers and ends
    the run; PENDING re-polls at 1 ms (`max-pending-interval`,
    :169-173); future ops sleep-poll until due; due ops are stamped,
    recorded as invocations, and handed to their worker.

Client workers re-open their client whenever the op's process differs
from the one their current client was opened for (ClientWorker
:36-70); failures to open complete the op as :fail with a no-client
error.  Worker exceptions become indeterminate :info completions
(:145-160) rather than crashing the run.
"""

from __future__ import annotations

import logging
import queue
import threading
import time as time_mod
from typing import Any, Callable, Optional

from . import client as jepsen_client
from . import telemetry
from .telemetry import flight
from .client import Client
from .control import health
from .control.core import RemoteDisconnected
from .generator import (
    PENDING,
    Context,
    friendly_exceptions,
    gen_op,
    gen_update,
    validate,
)
from .history import FAIL, INFO, INVOKE, NEMESIS, History, Op
from .nemesis import Nemesis
from .utils import Deadline, relative_time_nanos, with_relative_time

log = logging.getLogger(__name__)

#: How long to wait, in seconds, before rechecking a PENDING generator
#: (interpreter.clj:169-173: 1 ms).
MAX_PENDING_INTERVAL = 0.001

#: Poison pill telling a worker to exit.
_EXIT = object()


def _journal(op: Op) -> bool:
    """Should this op be recorded?  :sleep and :log are scheduling
    artifacts, not history events (interpreter.clj:176-181)."""
    return op.type not in ("sleep", "log")


class Worker:
    """One logical worker: a thread pulling ops from a private queue and
    pushing completions to the shared queue (interpreter.clj:22-34)."""

    def __init__(self, id: Any, completions: "queue.SimpleQueue[Op]"):
        self.id = id
        # SimpleQueue: C-implemented, far lighter than queue.Queue's
        # lock/condition machinery on the per-op handoff path.  The
        # reference's capacity-1 bound (ArrayBlockingQueue 1) needs no
        # enforcement here: the scheduler only hands an op to a FREE
        # worker, so at most one op (plus the exit sentinel) is ever
        # in flight.
        self.in_queue: "queue.SimpleQueue[Any]" = queue.SimpleQueue()
        self.completions = completions
        # Watchdog protocol (run()'s per-op deadlines).  `supervised` is
        # set by run() only when the test carries op/drain timeouts, so
        # the unsupervised per-op path costs one attribute check.  The
        # lock makes "push the completion" and "mark abandoned" mutually
        # exclusive: either the push lands (and `pushes` records it) or
        # the abandoned worker stays silent forever — the scheduler never
        # sees a completion for an op it already timed out.
        self.supervised = False
        self.abandoned = False
        self.pushes = 0
        self.lock = threading.Lock()
        self.thread = threading.Thread(
            target=self._run, name=f"jepsen-worker-{id}", daemon=True
        )

    def start(self) -> None:
        self.thread.start()

    def submit(self, op: Op) -> None:
        self.in_queue.put(op)

    def exit(self) -> None:
        self.in_queue.put(_EXIT)

    def join(self, timeout: Optional[float] = None) -> None:
        self.thread.join(timeout)

    def _run(self) -> None:
        while True:
            op = self.in_queue.get()
            if op is _EXIT:
                self._cleanup()
                return
            try:
                # Special op types the worker handles itself
                # (interpreter.clj:126-136).
                if op.type == "sleep":
                    time_mod.sleep(op.value or 0)
                    completion = op
                elif op.type == "log":
                    log.info("%s", op.value)
                    completion = op
                elif telemetry.enabled():
                    # The gate keeps the disabled per-op path free of
                    # even the attrs-dict build.
                    with telemetry.span("interpreter.op", f=str(op.f)):
                        completion = self.transact(op)
                else:
                    completion = self.transact(op)
            except Exception as e:  # noqa: BLE001 — worker must not die
                log.debug("worker %s: %s crashed: %r", self.id, op.f, e)
                completion = op.complete(
                    INFO, error=f"{type(e).__name__}: {e}"
                )
            if not self.supervised:
                self.completions.put(completion)
            else:
                with self.lock:
                    if self.abandoned:
                        # The scheduler already completed this op as a
                        # timeout and replaced us; a late completion now
                        # would double-count.  Exit silently.
                        self._cleanup()
                        return
                    self.pushes += 1
                    self.completions.put(completion)

    def transact(self, op: Op) -> Op:
        raise NotImplementedError

    def _cleanup(self) -> None:
        pass


#: Open-failure backoff: first retry waits this long, doubling per
#: consecutive failure up to the cap.  Keeps a dead node from hot-looping
#: opens even when health quarantine is disabled, while staying well
#: under any realistic op cadence once the node recovers.
OPEN_BACKOFF_BASE_S = 0.05
OPEN_BACKOFF_CAP_S = 1.0


class ClientWorker(Worker):
    """Wraps a Client; re-opens it when the op's process changes
    (interpreter.clj:36-70)."""

    def __init__(
        self, id: Any, completions: "queue.SimpleQueue[Op]", test: dict
    ):
        super().__init__(id, completions)
        self.test = test
        proto = test["client"]
        # Contract violations must become per-op :info completions, not
        # hot-loop crashes: auto-wrap like the reference
        # (interpreter.clj:31 client/validate).
        if not isinstance(proto, jepsen_client.Validate):
            proto = jepsen_client.validate(proto)
        self.prototype: Client = proto
        self.process: Any = None
        self.client: Optional[Client] = None
        # A worker is pinned to one node for its whole life, even as its
        # process id rotates across crashes (interpreter.clj:87-89).
        nodes = test.get("nodes") or [None]
        self.node: Any = nodes[id % len(nodes)] if isinstance(id, int) else None
        # Open-failure backoff state: seconds for the NEXT wait, and the
        # monotonic instant before which we won't attempt another open.
        self._open_backoff_s = 0.0
        self._open_not_before = 0.0

    def _drop_client(self) -> None:
        if self.client is not None:
            try:
                self.client.close(self.test)
            except Exception as e:  # noqa: BLE001
                log.debug("worker %s: close failed: %r", self.id, e)
            self.client = None

    def transact(self, op: Op) -> Op:
        if self.node is not None and health.is_quarantined(
            self.test, self.node
        ):
            # Fast-fail: invoke never reached the node, so :fail is
            # sound, and we pay no open/op timeout against the corpse.
            # Drop the stale client so re-admission reopens a fresh one.
            self._drop_client()
            self.process = op.process
            return op.complete(
                FAIL, error=f"node {self.node} quarantined"
            )
        if (
            self.client is not None
            and self.process != op.process
            and not self.client.reusable(self.test)
        ):
            self._drop_client()
        if self.client is None:
            wait = self._open_not_before - time_mod.monotonic()
            if wait > 0:
                time_mod.sleep(wait)
            try:
                self.client = self.prototype.open(self.test, self.node)
                self._open_backoff_s = 0.0
            except Exception as e:  # noqa: BLE001
                # Can't even get a client: the op certainly didn't run
                # (interpreter.clj:47-58).  Back off before the next
                # attempt so a dead node can't hot-loop opens, and feed
                # the health monitor its passive signal.
                telemetry.count("client.open.failed")
                health.signal(self.test, self.node, "open-failed")
                self._open_backoff_s = min(
                    max(self._open_backoff_s * 2, OPEN_BACKOFF_BASE_S),
                    OPEN_BACKOFF_CAP_S,
                )
                self._open_not_before = (
                    time_mod.monotonic() + self._open_backoff_s
                )
                self.process = op.process
                return op.complete(
                    FAIL, error=f"no client: {type(e).__name__}: {e}"
                )
        self.process = op.process
        try:
            return self.client.invoke(self.test, op)
        except (RemoteDisconnected, ConnectionError):
            # The transport died mid-op: indeterminate for the op (the
            # worker loop completes it :info) but a clear health signal.
            health.signal(self.test, self.node, "disconnect")
            raise

    def _cleanup(self) -> None:
        if self.client is not None:
            try:
                self.client.close(self.test)
            except Exception as e:  # noqa: BLE001
                log.debug("worker %s: close failed: %r", self.id, e)
            self.client = None


class NemesisWorker(Worker):
    """Applies ops to the test's nemesis; the nemesis object is shared
    and long-lived (interpreter.clj:92-100)."""

    def __init__(self, id: Any, completions: "queue.SimpleQueue[Op]",
                 test: dict):
        super().__init__(id, completions)
        self.test = test
        self.nemesis: Nemesis = test["nemesis"]

    def transact(self, op: Op) -> Op:
        out = self.nemesis.invoke(self.test, op)
        # Contract guard, mirroring the client path's Validate: the
        # completion must keep the invocation's process and f, or the
        # hot loop can't route it; and nemesis completions are
        # indeterminate by convention — never a second :invoke.
        if out.process != op.process or out.f != op.f:
            out = out.replace(process=op.process, f=op.f)
        if out.type == INVOKE:
            out = out.replace(type=INFO)
        return out


def spawn_worker(test: dict, completions: "queue.SimpleQueue[Op]",
                 id: Any) -> Worker:
    """interpreter.clj:102-167."""
    if id == NEMESIS:
        return NemesisWorker(id, completions, test)
    return ClientWorker(id, completions, test)


def run(
    test: dict,
    *,
    writer: Optional[Callable[[Op], None]] = None,
) -> History:
    """Runs the test's generator to completion against its client and
    nemesis, returning the dense-index history
    (interpreter.clj:184-337).  `writer`, if given, is called with every
    op as it is recorded — the incremental history persistence hook
    (store format streaming, interpreter.clj:251-253, 303-308)."""
    ctx = Context.for_test(test)
    gen = validate(friendly_exceptions(test["generator"]))

    # Supervision knobs (ISSUE: fault-tolerant run supervision).
    # op_timeout: seconds a single client/nemesis op may run before the
    # scheduler completes it as indeterminate :info, abandons the stuck
    # worker thread, and rotates in a fresh worker under the same id.
    # drain_timeout: global deadline on the end-of-run drain, so a hung
    # straggler can't keep the run from producing a savable history.
    op_timeout: Optional[float] = test.get("op_timeout")
    drain_timeout: Optional[float] = test.get("drain_timeout", op_timeout)
    supervised = op_timeout is not None or drain_timeout is not None

    completions: "queue.SimpleQueue[Op]" = queue.SimpleQueue()
    workers: dict[Any, Worker] = {
        thread: spawn_worker(test, completions, thread)
        for thread in ctx.all_threads()
    }
    for w in workers.values():
        w.supervised = supervised
        w.start()

    ops: list[Op] = []

    def record(op: Op) -> None:
        ops.append(op)
        if writer is not None:
            writer(op)

    op_index = 0
    outstanding = 0
    poll_timeout = 0.0  # seconds; 0 = don't block

    #: thread -> (invocation, monotonic deadline, worker pushes at submit).
    #: Populated only when supervised; the unsupervised hot path touches
    #: it behind a single None/bool check.
    in_flight: dict[Any, tuple[Op, float, int]] = {}
    drain_deadline: Optional[Deadline] = None

    def abandon(thread: Any, pushes0: int) -> bool:
        """Marks a worker abandoned unless its completion already landed
        in the queue; returns True when we own the op's completion."""
        w = workers[thread]
        with w.lock:
            if w.pushes > pushes0:
                return False  # real completion racing in; let it flow
            w.abandoned = True
        return True

    with with_relative_time():
        try:
            while True:
                completion: Optional[Op] = None
                try:
                    if poll_timeout > 0:
                        completion = completions.get(timeout=poll_timeout)
                    else:
                        completion = completions.get_nowait()
                except queue.Empty:
                    completion = None

                if completion is not None:
                    now = relative_time_nanos()
                    thread = ctx.process_to_thread(completion.process)
                    if supervised:
                        in_flight.pop(thread, None)
                    journal = _journal(completion)
                    if journal:
                        completion = completion.replace(
                            index=op_index, time=now
                        )
                        op_index += 1
                    ctx = ctx.free_thread(now, thread)
                    gen = gen_update(gen, test, ctx, completion)
                    # A crashed client process is gone forever; rotate in a
                    # fresh process id (interpreter.clj:245-249).
                    if completion.is_info and thread != NEMESIS:
                        ctx = ctx.with_next_process(thread)
                    if journal:
                        record(completion)
                    outstanding -= 1
                    poll_timeout = 0.0
                    continue

                if in_flight:
                    # Watchdog: any in-flight op past its deadline is
                    # completed here as indeterminate :info, its stuck
                    # worker abandoned, and a fresh worker rotated in
                    # under the same id (the process rotation below
                    # makes the replacement open a fresh client).
                    now_mono = time_mod.monotonic()
                    for thread, (op, dl, pushes0) in list(in_flight.items()):
                        if now_mono < dl:
                            continue
                        del in_flight[thread]
                        if not abandon(thread, pushes0):
                            continue
                        log.warning(
                            "op timeout: worker %s stuck in %r for > %g s; "
                            "abandoning thread and rotating process",
                            thread, op.f, op_timeout,
                        )
                        telemetry.count("interpreter.op-timeouts")
                        flight.note("op-timeout", thread=thread,
                                    f=str(op.f), timeout_s=op_timeout)
                        flight.dump("op-timeout")
                        stuck_node = getattr(workers[thread], "node", None)
                        if stuck_node is not None:
                            health.signal(test, stuck_node, "op-timeout")
                        now = relative_time_nanos()
                        timed_out = op.complete(
                            INFO,
                            error=f"op timed out after {op_timeout} s",
                        ).replace(index=op_index, time=now)
                        op_index += 1
                        ctx = ctx.free_thread(now, thread)
                        gen = gen_update(gen, test, ctx, timed_out)
                        if thread != NEMESIS:
                            ctx = ctx.with_next_process(thread)
                        record(timed_out)
                        outstanding -= 1
                        nw = spawn_worker(test, completions, thread)
                        nw.supervised = True
                        nw.start()
                        workers[thread] = nw

                now = relative_time_nanos()
                ctx = ctx.with_time(now)
                res = gen_op(gen, test, ctx)

                if res is None:
                    if outstanding > 0:
                        # Generator exhausted but ops are in flight: block
                        # for their completions (interpreter.clj:266-273).
                        if supervised:
                            if drain_deadline is None:
                                drain_deadline = Deadline(drain_timeout)
                            elif drain_deadline.expired() and in_flight:
                                # Drain deadline blown: mark every
                                # straggler indeterminate so the run still
                                # ends with a complete, savable history.
                                now = relative_time_nanos()
                                for thread, (op, _dl, pushes0) in list(
                                    in_flight.items()
                                ):
                                    del in_flight[thread]
                                    if not abandon(thread, pushes0):
                                        continue
                                    log.warning(
                                        "drain timeout: worker %s never "
                                        "completed %r; marking "
                                        "indeterminate", thread, op.f,
                                    )
                                    telemetry.count(
                                        "interpreter.drain-timeouts"
                                    )
                                    straggler = op.complete(
                                        INFO,
                                        error="indeterminate: drain "
                                        f"deadline ({drain_timeout} s) "
                                        "expired",
                                    ).replace(index=op_index, time=now)
                                    op_index += 1
                                    ctx = ctx.free_thread(now, thread)
                                    record(straggler)
                                    outstanding -= 1
                        poll_timeout = MAX_PENDING_INTERVAL
                        continue
                    break

                op, gen2 = res
                if op is PENDING:
                    poll_timeout = MAX_PENDING_INTERVAL
                    continue

                if op.time > now:
                    # Not due yet: wait on completions until it is
                    # (interpreter.clj:294-300).
                    poll_timeout = min(
                        (op.time - now) / 1e9, MAX_PENDING_INTERVAL * 10
                    )
                    continue

                # Due: journal the invocation (sleep/log ops occupy their
                # worker but stay out of the history,
                # interpreter.clj:176-181) and dispatch it.
                if _journal(op):
                    op = op.replace(index=op_index, time=now)
                    op_index += 1
                    record(op)
                else:
                    op = op.replace(time=now)
                gen = gen_update(gen2, test, ctx, op)
                thread = ctx.process_to_thread(op.process)
                ctx = ctx.busy_thread(now, thread)
                if supervised and _journal(op):
                    # sleep/log ops run in-worker, are bounded by
                    # construction, and never journal — exempt.
                    w = workers[thread]
                    in_flight[thread] = (
                        op,
                        time_mod.monotonic() + op_timeout
                        if op_timeout is not None
                        else float("inf"),
                        w.pushes,
                    )
                workers[thread].submit(op)
                outstanding += 1
                poll_timeout = 0.0
        finally:
            for w in workers.values():
                w.exit()
            for w in workers.values():
                # An abandoned worker is wedged inside its op and will
                # only see the exit pill if that op ever returns; don't
                # burn 10 s per straggler on a daemon thread.
                w.join(timeout=0.1 if w.abandoned else 10.0)

    telemetry.count("interpreter.ops-journaled", op_index)
    telemetry.gauge("interpreter.workers", len(workers))
    return History(ops, reindex=False)
