"""Core orchestration: the whole-test lifecycle.

Equivalent of /root/reference/jepsen/src/jepsen/core.clj:
`prepare-test` (:302-320), `run!` (:322-412), `run-case!` (:208-213),
`analyze!` (:215-228), and `log-results` (:230-243).  The lifecycle
(§3.1 of SURVEY.md):

    prepare → store dir + logging → save-0 → sessions → OS setup →
    DB cycle → client/nemesis setup → interpreter (history streamed to
    disk) → save-1 → snarf logs → teardown → analyze → save-2

The *test map* is the universal config object (core.clj:323-360).
Keys: name, nodes, concurrency (int or "3n"), client, nemesis, db, os,
net, generator, checker, model, ssh {dummy? ...}, store-dir,
leave-db-running, log-snarfing off by default for dummy runs.
"""

from __future__ import annotations

import logging
import os
import re
from typing import Any, Optional

from . import db as jdb
from . import interpreter, oses, store, telemetry
from .checker.core import check_safe
from .control import with_sessions
from .history import History
from .nemesis import Nemesis, noop as noop_nemesis
from .utils import real_pmap

log = logging.getLogger(__name__)


def parse_concurrency(spec: Any, n_nodes: int) -> int:
    """int, or "3n" = 3 × node count (cli.clj:150-168)."""
    if isinstance(spec, int):
        return spec
    m = re.fullmatch(r"(\d+)n", str(spec).strip())
    if m:
        return int(m.group(1)) * max(n_nodes, 1)
    return int(spec)


def prepare_test(test: dict) -> dict:
    """Fills defaults: start-time, parsed concurrency, noop nemesis
    (core.clj:302-320).  A workload-supplied "final-generator" (e.g. a
    set workload's final read) is phased onto client threads after the
    main generator — reference suites wire this by hand with
    gen/phases; here the test map carries it."""
    test = dict(test)
    test.setdefault("name", "noname")
    test.setdefault("nodes", ["n1", "n2", "n3", "n4", "n5"])
    test["concurrency"] = parse_concurrency(
        test.get("concurrency", "1n"), len(test["nodes"])
    )
    test.setdefault("nemesis", noop_nemesis)
    fg = test.pop("final-generator", None)
    if fg is not None and test.get("generator") is not None:
        from .generator import clients as gen_clients, phases as gen_phases

        test["generator"] = gen_phases(
            test["generator"], gen_clients(fg)
        )
    return test


def setup_nemesis(test: dict) -> Nemesis:
    nem = test.get("nemesis") or noop_nemesis
    return nem.setup(test)


def _with_clients(test: dict, method: str) -> None:
    """Opens a client per node and calls setup/teardown on it
    (core.clj:175-206)."""
    proto = test.get("client")
    if proto is None:
        return

    def one(node: str) -> None:
        c = proto.open(test, node)
        try:
            getattr(c, method)(test)
        finally:
            try:
                c.close(test)
            except Exception:  # noqa: BLE001
                pass

    if method == "teardown":
        # Best-effort: a node the nemesis left dead must not turn a
        # finished run into an error.
        def one_safe(node: str) -> None:
            try:
                one(node)
            except Exception as e:  # noqa: BLE001
                log.warning("client teardown on %s failed: %r", node, e)

        real_pmap(one_safe, test.get("nodes") or [])
    else:
        real_pmap(one, test.get("nodes") or [])


def run_case(test: dict, history_writer=None) -> History:
    """Client+nemesis setup, then the generator interpreter
    (core.clj:208-213)."""
    nem = setup_nemesis(test)
    test = dict(test)
    test["nemesis"] = nem
    try:
        with telemetry.span("lifecycle.client-setup"):
            _with_clients(test, "setup")
        with telemetry.span("lifecycle.interpreter"):
            return interpreter.run(test, writer=history_writer)
    finally:
        try:
            with telemetry.span("lifecycle.client-teardown"):
                _with_clients(test, "teardown")
        finally:
            nem.teardown(test)


def analyze(test: dict, history: History, dir: Optional[str] = None) -> dict:
    """Runs the test's checker over the history (core.clj:215-228).
    `dir` is where artifact-writing checkers put their output; defaults
    to the test's own store dir."""
    checker = test.get("checker")
    if checker is None:
        return {"valid": True, "note": "no checker"}
    opts: dict[str, Any] = {"history-key": None}
    if dir is not None:
        opts["dir"] = dir
    else:
        try:
            opts["dir"] = store.test_dir(test)
        except ValueError:
            pass
    with telemetry.span("lifecycle.analyze"):
        results = check_safe(checker, test, history, opts)
    # Surface robustness events (op timeouts, blown checker budgets,
    # degradation-ladder steps) next to the verdicts they shaped, so a
    # report reader can tell a clean "valid" from a degraded one.
    res_counters = telemetry.resilience_counters()
    if res_counters and isinstance(results, dict):
        results.setdefault("resilience", res_counters)
    return results


def log_results(results: dict) -> None:
    """core.clj:230-243."""
    valid = results.get("valid")
    if valid is True:
        log.info("Everything looks good! ヽ('ー`)ノ")
    elif valid == "unknown":
        log.warning("Errors occurred during analysis; validity unknown")
    else:
        log.warning("Analysis invalid! (ﾉಥ益ಥ）ﾉ ┻━┻")


def run(test: dict) -> dict:
    """The full lifecycle (core.clj:322-412).  Returns the test map with
    "history" and "results" added.

    With JEPSEN_TELEMETRY=1 the run is a telemetry scope: the registry
    is reset on entry, every lifecycle phase is spanned, and on exit
    telemetry.json + trace.json land in the run's store dir with the
    top-5 spans logged (telemetry/__init__.py)."""
    telemetry.reset()
    with telemetry.span("lifecycle.prepare"):
        test = prepare_test(test)
        test = store.make_test_dir(test)
    try:
        return _run_prepared(test)
    finally:
        # Export in a finally: a crashed run is exactly the one whose
        # phase profile matters.
        if telemetry.enabled():
            telemetry.export(store.test_dir(test))
            telemetry.log_top_spans(log)


def _run_prepared(test: dict) -> dict:
    """The lifecycle after prepare — wrapped so `run` can export
    telemetry for crashed runs too."""
    with telemetry.span("lifecycle.run", test=test.get("name")):
        handler = store.start_logging(test)
        try:
            with store.Store(test) as st:
                st.save_0(test)
                hw = st.history_writer()
                with with_sessions(test):
                    try:
                        with telemetry.span("lifecycle.os-setup"):
                            oses.setup(test)
                        with telemetry.span("lifecycle.db-cycle"):
                            jdb.cycle(test)
                        history = run_case(test, history_writer=hw.append)
                        test["history"] = history
                        with telemetry.span("lifecycle.save"):
                            st.save_1(test, history)
                    finally:
                        # Whatever happened — OS/DB setup crash, client bug
                        # mid-run — seal any partial history so the file
                        # stays readable for `analyze`.
                        try:
                            hw.close()
                        except Exception as e:  # noqa: BLE001
                            log.warning("history seal failed: %r", e)
                        # Snarf logs even when the run throws — failing runs
                        # are exactly the ones whose logs matter
                        # (core.clj:142-158 with-log-snarfing).
                        if test.get("db") is not None:
                            try:
                                with telemetry.span("lifecycle.snarf"):
                                    jdb.snarf_logs(test, store.test_dir(test))
                            except Exception as e:  # noqa: BLE001
                                log.warning("log snarfing failed: %r", e)
                        if not test.get("leave-db-running"):
                            try:
                                jdb.teardown(test)
                            except Exception as e:  # noqa: BLE001
                                log.warning("db teardown failed: %r", e)
                        try:
                            oses.teardown(test)
                        except Exception as e:  # noqa: BLE001
                            log.warning("os teardown failed: %r", e)
                results = analyze(test, test["history"])
                test["results"] = results
                with telemetry.span("lifecycle.save"):
                    st.save_2(results)
                log_results(results)
        finally:
            store.stop_logging(handler)
    return test


def rerun_analysis(test_dir: str, test: dict) -> dict:
    """Re-runs checkers over a stored history — the `analyze` CLI
    subcommand (cli.clj:402-441).  `test` supplies live objects
    (checker, model); the stored test map fills the rest."""
    tf = store.load(test_dir)
    try:
        stored = tf.test or {}
        # The stored map is the record of the run; the caller's map only
        # contributes live objects (checker/model/client...) and keys the
        # stored run never had — CLI defaults must not clobber the
        # recorded nodes/concurrency/etc.
        merged = {**test, **stored}
        for k in store.NONSERIALIZABLE_KEYS:
            if k in test:
                merged[k] = test[k]
        history = tf.history()
        # Artifacts go next to the file actually being analyzed, not a
        # path recomputed from CLI options.
        artifact_dir = (
            test_dir if os.path.isdir(test_dir) else os.path.dirname(tf.path)
        )
        results = analyze(merged, history, dir=artifact_dir)
        with store.format.Handle(
            tf.path
        ) as h:  # append fresh results to the same file
            h.save_results(results)
        merged["history"] = history
        merged["results"] = results
        return merged
    finally:
        tf.close()
