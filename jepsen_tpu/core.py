"""Core orchestration: the whole-test lifecycle.

Equivalent of /root/reference/jepsen/src/jepsen/core.clj:
`prepare-test` (:302-320), `run!` (:322-412), `run-case!` (:208-213),
`analyze!` (:215-228), and `log-results` (:230-243).  The lifecycle
(§3.1 of SURVEY.md):

    prepare → store dir + logging → save-0 → sessions → OS setup →
    DB cycle → client/nemesis setup → interpreter (history streamed to
    disk) → save-1 → snarf logs → teardown → analyze → save-2

The *test map* is the universal config object (core.clj:323-360).
Keys: name, nodes, concurrency (int or "3n"), client, nemesis, db, os,
net, generator, checker, model, ssh {dummy? ...}, store-dir,
leave-db-running, log-snarfing off by default for dummy runs.
"""

from __future__ import annotations

import logging
import os
import re
from typing import Any, Optional

from . import db as jdb
from . import interpreter, oses, store, telemetry
from .telemetry import flight, profile, slo
from .checker.core import check_safe
from .control import Session, health, with_sessions
from .history import History
from .nemesis import Nemesis, ledger as fault_ledger, noop as noop_nemesis
from .utils import real_pmap

log = logging.getLogger(__name__)


def parse_concurrency(spec: Any, n_nodes: int) -> int:
    """int, or "3n" = 3 × node count (cli.clj:150-168)."""
    if isinstance(spec, int):
        return spec
    m = re.fullmatch(r"(\d+)n", str(spec).strip())
    if m:
        return int(m.group(1)) * max(n_nodes, 1)
    return int(spec)


def prepare_test(test: dict) -> dict:
    """Fills defaults: start-time, parsed concurrency, noop nemesis
    (core.clj:302-320).  A workload-supplied "final-generator" (e.g. a
    set workload's final read) is phased onto client threads after the
    main generator — reference suites wire this by hand with
    gen/phases; here the test map carries it."""
    test = dict(test)
    test.setdefault("name", "noname")
    test.setdefault("nodes", ["n1", "n2", "n3", "n4", "n5"])
    test["concurrency"] = parse_concurrency(
        test.get("concurrency", "1n"), len(test["nodes"])
    )
    test.setdefault("nemesis", noop_nemesis)
    fg = test.pop("final-generator", None)
    if fg is not None and test.get("generator") is not None:
        from .generator import clients as gen_clients, phases as gen_phases

        test["generator"] = gen_phases(
            test["generator"], gen_clients(fg)
        )
    return test


def setup_nemesis(test: dict) -> Nemesis:
    nem = test.get("nemesis") or noop_nemesis
    return nem.setup(test)


def _with_clients(test: dict, method: str) -> None:
    """Opens a client per node and calls setup/teardown on it
    (core.clj:175-206)."""
    proto = test.get("client")
    if proto is None:
        return

    def one(node: str) -> None:
        c = proto.open(test, node)
        try:
            getattr(c, method)(test)
        finally:
            try:
                c.close(test)
            except Exception:  # noqa: BLE001
                pass

    if method == "teardown":
        # Best-effort: a node the nemesis left dead must not turn a
        # finished run into an error.  Runs over ALL nodes, quarantined
        # included — teardown owes dead nodes an attempt.
        def one_safe(node: str) -> None:
            try:
                one(node)
            except Exception as e:  # noqa: BLE001
                log.warning("client teardown on %s failed: %r", node, e)

        real_pmap(one_safe, test.get("nodes") or [])
    else:
        # Setup fans out over the non-quarantined nodes, collects every
        # per-node failure (real_pmap would hide siblings behind the
        # first), and lets the node-loss policy decide abort vs shrink.
        _ok, failed = health.node_fanout(health.eligible_nodes(test), one)
        health.absorb_failures(test, "client setup", failed)


def run_case(test: dict, history_writer=None) -> History:
    """Client+nemesis setup, then the generator interpreter
    (core.clj:208-213)."""
    nem = setup_nemesis(test)
    test = dict(test)
    test["nemesis"] = nem
    primary: Optional[BaseException] = None
    try:
        with telemetry.span("lifecycle.client-setup"):
            _with_clients(test, "setup")
        with telemetry.span("lifecycle.interpreter"):
            return interpreter.run(test, writer=history_writer)
    except BaseException as e:
        primary = e
        raise
    finally:
        # Teardown failures must not mask the interpreter's primary
        # exception — that's the one that explains the run.  Each phase
        # is isolated; failures are logged + counted, and only surface
        # as the raised error when the run itself succeeded.
        errors: list[tuple[str, BaseException]] = []
        try:
            with telemetry.span("lifecycle.client-teardown"):
                _with_clients(test, "teardown")
        except Exception as e:  # noqa: BLE001
            errors.append(("client", e))
        try:
            nem.teardown(test)
        except Exception as e:  # noqa: BLE001
            errors.append(("nemesis", e))
        for what, e in errors:
            telemetry.count("nemesis.teardown.failed")
            log.warning(
                "%s teardown failed%s: %r", what,
                " (primary exception takes precedence)" if primary else "",
                e,
            )
        if errors and primary is None:
            raise errors[0][1]


def analyze(test: dict, history: History, dir: Optional[str] = None) -> dict:
    """Runs the test's checker over the history (core.clj:215-228).
    `dir` is where artifact-writing checkers put their output; defaults
    to the test's own store dir."""
    checker = test.get("checker")
    if checker is None:
        return {"valid": True, "note": "no checker"}
    # Checker-as-a-service routing: with a daemon address (test map
    # "checkerd", set by --remote, or the JEPSEN_CHECKERD env var) the
    # linearizable pieces of the checker tree ship their work to the
    # shared pool; everything falls back in-process if it's down.
    addr = test.get("checkerd") or os.environ.get("JEPSEN_CHECKERD")
    if addr:
        from .checkerd.client import wrap_remote

        run_id = f"{test.get('name') or 'run'}@{os.getpid()}"
        checker = wrap_remote(checker, str(addr), run_id=run_id)
    # Online checking: close the run's streaming session (drains the
    # last buffer, runs the final proofs, measures verdict lag) BEFORE
    # the checkers run, so they find its verdicts ready to consume.
    sess = test.get("streaming-session")
    if sess is not None and not sess.finished:
        try:
            sess.finish()
        except Exception:  # noqa: BLE001 — fail-open: post-hoc covers it
            log.warning("streaming session finish failed; checking "
                        "post-hoc", exc_info=True)
    opts: dict[str, Any] = {"history-key": None}
    if dir is not None:
        opts["dir"] = dir
    else:
        try:
            opts["dir"] = store.test_dir(test)
        except ValueError:
            pass
    # The analyze span anchors cross-process nesting: its span id
    # becomes the parent of every span done FOR this run elsewhere
    # (checkerd cohorts, streaming commits), carried by the trace
    # context the wire protocol propagates.
    analyze_sid = telemetry.new_span_id()
    telemetry.set_parent_span(analyze_sid)
    try:
        with telemetry.span("lifecycle.analyze", span_id=analyze_sid,
                            trace_id=telemetry.trace_id()):
            results = check_safe(checker, test, history, opts)
    finally:
        telemetry.set_parent_span(None)
    # Surface robustness events (op timeouts, blown checker budgets,
    # degradation-ladder steps) next to the verdicts they shaped, so a
    # report reader can tell a clean "valid" from a degraded one.
    res_counters = telemetry.resilience_counters()
    resil: dict[str, Any] = dict(res_counters)
    hm = health.monitor_of(test)
    if hm is not None and hm.active:
        # Per-node availability timeline — only once any failure signal
        # fired, so a healthy run's results are byte-identical to a run
        # without the monitor.
        resil["nodes"] = hm.summary()
    if resil and isinstance(results, dict):
        results.setdefault("resilience", resil)
    if sess is not None and isinstance(results, dict):
        results.setdefault("streaming", sess.stats())
    # Anomaly forensics: every bad verdict ships a dossier (minimal
    # counterexample, death state, trace slice, nemesis correlation)
    # under <store>/forensics/.  Fail-open: assembly must never change
    # the verdict it documents.
    if isinstance(results, dict) and opts.get("dir"):
        try:
            from . import forensics
            fsum = forensics.assemble(
                test, results, history, opts["dir"], checker=checker
            )
            if fsum is not None:
                results.setdefault("forensics", fsum)
        except Exception:  # noqa: BLE001 — side output only
            log.warning("forensics assembly failed", exc_info=True)
    return results


def log_results(results: dict) -> None:
    """core.clj:230-243."""
    valid = results.get("valid")
    if valid is True:
        log.info("Everything looks good! ヽ('ー`)ノ")
    elif valid == "unknown":
        log.warning("Errors occurred during analysis; validity unknown")
    else:
        log.warning("Analysis invalid! (ﾉಥ益ಥ）ﾉ ┻━┻")


def run(test: dict) -> dict:
    """The full lifecycle (core.clj:322-412).  Returns the test map with
    "history" and "results" added.

    With JEPSEN_TELEMETRY=1 the run is a telemetry scope: the registry
    is reset on entry (scoped — fleet counters like nemesis.search.*
    survive, telemetry/__init__.py FLEET_COUNTER_PREFIXES), every
    lifecycle phase is spanned, and on exit telemetry.json +
    trace.json land in the run's store dir with the top-5 spans
    logged.  The scope also seeds the run's trace context (adopting
    test["trace-parent"] when a search loop or parent run propagated
    one), points the per-pass profile store and the flight recorder at
    the store dir, and dumps a postmortem when the run crashes."""
    telemetry.scoped_reset()
    telemetry.seed_trace(test.get("trace-parent"))
    flight.reset()
    with telemetry.span("lifecycle.prepare"):
        test = prepare_test(test)
        test = store.make_test_dir(test)
    run_dir = store.test_dir(test)
    profile.set_store(run_dir)
    flight.set_dir(run_dir)
    slo.set_dir(run_dir)
    try:
        return _run_prepared(test)
    except BaseException as e:
        flight.note("run-crashed", error=f"{type(e).__name__}: {e}",
                    test=test.get("name"))
        flight.dump("run-crashed")
        raise
    finally:
        # Export in a finally: a crashed run is exactly the one whose
        # phase profile matters.
        if telemetry.enabled():
            telemetry.export(run_dir)
            telemetry.log_top_spans(log)
        profile.set_store(None)
        flight.set_dir(None)
        slo.set_dir(None)


def _run_prepared(test: dict) -> dict:
    """The lifecycle after prepare — wrapped so `run` can export
    telemetry for crashed runs too."""
    with telemetry.span("lifecycle.run", test=test.get("name")):
        handler = store.start_logging(test)
        try:
            with store.Store(test) as st:
                st.save_0(test)
                hw = st.history_writer()
                # The fault ledger journals every nemesis intent into
                # the store dir (lazily — fault-free runs never create
                # the file), so a killed control process leaves a
                # durable record of what is still broken on the nodes.
                test["fault-ledger"] = fault_ledger.FaultLedger(
                    fault_ledger.ledger_path(store.test_dir(test))
                )
                # The node health monitor is passive until the first
                # failure signal: no thread, no probes, no overhead on
                # a healthy run (same lazy contract as the ledger).
                test["node-health"] = health.HealthMonitor(test)
                # Online checking (--streaming / JEPSEN_STREAMING): tee
                # the journal into a checking session that proves keys
                # WHILE the run generates them (jepsen_tpu/streaming/).
                writer = hw.append
                from .streaming import maybe_session, streaming_enabled
                if streaming_enabled(test):
                    sess = maybe_session(test)
                    if sess is not None:
                        test["streaming-session"] = sess

                        def writer(op, _hw=hw.append, _sess=sess):
                            _hw(op)  # durability first, checking second
                            _sess.feed(op)
                with with_sessions(test):
                    try:
                        with telemetry.span("lifecycle.os-setup"):
                            oses.setup(test)
                        with telemetry.span("lifecycle.db-cycle"):
                            jdb.cycle(test)
                        history = run_case(test, history_writer=writer)
                        test["history"] = history
                        with telemetry.span("lifecycle.save"):
                            st.save_1(test, history)
                    finally:
                        # Whatever happened — OS/DB setup crash, client bug
                        # mid-run — seal any partial history so the file
                        # stays readable for `analyze`.
                        try:
                            hw.close()
                        except Exception as e:  # noqa: BLE001
                            log.warning("history seal failed: %r", e)
                        # Snarf logs even when the run throws — failing runs
                        # are exactly the ones whose logs matter
                        # (core.clj:142-158 with-log-snarfing).
                        if test.get("db") is not None:
                            try:
                                with telemetry.span("lifecycle.snarf"):
                                    jdb.snarf_logs(test, store.test_dir(test))
                            except Exception as e:  # noqa: BLE001
                                log.warning("log snarfing failed: %r", e)
                        if not test.get("leave-db-running"):
                            try:
                                jdb.teardown(test)
                            except Exception as e:  # noqa: BLE001
                                log.warning("db teardown failed: %r", e)
                            else:
                                # A completed DB teardown kills every
                                # daemon: db-kill/db-pause faults can't
                                # outlive it, and their compensator
                                # (restart a binary teardown just
                                # removed) must not be replayed by a
                                # later `repair`.
                                led = test.get("fault-ledger")
                                if (led is not None
                                        and test.get("db") is not None
                                        and os.path.exists(led.path)):
                                    for tag in ("db-kill", "db-pause"):
                                        led.heal_matching(
                                            tag=tag, by="db-teardown"
                                        )
                        try:
                            oses.teardown(test)
                        except Exception as e:  # noqa: BLE001
                            log.warning("os teardown failed: %r", e)
                        # Residue sweep: only when faults were actually
                        # journaled (fault-free runs skip it entirely),
                        # while sessions are still open — its
                        # nemesis.residue.* counters then land in the
                        # results' resilience block.
                        led = test.get("fault-ledger")
                        if led is not None and os.path.exists(led.path):
                            try:
                                with telemetry.span(
                                    "lifecycle.residue-sweep"
                                ):
                                    residue = fault_ledger.probe_residue(
                                        test, ledger=led
                                    )
                                if not residue["clean"]:
                                    log.warning(
                                        "fault residue after teardown: "
                                        "%s — run `jepsen repair %s`",
                                        residue, store.test_dir(test),
                                    )
                            except Exception as e:  # noqa: BLE001
                                log.warning("residue sweep failed: %r", e)
                results = analyze(test, test["history"])
                test["results"] = results
                with telemetry.span("lifecycle.save"):
                    st.save_2(results)
                log_results(results)
        finally:
            hm = test.pop("node-health", None)
            if hm is not None:
                try:
                    hm.stop()
                except Exception:  # noqa: BLE001
                    pass
            led = test.pop("fault-ledger", None)
            if led is not None:
                try:
                    led.close()
                except Exception:  # noqa: BLE001
                    pass
            store.stop_logging(handler)
    return test


def rerun_analysis(test_dir: str, test: dict) -> dict:
    """Re-runs checkers over a stored history — the `analyze` CLI
    subcommand (cli.clj:402-441).  `test` supplies live objects
    (checker, model); the stored test map fills the rest."""
    tf = store.load(test_dir)
    try:
        stored = tf.test or {}
        # The stored map is the record of the run; the caller's map only
        # contributes live objects (checker/model/client...) and keys the
        # stored run never had — CLI defaults must not clobber the
        # recorded nodes/concurrency/etc.
        merged = {**test, **stored}
        for k in store.NONSERIALIZABLE_KEYS:
            if k in test:
                merged[k] = test[k]
        # `analyze --remote` must beat whatever address (or absence)
        # the original run recorded.
        if "checkerd" in test:
            merged["checkerd"] = test["checkerd"]
        history = tf.history()
        # Artifacts go next to the file actually being analyzed, not a
        # path recomputed from CLI options.
        artifact_dir = (
            test_dir if os.path.isdir(test_dir) else os.path.dirname(tf.path)
        )
        results = analyze(merged, history, dir=artifact_dir)
        with store.format.Handle(
            tf.path
        ) as h:  # append fresh results to the same file
            h.save_results(results)
        merged["history"] = history
        merged["results"] = results
        return merged
    finally:
        tf.close()


def repair(test_dir: str, test: Optional[dict] = None) -> dict:
    """Recovers a crashed run's cluster: loads the fault ledger from
    `test_dir`, reopens sessions, replays outstanding compensators
    newest-first (reverse injection order), journals a healed record
    for each success, and finishes with a residue probe sweep — the
    `jepsen repair` CLI subcommand.

    `test` supplies live objects the stored map cannot carry (remote,
    ssh opts, db for db-start/db-resume compensators); the stored test
    map fills nodes and the rest.  Session opening is per-node
    best-effort — one unreachable node is reported in "unreachable",
    not fatal, and healing proceeds on the rest.

    Returns {"outstanding": n, "healed": [ids], "failed": {id: result},
    "unreachable": {node: err}, "residue": sweep, "clean": bool}.
    Running repair on a clean dir (or twice) is a no-op."""
    path = fault_ledger.ledger_path(test_dir)
    outstanding = fault_ledger.outstanding_entries(
        fault_ledger.read_records(path)
    )

    stored: dict = {}
    tf_path = os.path.join(test_dir, store.TEST_FILE)
    if os.path.exists(tf_path):
        tf = store.load(test_dir)
        try:
            stored = tf.test or {}
        finally:
            tf.close()
    test = test or {}
    merged = {**test, **stored}
    for k in store.NONSERIALIZABLE_KEYS:
        if k in test:
            merged[k] = test[k]

    report: dict[str, Any] = {
        "ledger": path,
        "outstanding": len(outstanding),
        "healed": [],
        "failed": {},
        "unreachable": {},
    }
    if not outstanding:
        log.info("repair %s: ledger clean, nothing to do", test_dir)
        report["residue"] = {"clean": True, "outstanding": 0, "nodes": {}}
        report["clean"] = True
        return report

    sessions: dict[str, Session] = {}
    for node in merged.get("nodes") or []:
        try:
            sessions[node] = Session.connect(merged, node)
        except Exception as e:  # noqa: BLE001 — heal the reachable rest
            log.warning("repair: cannot reach %s: %r", node, e)
            report["unreachable"][node] = f"{type(e).__name__}: {e}"
    merged["sessions"] = sessions

    # Reopening truncates any torn tail the dying writer left, so the
    # healed records land in a valid file.
    led = fault_ledger.FaultLedger(path)
    try:
        for entry in outstanding:
            res = fault_ledger.run_compensator(merged, entry)
            if res["ok"]:
                led.healed(entry["id"], by="repair")
                report["healed"].append(entry["id"])
                log.info(
                    "repair: healed entry %s (%s/%s)", entry["id"],
                    entry.get("fault"), entry.get("tag") or "-",
                )
            else:
                report["failed"][entry["id"]] = res
                log.warning(
                    "repair: entry %s (%s/%s) NOT healed: %s",
                    entry["id"], entry.get("fault"),
                    entry.get("tag") or "-",
                    res.get("error") or res.get("nodes"),
                )
        report["residue"] = fault_ledger.probe_residue(merged, ledger=led)
    finally:
        led.close()
        for s in sessions.values():
            try:
                s.disconnect()
            except Exception:  # noqa: BLE001
                pass
        merged.pop("sessions", None)
    report["clean"] = (
        report["residue"]["clean"]
        and not report["failed"]
        and not report["unreachable"]
    )
    return report
