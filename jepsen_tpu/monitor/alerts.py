"""Alert routing: SLO transitions become actions.

The SLO engine (telemetry/slo.py) turns registry state into
firing/cleared transitions; this module turns transitions into
*deliveries* against operator-configured sinks:

    file:/path/alerts.jsonl      append one JSON line per event
    webhook:http://host/hook     POST the event as JSON
    exec:/path/script            run the script, event JSON on stdin

Routing discipline (the part a pager cares about):

  * **dedup** — a rule that re-fires within `dedup_s` of its last
    delivered firing (flapping) is suppressed and counted
    (`alert.deduped`), so one incident pages once;
  * **re-notify** — a rule still firing `renotify_s` after its last
    delivery is re-delivered with ``"renotify": true``, so a
    long-burning incident is not forgotten after the first page;
  * **evidence attach** — every firing event carries the newest
    forensics dossier under the store dir and the flight-recorder
    postmortem that `slo.evaluate()` dumped at fire time, so the page
    links straight to the evidence;
  * sink failures are counted (`alert.sink-errors`), never raised —
    alerting must not take down the thing it watches.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import subprocess
import time
import urllib.request
from typing import Any, Optional

from .. import telemetry
from ..forensics import FORENSICS_DIR
from ..telemetry.flight import POSTMORTEM_FILE

log = logging.getLogger(__name__)

#: Suppress re-fires of the same rule within this window.
DEDUP_S = 60.0

#: Re-deliver a still-firing rule after this long.
RENOTIFY_S = 300.0

_SINK_SCHEMES = ("file:", "webhook:", "exec:")


def _newest_under(root: str, limit: int = 2000) -> Optional[str]:
    """Newest-mtime file under `root` (bounded walk), or None."""
    best: Optional[tuple[float, str]] = None
    seen = 0
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in filenames:
            seen += 1
            if seen > limit:
                return best[1] if best else None
            p = os.path.join(dirpath, fn)
            try:
                m = os.path.getmtime(p)
            except OSError:
                continue
            if best is None or m > best[0]:
                best = (m, p)
    return best[1] if best else None


class AlertRouter:
    """Delivers SLO transitions to configured sinks with dedup and
    re-notify semantics."""

    def __init__(
        self,
        sinks: Any = (),
        *,
        store_dir: Optional[str] = None,
        dedup_s: float = DEDUP_S,
        renotify_s: float = RENOTIFY_S,
    ):
        self.sinks: list[str] = []
        self.store_dir = store_dir
        self.dedup_s = dedup_s
        self.renotify_s = renotify_s
        #: rule -> {"firing": bool, "last_delivery": t, "fires": n}
        self._state: dict[str, dict] = {}
        for spec in sinks or ():
            if isinstance(spec, str) and spec.startswith(_SINK_SCHEMES):
                self.sinks.append(spec)
            else:
                telemetry.count("alert.bad-sink")
                log.warning("ignoring unrecognized alert sink %r", spec)

    # -- evidence -----------------------------------------------------------

    def _evidence(self) -> dict:
        out: dict[str, Optional[str]] = {"dossier": None,
                                         "postmortem": None}
        d = self.store_dir
        if not d:
            return out
        froot = os.path.join(d, FORENSICS_DIR)
        if os.path.isdir(froot):
            out["dossier"] = _newest_under(froot)
        pm = os.path.join(d, POSTMORTEM_FILE)
        if os.path.exists(pm):
            out["postmortem"] = pm
        return out

    # -- delivery -----------------------------------------------------------

    def _deliver(self, event: dict) -> None:
        data = json.dumps(event, sort_keys=True, default=repr)
        delivered = 0
        for spec in self.sinks:
            try:
                if spec.startswith("file:"):
                    path = spec[len("file:"):]
                    os.makedirs(os.path.dirname(path) or ".",
                                exist_ok=True)
                    with open(path, "a") as f:
                        f.write(data + "\n")
                elif spec.startswith("webhook:"):
                    url = spec[len("webhook:"):]
                    req = urllib.request.Request(
                        url,
                        data=data.encode(),
                        headers={"Content-Type": "application/json"},
                    )
                    urllib.request.urlopen(req, timeout=5.0).close()
                else:  # exec:
                    subprocess.run(
                        [spec[len("exec:"):]],
                        input=data.encode(),
                        timeout=15.0,
                        check=False,
                        stdout=subprocess.DEVNULL,
                        stderr=subprocess.DEVNULL,
                    )
                delivered += 1
            except Exception as e:  # noqa: BLE001 — never raise
                telemetry.count("alert.sink-errors")
                log.warning("alert sink %s failed: %r", spec, e)
        if delivered:
            telemetry.count("alert.delivered", delivered)

    def _event(self, transition: dict, **extra: Any) -> dict:
        ev = dict(transition)
        ev["host"] = socket.gethostname()
        ev.update(self._evidence() if transition.get("rec") == "firing"
                  else {})
        ev.update(extra)
        return ev

    # -- API ----------------------------------------------------------------

    def route(self, transitions: Any,
              now: Optional[float] = None) -> int:
        """Routes one evaluation sweep's transitions; returns the
        number of events delivered to sinks."""
        if now is None:
            now = time.time()
        sent = 0
        for tr in transitions or ():
            rule = tr.get("rule")
            rec = tr.get("rec")
            if not rule or rec not in ("firing", "cleared"):
                continue
            st = self._state.setdefault(
                rule, {"firing": False, "last_delivery": None, "fires": 0}
            )
            if rec == "firing":
                st["firing"] = True
                st["fires"] += 1
                last = st["last_delivery"]
                if last is not None and now - last < self.dedup_s:
                    telemetry.count("alert.deduped")
                    continue
                telemetry.count("alert.fired")
                self._deliver(self._event(tr))
                st["last_delivery"] = now
                sent += 1
            else:
                st["firing"] = False
                if st["last_delivery"] is None:
                    continue  # never paged: nothing to resolve
                telemetry.count("alert.cleared")
                self._deliver(self._event(tr))
                sent += 1
        return sent

    def tick(self, firing: Any, now: Optional[float] = None) -> int:
        """Re-notify sweep: `firing` is slo.firing_gauges() ({rule:
        0|1}); rules still firing `renotify_s` past their last delivery
        are re-delivered."""
        if now is None:
            now = time.time()
        sent = 0
        for rule, on in (firing or {}).items():
            if not on:
                continue
            st = self._state.get(rule)
            if (st is None or not st["firing"]
                    or st["last_delivery"] is None):
                continue
            if now - st["last_delivery"] < self.renotify_s:
                continue
            telemetry.count("alert.renotified")
            self._deliver(self._event(
                {"rec": "firing", "rule": rule, "t": now},
                renotify=True,
            ))
            st["last_delivery"] = now
            sent += 1
        return sent

    def flush(self, reason: str = "shutdown",
              now: Optional[float] = None) -> int:
        """Final delivery on graceful shutdown: every rule still firing
        gets one closing ``"rec": "shutdown"`` event so the pager knows
        the watcher (not the incident) went away.  Sinks that already
        saw a clear deliver nothing."""
        if now is None:
            now = time.time()
        sent = 0
        for rule, st in self._state.items():
            if not st["firing"] or st["last_delivery"] is None:
                continue
            self._deliver(self._event(
                {"rec": "shutdown", "rule": rule, "t": now},
                reason=reason,
            ))
            sent += 1
        if sent:
            telemetry.count("alert.flushed", sent)
        return sent

    def status(self) -> dict:
        return {
            "sinks": list(self.sinks),
            "rules": {
                rule: {"firing": st["firing"], "fires": st["fires"]}
                for rule, st in self._state.items()
            },
        }
