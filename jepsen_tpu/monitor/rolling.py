"""Rolling-window online checking for the standing monitor.

A `jepsen monitor` run never finishes: ops keep arriving and the
verdict must stay current while memory stays constant.  The streaming
pipeline (streaming/pipeline.py) already checks incrementally but
retains every row until finish(); this module adds the missing half of
ROADMAP item 5 — history *discard*.

Per key, a `RollingChecker` owns a PackedBuilder + FrontierCarry pair
and, after each `advance()`, asks the builder to drop the longest
stable prefix the frontier can never revisit
(`PackedBuilder.discard_stable_prefix`) and shifts the carry in
lockstep (`FrontierCarry.rebase`).  The discard conditions guarantee
the retained computation is bit-identical to the undiscarded run
(tests/test_monitor.py asserts verdict parity), so resident history per
key is bounded by the advance cadence plus one processed block —
constant for a week-long run.

Honesty at the edge: once a prefix is discarded, the post-hoc fallback
that a dead frontier normally escalates to is impossible — the full
history no longer exists.  A frontier death therefore becomes an
*epoch restart*: the key's verdict for the dying epoch is recorded as
"unknown" (never "valid"), counted (`monitor.epoch-restarts`), and a
fresh builder/frontier pair starts a new epoch from the live stream.
The alert router turns that into a page; the monitor keeps running.
"""

from __future__ import annotations

import collections
import logging
import time
from typing import Any, Hashable, Optional

from .. import telemetry
from ..history.packed import PackedBuilder
from ..models.base import PackedModel
from ..streaming.frontier import FrontierCarry

log = logging.getLogger(__name__)

#: Rough per-row resident cost of a builder row tuple (8 ints + tuple
#: header) — used for the monitor.resident-history-bytes gauge.
ROW_BYTES = 120

#: Bounded per-key checkpoint ring for verdict-lag estimation.
LAG_POINTS = 256


class _KeyState:
    __slots__ = (
        "builder", "frontier", "rows_at_advance", "discarded_rows",
        "discarded_bars", "epoch", "unknown_epochs", "lag_points",
        "last_reason",
    )

    def __init__(self, builder: PackedBuilder, frontier: FrontierCarry):
        self.builder = builder
        self.frontier = frontier
        self.rows_at_advance = 0
        self.discarded_rows = 0
        self.discarded_bars = 0
        self.epoch = 0
        self.unknown_epochs = 0
        self.lag_points: collections.deque = collections.deque(
            maxlen=LAG_POINTS
        )
        self.last_reason: Optional[str] = None


class RollingChecker:
    """Keyed rolling online checker: feed ops, memory stays bounded.

    `discard=False` runs the identical computation without dropping
    history — the parity baseline the tests compare against."""

    def __init__(
        self,
        pm: PackedModel,
        *,
        bars_per_block: int = 64,
        blocks_per_call: int = 4,
        beam: int = 8,
        advance_rows: int = 1024,
        retain_blocks: int = 1,
        discard: bool = True,
        max_window: int = 32768,
        info_window: Optional[int] = None,
    ):
        self.pm = pm
        self.K = bars_per_block
        self.NB = blocks_per_call
        self.beam = beam
        self.advance_rows = max(1, advance_rows)
        self.retain_blocks = max(1, retain_blocks)
        self.discard = discard
        self.max_window = max_window
        self.info_window = info_window
        self._keys: dict[Hashable, _KeyState] = {}

    # -- internals ----------------------------------------------------------

    def _fresh(self) -> tuple[PackedBuilder, FrontierCarry]:
        return (
            PackedBuilder(self.pm.encode),
            FrontierCarry(
                self.pm,
                beam=self.beam,
                bars_per_block=self.K,
                blocks_per_call=self.NB,
                max_window=self.max_window,
                info_window=self.info_window,
            ),
        )

    def _state(self, key: Hashable) -> _KeyState:
        ks = self._keys.get(key)
        if ks is None:
            builder, frontier = self._fresh()
            ks = self._keys[key] = _KeyState(builder, frontier)
        return ks

    def _restart_epoch(self, key: Hashable, ks: _KeyState,
                       reason: str) -> None:
        """Frontier died after history was discarded: the epoch's
        verdict is honestly unknown; a fresh pair picks up the live
        stream (its builder tolerates completions whose invocations
        died with the old epoch)."""
        ks.unknown_epochs += 1
        ks.epoch += 1
        ks.last_reason = reason
        ks.builder, ks.frontier = self._fresh()
        ks.rows_at_advance = 0
        ks.discarded_rows = 0
        ks.discarded_bars = 0
        ks.lag_points.clear()
        telemetry.count("monitor.epoch-restarts")
        log.warning("monitor key %r: epoch restart (%s)", key, reason)

    def _advance(self, key: Hashable, ks: _KeyState,
                 now: Optional[float]) -> None:
        packed, s = ks.builder.snapshot()
        ks.frontier.advance(packed, s)
        ks.rows_at_advance = ks.builder.n_rows
        if now is not None:
            ks.lag_points.append(
                (ks.discarded_bars + ks.builder.n_rows, now)
            )
        if ks.frontier.dead:
            self._restart_epoch(
                key, ks, ks.frontier.dead_reason or "frontier died"
            )
            return
        if not self.discard:
            return
        # Leave `retain_blocks` processed blocks resident beyond the
        # one discard_stable_prefix always keeps.
        eff_blocks = ks.frontier.blocks_done - (self.retain_blocks - 1)
        rows, bars, _shift = ks.builder.discard_stable_prefix(
            bars_per_block=self.K, blocks_done=eff_blocks
        )
        if rows:
            ks.frontier.rebase(rows, bars)
            if ks.frontier.dead:
                self._restart_epoch(
                    key, ks, ks.frontier.dead_reason or "rebase failed"
                )
                return
            ks.discarded_rows += rows
            ks.discarded_bars += bars
            ks.rows_at_advance = ks.builder.n_rows
            telemetry.count("monitor.discards")
            telemetry.count("monitor.discarded-rows", rows)

    # -- API ----------------------------------------------------------------

    def feed(self, key: Hashable, op: Any,
             now: Optional[float] = None) -> None:
        """Appends one op to `key`'s stream, advancing + discarding
        when the advance cadence is due."""
        ks = self._state(key)
        ks.builder.append(op)
        if ks.builder.n_rows - ks.rows_at_advance >= self.advance_rows:
            self._advance(key, ks, now)

    def feed_many(self, key: Hashable, ops: list,
                  now: Optional[float] = None) -> None:
        """feed() for a per-key burst: one columnar append, then at
        most one advance (advance resets the cadence watermark, so a
        burst crossing the threshold multiple times still advances
        once — same as the last scalar feed of the burst would)."""
        ks = self._state(key)
        ks.builder.append_many(ops)
        if ks.builder.n_rows - ks.rows_at_advance >= self.advance_rows:
            self._advance(key, ks, now)

    def pump(self, now: Optional[float] = None) -> None:
        """Advances every key regardless of cadence (idle-stream
        flush)."""
        for key, ks in list(self._keys.items()):
            if ks.builder.n_rows > ks.rows_at_advance:
                self._advance(key, ks, now)

    def finish(self) -> dict:
        """Closes every stream: {key: True | "unknown"}.  True means a
        witness survived the whole retained run AND no epoch was lost;
        anything else is unknown (escalation is impossible once history
        was discarded, so this path never claims invalid)."""
        verdicts: dict = {}
        for key, ks in self._keys.items():
            ok: Optional[bool] = None
            if not ks.frontier.dead:
                try:
                    packed = ks.builder.finish()
                    ok = ks.frontier.finalize(packed)
                except Exception as e:  # noqa: BLE001 — honest unknown
                    log.warning("monitor key %r finalize failed: %r",
                                key, e)
                    ok = None
            if ok and ks.unknown_epochs == 0:
                verdicts[key] = True
            else:
                verdicts[key] = "unknown"
        return verdicts

    # -- observability ------------------------------------------------------

    def resident_rows(self) -> int:
        return sum(ks.builder.n_rows for ks in self._keys.values())

    def resident_bytes(self) -> int:
        """Estimated resident history: builder rows plus the carried
        device window per key."""
        total = 0
        for ks in self._keys.values():
            total += ks.builder.n_rows * ROW_BYTES
            f = ks.frontier
            if f._member is not None:
                total += f._W * f.B  # bool member matrix
                total += f.B * (self.pm.state_width * 4 + 1)
            if f._prev_active is not None:
                total += int(f._prev_active.nbytes)
        return total

    def proven_rows(self) -> int:
        return sum(
            ks.discarded_bars + ks.frontier.bars_done
            for ks in self._keys.values()
        )

    def verdict_lag_s(self, now: Optional[float] = None) -> float:
        """Seconds since the oldest not-yet-proven row was ingested —
        the standing run's analog of pipeline verdict lag.  Exact for
        all-OK streams (every row is a barrier); an approximation when
        info ops are present."""
        if now is None:
            now = time.monotonic()
        worst = 0.0
        for ks in self._keys.values():
            proven = ks.discarded_bars + ks.frontier.bars_done
            pts = ks.lag_points
            while pts and pts[0][0] <= proven:
                pts.popleft()
            if pts:
                worst = max(worst, now - pts[0][1])
        return worst

    def epochs(self) -> dict:
        """Per-key epoch bookkeeping: {key: {"epoch", "unknown",
        "last-reason"}} — what the live nemesis driver correlates a
        fault window against (did THIS window kill a frontier?)."""
        return {
            key: {
                "epoch": ks.epoch,
                "unknown": ks.unknown_epochs,
                "last-reason": ks.last_reason,
            }
            for key, ks in self._keys.items()
        }

    def status(self) -> dict:
        keys = self._keys
        return {
            "keys": len(keys),
            "resident-rows": self.resident_rows(),
            "resident-bytes": self.resident_bytes(),
            "discarded-rows": sum(
                ks.discarded_rows for ks in keys.values()
            ),
            "blocks-done": sum(
                ks.frontier.blocks_done for ks in keys.values()
            ),
            "epoch-restarts": sum(
                ks.unknown_epochs for ks in keys.values()
            ),
        }
