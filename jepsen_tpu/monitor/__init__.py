"""Always-on continuous verification (`jepsen monitor`).

The composition layer ROADMAP item 5 names as the product: a paced
generator, rolling-window online checking with constant memory
(rolling.py), a durable time-series observatory
(telemetry/timeseries.py), SLO evaluation with alert routing
(alerts.py), and the standing loop that ties them together (loop.py).
Live-target mode (`--suite`, monitor/live.py) swaps the synthetic
source for a suite-backed client pool with an evolving in-run fault
schedule and supervised recovery; it is imported lazily so the base
monitor stays free of suite dependencies.  The multi-tenant layer
(`jepsen fleet`, fleet.py + retention.py) supervises N such monitors
as isolated tenant children over one checkerd federation.
"""

from .alerts import AlertRouter
from .fleet import (FleetRegistry, FleetSupervisor, TenantSpec,
                    tenant_store_dir)
from .loop import MonitorConfig, run_monitor
from .retention import RetentionPolicy
from .rolling import RollingChecker

__all__ = [
    "AlertRouter",
    "FleetRegistry",
    "FleetSupervisor",
    "MonitorConfig",
    "RetentionPolicy",
    "RollingChecker",
    "TenantSpec",
    "run_monitor",
    "tenant_store_dir",
]
