"""Disk-budgeted retention sweeper for standing monitor runs.

A standing tenant accumulates two kinds of disk state under its store
dir: verdict/fault dossiers (`forensics/monitor/*.json`, one JSON per
non-valid epoch or fault postmortem) and the tiered series store
(`series-t{0,1,2}.jtpu` plus at most one rotated `.1` predecessor per
tier).  The monitor bounds RSS but nothing bounded disk — a
months-long run grows forever.  `sweep()` enforces three independent
ceilings per tenant:

  - **count** (`retain_dossiers`): keep at most N dossiers, deleting
    oldest-first by mtime;
  - **age** (`retain_days`): delete dossiers and rotated series
    generations older than D days;
  - **bytes** (`budget_bytes`): if the tenant's total dossier+series
    footprint still exceeds the budget, delete more oldest-first
    dossiers, then the oldest rotated series generations.

Invariants, in every phase: the *newest* dossier is never deleted
(the most recent forensic evidence always survives a sweep, however
old), and an *open* series file (`series-t{t}.jtpu`, the one the
writer holds) is never touched — only rotated `.1` generations are
GC-able.  Sweeps are idempotent: a second pass over an already-swept
store deletes nothing.

Counters live under `fleet.retention.*` (sweeps, dossiers-deleted,
series-deleted, bytes-freed, errors) so the fleet supervisor's
periodic sweeps are observable per scrape.
"""

from __future__ import annotations

import glob
import os
from dataclasses import dataclass
from typing import List, Optional, Tuple

from .. import telemetry
from ..forensics import FORENSICS_DIR

MONITOR_FORENSICS = "monitor"

#: Open (writer-held) series files — never deleted.  Rotated
#: generations carry a ``.1`` suffix and are the only GC-able tier
#: files.
_OPEN_SERIES = tuple(f"series-t{t}.jtpu" for t in range(3))


@dataclass(frozen=True)
class RetentionPolicy:
    """Per-tenant retention knobs (CLI: --retain-dossiers,
    --retain-days, --retain-bytes)."""

    retain_dossiers: int = 64
    retain_days: float = 14.0
    budget_bytes: Optional[int] = None


def _mtime_size(path: str) -> Tuple[float, int]:
    st = os.stat(path)
    return st.st_mtime, st.st_size


def _dossiers(store_dir: str) -> List[Tuple[float, int, str]]:
    """(mtime, size, path) for every monitor dossier, oldest first."""
    d = os.path.join(store_dir, FORENSICS_DIR, MONITOR_FORENSICS)
    out = []
    for p in glob.glob(os.path.join(d, "*.json")):
        try:
            mt, sz = _mtime_size(p)
        except OSError:
            continue
        out.append((mt, sz, p))
    out.sort()
    return out


def _rotated_series(store_dir: str) -> List[Tuple[float, int, str]]:
    """(mtime, size, path) for rotated series generations, oldest
    first.  Open tier files are excluded by construction."""
    out = []
    for p in glob.glob(os.path.join(store_dir, "series-t*.jtpu.1")):
        try:
            mt, sz = _mtime_size(p)
        except OSError:
            continue
        out.append((mt, sz, p))
    out.sort()
    return out


def disk_bytes(store_dir: str) -> int:
    """Total dossier + series footprint for one tenant store — the
    figure the byte budget and the fleet dashboard both report."""
    total = 0
    for _, sz, _ in _dossiers(store_dir):
        total += sz
    for _, sz, _ in _rotated_series(store_dir):
        total += sz
    for name in _OPEN_SERIES:
        try:
            total += os.path.getsize(os.path.join(store_dir, name))
        except OSError:
            pass
    return total


def _unlink(path: str, report: dict) -> int:
    """Best-effort delete; returns bytes freed (0 on failure)."""
    try:
        sz = os.path.getsize(path)
        os.unlink(path)
    except OSError:
        telemetry.count("fleet.retention.errors")
        return 0
    report["deleted"].append(os.path.basename(path))
    return sz


def sweep(store_dir: str, policy: RetentionPolicy,
          now: Optional[float] = None) -> dict:
    """One retention pass over a tenant store.  Returns a report dict
    ({deleted, dossiers-deleted, series-deleted, bytes-freed,
    disk-bytes}); safe to call concurrently with a live monitor (it
    only ever removes closed files)."""
    import time as _time
    now = _time.time() if now is None else now
    telemetry.count("fleet.retention.sweeps")
    report: dict = {"deleted": [], "dossiers-deleted": 0,
                    "series-deleted": 0, "bytes-freed": 0}

    dossiers = _dossiers(store_dir)
    # Phase 1 — count ceiling: oldest beyond retain_dossiers go, but
    # the newest dossier always survives (retain_dossiers >= 1).
    keep = max(1, int(policy.retain_dossiers))
    excess = dossiers[:-keep] if len(dossiers) > keep else []
    # Phase 2 — age ceiling on the remainder, newest still exempt.
    cutoff = now - policy.retain_days * 86400.0
    aged = [d for d in dossiers[len(excess):-1] if d[0] < cutoff]
    for _, _, p in excess + aged:
        freed = _unlink(p, report)
        if freed:
            report["dossiers-deleted"] += 1
            report["bytes-freed"] += freed

    # Phase 2b — rotated series generations past the age ceiling.
    rotated = _rotated_series(store_dir)
    stale = [r for r in rotated if r[0] < cutoff]
    for _, _, p in stale:
        freed = _unlink(p, report)
        if freed:
            report["series-deleted"] += 1
            report["bytes-freed"] += freed

    # Phase 3 — byte budget: more oldest-first dossiers (newest
    # exempt), then oldest rotated generations, until under budget.
    if policy.budget_bytes is not None:
        total = disk_bytes(store_dir)
        if total > policy.budget_bytes:
            survivors = _dossiers(store_dir)
            for _, _, p in survivors[:-1]:
                if total <= policy.budget_bytes:
                    break
                freed = _unlink(p, report)
                if freed:
                    report["dossiers-deleted"] += 1
                    report["bytes-freed"] += freed
                    total -= freed
            for _, _, p in _rotated_series(store_dir):
                if total <= policy.budget_bytes:
                    break
                freed = _unlink(p, report)
                if freed:
                    report["series-deleted"] += 1
                    report["bytes-freed"] += freed
                    total -= freed

    if report["dossiers-deleted"]:
        telemetry.count("fleet.retention.dossiers-deleted",
                        report["dossiers-deleted"])
    if report["series-deleted"]:
        telemetry.count("fleet.retention.series-deleted",
                        report["series-deleted"])
    if report["bytes-freed"]:
        telemetry.count("fleet.retention.bytes-freed",
                        report["bytes-freed"])
    report["disk-bytes"] = disk_bytes(store_dir)
    return report
