"""`jepsen monitor`: the standing continuous-verification loop.

Composition layer over the subsystems PRs 6-13 built (ROADMAP item 5):

  * a paced op source — either the in-process linearizable-by-
    construction keyed register workload (utils/histgen.py's
    pending-dict idiom, driven incrementally), or, with `--suite`, a
    pool of real suite clients against real daemons plus a live
    nemesis driver evolving in-run fault schedules (monitor/live.py);
  * a `RollingChecker` (monitor/rolling.py) holding memory constant
    via stable-prefix discards;
  * a `SeriesStore` + `Sampler` (telemetry/timeseries.py) persisting
    every gauge/counter/SLO state and per-pass profile medians on a
    fixed cadence;
  * the SLO engine evaluated each cadence with the quantile gauges
    (verdict-lag p95 instead of last-sample) and an `AlertRouter`
    turning transitions into sink deliveries;
  * an optional checkerd/router tee: each completed window of ops is
    also submitted to a daemon for an independent post-hoc verdict
    (best-effort, counted, never blocking the loop);
  * epoch restarts (a dead frontier after discard) write a forensics
    dossier under the store dir, so the alert that follows carries
    evidence.

Telemetry growth is bounded every cadence: the trace-event ring is
trimmed (spans keep their aggregate stats), the flight ring is already
a 512-deep deque, quantile rings and series stores are bounded deques
and rotated files — `monitor.resident-history-bytes` gauges what
remains so the memory ceiling is itself monitored.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import queue
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from .. import telemetry
from ..history.core import Op
from ..models.registers import cas_register
from ..ops import degrade
from ..telemetry import flight, profile, slo, timeseries
from .alerts import AlertRouter
from .rolling import RollingChecker

log = logging.getLogger(__name__)

#: Directory under the store dir where epoch-restart dossiers land
#: (same root the alert router scans).
MONITOR_FORENSICS = "monitor"

SUMMARY_FILE = "monitor-summary.json"


@dataclass
class MonitorConfig:
    """Knobs for one monitor run (CLI flags map 1:1)."""

    store_dir: str
    rate: float = 1000.0          # target completed ops per second
    duration_s: float = 60.0      # 0 = run until stopped
    keys: int = 8
    procs_per_key: int = 4
    cadence_s: float = 5.0        # sample/evaluate/alert cadence
    seed: int = 45100
    info_rate: float = 0.0
    max_ops: Optional[int] = None
    # rolling checker
    bars_per_block: int = 64
    blocks_per_call: int = 4
    beam: int = 8
    advance_rows: int = 1024
    retain_blocks: int = 1
    discard: bool = True
    # alerting
    sinks: tuple = ()
    dedup_s: float = 60.0
    renotify_s: float = 300.0
    #: fire a synthetic SLO for the first N seconds then clear it —
    #: the smoke's deterministic fire->alert->clear round trip.
    inject_slo_s: float = 0.0
    # integration
    endpoint: Optional[str] = None   # checkerd/router tee address
    tee_window_ops: int = 4096
    tenant: Optional[str] = None     # DRR identity on the tee SUBMIT
    tee_deadline_s: float = 120.0    # per-window verdict deadline
    serve_port: Optional[int] = None
    extra_rules: tuple = field(default_factory=tuple)
    # live (suite-backed) mode — monitor/live.py
    suite: Optional[str] = None      # kvdb|logd|electd|txnd|repkv
    nodes: tuple = ()                # override the suite's node list
    live_faults: tuple = ()          # fault families ("none" disables)
    search_dir: Optional[str] = None  # coverage-search checkpoint dir
    window_gap_s: float = 0.75       # quiet gap between fault windows
    live_seed_duration_s: float = 2.0
    supervise: bool = True           # restart daemons dead out-of-window


class _OpSource:
    """Incremental keyed register workload: linearizable by
    construction (each op's effect applies atomically at completion —
    histgen.random_register_history's pending-dict idiom, emitted one
    event at a time, forever)."""

    def __init__(self, keys: int, procs_per_key: int, seed: int,
                 info_rate: float):
        self.keys = keys
        self.procs = procs_per_key
        self.info_rate = info_rate
        self.rng = random.Random(seed)
        self.value: list[Optional[int]] = [None] * keys
        self.pending: list[dict] = [dict() for _ in range(keys)]
        self.index = 0
        self._key = 0

    def _emit(self, key: int, op_type: str, f: str, value: Any,
              p: int) -> tuple[int, Op]:
        self.index += 1
        return key, Op(
            type=op_type, f=f, value=value,
            process=key * self.procs + p, index=self.index,
        )

    def next_event(self) -> tuple[int, Op]:
        """One (key, op) event: an invocation or a completion."""
        rng = self.rng
        key = self._key
        self._key = (self._key + 1) % self.keys
        pending = self.pending[key]
        p = rng.randrange(self.procs)
        if p in pending:
            f, payload, as_info = pending.pop(p)
            value = self.value[key]
            if as_info:
                if f == "write" and rng.random() < 0.5:
                    self.value[key] = payload
                elif (f == "cas" and rng.random() < 0.5
                        and value == payload[0]):
                    self.value[key] = payload[1]
                return self._emit(key, "info", f, payload, p)
            if f == "read":
                return self._emit(key, "ok", "read", value, p)
            if f == "write":
                self.value[key] = payload
                return self._emit(key, "ok", "write", payload, p)
            if value == payload[0]:
                self.value[key] = payload[1]
                return self._emit(key, "ok", "cas", payload, p)
            return self._emit(key, "fail", "cas", payload, p)
        f = rng.choice(("read", "write", "cas"))
        if f == "read":
            payload: Any = None
        elif f == "write":
            payload = rng.randrange(5)
        else:
            payload = (rng.randrange(5), rng.randrange(5))
        as_info = f != "read" and rng.random() < self.info_rate
        pending[p] = (f, payload, as_info)
        return self._emit(key, "invoke", f, payload, p)


class _Tee:
    """Best-effort checkerd tee: windows of op dicts are submitted to
    a daemon/router for an independent post-hoc verdict.  A bounded
    queue + worker thread; a slow or dead daemon drops windows
    (counted), never stalls the monitor.

    Overload handling: an `F_SHED` from the daemon's admission path is
    *not* a daemon failure — treating it as one (the old behaviour)
    permanently degraded the tee to in-process checking, silently
    un-sharing the fleet.  Sheds now back off for the server-provided
    `retry-after-s` (bounded by MAX_SHED_WAIT_S) and retry while the
    window's deadline budget can still cover another attempt, counted
    under `monitor.shed.*`; only a truly unmeetable deadline drops the
    window."""

    def __init__(self, endpoint: str, keys: int, run_id: str,
                 tenant: Optional[str] = None,
                 deadline_s: float = 120.0):
        from ..checkerd.protocol import model_to_spec

        self.endpoint = endpoint
        self.keys = keys
        self.run_id = run_id
        self.tenant = tenant
        self.deadline_s = deadline_s
        self.spec = model_to_spec(cas_register()) or {}
        self.q: queue.Queue = queue.Queue(maxsize=4)
        self.windows: list[list[dict]] = [[] for _ in range(keys)]
        self.pending_events = 0
        self.n = 0
        self._thread = threading.Thread(
            target=self._work, name="monitor-tee", daemon=True
        )
        self._thread.start()

    def feed(self, key: int, op: Op) -> None:
        self.windows[key].append(op.to_dict())
        self.pending_events += 1

    def flush(self, window_ops: int) -> None:
        if self.pending_events < window_ops:
            return
        self.n += 1
        try:
            self.q.put_nowait((f"{self.run_id}-w{self.n}", self.windows))
            telemetry.count("monitor.tee-submitted")
        except queue.Full:
            telemetry.count("monitor.tee-dropped")
        self.windows = [[] for _ in range(self.keys)]
        self.pending_events = 0

    def _submit_once(self, run: str, windows: list,
                     budget_s: float) -> dict:
        from ..checkerd.client import CheckerdClient

        with CheckerdClient(self.endpoint) as c:
            ticket = c.submit_ops(run, self.spec, windows,
                                  tenant=self.tenant,
                                  deadline_s=self.deadline_s)
            return c.wait(ticket, deadline_s=budget_s)

    def _work(self) -> None:
        from ..checkerd.client import MAX_SHED_WAIT_S, ShedByServer

        while True:
            run, windows = self.q.get()
            deadline = time.monotonic() + self.deadline_s
            try:
                while True:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        telemetry.count("monitor.shed.deadline-unmet")
                        log.warning("monitor tee %s: window %s shed "
                                    "past its %.0fs deadline, dropped",
                                    self.endpoint, run, self.deadline_s)
                        break
                    try:
                        res = self._submit_once(run, windows, remaining)
                    except ShedByServer as e:
                        # Overload, not failure: honour the server's
                        # retry-after (bounded) and try again while
                        # the deadline budget allows.
                        wait = min(max(e.retry_after_s, 0.05),
                                   MAX_SHED_WAIT_S,
                                   deadline - time.monotonic())
                        if wait <= 0:
                            continue  # deadline check drops it
                        telemetry.count("monitor.shed.backoffs")
                        time.sleep(wait)
                        continue
                    valid = (res.get("result") or {}).get("valid")
                    telemetry.count(
                        "monitor.tee-valid" if valid is True
                        else "monitor.tee-nonvalid"
                    )
                    break
            except Exception as e:  # noqa: BLE001 — tee is best-effort
                telemetry.count("monitor.tee-errors")
                log.warning("monitor tee %s failed: %r",
                            self.endpoint, e)


def _atomic_json(path: str, doc: dict) -> None:
    """tmp + fsync + rename: readers (the web UI, `jepsen fleet`) see
    either the old document or the new one, never a torn tail."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=2, default=repr)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _write_dossier(store_dir: str, stem: str, doc: dict) -> Optional[str]:
    """One JSON dossier under the forensics root the alert router
    attaches evidence from."""
    from ..forensics import FORENSICS_DIR

    d = os.path.join(store_dir, FORENSICS_DIR, MONITOR_FORENSICS)
    path = os.path.join(d, f"{stem}.json")
    try:
        os.makedirs(d, exist_ok=True)
        _atomic_json(path, doc)
        return path
    except OSError as e:
        log.warning("monitor dossier write failed: %r", e)
        return None


def _write_epoch_dossier(store_dir: str, checker: RollingChecker,
                         n: int) -> Optional[str]:
    """A frontier death after discard has no post-hoc fallback; what
    it does have is evidence."""
    return _write_dossier(store_dir, f"epoch-restart-{n}", {
        "what": "monitor epoch restart",
        "why": "frontier died after history discard; the "
               "dying epoch's verdict is unknown",
        "t": time.time(),
        "checker": checker.status(),
        "flight": flight.status(),
    })


def run_monitor(cfg: MonitorConfig,
                stop: Optional[threading.Event] = None) -> dict:
    """Runs the monitor until `duration_s` elapses, `max_ops` complete,
    or `stop` is set.  Returns (and persists) a summary dict."""
    os.makedirs(cfg.store_dir, exist_ok=True)
    telemetry.enable()
    slo.set_dir(cfg.store_dir)
    flight.set_dir(cfg.store_dir)
    profile.set_store(cfg.store_dir)
    rules = list(slo.DEFAULT_RULES) + list(slo.MONITOR_RULES)
    if cfg.suite:
        rules += list(slo.LIVE_MONITOR_RULES)
    if cfg.tenant:
        rules += list(slo.TENANT_RULES)
    rules += list(cfg.extra_rules)
    if cfg.inject_slo_s > 0:
        rules.append(slo.Rule(
            "monitor-injected", "gauge-above", "monitor.injected", 0.5
        ))
    slo.reset(tuple(rules))

    store = timeseries.SeriesStore(cfg.store_dir)
    sampler = timeseries.Sampler(
        store, profile_path=profile.store_path()
    )
    router = AlertRouter(
        cfg.sinks, store_dir=cfg.store_dir,
        dedup_s=cfg.dedup_s, renotify_s=cfg.renotify_s,
    )
    pm = cas_register().packed()
    checker = RollingChecker(
        pm,
        bars_per_block=cfg.bars_per_block,
        blocks_per_call=cfg.blocks_per_call,
        beam=cfg.beam,
        advance_rows=cfg.advance_rows,
        retain_blocks=cfg.retain_blocks,
        discard=cfg.discard,
    )
    # Graceful shutdown (live satellite, but useful everywhere): turn
    # SIGTERM/SIGINT into a stop-flag so the finally block drains
    # in-flight ops, heals open fault windows, flushes the series
    # store and alert router, and ticks a final verdict.
    if stop is None:
        stop = threading.Event()
    prev_handlers: dict = {}
    if threading.current_thread() is threading.main_thread():
        import signal as _signal

        def _graceful(signum: int, frame: Any) -> None:
            log.info("monitor: signal %d, draining gracefully", signum)
            telemetry.count("monitor.graceful-shutdowns")
            stop.set()

        for _sig in (_signal.SIGTERM, _signal.SIGINT):
            prev_handlers[_sig] = _signal.signal(_sig, _graceful)

    live = None
    if cfg.suite:
        from . import live as live_mod

        live = live_mod.LiveContext(cfg)
        try:
            source: Any = live.start(checker.status)
        except BaseException:
            import contextlib
            import signal as _signal

            with contextlib.suppress(Exception):
                live.finalize()
            for _sig, h in prev_handlers.items():
                _signal.signal(_sig, h)
            store.close()
            raise
    else:
        source = _OpSource(cfg.keys, cfg.procs_per_key, cfg.seed,
                           cfg.info_rate)
    tee = (_Tee(cfg.endpoint, cfg.keys, f"monitor-{os.getpid()}",
                tenant=cfg.tenant, deadline_s=cfg.tee_deadline_s)
           if cfg.endpoint else None)
    server = None
    if cfg.serve_port is not None:
        from .. import web

        server = web.make_server(cfg.store_dir, port=cfg.serve_port)
        threading.Thread(
            target=server.serve_forever, name="monitor-web", daemon=True
        ).start()
        log.info("monitor dashboard at http://127.0.0.1:%d/monitor",
                 server.server_address[1])

    t0 = time.monotonic()
    wall0 = time.time()
    deadline = t0 + cfg.duration_s if cfg.duration_s > 0 else None
    next_sample = t0 + cfg.cadence_s
    events = 0
    completed = 0
    epoch_dossiers = 0
    rate_window: collections.deque = collections.deque(maxlen=8)
    rate_window.append((t0, 0))
    ingest_window = collections.deque(maxlen=rate_window.maxlen)
    ingest_window.append((t0, telemetry.counter_value("ingest.append.ops")))
    burst = max(1, min(512, int(cfg.rate * cfg.cadence_s / 50) or 1))
    telemetry.count("monitor.runs")

    def cadence(now: float) -> None:
        nonlocal epoch_dossiers
        # --- gauges for this tick
        lag = checker.verdict_lag_s(now)
        telemetry.gauge("monitor.verdict-lag-s", lag)
        timeseries.observe("monitor.verdict-lag-s", lag)
        telemetry.gauge("monitor.resident-history-bytes",
                        checker.resident_bytes())
        telemetry.gauge("monitor.resident-rows", checker.resident_rows())
        telemetry.gauge("monitor.series-disk-bytes", store.disk_bytes())
        rate_window.append((now, completed))
        ingest_window.append(
            (now, telemetry.counter_value("ingest.append.ops")))
        (tA, cA), (tB, cB) = rate_window[0], rate_window[-1]
        if tB > tA:
            telemetry.gauge("monitor.ops-per-s",
                            round((cB - cA) / (tB - tA), 1))
        # Measured ingest throughput (ingest.append.ops delta over the
        # same rolling window): the PackedBuilder-side rate the
        # roofline/ingest work optimizes against.
        (tI, iA), (tJ, iB) = ingest_window[0], ingest_window[-1]
        if tJ > tI and iB > iA:
            telemetry.gauge("monitor.ingest-ops-per-s",
                            round((iB - iA) / (tJ - tI), 1))
        if cfg.inject_slo_s > 0:
            telemetry.gauge(
                "monitor.injected",
                1.0 if now - t0 <= cfg.inject_slo_s else 0.0,
            )
        # --- epoch restarts -> dossiers (evidence for the next alert)
        restarts = checker.status()["epoch-restarts"]
        while epoch_dossiers < restarts:
            epoch_dossiers += 1
            _write_epoch_dossier(cfg.store_dir, checker, epoch_dossiers)
        # --- bound trace-event growth (satellite: constant memory)
        mark = telemetry.event_mark()
        if mark:
            telemetry.trim_events(0)
            telemetry.count("monitor.events-trimmed", mark)
        # --- evaluate + alert + persist
        extras = timeseries.quantile_gauges()
        transitions = slo.evaluate(
            extra_gauges=extras, chip_state=degrade.chip_state()
        )
        # Each firing gets a forensics dossier *before* routing, so the
        # alert event that reaches the sink carries its evidence path.
        for tr in transitions:
            if tr.get("rec") == "firing":
                _write_dossier(
                    cfg.store_dir,
                    f"slo-{tr.get('rule')}-{int(now - t0)}s",
                    {
                        "what": "monitor SLO firing",
                        "transition": tr,
                        "t": time.time(),
                        "checker": checker.status(),
                        "gauges": extras,
                        "flight": flight.status(),
                    },
                )
        router.route(transitions)
        router.tick(slo.firing_gauges())
        sampler.sample(extra=extras)
        telemetry.count("monitor.samples")
        if tee is not None:
            tee.flush(cfg.tee_window_ops)

    try:
        while True:
            now = time.monotonic()
            if stop is not None and stop.is_set():
                break
            if deadline is not None and now >= deadline:
                break
            if cfg.max_ops is not None and completed >= cfg.max_ops:
                break
            # Drain the whole burst per key through the columnar ingest
            # (PackedBuilder.append_many) instead of per-op feeds.
            by_key: dict = {}
            for _ in range(burst):
                ev = source.next_event()
                if ev is None:
                    # Live pool produced nothing (wounded cluster);
                    # the blocking get already paced us.
                    break
                key, op = ev
                by_key.setdefault(key, []).append(op)
                if tee is not None:
                    tee.feed(key, op)
                events += 1
                if op.type != "invoke":
                    completed += 1
            t_feed = time.monotonic()
            for key, kops in by_key.items():
                checker.feed_many(key, kops, t_feed)
            # Pace: one completed op ~= two events.  Live mode paces at
            # the source (real clients, per-worker intervals), so only
            # the synthetic source sleeps here.
            target = t0 + events / (2.0 * cfg.rate)
            now = time.monotonic()
            if now >= next_sample:
                cadence(now)
                next_sample += cfg.cadence_s
            if live is None and now < target:
                time.sleep(min(target - now, 0.25))
    finally:
        if prev_handlers:
            import signal as _signal

            for _sig, h in prev_handlers.items():
                _signal.signal(_sig, h)
        if live is not None:
            # Graceful drain: stop the driver (healing any open fault
            # window), stop the supervisor, and feed the in-flight ops
            # the client pool still holds.
            leftovers = live.shutdown()
            if leftovers:
                by_key = {}
                for key, op in leftovers:
                    by_key.setdefault(key, []).append(op)
                    events += 1
                    if op.type != "invoke":
                        completed += 1
                t_feed = time.monotonic()
                for key, kops in by_key.items():
                    checker.feed_many(key, kops, t_feed)
        now = time.monotonic()
        checker.pump(now)
        cadence(now)
        verdicts = checker.finish()
        router.flush()
        status = checker.status()
        summary = {
            "ops": completed,
            "events": events,
            "duration_s": round(now - t0, 3),
            "rate_target": cfg.rate,
            "rate_measured": round(completed / max(1e-9, now - t0), 1),
            "started_at": wall0,
            "keys": cfg.keys,
            "discard": cfg.discard,
            "verdicts": {str(k): v for k, v in verdicts.items()},
            "ok_keys": sum(1 for v in verdicts.values() if v is True),
            "unknown_keys": sum(
                1 for v in verdicts.values() if v != True  # noqa: E712
            ),
            "checker": status,
            "verdict_lag_s": checker.verdict_lag_s(now),
            "series_disk_bytes": store.disk_bytes(),
            "alerts": router.status(),
            "slo": slo.status(),
        }
        if live is not None:
            try:
                summary["live"] = live.finalize()
            except Exception as e:  # noqa: BLE001 — summary must land
                log.warning("live finalize failed: %r", e)
                summary["live"] = {"error": f"{type(e).__name__}: {e}"}
        try:
            _atomic_json(os.path.join(cfg.store_dir, SUMMARY_FILE),
                         summary)
        except OSError as e:
            log.warning("monitor summary write failed: %r", e)
        store.close()
        if server is not None:
            server.shutdown()
    return summary
